"""Streaming collect (ISSUE 9): verify refresh broadcast messages
incrementally as they arrive instead of at the all-messages barrier.

The barrier path (`refresh.collect` / `collect_sessions`) gathers every
message first, then runs each verification family as one fused batch.
In a serving loop that wastes the arrival window: by the time the last
committee member's broadcast lands, nothing has been checked. Here a
`StreamingCollect` session does the per-message work EAGERLY on each
`offer` — wire-shape and broadcast-public-key gates, the message's
Feldman rows, its ring-Pedersen and Paillier correct-key proofs — and
stages the O(n) pair rows (PDL-with-slack + Alice range), whose RLC fold
runs once at quorum in `finalize`/`finalize_streams` (fused across every
quorum-ready session the serving scheduler coalesces, exactly the
batch shape `collect_sessions` uses).

## Equivalence contract (pinned by tests/test_streaming.py, tier-1)

Verdicts, identifiable-abort blame, and LocalKey mutation are
bit-identical to barrier `collect` on the canonical message list — the
arrived messages in `expected_senders` order. The mechanism is
structural, not coincidental: every check order, error construction,
and mutation point lives in the shared per-session helpers of
`protocol.refresh` (check_structure / pair_blame / share_recovery_check
/ adopt_session), and `finalize` replays the barrier's phase order over
the eagerly-computed verdicts. Eager results are per-message and
order-independent, so arrival order, duplicates (first arrival wins),
and late messages (after finalize) cannot change the outcome.

## Secrecy

Streaming partial state holds broadcast messages, boolean verdicts, and
staged (proof, statement) rows — all broadcast-public material. The
receiver's secrets (paillier_dk, the new dk) are only touched inside
the shared `adopt_session` at finalize, same as the barrier path; no
cross-session material enters any per-session buffer (SECURITY.md
"Serving discipline").

## Memory (ISSUE 10)

A session's staged pair rows are REFERENCES into the broadcast
messages — O(n) per arrived message, tracked by the
`fsdkr_mem_stream_rows` gauge — and the wide staged operand data only
materializes at finalize, which runs `backend.verify_pairs` and
therefore inherits the bytes-budgeted tile plan (backend.memplan,
FSDKR_MEM_BUDGET_MB): build -> stage -> verify -> wipe per tile, RLC
folds as running per-group partial products. A serving worker's
per-session resident memory is thus bounded by O(n) references plus
O(tile) staged bytes regardless of committee size or how many sessions
a coalesced `finalize_streams` launch fuses
(tests/test_memplan.py::test_streaming_collect_on_tiles_parity).
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

from ..backend import get_backend
from ..config import ProtocolConfig, DEFAULT_CONFIG
from ..core.paillier import DecryptionKey
from ..core.secp256k1 import GENERATOR
from ..errors import PublicShareValidationError, RingPedersenProofError
from ..proofs.pdl_slack import PDLwSlackStatement
from ..proofs.composite_dlog import DLogStatement
from ..utils.trace import phase
from .local_key import LocalKey
from .refresh import (
    RefreshMessage,
    adopt_session,
    check_structure,
    fused_isolated,
    pair_blame,
    share_recovery_check,
)

__all__ = ["StreamingCollect", "finalize_streams"]


class StreamingCollect:
    """One receiver's incremental collect session.

    Lifecycle: construct (expected sender set fixed) -> `offer` each
    arriving RefreshMessage (any order; duplicates ignored) -> once
    `ready`, `finalize()` — or let the serving scheduler batch it into a
    fused `finalize_streams` launch. `offer` and `finalize` must not
    race each other (the serving loop serializes them; they may run on
    different threads).
    """

    def __init__(
        self,
        local_key: LocalKey,
        new_dk: DecryptionKey,
        expected_senders: Optional[Sequence[int]] = None,
        join_messages: Sequence = (),
        config: ProtocolConfig = DEFAULT_CONFIG,
    ):
        if expected_senders is None:
            expected_senders = range(1, local_key.n + 1)
        self.expected: Tuple[int, ...] = tuple(expected_senders)
        if len(set(self.expected)) != len(self.expected):
            raise ValueError("expected_senders must be distinct")
        self.joins = tuple(join_messages)
        self.new_n = len(self.expected) + len(self.joins)
        self.local_key = local_key
        self.new_dk = new_dk
        self.config = config
        self._backend = get_backend(config)
        self._lock = threading.Lock()
        # per-arrived-message state, keyed by party index; values are
        # verdict lists/bools or the Exception the eager backend call
        # raised (finalize replays them in canonical order)
        self._msgs: Dict[int, RefreshMessage] = {}
        self._struct_ok: Dict[int, bool] = {}
        self._feld: Dict[int, object] = {}
        self._rp: Dict[int, object] = {}
        self._ck: Dict[int, object] = {}
        self._pairs: Dict[int, Tuple[list, list]] = {}
        self._done = False
        self._result: Optional[Exception] = None

    # -- arrival --------------------------------------------------------
    def offer(self, msg: RefreshMessage) -> str:
        """Accept one broadcast message and run its eager checks.
        Returns "accepted", "duplicate" (party already arrived — first
        arrival wins), "late" (session already finalized), or
        "unexpected" (party not in the expected sender set)."""
        with self._lock:
            if self._done:
                return "late"
            pid = msg.party_index
            if pid not in self.expected:
                return "unexpected"
            if pid in self._msgs:
                return "duplicate"
            self._msgs[pid] = msg
        self._eager(pid, msg)
        return "accepted"

    def _eager(self, pid: int, msg: RefreshMessage) -> None:
        """Per-message eager work: structural gate, Feldman rows,
        ring-Pedersen, correct-key, pair-row staging. Backend exceptions
        are recorded, not raised — finalize surfaces them with barrier
        ordering. Every verdict here is order-independent (a function of
        this message + the receiver's pre-adopt key vectors alone)."""
        key = self.local_key
        with phase("collect.stream.offer", items=self.new_n):
            lens = (
                len(msg.pdl_proof_vec),
                len(msg.points_committed_vec),
                len(msg.points_encrypted_vec),
            )
            ok = (
                all(l == self.new_n for l in lens)
                and len(msg.range_proofs) == self.new_n
                and msg.public_key == key.y_sum_s
            )
            self._struct_ok[pid] = ok
            if not ok:
                # finalize's check_structure raises the barrier-ordered
                # error; eager verification of a malformed message could
                # only crash the codecs the barrier never reaches
                return
            try:
                self._feld[pid] = list(
                    self._backend.validate_feldman(
                        [
                            (
                                msg.coefficients_committed_vec,
                                msg.points_committed_vec[i],
                                i + 1,
                            )
                            for i in range(self.new_n)
                        ]
                    )
                )
            except Exception as e:
                self._feld[pid] = e
            try:
                self._rp[pid] = list(
                    self._backend.verify_ring_pedersen(
                        [(msg.ring_pedersen_proof, msg.ring_pedersen_statement)],
                        self.config.m_security,
                    )
                )[0]
            except Exception as e:
                self._rp[pid] = e
            try:
                self._ck[pid] = list(
                    self._backend.verify_correct_key(
                        [(msg.dk_correctness_proof, msg.ek)],
                        self.config.correct_key_rounds,
                    )
                )[0]
            except Exception as e:
                self._ck[pid] = e
            # stage the pair rows; their fold is the quorum-time launch
            pdl_rows, range_rows = [], []
            for i in range(self.new_n):
                st = PDLwSlackStatement(
                    ciphertext=msg.points_encrypted_vec[i],
                    ek=key.paillier_key_vec[i],
                    Q=msg.points_committed_vec[i],
                    G=GENERATOR,
                    h1=key.h1_h2_n_tilde_vec[i].g,
                    h2=key.h1_h2_n_tilde_vec[i].ni,
                    N_tilde=key.h1_h2_n_tilde_vec[i].N,
                )
                pdl_rows.append((msg.pdl_proof_vec[i], st))
                range_rows.append(
                    (
                        msg.range_proofs[i],
                        msg.points_encrypted_vec[i],
                        key.paillier_key_vec[i],
                        key.h1_h2_n_tilde_vec[i],
                    )
                )
            self._pairs[pid] = (pdl_rows, range_rows)
            _track_session(self)

    # -- introspection --------------------------------------------------
    @property
    def arrived(self) -> int:
        return len(self._msgs)

    @property
    def ready(self) -> bool:
        """Quorum: every expected sender's message has arrived."""
        return not self._done and len(self._msgs) == len(self.expected)

    @property
    def done(self) -> bool:
        return self._done

    @property
    def error(self) -> Optional[Exception]:
        """The finalize verdict (None = success); None before finalize."""
        return self._result

    def missing(self) -> List[int]:
        return [pid for pid in self.expected if pid not in self._msgs]

    def canonical_msgs(self) -> List[RefreshMessage]:
        """The arrived messages in expected-sender order — the exact
        list barrier `collect` would be called with."""
        return [self._msgs[pid] for pid in self.expected]

    def close(self, error: Optional[Exception] = None) -> bool:
        """Terminate this session WITHOUT adoption — the deadline
        reaper's entry point (ISSUE 11) and a teardown hygiene hook.
        Marks the session done with `error` as its stored verdict and
        releases the staged pair-row references now; afterwards `offer`
        returns "late" and any finalize (including a fused launch
        already holding this session) replays the stored verdict
        instead of verifying or mutating the LocalKey. Returns False
        (no-op) when the session already finished — a completed verdict
        is never overwritten."""
        with self._lock:
            if self._done:
                return False
            self._done = True
            self._result = error
            self._pairs.clear()
            return True

    # -- completion -----------------------------------------------------
    def finalize(self) -> None:
        """Finish this session alone: quorum-time pair fold + the
        barrier-ordered verdict replay + adoption. Raises exactly what
        barrier `collect` would; idempotent (a second finalize re-raises
        the stored verdict without re-verifying or re-adopting)."""
        err = finalize_streams([self], self.config)[0]
        if err is not None:
            raise err


def finalize_streams(
    streams: Sequence[StreamingCollect],
    config: ProtocolConfig = DEFAULT_CONFIG,
) -> List[Optional[Exception]]:
    """Finish many quorum-ready streaming sessions with the pair-family
    fold fused across all of them (the coalesced launch the serving
    scheduler batches for; row layout matches `collect_sessions`). All
    sessions must share `config`. Returns one entry per session — None
    on success or the exception barrier `collect` would have raised; a
    failing session never blocks the others. Already-finalized sessions
    replay their stored verdict; sessions short of quorum get a
    ValueError entry and stay open."""
    S = len(streams)
    errors: List[Optional[Exception]] = [None] * S
    with phase("collect.stream.finalize", items=S, sessions=S):
        return _finalize_impl(streams, errors, config)


def _finalize_impl(streams, errors, config):
    backend = get_backend(config)
    # idle-time pool refill, same as barrier collect entry: the fold
    # launches below release the GIL, so background production overlaps
    from .. import precompute

    precompute.kick()
    S = len(streams)
    msgs_l: List[Optional[list]] = [None] * S
    replayed = set()
    for s, st in enumerate(streams):
        if st._done:
            errors[s] = st._result
            replayed.add(s)
            continue
        missing = st.missing()
        if missing:
            errors[s] = ValueError(
                f"streaming session short of quorum: missing senders {missing}"
            )
            replayed.add(s)  # stays open: do not mark done below
            continue
        msgs_l[s] = st.canonical_msgs()

    def alive():
        return [
            s for s in range(S) if errors[s] is None and msgs_l[s] is not None
        ]

    # ---- 1. structure, canonical order (shared helper) ----------------
    for s in alive():
        try:
            check_structure(msgs_l[s], streams[s].local_key, streams[s].new_n)
        except Exception as e:
            errors[s] = e

    # ---- 2. Feldman replay --------------------------------------------
    for s in alive():
        st = streams[s]
        verdicts: List[bool] = []
        exc = None
        for pid in st.expected:
            r = st._feld.get(pid)
            if isinstance(r, Exception):
                exc = r
                break
            verdicts.extend(r)
        if exc is not None:
            errors[s] = exc
        elif not all(verdicts):
            errors[s] = PublicShareValidationError()

    # ---- 3. pair fold at quorum, fused across sessions ----------------
    pdl_items: list = []
    range_items: list = []
    pair_spans: Dict[int, Tuple[int, int]] = {}
    for s in alive():
        st = streams[s]
        lo = len(pdl_items)
        for pid in st.expected:
            p_rows, r_rows = st._pairs[pid]
            pdl_items.extend(p_rows)
            range_items.extend(r_rows)
        pair_spans[s] = (lo, len(pdl_items))
    if pdl_items:
        # spans ride only on the full fused call (cross-session dedup +
        # session-first blame in tpu_verifier.verify_pairs); per-session
        # retry slices are single-session, where spans would be stale
        def _pairs_call(p_slice, r_slice):
            if len(p_slice) == len(pdl_items):
                return backend.verify_pairs(
                    p_slice, r_slice, session_spans=pair_spans
                )
            return backend.verify_pairs(p_slice, r_slice)

        pdl_verdicts, range_verdicts = fused_isolated(
            _pairs_call, (pdl_items, range_items), pair_spans, errors
        )
        for s, (lo, _hi) in pair_spans.items():
            if errors[s] is not None:
                continue
            try:
                pair_blame(
                    msgs_l[s], streams[s].new_n,
                    pdl_verdicts, range_verdicts, lo,
                )
            except Exception as e:
                errors[s] = e

    # ---- 4. ring-Pedersen: eager verdicts + the joins' rows -----------
    jrp_items: list = []
    jrp_spans: Dict[int, Tuple[int, int]] = {}
    for s in alive():
        lo = len(jrp_items)
        jrp_items += [
            (j.ring_pedersen_proof, j.ring_pedersen_statement)
            for j in streams[s].joins
        ]
        jrp_spans[s] = (lo, len(jrp_items))
    jrp_verdicts = (
        fused_isolated(
            lambda items: (
                backend.verify_ring_pedersen(items, config.m_security),
            ),
            (jrp_items,),
            jrp_spans,
            errors,
        )[0]
        if jrp_items
        else []
    )
    for s in alive():
        st = streams[s]
        verdicts, exc = [], None
        for pid in st.expected:
            r = st._rp.get(pid)
            if isinstance(r, Exception):
                exc = r
                break
            verdicts.append(r)
        if exc is not None:
            errors[s] = exc
            continue
        lo, hi = jrp_spans[s]
        if not (all(verdicts) and all(jrp_verdicts[lo:hi])):
            errors[s] = RingPedersenProofError()

    # ---- 5. share recovery (host) -------------------------------------
    sums: Dict[int, tuple] = {}
    with phase("collect.share_recovery", items=len(alive())):
        for s in alive():
            try:
                sums[s] = share_recovery_check(msgs_l[s], streams[s].local_key)
            except Exception as e:
                errors[s] = e

    # ---- 6. correct-key: eager verdicts + the joins' rows + dlog ------
    jck_items: list = []
    jck_spans: Dict[int, Tuple[int, int]] = {}
    dlog_items: list = []
    dlog_spans: Dict[int, Tuple[int, int]] = {}
    for s in alive():
        st = streams[s]
        lo = len(jck_items)
        jck_items += [(j.dk_correctness_proof, j.ek) for j in st.joins]
        jck_spans[s] = (lo, len(jck_items))
        dlo = len(dlog_items)
        for join in st.joins:
            inverse_st = DLogStatement(
                N=join.dlog_statement.N,
                g=join.dlog_statement.ni,
                ni=join.dlog_statement.g,
            )
            dlog_items.append(
                (join.composite_dlog_proof_base_h1, join.dlog_statement)
            )
            dlog_items.append((join.composite_dlog_proof_base_h2, inverse_st))
        dlog_spans[s] = (dlo, len(dlog_items))
    jck_verdicts = (
        fused_isolated(
            lambda items: (
                backend.verify_correct_key(items, config.correct_key_rounds),
            ),
            (jck_items,),
            jck_spans,
            errors,
        )[0]
        if jck_items
        else []
    )
    dlog_verdicts = (
        fused_isolated(
            lambda items: (backend.verify_composite_dlog(items),),
            (dlog_items,),
            dlog_spans,
            errors,
        )[0]
        if dlog_items
        else []
    )
    # an eager correct-key backend exception surfaces here — after share
    # recovery, before adoption: the barrier's fused-ck phase position
    ck_lists: Dict[int, list] = {}
    for s in alive():
        st = streams[s]
        verdicts, exc = [], None
        for pid in st.expected:
            r = st._ck.get(pid)
            if isinstance(r, Exception):
                exc = r
                break
            verdicts.append(r)
        if exc is not None:
            errors[s] = exc
            continue
        lo, hi = jck_spans[s]
        ck_lists[s] = verdicts + list(jck_verdicts[lo:hi])

    # ---- 7. adoption (shared helper; mutating phase) ------------------
    with phase("collect.adopt", items=len(alive())):
        for s in alive():
            st = streams[s]
            dlo, dhi = dlog_spans[s]
            try:
                adopt_session(
                    msgs_l[s], st.local_key, st.new_dk, st.joins,
                    ck_lists[s], dlog_verdicts[dlo:dhi], sums[s],
                    st.new_n, config,
                )
            except Exception as e:
                errors[s] = e

    for s, st in enumerate(streams):
        if s in replayed:
            continue
        st._done = True
        st._result = errors[s]
        # staged pair-row references retire with the session (the wide
        # staged operands already died tile-by-tile inside verify_pairs)
        st._pairs.clear()
    return errors


# Live staged pair-row accounting across open streaming sessions — the
# serving loop's bounded-per-session-memory reading (module docstring
# "Memory"). A WeakSet + function gauge, not inc/dec counters: serving
# abort paths can drop a StreamingCollect without ever reaching
# finalize, and a decrement-based gauge would leak upward forever in
# exactly the degraded scenarios it exists to monitor. Garbage-collected
# sessions simply fall out of the sum.
_OPEN_SESSIONS: "weakref.WeakSet[StreamingCollect]" = weakref.WeakSet()


def _stream_rows_total() -> float:
    total = 0
    for st in list(_OPEN_SESSIONS):
        total += len(st._pairs) * st.new_n
    return float(total)


def _track_session(st: "StreamingCollect") -> None:
    from ..telemetry import registry

    _OPEN_SESSIONS.add(st)
    registry.gauge(
        "fsdkr_mem_stream_rows",
        "pair rows currently staged across open streaming-collect "
        "sessions (references into broadcast messages)",
    ).set_function(_stream_rows_total)
