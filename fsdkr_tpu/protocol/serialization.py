"""Canonical wire serialization for protocol messages and key material.

The reference derives serde on every broadcast message
(`/root/reference/src/refresh_message.rs:29-30`,
`src/add_party_message.rs:34-35`) and on `LocalKey`; SURVEY.md §5 notes the
refresh state surface is exactly the checkpoint/resume surface. This module
defines this framework's own canonical JSON encoding: integers as
lowercase hex strings, points as hex compressed SEC1, field names matching
the dataclasses. `hash_choice`-style type-level parameters are not wire
data (reference quirk 7).
"""

from __future__ import annotations

import json

from ..core.paillier import DecryptionKey, EncryptionKey
from ..core.secp256k1 import Point, Scalar
from ..core.vss import ShamirSecretSharing, VerifiableSS
from ..proofs.alice_range import AliceProof
from ..proofs.composite_dlog import CompositeDLogProof, DLogStatement
from ..proofs.correct_key import NiCorrectKeyProof
from ..proofs.pdl_slack import PDLwSlackProof
from ..proofs.ring_pedersen import RingPedersenProof, RingPedersenStatement
from .join import JoinMessage
from .local_key import LocalKey, SharedKeys
from .refresh import RefreshMessage

__all__ = [
    "refresh_message_to_json",
    "refresh_message_from_json",
    "join_message_to_json",
    "join_message_from_json",
    "local_key_to_json",
    "local_key_from_json",
]


# ---- primitives -----------------------------------------------------------
def _int_enc(x: int) -> str:
    return format(x, "x")


_HEX = frozenset("0123456789abcdef")


def _int_dec(s: str) -> int:
    """Strict canonical decode: lowercase hex magnitude only. int(s, 16)
    would admit a leading minus (letting an attacker smuggle negative
    values into exponent/transcript positions), '+', underscores, and
    whitespace — none of which the encoder ever emits. Malformed wire
    bytes fail closed HERE, at message decode, where the caller knows
    exactly which party sent them."""
    if not isinstance(s, str) or not s or not _HEX.issuperset(s):
        raise ValueError(f"non-canonical wire integer: {s!r:.40}")
    return int(s, 16)


def _point_enc(p: Point) -> str:
    return p.to_bytes(compressed=True).hex()


def _point_dec(s: str) -> Point:
    return Point.from_bytes(bytes.fromhex(s))


def _ek_enc(ek: EncryptionKey) -> dict:
    return {"n": _int_enc(ek.n)}


def _ek_dec(d: dict) -> EncryptionKey:
    n = _int_dec(d["n"])
    return EncryptionKey(n=n, nn=n * n)


def _vss_enc(v: VerifiableSS) -> dict:
    # the delegation certificate (proofs.msm_delegate, FSDKR_DELEGATE)
    # is optional on the wire: the key is emitted ONLY when present, so
    # certificate-free messages byte-match the pre-delegation encoding
    out = {
        "threshold": v.parameters.threshold,
        "share_count": v.parameters.share_count,
        "commitments": [_point_enc(c) for c in v.commitments],
    }
    if v.delegate_cert is not None:
        out["delegate_cert"] = _point_enc(v.delegate_cert)
    return out


def _vss_dec(d: dict) -> VerifiableSS:
    cert = d.get("delegate_cert")
    return VerifiableSS(
        parameters=ShamirSecretSharing(d["threshold"], d["share_count"]),
        commitments=[_point_dec(c) for c in d["commitments"]],
        delegate_cert=_point_dec(cert) if cert is not None else None,
    )


def _dlog_enc(st: DLogStatement) -> dict:
    return {"N": _int_enc(st.N), "g": _int_enc(st.g), "ni": _int_enc(st.ni)}


def _dlog_dec(d: dict) -> DLogStatement:
    return DLogStatement(N=_int_dec(d["N"]), g=_int_dec(d["g"]), ni=_int_dec(d["ni"]))


def _pdl_enc(p: PDLwSlackProof) -> dict:
    return {
        "z": _int_enc(p.z),
        "u1": _point_enc(p.u1),
        "u2": _int_enc(p.u2),
        "u3": _int_enc(p.u3),
        "s1": _int_enc(p.s1),
        "s2": _int_enc(p.s2),
        "s3": _int_enc(p.s3),
    }


def _pdl_dec(d: dict) -> PDLwSlackProof:
    return PDLwSlackProof(
        z=_int_dec(d["z"]),
        u1=_point_dec(d["u1"]),
        u2=_int_dec(d["u2"]),
        u3=_int_dec(d["u3"]),
        s1=_int_dec(d["s1"]),
        s2=_int_dec(d["s2"]),
        s3=_int_dec(d["s3"]),
    )


def _alice_enc(p: AliceProof) -> dict:
    return {k: _int_enc(getattr(p, k)) for k in ("z", "e", "s", "s1", "s2")}


def _alice_dec(d: dict) -> AliceProof:
    return AliceProof(**{k: _int_dec(d[k]) for k in ("z", "e", "s", "s1", "s2")})


def _rp_st_enc(st: RingPedersenStatement) -> dict:
    return {"S": _int_enc(st.S), "T": _int_enc(st.T), "N": _int_enc(st.N)}


def _rp_st_dec(d: dict) -> RingPedersenStatement:
    n = _int_dec(d["N"])
    return RingPedersenStatement(
        S=_int_dec(d["S"]), T=_int_dec(d["T"]), N=n, ek=EncryptionKey.from_n(n)
    )


def _rp_proof_enc(p: RingPedersenProof) -> dict:
    return {"A": [_int_enc(a) for a in p.A], "Z": [_int_enc(z) for z in p.Z]}


def _rp_proof_dec(d: dict) -> RingPedersenProof:
    return RingPedersenProof(
        A=[_int_dec(a) for a in d["A"]], Z=[_int_dec(z) for z in d["Z"]]
    )


def _ck_enc(p: NiCorrectKeyProof) -> dict:
    return {"sigma_vec": [_int_enc(s) for s in p.sigma_vec]}


def _ck_dec(d: dict) -> NiCorrectKeyProof:
    return NiCorrectKeyProof(sigma_vec=[_int_dec(s) for s in d["sigma_vec"]])


def _cdl_enc(p: CompositeDLogProof) -> dict:
    return {"x_commit": _int_enc(p.x_commit), "y": _int_enc(p.y)}


def _cdl_dec(d: dict) -> CompositeDLogProof:
    return CompositeDLogProof(x_commit=_int_dec(d["x_commit"]), y=_int_dec(d["y"]))


# ---- RefreshMessage -------------------------------------------------------
def refresh_message_to_json(m: RefreshMessage) -> str:
    return json.dumps(
        {
            "old_party_index": m.old_party_index,
            "party_index": m.party_index,
            "pdl_proof_vec": [_pdl_enc(p) for p in m.pdl_proof_vec],
            "range_proofs": [_alice_enc(p) for p in m.range_proofs],
            "coefficients_committed_vec": _vss_enc(m.coefficients_committed_vec),
            "points_committed_vec": [_point_enc(p) for p in m.points_committed_vec],
            "points_encrypted_vec": [_int_enc(c) for c in m.points_encrypted_vec],
            "dk_correctness_proof": _ck_enc(m.dk_correctness_proof),
            "dlog_statement": _dlog_enc(m.dlog_statement),
            "ek": _ek_enc(m.ek),
            "remove_party_indices": list(m.remove_party_indices),
            "public_key": _point_enc(m.public_key),
            "ring_pedersen_statement": _rp_st_enc(m.ring_pedersen_statement),
            "ring_pedersen_proof": _rp_proof_enc(m.ring_pedersen_proof),
        },
        sort_keys=True,
    )


def refresh_message_from_json(s: str) -> RefreshMessage:
    d = json.loads(s)
    return RefreshMessage(
        old_party_index=d["old_party_index"],
        party_index=d["party_index"],
        pdl_proof_vec=[_pdl_dec(p) for p in d["pdl_proof_vec"]],
        range_proofs=[_alice_dec(p) for p in d["range_proofs"]],
        coefficients_committed_vec=_vss_dec(d["coefficients_committed_vec"]),
        points_committed_vec=[_point_dec(p) for p in d["points_committed_vec"]],
        points_encrypted_vec=[_int_dec(c) for c in d["points_encrypted_vec"]],
        dk_correctness_proof=_ck_dec(d["dk_correctness_proof"]),
        dlog_statement=_dlog_dec(d["dlog_statement"]),
        ek=_ek_dec(d["ek"]),
        remove_party_indices=list(d["remove_party_indices"]),
        public_key=_point_dec(d["public_key"]),
        ring_pedersen_statement=_rp_st_dec(d["ring_pedersen_statement"]),
        ring_pedersen_proof=_rp_proof_dec(d["ring_pedersen_proof"]),
    )


# ---- JoinMessage ----------------------------------------------------------
def join_message_to_json(m: JoinMessage) -> str:
    return json.dumps(
        {
            "ek": _ek_enc(m.ek),
            "dk_correctness_proof": _ck_enc(m.dk_correctness_proof),
            "party_index": m.party_index,
            "dlog_statement": _dlog_enc(m.dlog_statement),
            "composite_dlog_proof_base_h1": _cdl_enc(m.composite_dlog_proof_base_h1),
            "composite_dlog_proof_base_h2": _cdl_enc(m.composite_dlog_proof_base_h2),
            "ring_pedersen_statement": _rp_st_enc(m.ring_pedersen_statement),
            "ring_pedersen_proof": _rp_proof_enc(m.ring_pedersen_proof),
        },
        sort_keys=True,
    )


def join_message_from_json(s: str) -> JoinMessage:
    d = json.loads(s)
    return JoinMessage(
        ek=_ek_dec(d["ek"]),
        dk_correctness_proof=_ck_dec(d["dk_correctness_proof"]),
        party_index=d["party_index"],
        dlog_statement=_dlog_dec(d["dlog_statement"]),
        composite_dlog_proof_base_h1=_cdl_dec(d["composite_dlog_proof_base_h1"]),
        composite_dlog_proof_base_h2=_cdl_dec(d["composite_dlog_proof_base_h2"]),
        ring_pedersen_statement=_rp_st_dec(d["ring_pedersen_statement"]),
        ring_pedersen_proof=_rp_proof_dec(d["ring_pedersen_proof"]),
    )


# ---- LocalKey (checkpoint surface; contains secrets — caller handles) -----
def local_key_to_json(k: LocalKey) -> str:
    return json.dumps(
        {
            "paillier_dk": {"p": _int_enc(k.paillier_dk.p), "q": _int_enc(k.paillier_dk.q)},
            "pk_vec": [_point_enc(p) for p in k.pk_vec],
            "keys_linear": {
                "x_i": _int_enc(k.keys_linear.x_i.to_int()),
                "y": _point_enc(k.keys_linear.y),
            },
            "paillier_key_vec": [_ek_enc(e) for e in k.paillier_key_vec],
            "y_sum_s": _point_enc(k.y_sum_s),
            "h1_h2_n_tilde_vec": [_dlog_enc(s) for s in k.h1_h2_n_tilde_vec],
            "vss_scheme": _vss_enc(k.vss_scheme),
            "i": k.i,
            "t": k.t,
            "n": k.n,
        },
        sort_keys=True,
    )


def local_key_from_json(s: str) -> LocalKey:
    d = json.loads(s)
    return LocalKey(
        paillier_dk=DecryptionKey(
            p=_int_dec(d["paillier_dk"]["p"]), q=_int_dec(d["paillier_dk"]["q"])
        ),
        pk_vec=[_point_dec(p) for p in d["pk_vec"]],
        keys_linear=SharedKeys(
            x_i=Scalar.from_int(_int_dec(d["keys_linear"]["x_i"])),
            y=_point_dec(d["keys_linear"]["y"]),
        ),
        paillier_key_vec=[_ek_dec(e) for e in d["paillier_key_vec"]],
        y_sum_s=_point_dec(d["y_sum_s"]),
        h1_h2_n_tilde_vec=[_dlog_dec(x) for x in d["h1_h2_n_tilde_vec"]],
        vss_scheme=_vss_dec(d["vss_scheme"]),
        i=d["i"],
        t=d["t"],
        n=d["n"],
    )
