"""The central mutable state object: a party's share of a GG20 key.

Equivalent of `multi-party-ecdsa`'s `LocalKey<E>` with the exact field set
the reference reads/rewrites (`/root/reference/src/add_party_message.rs:280-291`,
mutation sites `src/refresh_message.rs:64,315-317,394,436,446-464`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

from ..core.paillier import DecryptionKey, EncryptionKey
from ..core.secp256k1 import Point, Scalar
from ..core.vss import VerifiableSS
from ..proofs.composite_dlog import DLogStatement


@dataclass
class SharedKeys:
    """`SharedKeys{x_i, y}`: the linear share and its public point
    (reference `src/add_party_message.rs:199-202`)."""

    x_i: Scalar
    y: Point


@dataclass
class PaillierKeyPair:
    """A fresh Paillier pair as produced by `Keys::create`
    (reference `src/add_party_message.rs:102`)."""

    ek: EncryptionKey
    dk: DecryptionKey


@dataclass
class LocalKey:
    """Field-for-field equivalent of the reference's `LocalKey`:

    - paillier_dk: this party's Paillier secret key
    - pk_vec: per-party public shares X_j = x_j * G (1-based order)
    - keys_linear: own share x_i and y = x_i * G
    - paillier_key_vec: per-party Paillier public keys
    - y_sum_s: the unchanged group public key y
    - h1_h2_n_tilde_vec: per-party ring-Pedersen / dlog parameters
    - vss_scheme: this party's most recent Feldman scheme
    - i: own party index (1-based), t: threshold, n: committee size
    """

    paillier_dk: DecryptionKey
    pk_vec: List[Point]
    keys_linear: SharedKeys
    paillier_key_vec: List[EncryptionKey]
    y_sum_s: Point
    h1_h2_n_tilde_vec: List[DLogStatement]
    vss_scheme: VerifiableSS
    i: int
    t: int
    n: int

    def clone(self) -> "LocalKey":
        import copy

        return copy.deepcopy(self)

    def public_key(self) -> Point:
        return self.y_sum_s
