"""In-process broadcast simulation — a first-class test fixture
(SURVEY.md §4 rebuild implication iii).

The reference models the broadcast channel as vectors pushed into
per-party buckets (`/root/reference/src/test.rs:238-334`); removal is
exclusion from broadcast. Same here, as a reusable object instead of
test-local loops.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from ..config import ProtocolConfig, DEFAULT_CONFIG
from ..core.paillier import DecryptionKey
from .local_key import LocalKey
from .refresh import RefreshMessage


class BroadcastChannel:
    """Reliable broadcast with per-party delivery buckets and exclusion
    (used to model party removal, reference `src/test.rs:260-278`)."""

    def __init__(self, party_indices: Sequence[int]):
        self.buckets: Dict[int, List[RefreshMessage]] = {
            i: [] for i in party_indices
        }

    def broadcast(self, msg: RefreshMessage, exclude: Sequence[int] = ()) -> None:
        for party, bucket in self.buckets.items():
            if party in exclude:
                continue
            bucket.append(msg)

    def inbox(self, party_index: int) -> List[RefreshMessage]:
        return self.buckets[party_index]


def simulate_dkr(
    keys: List[LocalKey], config: ProtocolConfig = DEFAULT_CONFIG
) -> tuple[List[RefreshMessage], List[DecryptionKey]]:
    """Full refresh round: everyone distributes, everyone collects
    (reference `src/test.rs:311-334`)."""
    n = len(keys)
    results = RefreshMessage.distribute_batch(
        [(key.i, key) for key in keys], n, config
    )
    broadcast: List[RefreshMessage] = [m for m, _ in results]
    new_dks: List[DecryptionKey] = [dk for _, dk in results]
    for i, key in enumerate(keys):
        RefreshMessage.collect(broadcast, key, new_dks[i], (), config)
    return broadcast, new_dks


def simulate_dkr_removal(
    keys: List[LocalKey],
    remove_party_indices: Sequence[int],
    config: ProtocolConfig = DEFAULT_CONFIG,
) -> None:
    """Refresh with removal: removed parties are excluded from broadcast and
    must fail their own collect (reference `src/test.rs:238-309`).

    Reference-behavior quirk preserved deliberately: the reference's
    removal harness runs the survivors' `collect` on *clones* held in a
    side map (`src/test.rs:246,253` builds `party_key` from clones;
    `:286-298` mutates those clones), so the caller's keys are left at
    their pre-refresh values. This keeps later rounds consistent even
    though removed parties — which could not collect — rebroadcast from
    stale state. We mirror that observable behavior: survivors' collect is
    exercised (must succeed) on clones, removed parties' collect must
    fail, and the input keys emerge unrotated apart from the vss_scheme
    mutation done by distribute.
    """
    from ..errors import FsDkrError

    n = len(keys)
    channel = BroadcastChannel([k.i for k in keys])
    new_dks: Dict[int, DecryptionKey] = {}

    messages: List[RefreshMessage] = []
    for key in keys:
        msg, dk = RefreshMessage.distribute(key.i, key, n, config)
        new_dks[key.i] = dk
        messages.append(msg)

    for msg in messages:
        # a removed party doesn't list itself (reference :260-268)
        msg.remove_party_indices = [
            r for r in remove_party_indices if r != msg.party_index
        ]
        channel.broadcast(msg, exclude=msg.remove_party_indices)

    for r in remove_party_indices:
        assert len(channel.inbox(r)) == 1  # only its own message

    for key in keys:
        if key.i in remove_party_indices:
            continue
        # survivors must be able to collect — exercised on a clone
        # (reference discards the refreshed state, see docstring)
        RefreshMessage.collect(
            channel.inbox(key.i), key.clone(), new_dks[key.i], (), config
        )

    for r in remove_party_indices:
        key = next(k for k in keys if k.i == r)
        try:
            RefreshMessage.collect(channel.inbox(r), key.clone(), new_dks[r], (), config)
        except FsDkrError:
            continue
        raise AssertionError("removed party unexpectedly completed collect")
