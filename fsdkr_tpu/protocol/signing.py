"""GG20-compatible threshold ECDSA signing harness.

Equivalent of the reference's test-only use of `multi-party-ecdsa`'s
`OfflineStage` / `SignManual` (`/root/reference/src/test.rs:336-382`):
enough of GG20's signing algebra to prove that refreshed `LocalKey`s still
sign together under *different* quorums — the property the
sign→rotate→sign scenarios assert.

The offline stage runs GG20's actual share-conversion algebra in-process:
- additive reshare: w_i = lambda_i(S) * x_i so that sum w_i = x
- nonce/blinding: each party picks k_i, gamma_i
- the cross terms of k*gamma and k*w are computed by real Paillier MtA
  (ciphertext mul/add under the receiver's key — the algebra Bob's proofs
  in fsdkr_tpu.proofs.bob_range attest to; the ZK wrapping is omitted in
  this honest-party simulation, as the reference's Simulation also elides
  network adversaries)
- delta = k*gamma is revealed; R = (sum Gamma_i) * delta^{-1} = G * k^{-1}
- partial sigs: s_i = m*k_i + r*sigma_i; s = sum s_i

The final (r, s) verifies under vanilla ECDSA against y_sum_s.
"""

from __future__ import annotations

import hashlib
import secrets
from dataclasses import dataclass
from typing import List, Sequence

from ..core import paillier, vss
from ..core.secp256k1 import GENERATOR, N as CURVE_ORDER, Point, Scalar
from .local_key import LocalKey


@dataclass
class CompletedOfflineStage:
    """Per-party output of the offline stage (GG20's CompletedOfflineStage
    role: everything needed to sign any message with one add)."""

    party_index: int  # 1-based position inside the quorum
    r: Scalar  # R.x mod q, shared
    R: Point
    k_i: Scalar
    sigma_i: Scalar  # additive share of k*x
    public_key: Point

    # PartialSignature equivalent
    def partial_sig(self, message: Scalar) -> Scalar:
        return message * self.k_i + self.r * self.sigma_i


@dataclass
class PartialSignature:
    value: Scalar


def message_scalar(message: bytes) -> Scalar:
    return Scalar.from_int(int.from_bytes(hashlib.sha256(message).digest(), "big"))


def _mta(ek_a, dk_a, a: Scalar, b: Scalar) -> tuple[Scalar, Scalar]:
    """One MtA exchange: Alice holds a (and the Paillier key), Bob holds b.
    Returns additive shares (alpha for Alice, beta for Bob) of a*b mod q."""
    enc_a = paillier.encrypt(ek_a, a.to_int())
    # Bob: Enc(a)*b + Enc(beta_prim); beta_prim stat-hides a*b (< q^2 << n/2)
    beta_prim = secrets.randbelow(ek_a.n >> 1)
    c = paillier.add(
        ek_a,
        paillier.mul(ek_a, enc_a, b.to_int()),
        paillier.encrypt(ek_a, beta_prim),
    )
    alpha = Scalar.from_int(paillier.decrypt(dk_a, ek_a, c))
    beta = Scalar.from_int(-beta_prim)
    return alpha, beta


def simulate_offline_stage(
    local_keys: Sequence[LocalKey], s_l: Sequence[int]
) -> List[CompletedOfflineStage]:
    """Run the offline stage for quorum `s_l` (1-based key indices, as in
    the reference's OfflineStage::new, `/root/reference/src/test.rs:343-352`)."""
    quorum = [local_keys[i - 1] for i in s_l]
    m = len(quorum)
    if m < quorum[0].t + 1:
        raise ValueError("quorum smaller than threshold+1")

    # additive reshare: w_i = lambda_i * x_i over 0-based indices s_l-1
    zero_based = [i - 1 for i in s_l]
    params = vss.ShamirSecretSharing(quorum[0].t, quorum[0].n)
    w = [
        vss.map_share_to_new_params(params, zero_based[j], zero_based)
        * quorum[j].keys_linear.x_i
        for j in range(m)
    ]

    k = [Scalar.random() for _ in range(m)]
    gamma = [Scalar.random() for _ in range(m)]

    # delta_i / sigma_i accumulate own product + MtA cross-term shares
    delta = [k[i] * gamma[i] for i in range(m)]
    sigma = [k[i] * w[i] for i in range(m)]
    for i in range(m):
        for j in range(m):
            if i == j:
                continue
            ek_i = quorum[i].paillier_key_vec[quorum[i].i - 1]
            dk_i = quorum[i].paillier_dk
            alpha, beta = _mta(ek_i, dk_i, k[i], gamma[j])
            delta[i] = delta[i] + alpha
            delta[j] = delta[j] + beta
            mu, nu = _mta(ek_i, dk_i, k[i], w[j])  # MtAwc in GG20
            sigma[i] = sigma[i] + mu
            sigma[j] = sigma[j] + nu

    delta_sum = Scalar.zero()
    for d in delta:
        delta_sum = delta_sum + d

    Gamma = Point.identity()
    for g in gamma:
        Gamma = Gamma + GENERATOR * g
    R = Gamma * delta_sum.invert()
    r = Scalar.from_int(R.x_coord())

    return [
        CompletedOfflineStage(
            party_index=i + 1,
            r=r,
            R=R,
            k_i=k[i],
            sigma_i=sigma[i],
            public_key=quorum[i].y_sum_s,
        )
        for i in range(m)
    ]


class SignManual:
    """Mirror of the reference's SignManual two-step API
    (`/root/reference/src/test.rs:357-382`): construct with the message to
    get a partial signature, then `complete` with the others' partials."""

    def __init__(self, message: Scalar, offline: CompletedOfflineStage):
        self.message = message
        self.offline = offline
        self.local_sig = PartialSignature(value=offline.partial_sig(message))

    def complete(self, others: Sequence[PartialSignature]) -> tuple[Scalar, Scalar]:
        s = self.local_sig.value
        for p in others:
            s = s + p.value
        r = self.offline.r
        # low-s normalization, standard ECDSA malleability rule
        if s.to_int() > CURVE_ORDER // 2:
            s = Scalar.from_int(CURVE_ORDER - s.to_int())
        if not r or not s:
            raise ValueError("degenerate signature")
        return r, s


def ecdsa_verify(signature: tuple[Scalar, Scalar], public_key: Point, message: Scalar) -> bool:
    """Vanilla ECDSA verification (the reference delegates to
    gg_2020::party_i::verify, `/root/reference/src/test.rs:381`)."""
    r, s = signature
    if not r or not s:
        return False
    s_inv = s.invert()
    u1 = message * s_inv
    u2 = r * s_inv
    point = GENERATOR * u1 + public_key * u2
    if point == Point.identity():
        return False
    return Scalar.from_int(point.x_coord()).v == r.v


def simulate_signing(offline: Sequence[CompletedOfflineStage], message: bytes) -> None:
    """Every quorum member completes the signature from the others'
    partials; all results must verify (reference `src/test.rs:357-382`)."""
    msg = message_scalar(message)
    pk = offline[0].public_key
    parties = [SignManual(msg, o) for o in offline]
    partials = [p.local_sig for p in parties]
    for i, p in enumerate(parties):
        others = partials[:i] + partials[i + 1 :]
        sig = p.complete(others)
        assert ecdsa_verify(sig, pk, msg), "threshold signature failed to verify"
