"""Join (add/replace party) protocol messages.

Equivalent of the reference's `JoinMessage`
(`/root/reference/src/add_party_message.rs`): a new party broadcasts its
Paillier key + correctness proof + dlog statement/proofs + ring-Pedersen
parameters, is assigned an index out-of-band, and derives its first
LocalKey from the refresh broadcast.

Reference behavior preserved deliberately (SURVEY.md §3.4): the joining
party does NOT verify the O(n^2) PDL/range proofs — only ring-Pedersen and
structure checks — trusting the ciphertext column addressed to it.
Missing-slot fillers (quirk 3) are made deterministic: absent Paillier
slots become zero keys as in the reference, but absent dlog slots raise
instead of generating random garbage.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

from ..config import ProtocolConfig, DEFAULT_CONFIG
from ..core import paillier, vss
from ..core.paillier import EncryptionKey
from ..core.secp256k1 import GENERATOR, Scalar
from ..errors import (
    BroadcastedPublicKeyError,
    NewPartyUnassignedIndexError,
    PublicShareValidationError,
    RingPedersenProofValidation,
)
from ..backend import get_backend
from ..proofs.composite_dlog import CompositeDLogProof, DLogStatement
from ..proofs.correct_key import NiCorrectKeyProof
from ..proofs.ring_pedersen import RingPedersenProof, RingPedersenStatement
from .local_key import LocalKey, PaillierKeyPair, SharedKeys


@dataclass
class JoinMessage:
    """Field set mirrors `/root/reference/src/add_party_message.rs:36-45`."""

    ek: EncryptionKey
    dk_correctness_proof: NiCorrectKeyProof
    party_index: Optional[int]
    dlog_statement: DLogStatement
    composite_dlog_proof_base_h1: CompositeDLogProof
    composite_dlog_proof_base_h2: CompositeDLogProof
    ring_pedersen_statement: RingPedersenStatement
    ring_pedersen_proof: RingPedersenProof

    # ------------------------------------------------------------------
    @staticmethod
    def distribute(
        config: ProtocolConfig = DEFAULT_CONFIG,
    ) -> tuple["JoinMessage", PaillierKeyPair]:
        """New-party sender path (reference :101-124): three independent
        modulus generations (Paillier pair, h1/h2/N-tilde, ring-Pedersen)."""
        from .keygen import create_paillier_keypair, generate_dlog_statement_proofs

        pair = create_paillier_keypair(config)
        dlog_statement, proof_h1, proof_h2 = generate_dlog_statement_proofs(config)
        rp_statement, rp_witness = RingPedersenStatement.generate(config)
        rp_proof = RingPedersenProof.prove(
            rp_witness, rp_statement, config.m_security,
            hash_alg=config.hash_alg,
        )

        msg = JoinMessage(
            ek=pair.ek,
            dk_correctness_proof=NiCorrectKeyProof.proof(
                pair.dk, rounds=config.correct_key_rounds,
                hash_alg=config.hash_alg,
            ),
            party_index=None,
            dlog_statement=dlog_statement,
            composite_dlog_proof_base_h1=proof_h1,
            composite_dlog_proof_base_h2=proof_h2,
            ring_pedersen_statement=rp_statement,
            ring_pedersen_proof=rp_proof,
        )
        return msg, pair

    def set_party_index(self, new_party_index: int) -> None:
        self.party_index = new_party_index

    def get_party_index(self) -> int:
        if self.party_index is None:
            raise NewPartyUnassignedIndexError()
        return self.party_index

    # ------------------------------------------------------------------
    def collect(
        self,
        refresh_messages: Sequence,
        paillier_key: PaillierKeyPair,
        join_messages: Sequence["JoinMessage"],
        t: int,
        n: int,
        config: ProtocolConfig = DEFAULT_CONFIG,
    ) -> LocalKey:
        """New-party receiver path: derive the first LocalKey
        (reference :136-294)."""
        from .refresh import RefreshMessage

        backend = get_backend(config)
        RefreshMessage.validate_collect(refresh_messages, t, n, config)

        rp_items = [
            (m.ring_pedersen_proof, m.ring_pedersen_statement) for m in refresh_messages
        ] + [(j.ring_pedersen_proof, j.ring_pedersen_statement) for j in join_messages]
        rp_verdicts = backend.verify_ring_pedersen(rp_items, config.m_security)
        for k, msg in enumerate(refresh_messages):
            if not rp_verdicts[k]:
                raise RingPedersenProofValidation(party_index=msg.party_index)
        for k, join in enumerate(join_messages):
            if not rp_verdicts[len(refresh_messages) + k]:
                raise RingPedersenProofValidation(
                    party_index=join.party_index if join.party_index is not None else -1
                )

        party_index = self.get_party_index()
        for join in join_messages:
            join.get_party_index()

        parameters = vss.ShamirSecretSharing(threshold=t, share_count=n)
        cipher_sum, li_vec = RefreshMessage.get_ciphertext_sum(
            refresh_messages, party_index, parameters, paillier_key.ek
        )
        # same Lagrange-weight hardening as refresh collect: the
        # interpolated Feldman constant terms must re-derive the group
        # key every sender broadcast (all-equal gated below)
        if (
            RefreshMessage.interpolate_constant_term(refresh_messages, li_vec, t)
            != refresh_messages[0].public_key
        ):
            raise PublicShareValidationError()
        new_share = paillier.decrypt(paillier_key.dk, paillier_key.ek, cipher_sum)
        new_share_fe = Scalar.from_int(new_share)

        keys_linear = SharedKeys(x_i=new_share_fe, y=GENERATOR * new_share_fe)

        from .refresh import combine_committed_points

        pk_vec = combine_committed_points(
            refresh_messages, li_vec, t, n,
            use_device=config.device_ec,
        )

        # same consistency gate as refresh collect: the decrypted share must
        # match the committed public share
        if keys_linear.y != pk_vec[party_index - 1]:
            raise PublicShareValidationError()

        available_eks = {m.party_index: m.ek for m in refresh_messages}
        available_eks[party_index] = paillier_key.ek
        for join in join_messages:
            available_eks[join.get_party_index()] = join.ek

        available_dlog = {m.party_index: m.dlog_statement for m in refresh_messages}
        available_dlog[party_index] = self.dlog_statement
        for join in join_messages:
            available_dlog[join.get_party_index()] = join.dlog_statement

        # absent Paillier slots become zero keys, as in the reference
        # (:244-255); absent dlog slots raise instead of random garbage
        # (conscious fix of quirk 3)
        paillier_key_vec: List[EncryptionKey] = []
        h1_h2_n_tilde_vec: List[DLogStatement] = []
        for party in range(1, n + 1):
            paillier_key_vec.append(
                available_eks.get(party, EncryptionKey(n=0, nn=0))
            )
            if party not in available_dlog:
                raise NewPartyUnassignedIndexError()
            h1_h2_n_tilde_vec.append(available_dlog[party])

        # all senders must broadcast the same public key (reference :270-274)
        for msg in refresh_messages:
            if msg.public_key != refresh_messages[0].public_key:
                raise BroadcastedPublicKeyError()

        own_scheme, _ = vss.share(t, n, new_share_fe)

        return LocalKey(
            paillier_dk=paillier_key.dk,
            pk_vec=pk_vec,
            keys_linear=keys_linear,
            paillier_key_vec=paillier_key_vec,
            y_sum_s=refresh_messages[0].public_key,
            h1_h2_n_tilde_vec=h1_h2_n_tilde_vec,
            vss_scheme=own_scheme,
            i=party_index,
            t=t,
            n=n,
        )
