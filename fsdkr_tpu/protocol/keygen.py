"""Distributed key generation producing valid `LocalKey`s.

Equivalent of the reference's test-only GG20 keygen simulation
(`/root/reference/src/test.rs:226-236` driving `multi-party-ecdsa` Keygen
state machines through `round-based::Simulation`). Here the DKG rounds are
executed directly in-process (SURVEY.md §4 rebuild implication iv): each
party Feldman-shares a random u_i, x_i = sum of received shares, the group
key is y = (sum u_i) * G — exactly the algebra the GG20 keygen state
machines settle on, without the message-routing scaffolding.

Also provides `generate_h1_h2_n_tilde` / `generate_dlog_statement_proofs`,
the setup used by the join path (`/root/reference/src/add_party_message.rs:50-92`).
"""

from __future__ import annotations

import secrets
from typing import List

from ..config import ProtocolConfig, DEFAULT_CONFIG
from ..core import intops, paillier, primes, vss
from ..core.secp256k1 import GENERATOR, Point, Scalar
from ..proofs.composite_dlog import CompositeDLogProof, DLogStatement
from .local_key import LocalKey, PaillierKeyPair, SharedKeys


def generate_h1_h2_n_tilde(
    config: ProtocolConfig = DEFAULT_CONFIG,
) -> tuple[int, int, int, int, int]:
    """Fresh (N_tilde, h1, h2, xhi, xhi_inv) with h2 = h1^xhi and the
    returned exponents negated mod phi so that h2 = h1^{-xhi_ret}
    (reference `/root/reference/src/add_party_message.rs:50-66`)."""
    n_tilde, p, q = primes.gen_modulus(config.paillier_bits)
    phi = (p - 1) * (q - 1)
    h1 = intops.sample_unit(n_tilde)
    while True:
        xhi = secrets.randbelow(phi)
        xhi_inv = intops.mod_inv(xhi, phi)
        if xhi_inv is not None:
            break
    h2 = intops.mod_pow(h1, xhi, n_tilde)
    return n_tilde, h1, h2, phi - xhi, phi - xhi_inv


def generate_dlog_statement_proofs(
    config: ProtocolConfig = DEFAULT_CONFIG,
) -> tuple[DLogStatement, CompositeDLogProof, CompositeDLogProof]:
    """DLogStatement + composite-dlog proofs in both base directions
    (reference `/root/reference/src/add_party_message.rs:69-92`)."""
    n_tilde, h1, h2, xhi, xhi_inv = generate_h1_h2_n_tilde(config)
    st_h1 = DLogStatement(N=n_tilde, g=h1, ni=h2)
    st_h2 = DLogStatement(N=n_tilde, g=h2, ni=h1)
    return (
        st_h1,
        CompositeDLogProof.prove(st_h1, xhi, config.hash_alg),
        CompositeDLogProof.prove(st_h2, xhi_inv, config.hash_alg),
    )


def create_paillier_keypair(config: ProtocolConfig = DEFAULT_CONFIG) -> PaillierKeyPair:
    ek, dk = paillier.keygen(config.paillier_bits)
    return PaillierKeyPair(ek=ek, dk=dk)


def simulate_keygen(
    t: int, n: int, config: ProtocolConfig = DEFAULT_CONFIG
) -> List[LocalKey]:
    """Run an in-process (t, n) DKG; returns one LocalKey per party."""
    if not (0 < t < n):
        raise ValueError("need 0 < t < n")

    # round 1-2: every party shares a random u_j
    contributions = [vss.share(t, n, Scalar.random()) for _ in range(n)]
    y = Point.identity()
    for scheme, _ in contributions:
        y = y + scheme.commitments[0]

    # party i's share: x_i = sum_j f_j(i)
    x = []
    for i in range(n):
        acc = Scalar.zero()
        for _, shares in contributions:
            acc = acc + shares[i]
        x.append(acc)
    pk_vec = [GENERATOR * x_i for x_i in x]

    # per-party auxiliary setup: Paillier pair + h1/h2/N_tilde
    paillier_pairs = [paillier.keygen(config.paillier_bits) for _ in range(n)]
    dlog_statements = []
    for _ in range(n):
        n_tilde, h1, h2, _, _ = generate_h1_h2_n_tilde(config)
        dlog_statements.append(DLogStatement(N=n_tilde, g=h1, ni=h2))

    keys = []
    for i in range(n):
        ek_i, dk_i = paillier_pairs[i]
        own_scheme, _ = vss.share(t, n, x[i])
        keys.append(
            LocalKey(
                paillier_dk=dk_i,
                pk_vec=list(pk_vec),
                keys_linear=SharedKeys(x_i=x[i], y=GENERATOR * x[i]),
                paillier_key_vec=[pp[0] for pp in paillier_pairs],
                y_sum_s=y,
                h1_h2_n_tilde_vec=list(dlog_statements),
                vss_scheme=own_scheme,
                i=i + 1,
                t=t,
                n=n,
            )
        )
    return keys
