"""The refresh protocol: one broadcast message per party + local batch
verification.

Equivalent of the reference's `RefreshMessage`
(`/root/reference/src/refresh_message.rs`): `distribute` (:51-145),
`validate_collect` (:147-191), `get_ciphertext_sum` (:193-237),
`replace` (:239-319), `collect` (:321-467).

Deliberate deviations from the reference (SURVEY.md §5 quirks, each a
conscious fix, pinned by tests):
1. `collect` rebuilds pk_vec by assignment, not `Vec::insert` (quirk 1);
   a regression test pins len(pk_vec) == n afterwards.
2. `distribute` raises an error on t > new_n/2 instead of panicking
   (quirk 2).
3. The ring-Pedersen statement broadcast omits the secret phi (see
   fsdkr_tpu.proofs.ring_pedersen).
4. Verification is *batched*: all proof instances are gathered first, one
   batched verify per proof family runs (host or TPU backend), and
   failures are then attributed to parties in the reference's original
   loop order — same first-error semantics, batch execution.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..backend import get_backend
from ..config import ProtocolConfig, DEFAULT_CONFIG
from ..core import paillier, vss
from ..core.paillier import DecryptionKey, EncryptionKey
from ..core.secp256k1 import GENERATOR, Point, Scalar
from ..errors import (
    BroadcastedPublicKeyError,
    ModuliTooSmall,
    NewPartyUnassignedIndexError,
    PaillierVerificationError,
    PartiesThresholdViolation,
    PDLwSlackProofError,
    PublicShareValidationError,
    RangeProofError,
    RingPedersenProofError,
    SizeMismatchError,
    DLogProofValidation,
)
from ..proofs.alice_range import AliceProof
from ..proofs.composite_dlog import DLogStatement
from ..proofs.correct_key import NiCorrectKeyProof
from ..proofs.pdl_slack import PDLwSlackProof, PDLwSlackStatement, PDLwSlackWitness
from ..proofs.ring_pedersen import RingPedersenProof, RingPedersenStatement
from .local_key import LocalKey


@dataclass
class RefreshMessage:
    """The broadcast message; field set mirrors
    `/root/reference/src/refresh_message.rs:31-48` ("everything here can be
    broadcasted")."""

    old_party_index: int
    party_index: int
    pdl_proof_vec: List[PDLwSlackProof]
    range_proofs: List[AliceProof]
    coefficients_committed_vec: vss.VerifiableSS
    points_committed_vec: List[Point]
    points_encrypted_vec: List[int]
    dk_correctness_proof: NiCorrectKeyProof
    dlog_statement: DLogStatement
    ek: EncryptionKey
    remove_party_indices: List[int]
    public_key: Point
    ring_pedersen_statement: RingPedersenStatement
    ring_pedersen_proof: RingPedersenProof

    # ------------------------------------------------------------------
    @staticmethod
    def distribute(
        old_party_index: int,
        local_key: LocalKey,
        new_n: int,
        config: ProtocolConfig = DEFAULT_CONFIG,
    ) -> Tuple["RefreshMessage", DecryptionKey]:
        """Sender path (reference :51-145). Mutates local_key.vss_scheme.

        Returns the broadcast message and the *new* Paillier decryption key,
        which the caller feeds back into `collect`.
        """
        return RefreshMessage.distribute_batch(
            [(old_party_index, local_key)], new_n, config
        )[0]

    @staticmethod
    def distribute_batch(
        senders: Sequence[Tuple[int, LocalKey]],
        new_n: int,
        config: ProtocolConfig = DEFAULT_CONFIG,
    ) -> List[Tuple["RefreshMessage", DecryptionKey]]:
        """All senders' paths as fused cross-party batches.

        The reference runs each sender's fan-out serially (one
        `distribute` per party); here the per-receiver columns of every
        sender concatenate into ONE launch per proof family, widening each
        batch by the sender count — the cross-sender batch axis of
        SURVEY.md §1. `distribute` is the single-sender special case.
        Mutates each local_key.vss_scheme.
        """
        from ..utils.trace import phase

        # the root prover span: every distribute.* phase (and the engine
        # tile spans they fan out) nests under it in the trace timeline
        with phase(
            "distribute",
            items=len(senders) * new_n,
            senders=len(senders),
            new_n=new_n,
        ):
            return RefreshMessage._distribute_batch_impl(
                senders, new_n, config
            )

    @staticmethod
    def _distribute_batch_impl(
        senders: Sequence[Tuple[int, LocalKey]],
        new_n: int,
        config: ProtocolConfig = DEFAULT_CONFIG,
    ) -> List[Tuple["RefreshMessage", DecryptionKey]]:
        from ..backend.powm import get_batch_powm
        from .. import precompute

        powm = get_batch_powm(config)
        # FSDKR_PRECOMPUTE (fsdkr_tpu/precompute): consume-or-compute at
        # every phase boundary below — pooled rows take their offline-
        # produced values (bit-identical to inline sampling+compute),
        # dry rows fall back to the inline columns of that same phase
        pre_on = precompute.enabled()
        if pre_on:
            # this epoch is about to drain its pools: suspend the
            # committee's targets so a mid-epoch producer kick cannot
            # refill pools whose keys rotate at the end of this call
            # (re-registered for the next epoch below)
            owner = precompute.current_registration_owner()
            if owner is None:
                owner = precompute.committee_owner(
                    senders[0][1].h1_h2_n_tilde_vec[:new_n]
                )
            precompute.suspend_targets(owner)

        # validate every sender BEFORE the first mutation: a late failure
        # must not leave earlier senders' vss_scheme replaced by schemes
        # whose shares were never broadcast
        for _, local_key in senders:
            t = local_key.t
            if t > new_n // 2:
                raise PartiesThresholdViolation(threshold=t, refreshed_keys=new_n)
            if new_n <= t:
                raise NewPartyUnassignedIndexError()

        per = []  # per-sender working state, in input order
        for old_party_index, local_key in senders:
            coeffs, secret_shares = vss.sample_poly(
                local_key.t, new_n, local_key.keys_linear.x_i
            )
            receiver_eks = [local_key.paillier_key_vec[i] for i in range(new_n)]
            randomness_vec = []
            rn_vec = []  # pooled r^n mod n^2 per receiver (None -> inline)
            for ek_i in receiver_eks:
                ent = precompute.take("enc", ek_i.n) if pre_on else None
                if ent is None:
                    randomness_vec.append(paillier.sample_randomness(ek_i))
                    rn_vec.append(None)
                else:
                    randomness_vec.append(ent[0])
                    rn_vec.append(ent[1])
            per.append(
                dict(
                    old_i=old_party_index,
                    key=local_key,
                    coeffs=coeffs,
                    shares=secret_shares,
                    eks=receiver_eks,
                    rand=randomness_vec,
                    rn=rn_vec,
                )
            )

        # Feldman coefficient commitments A_k = a_k * G, all senders in one
        # device launch on the TPU backend (t+1 host ladders per sender
        # otherwise — ~66 s at n=256)
        if config.device_ec:
            from ..ops.ec_batch import batch_generator_mul

            flat_coeff_points = batch_generator_mul(
                [c.to_int() for p in per for c in p["coeffs"]]
            )
            pos = 0
            for p in per:
                cnt = len(p["coeffs"])
                commitments = flat_coeff_points[pos : pos + cnt]
                pos += cnt
                p["scheme"] = vss.VerifiableSS(
                    vss.ShamirSecretSharing(p["key"].t, new_n), commitments
                )
        else:
            for p in per:
                p["scheme"] = vss.VerifiableSS(
                    vss.ShamirSecretSharing(p["key"].t, new_n),
                    [GENERATOR * c for c in p["coeffs"]],
                )
        for p in per:
            del p["coeffs"]  # polynomial coefficients are secret round state
            p["key"].vss_scheme = p["scheme"]

        from ..utils.trace import phase

        # flattened share ints, reused by the commit-point launch and the
        # encryption column below (built once; holds secret material)
        flat_share_ints = [s.to_int() for p in per for s in p["shares"]]

        # commit points S_i = sigma_i * G (reference :67-69): one batched
        # device launch across all (sender, receiver) pairs on the TPU
        # backend — the host ladder costs ~2 ms/point, which at n=256 is
        # ~130 s of serial prover work
        with phase("distribute.commit_points", items=len(flat_share_ints)):
            if config.device_ec:
                from ..ops.ec_batch import batch_generator_mul

                flat_points = batch_generator_mul(flat_share_ints)
                for k, p in enumerate(per):
                    p["points"] = flat_points[k * new_n : (k + 1) * new_n]
            else:
                for p in per:
                    p["points"] = [GENERATOR * s for s in p["shares"]]

        # FSDKR_DELEGATE: attach the 2G2T-style MSM-delegation
        # certificate to each sender's VSS scheme (proofs.msm_delegate)
        # — one fixed-base generator mul per sender, broadcast-public,
        # checked by receivers instead of the per-share Horner MSMs.
        from ..proofs import msm_delegate

        if msm_delegate.delegate_enabled():
            with phase("distribute.delegate_certs", items=len(per)):
                for p in per:
                    msm_delegate.emit_cert(
                        p["scheme"], p["shares"], p["points"],
                        config.hash_alg,
                    )

        # ---- fully fused prover columns over all (sender, receiver)
        # pairs: the encryption column and BOTH proof families' stage-1
        # commitment columns share launches by exponent width (the
        # encryption r^n and the two beta^n columns are one 2048-bit
        # launch; x/a, rho, alpha, gamma columns pair up likewise), then
        # both families' r^e response columns share the stage-2 launch.
        # A launch is priced by its sequential modexp depth, so halving
        # the launch count at fixed width ~halves prover latency when
        # batches underfeed the chip.
        from ..backend.powm import powm_columns

        flat_rand = [r for p in per for r in p["rand"]]
        flat_rn = [x for p in per for x in p["rn"]]
        flat_nv = [ek.n for p in per for ek in p["eks"]]
        flat_nnv = [ek.nn for p in per for ek in p["eks"]]
        flat_h1 = [p["key"].h1_h2_n_tilde_vec[i].g for p in per for i in range(new_n)]
        flat_h2 = [p["key"].h1_h2_n_tilde_vec[i].ni for p in per for i in range(new_n)]
        flat_nt = [p["key"].h1_h2_n_tilde_vec[i].N for p in per for i in range(new_n)]
        flat_witnesses = [
            PDLwSlackWitness(x=s, r=r)
            for p in per
            for s, r in zip(p["shares"], p["rand"])
        ]

        with phase("distribute.prove_stage1", items=len(flat_rand)):
            # sub-phase traces (BENCH_r06 put this whole block at 20.5 s
            # with no internal split): nonce sampling, the Paillier
            # r^n/beta^n wall, and the mod-N~ commitment columns are
            # separately attributable. Both provers return their
            # Paillier beta^n column LAST (documented contract of
            # prove_stage1/generate_stage1), so the full-width public-
            # exponent columns (enc r^n + both beta^n — one width class)
            # stay fused in one launch set, and the h1/h2 joint columns
            # keep their cross-family comb groups in the other. Under
            # FSDKR_PRECOMPUTE the pooled rows vanish from both launch
            # sets (their powers were produced offline); only the
            # witness factor h1^x — one column, shared by both families
            # via powm_columns dedup — plus any dry-pool fallback rows
            # remain on the online critical path.
            pooled_pdl = pooled_alice = None
            with phase("distribute.stage1.sample", items=len(flat_rand)):
                if pre_on:
                    envs = list(zip(flat_h1, flat_h2, flat_nt, flat_nv))
                    pooled_pdl = [precompute.take("pdl", e) for e in envs]
                    pooled_alice = [precompute.take("alice", e) for e in envs]
                pdl_state, pdl_cols = PDLwSlackProof.prove_stage1(
                    flat_witnesses, flat_h1, flat_h2, flat_nt, flat_nv,
                    flat_nnv, hash_alg=config.hash_alg, pooled=pooled_pdl,
                )
                alice_state, alice_cols = AliceProof.generate_stage1(
                    flat_share_ints, flat_rand, flat_h1, flat_h2, flat_nt,
                    flat_nv, flat_nnv, hash_alg=config.hash_alg,
                    pooled=pooled_alice,
                )
            # encryption column r^n mod n^2: only rows without a pooled
            # randomizer power
            enc_fb = [i for i, x in enumerate(flat_rn) if x is None]
            enc_col = (
                [flat_rand[i] for i in enc_fb],
                [flat_nv[i] for i in enc_fb],
                [flat_nnv[i] for i in enc_fb],
            )
            with phase(
                "distribute.stage1.enc_beta_pow",
                items=len(enc_col[0])
                + len(pdl_cols[-1][0]) + len(alice_cols[-1][0]),
            ):
                res_pail = powm_columns(
                    powm, enc_col, pdl_cols[-1], alice_cols[-1]
                )
            with phase(
                "distribute.stage1.commit_pow",
                items=sum(
                    len(c[0]) for c in pdl_cols[:-1] + alice_cols[:-1]
                ),
            ):
                res_commit = powm_columns(
                    powm, *pdl_cols[:-1], *alice_cols[:-1]
                )
            n_pdl = len(pdl_cols)
            pdl_res1 = res_commit[: n_pdl - 1] + [res_pail[1]]
            alice_res1 = res_commit[n_pdl - 1 :] + [res_pail[2]]
            rn_full = list(flat_rn)
            for j, i in enumerate(enc_fb):
                rn_full[i] = res_pail[0][j]

        # ciphertexts from the fused encryption column (randomness is
        # unit-sampled above — inline or by the pool producer, the
        # guarantee encrypt_with_randomness_batch enforces); own phase:
        # ~n^2 host bigint multiplies at scale
        with phase("distribute.encrypt", items=len(flat_share_ints)):
            flat_enc = paillier.combine_with_rn(
                flat_share_ints, rn_full, flat_nv, flat_nnv
            )
        # (the share ints also live on as alice_state["avals"] until the
        # proofs are assembled — same round-state lifetime as the nonces)
        del flat_share_ints
        for k, p in enumerate(per):
            p["enc"] = flat_enc[k * new_n : (k + 1) * new_n]

        flat_statements = [
            PDLwSlackStatement(
                ciphertext=p["enc"][i],
                ek=p["eks"][i],
                Q=p["points"][i],
                G=GENERATOR,
                h1=p["key"].h1_h2_n_tilde_vec[i].g,
                h2=p["key"].h1_h2_n_tilde_vec[i].ni,
                N_tilde=p["key"].h1_h2_n_tilde_vec[i].N,
            )
            for p in per
            for i in range(new_n)
        ]

        with phase("distribute.prove_stage2", items=len(flat_rand)):
            pdl_state, pdl_cols2 = PDLwSlackProof.prove_stage2(
                pdl_state, pdl_res1, flat_statements,
                device_ec=config.device_ec,
            )
            alice_state, alice_cols2 = AliceProof.generate_stage2(
                alice_state, alice_res1, flat_enc
            )
            res2 = powm_columns(powm, *pdl_cols2, *alice_cols2)
            flat_pdl = PDLwSlackProof.prove_finish(
                pdl_state, res2[: len(pdl_cols2)]
            )
            flat_range = AliceProof.generate_finish(
                alice_state, res2[len(pdl_cols2) :]
            )

        # ---- per-sender key material: consume pooled bundles first
        # (complete offline-produced ek/dk + correct-key proof + ring-
        # Pedersen statement+proof — every part a function of the fresh
        # key alone), then batch the remainder inline — batched prime
        # pipeline (candidate windows through the FSDKR_THREADS-parallel
        # Miller-Rabin batch) and fused correct-key / ring-Pedersen
        # prover columns (secret-CRT engine under FSDKR_CRT)
        key_bundles: list = []
        if pre_on:
            kp = config.key_material_pool_key
            for _ in per:
                b = precompute.take("keys", kp)
                if b is None:
                    break  # dry: the remaining senders compute inline
                key_bundles.append(b)
        # phase item counts follow the stage-1 convention: only the
        # inline-computed rows are this phase's work (pooled bundles
        # cost a pop, not a keygen)
        miss = len(per) - len(key_bundles)
        with phase("distribute.keygen", items=miss):
            ek_dk_inline = (
                paillier.keygen_batch(config.paillier_bits, miss)
                if miss else []
            )
        with phase("distribute.ring_pedersen_gen", items=miss):
            rp_inline = (
                RingPedersenStatement.generate_batch(miss, config)
                if miss else []
            )
        with phase("distribute.correct_key_prove", items=miss):
            ck_inline = (
                NiCorrectKeyProof.proof_batch(
                    [dk for _, dk in ek_dk_inline],
                    rounds=config.correct_key_rounds,
                    powm=powm, hash_alg=config.hash_alg,
                )
                if miss else []
            )
        with phase("distribute.ring_pedersen_prove", items=miss):
            rp_proofs_inline = (
                RingPedersenProof.prove_batch(
                    [w for _, w in rp_inline], [st for st, _ in rp_inline],
                    config.m_security, powm, config.hash_alg,
                )
                if miss else []
            )
        # merged per-sender views: pooled bundles fill the first slots
        # (take order), inline results the rest — deterministic, so the
        # seeded-parity arms assign identical material to each sender
        ek_dk = [(b[0], b[1]) for b in key_bundles] + ek_dk_inline
        ck_proofs = [b[2] for b in key_bundles] + ck_inline
        rp_statements = (
            [b[3] for b in key_bundles] + [st for st, _ in rp_inline]
        )
        rp_proofs = [b[4] for b in key_bundles] + rp_proofs_inline

        out = []
        for k, p in enumerate(per):
            local_key = p["key"]
            msg = RefreshMessage(
                old_party_index=p["old_i"],
                party_index=local_key.i,
                pdl_proof_vec=flat_pdl[k * new_n : (k + 1) * new_n],
                range_proofs=flat_range[k * new_n : (k + 1) * new_n],
                coefficients_committed_vec=p["scheme"],
                points_committed_vec=p["points"],
                points_encrypted_vec=p["enc"],
                dk_correctness_proof=ck_proofs[k],
                dlog_statement=local_key.h1_h2_n_tilde_vec[local_key.i - 1],
                ek=ek_dk[k][0],
                remove_party_indices=[],
                public_key=local_key.y_sum_s,
                ring_pedersen_statement=rp_statements[k],
                ring_pedersen_proof=rp_proofs[k],
            )
            out.append((msg, ek_dk[k][1]))

        # ---- steady-state refill targets: next epoch's demand is what
        # this call consumed, keyed by the NEXT epoch's receiver moduli —
        # collect() installs each sender's fresh ek into
        # paillier_key_vec, so the Paillier-width pools must be produced
        # against the keys just generated (the mod-N~ environments are
        # stable across refreshes). The background producer then fills
        # during idle time / overlapped with collect().
        if pre_on:
            next_eks = list(senders[0][1].paillier_key_vec[:new_n])
            for k, p in enumerate(per):
                idx = p["key"].i
                if 1 <= idx <= new_n:
                    next_eks[idx - 1] = ek_dk[k][0]
            targets = []
            for i in range(new_n):
                d = senders[0][1].h1_h2_n_tilde_vec[i]
                env = (d.g, d.ni, d.N, next_eks[i].n)
                targets += [
                    ("enc", next_eks[i].n, len(per)),
                    ("pdl", env, len(per)),
                    ("alice", env, len(per)),
                ]
            # owner tag (ISSUE 9 / ROADMAP 5a): the per-receiver targets
            # belong to THIS committee (`owner` from the top of this
            # call: the serving layer's explicit scope, or the stable
            # mod-N~ environment fingerprint) — so a churn (join/replace/
            # remove) can invalidate them explicitly instead of leaving
            # stale-keyed secret pools to age out. REPLACE semantics wipe
            # whatever the drained epoch left behind; the config-keyed
            # key-material pool is shared by every committee and
            # registered under the fleet owner instead.
            precompute.replace_targets(targets, owner=owner)
            precompute.register_targets(
                [("keys", config.key_material_pool_key, len(per))],
                owner=precompute.producer.KEYS_POOL_OWNER,
            )
            precompute.kick()
        return out

    # ------------------------------------------------------------------
    @staticmethod
    def validate_collect(
        refresh_messages: Sequence["RefreshMessage"],
        t: int,
        n: int,
        config: ProtocolConfig = DEFAULT_CONFIG,
    ) -> None:
        """Structure checks + batched Feldman validation (reference :147-191)."""
        if len(refresh_messages) <= t:
            raise PartiesThresholdViolation(
                threshold=t, refreshed_keys=len(refresh_messages)
            )

        # every per-receiver vector must cover the full new committee; the
        # reference only compares against messages[0]'s length
        # (src/refresh_message.rs:157-175), which can crash the Feldman loop
        # below or misattribute blame — we check against n directly
        for k, msg in enumerate(refresh_messages):
            lens = (
                len(msg.pdl_proof_vec),
                len(msg.points_committed_vec),
                len(msg.points_encrypted_vec),
            )
            if any(l != n for l in lens) or len(msg.range_proofs) != n:
                raise SizeMismatchError(k, *lens)

        backend = get_backend(config)
        items = [
            (msg.coefficients_committed_vec, msg.points_committed_vec[i], i + 1)
            for msg in refresh_messages
            for i in range(n)
        ]
        if not all(_feldman_streamed(backend, items)):
            raise PublicShareValidationError()

    # ------------------------------------------------------------------
    @staticmethod
    def get_ciphertext_sum(
        refresh_messages: Sequence["RefreshMessage"],
        party_index: int,
        parameters: vss.ShamirSecretSharing,
        ek: EncryptionKey,
    ) -> Tuple[int, List[Scalar]]:
        """Homomorphic Lagrange combination of the first t+1 senders'
        ciphertext columns addressed to `party_index` — the "one
        decryption" optimization (reference :193-237)."""
        t = parameters.threshold
        ciphertexts = [
            msg.points_encrypted_vec[party_index - 1] for msg in refresh_messages
        ]
        indices = [msg.old_party_index - 1 for msg in refresh_messages[: t + 1]]
        li_vec = [
            vss.map_share_to_new_params(parameters, indices[i], indices)
            for i in range(t + 1)
        ]
        acc = paillier.encrypt(ek, 0)
        for i in range(t + 1):
            acc = paillier.add(ek, acc, paillier.mul(ek, ciphertexts[i], li_vec[i].to_int()))
        return acc, li_vec

    # ------------------------------------------------------------------
    @staticmethod
    def interpolate_constant_term(
        refresh_messages: Sequence["RefreshMessage"],
        li_vec: Sequence[Scalar],
        t: int,
    ) -> Point:
        """sum_j lambda_j * A_0^{(j)} over the first t+1 senders' Feldman
        constant-term commitments. Each A_0^{(j)} commits to sender j's
        OLD share x_j, so with honest Lagrange weights this re-derives
        the (unchanged) group public key — the hardening gate both
        collect paths compare against y (reference quirk 4 / TODO at
        src/refresh_message.rs:199 leaves the broadcast old_party_index
        untrusted-but-unchecked)."""
        acc = refresh_messages[0].coefficients_committed_vec.commitments[0] * li_vec[0]
        for j in range(1, t + 1):
            acc = acc + (
                refresh_messages[j].coefficients_committed_vec.commitments[0]
                * li_vec[j]
            )
        return acc

    # ------------------------------------------------------------------
    @staticmethod
    def replace(
        new_parties: Sequence["JoinMessage"],
        key: LocalKey,
        old_to_new_map: Dict[int, int],
        new_n: int,
        config: ProtocolConfig = DEFAULT_CONFIG,
    ) -> Tuple["RefreshMessage", DecryptionKey]:
        """State surgery for index remapping + joins, then an ordinary
        distribute (reference :239-319)."""
        # churn invalidation (ROADMAP 5a): the pools registered at the end
        # of the last epoch's distribute are keyed by the pre-churn
        # committee layout (receiver moduli + mod-N~ environments); the
        # surgery below changes that layout, so those entries can never be
        # consumed again — wipe them NOW instead of letting single-use
        # secrets age out through the target TTL
        from .. import precompute

        if precompute.enabled():
            precompute.invalidate_owner(
                precompute.committee_owner(key.h1_h2_n_tilde_vec)
            )
        size = max(new_n, len(key.paillier_key_vec))
        new_ek_vec: List[Optional[EncryptionKey]] = [None] * size
        new_dlog_vec: List[Optional[DLogStatement]] = [None] * size

        for old_idx, new_idx in old_to_new_map.items():
            new_ek_vec[new_idx - 1] = key.paillier_key_vec[old_idx - 1]
            new_dlog_vec[new_idx - 1] = key.h1_h2_n_tilde_vec[old_idx - 1]

        for join in new_parties:
            idx = join.get_party_index()
            new_ek_vec[idx - 1] = join.ek
            new_dlog_vec[idx - 1] = join.dlog_statement

        # slots not covered by the map or a join keep their old entry
        # (mirrors the reference's in-place writes)
        for slot in range(size):
            if new_ek_vec[slot] is None and slot < len(key.paillier_key_vec):
                new_ek_vec[slot] = key.paillier_key_vec[slot]
                new_dlog_vec[slot] = key.h1_h2_n_tilde_vec[slot]
        if any(v is None for v in new_ek_vec[:new_n]):
            raise NewPartyUnassignedIndexError()

        key.paillier_key_vec = list(new_ek_vec[:new_n])
        key.h1_h2_n_tilde_vec = list(new_dlog_vec[:new_n])

        old_party_index = key.i
        key.i = old_to_new_map[key.i]
        key.n = new_n

        return RefreshMessage.distribute(old_party_index, key, new_n, config)

    # ------------------------------------------------------------------
    @staticmethod
    def collect(
        refresh_messages: Sequence["RefreshMessage"],
        local_key: LocalKey,
        new_dk: DecryptionKey,
        join_messages: Sequence["JoinMessage"] = (),
        config: ProtocolConfig = DEFAULT_CONFIG,
    ) -> None:
        """Receiver path — the north-star O(n^2) verification loop,
        executed as per-family batches (reference :321-467)."""
        err = RefreshMessage.collect_sessions(
            [(refresh_messages, local_key, new_dk, tuple(join_messages))], config
        )[0]
        if err is not None:
            raise err

    @staticmethod
    def collect_stream(
        local_key: LocalKey,
        new_dk: DecryptionKey,
        expected_senders: Optional[Sequence[int]] = None,
        join_messages: Sequence["JoinMessage"] = (),
        config: ProtocolConfig = DEFAULT_CONFIG,
    ) -> "StreamingCollect":
        """Streaming counterpart of `collect` (ISSUE 9): returns a
        StreamingCollect session that verifies broadcast messages
        incrementally as they are `offer`ed — cheap structural checks and
        the per-message proof families eagerly, the pair-family RLC fold
        at quorum (`finalize()`). Verdicts, identifiable-abort blame, and
        LocalKey mutation are bit-identical to barrier `collect` on the
        same message set in `expected_senders` order (default: this
        committee's party indices 1..n). See protocol.streaming."""
        from .streaming import StreamingCollect

        return StreamingCollect(
            local_key, new_dk, expected_senders, join_messages, config
        )

    @staticmethod
    def collect_sessions(
        sessions: Sequence[
            Tuple[
                Sequence["RefreshMessage"],
                LocalKey,
                DecryptionKey,
                Sequence["JoinMessage"],
            ]
        ],
        config: ProtocolConfig = DEFAULT_CONFIG,
    ) -> List[Optional[Exception]]:
        """collect() for many INDEPENDENT refresh sessions with every
        verification family fused across sessions into one batch launch
        (the session-stacked layout of BASELINE.json config 5: 64 n=16
        sessions feed the same row axis one n=256 session would, and the
        rows shard over the configured mesh like any other batch).

        Per session the semantics are exactly `collect`'s: same check
        order, same identifiable-abort error types, same LocalKey
        mutation points. Returns one entry per session — None on success
        or the exception `collect` would have raised (a failing session
        never blocks the others).
        """
        from ..utils.trace import phase

        # the root verifier span; the collect.* family phases
        # (TracedVerifier) and their engine tiles nest under it
        with phase("collect", items=len(sessions), sessions=len(sessions)):
            return RefreshMessage._collect_sessions_impl(sessions, config)

    @staticmethod
    def _collect_sessions_impl(
        sessions,
        config: ProtocolConfig = DEFAULT_CONFIG,
    ) -> List[Optional[Exception]]:
        backend = get_backend(config)
        # idle-time pool refill (FSDKR_PRECOMPUTE): verification's
        # native/GMP launches release the GIL, so the background
        # producer's offline work genuinely overlaps this collect
        from .. import precompute

        precompute.kick()
        S = len(sessions)
        errors: List[Optional[Exception]] = [None] * S
        new_ns: List[int] = [0] * S

        def alive():
            return [s for s in range(S) if errors[s] is None]

        def fused_multi(call, lists, spans):
            return fused_isolated(call, lists, spans, errors)

        def fused(call, items, spans):
            """Single-list fused_isolated."""
            return fused_isolated(
                lambda lst: (call(lst),), (items,), spans, errors
            )[0]

        # ---- structure checks + fused Feldman validation --------------
        # (validate_collect semantics, reference :147-191)
        feld_items: list = []
        feld_spans: Dict[int, Tuple[int, int]] = {}
        for s, (msgs, key, _dk, joins) in enumerate(sessions):
            new_n = len(msgs) + len(joins)
            new_ns[s] = new_n
            try:
                check_structure(msgs, key, new_n)
            except Exception as e:
                errors[s] = e
                continue
            lo = len(feld_items)
            feld_items.extend(
                (msg.coefficients_committed_vec, msg.points_committed_vec[i], i + 1)
                for msg in msgs
                for i in range(new_n)
            )
            feld_spans[s] = (lo, len(feld_items))
        if feld_items:
            # the EC columns stream through the same bytes-budgeted tile
            # plan as the pair rows (backend.memplan): Feldman verdicts
            # are row-local (the per-scheme RLC combine falls back to
            # exact per-row checks on failure), so cutting the row axis
            # cannot change any verdict
            feld_verdicts = fused(
                lambda items: _feldman_streamed(backend, items),
                feld_items,
                feld_spans,
            )
            for s, (lo, hi) in feld_spans.items():
                if errors[s] is None and not all(feld_verdicts[lo:hi]):
                    errors[s] = PublicShareValidationError()

        # ---- gather the O(n^2) PDL + range instances, all sessions ----
        pdl_items: list = []
        range_items: list = []
        pair_spans: Dict[int, Tuple[int, int]] = {}
        for s in alive():
            msgs, key, _dk, _joins = sessions[s]
            new_n = new_ns[s]
            lo = len(pdl_items)
            for msg in msgs:
                for i in range(new_n):
                    st = PDLwSlackStatement(
                        ciphertext=msg.points_encrypted_vec[i],
                        ek=key.paillier_key_vec[i],
                        Q=msg.points_committed_vec[i],
                        G=GENERATOR,
                        h1=key.h1_h2_n_tilde_vec[i].g,
                        h2=key.h1_h2_n_tilde_vec[i].ni,
                        N_tilde=key.h1_h2_n_tilde_vec[i].N,
                    )
                    pdl_items.append((msg.pdl_proof_vec[i], st))
                    range_items.append(
                        (
                            msg.range_proofs[i],
                            msg.points_encrypted_vec[i],
                            key.paillier_key_vec[i],
                            key.h1_h2_n_tilde_vec[i],
                        )
                    )
            pair_spans[s] = (lo, len(pdl_items))

        if pdl_items:
            # both families share one fused launch set (verify_pairs).
            # The session->row-span map rides along so the fused call
            # can amortize across sessions (cross-session dedup +
            # session-first blame, tpu_verifier.verify_pairs) — but
            # ONLY on the full fused call: fused_isolated's per-session
            # retry slices are single-session, so spans would be stale
            # there (detected by length).
            def _pairs_call(p_slice, r_slice):
                if len(p_slice) == len(pdl_items):
                    return backend.verify_pairs(
                        p_slice, r_slice, session_spans=pair_spans
                    )
                return backend.verify_pairs(p_slice, r_slice)

            pdl_verdicts, range_verdicts = fused_multi(
                _pairs_call, (pdl_items, range_items), pair_spans
            )
            # attribution in the reference's loop order (msg outer, i
            # inner; PDL before range — src/refresh_message.rs:330-350)
            for s, (start, _hi) in pair_spans.items():
                if errors[s] is not None:
                    continue
                msgs, _key, _dk, _joins = sessions[s]
                try:
                    pair_blame(
                        msgs, new_ns[s], pdl_verdicts, range_verdicts, start
                    )
                except Exception as e:
                    errors[s] = e

        # ---- ring-Pedersen batches (reference :352-365) ---------------
        rp_items: list = []
        rp_spans: Dict[int, Tuple[int, int]] = {}
        for s in alive():
            msgs, _key, _dk, joins = sessions[s]
            lo = len(rp_items)
            rp_items += [
                (m.ring_pedersen_proof, m.ring_pedersen_statement) for m in msgs
            ] + [(j.ring_pedersen_proof, j.ring_pedersen_statement) for j in joins]
            rp_spans[s] = (lo, len(rp_items))
        if rp_items:
            rp_verdicts = fused(
                lambda items: backend.verify_ring_pedersen(items, config.m_security),
                rp_items,
                rp_spans,
            )
            for s, (lo, hi) in rp_spans.items():
                if errors[s] is None and not all(rp_verdicts[lo:hi]):
                    errors[s] = RingPedersenProofError()

        # ---- share recovery inputs (reference :367-373) ---------------
        from ..utils.trace import phase

        sums: Dict[int, tuple] = {}
        with phase("collect.share_recovery", items=len(alive())):
            for s in alive():
                msgs, key, _dk, _joins = sessions[s]
                try:
                    sums[s] = share_recovery_check(msgs, key)
                except Exception as e:
                    errors[s] = e

        # ---- Paillier correct-key + composite dlog, fused -------------
        ck_items: list = []
        ck_spans: Dict[int, Tuple[int, int]] = {}
        dlog_items: list = []
        dlog_spans: Dict[int, Tuple[int, int]] = {}
        for s in alive():
            msgs, _key, _dk, joins = sessions[s]
            ck_lo = len(ck_items)
            ck_items += [(m.dk_correctness_proof, m.ek) for m in msgs]
            ck_items += [(j.dk_correctness_proof, j.ek) for j in joins]
            ck_spans[s] = (ck_lo, len(ck_items))
            dlog_lo = len(dlog_items)
            for join in joins:
                inverse_st = DLogStatement(
                    N=join.dlog_statement.N,
                    g=join.dlog_statement.ni,
                    ni=join.dlog_statement.g,
                )
                dlog_items.append(
                    (join.composite_dlog_proof_base_h1, join.dlog_statement)
                )
                dlog_items.append((join.composite_dlog_proof_base_h2, inverse_st))
            dlog_spans[s] = (dlog_lo, len(dlog_items))
        ck_verdicts = (
            fused(
                lambda items: backend.verify_correct_key(
                    items, config.correct_key_rounds
                ),
                ck_items,
                ck_spans,
            )
            if ck_items
            else []
        )
        dlog_verdicts = (
            fused(backend.verify_composite_dlog, dlog_items, dlog_spans)
            if dlog_items
            else []
        )

        # ---- per-session adoption gates + key rotation ----------------
        # (mutating phase; order and mutation points match collect /
        # reference :375-467 — a failure mid-way leaves the same partial
        # paillier_key_vec updates the reference would)
        with phase("collect.adopt", items=len(alive())):
            for s in alive():
                msgs, local_key, new_dk, joins = sessions[s]
                ck0, ck1 = ck_spans[s]
                d0, d1 = dlog_spans[s]
                try:
                    adopt_session(
                        msgs, local_key, new_dk, joins,
                        ck_verdicts[ck0:ck1], dlog_verdicts[d0:d1],
                        sums[s], new_ns[s], config,
                    )
                except Exception as e:
                    errors[s] = e
        return errors


def _feldman_streamed(backend, items):
    """validate_feldman under the bytes-budgeted memory plan
    (backend.memplan.streamed_rows): tiles of the EC row axis verify one
    at a time, so the Feldman columns never hold the whole n^2 point set
    staged at once — the same discipline the pair rows get from
    `_verify_pairs_streamed`. Single-tile plans (and FSDKR_MEM_PLAN=0)
    call through unchanged."""
    from ..backend import memplan

    return memplan.streamed_rows(
        backend.validate_feldman, items, memplan.ec_row_bytes(), "feldman"
    )


def fused_isolated(call, lists, spans, errors):
    """Run one fused backend launch over parallel item lists (all
    sharing the same session spans); if a malformed session makes the
    whole batch raise (e.g. a crafted proof field the batch codec
    rejects), isolate per session so the bad session gets the error and
    the others still verify — the "a failing session never blocks the
    others" guarantee. `errors` is the per-session error slate (an entry
    set here makes later phases skip that session). Returns one verdict
    list per input list. Shared by the barrier (_collect_sessions_impl)
    and streaming (protocol.streaming.finalize_streams) paths."""
    try:
        return call(*lists)
    except Exception:
        outs = tuple([None] * len(lst) for lst in lists)
        for s, (lo, hi) in spans.items():
            if errors[s] is not None:
                continue
            try:
                res = call(*(lst[lo:hi] for lst in lists))
                for out, part in zip(outs, res):
                    out[lo:hi] = part
            except Exception as e:
                errors[s] = e  # rows stay None; phases skip s
        return outs


# ---------------------------------------------------------------------------
# Per-session collect stages, shared by the barrier path
# (_collect_sessions_impl) and the streaming path (protocol.streaming).
# Keeping check order, error construction, and mutation points in ONE set
# of functions is what makes streaming-vs-barrier verdict and
# identifiable-abort blame identity a structural property instead of a
# test-pinned coincidence (ISSUE 9 acceptance).


def check_structure(msgs: Sequence["RefreshMessage"], key: LocalKey, new_n: int) -> None:
    """Threshold + per-message wire-shape + broadcast-public-key gates
    (reference :147-191 plus the quirk-5 generalization), first error in
    message order."""
    if len(msgs) <= key.t:
        raise PartiesThresholdViolation(
            threshold=key.t, refreshed_keys=len(msgs)
        )
    for k, msg in enumerate(msgs):
        lens = (
            len(msg.pdl_proof_vec),
            len(msg.points_committed_vec),
            len(msg.points_encrypted_vec),
        )
        if any(l != new_n for l in lens) or len(msg.range_proofs) != new_n:
            raise SizeMismatchError(k, *lens)
        # the reference gates broadcast public_key only on the join path
        # (add_party_message.rs:268-274, quirk 5); here an existing party
        # knows the true group key, so gate every broadcast against it —
        # an inconsistent sender is caught by verifiers too, not just
        # joiners
        if msg.public_key != key.y_sum_s:
            raise BroadcastedPublicKeyError(msg.party_index)


def pair_blame(
    msgs: Sequence["RefreshMessage"],
    new_n: int,
    pdl_verdicts: Sequence,
    range_verdicts: Sequence,
    start: int = 0,
) -> None:
    """Attribute pair-loop failures in the reference's loop order (msg
    outer, i inner; PDL before range — src/refresh_message.rs:330-350).
    `start` is this session's first row in the fused verdict arrays."""
    row = start
    for msg in msgs:
        for i in range(new_n):
            if pdl_verdicts[row] is not None:
                raise PDLwSlackProofError(*pdl_verdicts[row])
            if not range_verdicts[row]:
                raise RangeProofError(party_index=i)
            row += 1


def share_recovery_check(
    msgs: Sequence["RefreshMessage"], key: LocalKey
) -> Tuple[EncryptionKey, int, List[Scalar]]:
    """Homomorphic share-recovery inputs + the constant-term Lagrange
    gate (reference :367-373 plus the quirk-4 hardening): the Lagrange
    weights must re-derive the unchanged group key, or a lying/
    duplicated old_party_index silently rotates the committee onto a
    DIFFERENT secret (see interpolate_constant_term)."""
    old_ek = key.paillier_key_vec[key.i - 1]
    cipher_sum, li_vec = RefreshMessage.get_ciphertext_sum(
        msgs, key.i, key.vss_scheme.parameters, old_ek
    )
    y_check = RefreshMessage.interpolate_constant_term(msgs, li_vec, key.t)
    if y_check != key.y_sum_s:
        raise PublicShareValidationError()
    return old_ek, cipher_sum, li_vec


def adopt_session(
    msgs: Sequence["RefreshMessage"],
    local_key: LocalKey,
    new_dk: DecryptionKey,
    joins: Sequence["JoinMessage"],
    ck_verdicts: Sequence[bool],
    dlog_verdicts: Sequence[bool],
    recovered: Tuple[EncryptionKey, int, List[Scalar]],
    new_n: int,
    config: ProtocolConfig,
) -> None:
    """The mutating adoption phase of one session (reference :375-467):
    correct-key/dlog verdict gates, moduli-size gates, paillier_key_vec
    installs, own-share decrypt + Feldman consistency gate, key rotation.
    `ck_verdicts` covers msgs then joins; `dlog_verdicts` two per join.
    A failure mid-way leaves the same partial paillier_key_vec updates
    the reference would."""
    for k, msg in enumerate(msgs):
        if not ck_verdicts[k]:
            raise PaillierVerificationError(party_index=msg.party_index)
        n_len = msg.ek.n.bit_length()
        if n_len > config.paillier_bits or n_len < config.paillier_bits - 1:
            raise ModuliTooSmall(
                party_index=msg.party_index, moduli_size=n_len
            )
        local_key.paillier_key_vec[msg.party_index - 1] = msg.ek

    for k, join in enumerate(joins):
        party_index = join.get_party_index()
        if not ck_verdicts[len(msgs) + k]:
            raise PaillierVerificationError(party_index=party_index)
        if not (dlog_verdicts[2 * k] and dlog_verdicts[2 * k + 1]):
            raise DLogProofValidation(party_index=party_index)
        n_len = join.ek.n.bit_length()
        if n_len > config.paillier_bits or n_len < config.paillier_bits - 1:
            raise ModuliTooSmall(
                party_index=party_index, moduli_size=n_len
            )
        local_key.paillier_key_vec[party_index - 1] = join.ek

    # ---- decrypt own new share; rotate key material -------------------
    old_ek, cipher_sum, li_vec = recovered
    new_share = paillier.decrypt(local_key.paillier_dk, old_ek, cipher_sum)
    new_share_fe = Scalar.from_int(new_share)

    # pk_vec rebuild by assignment — conscious fix of quirk 1
    # (reference :455-464 uses Vec::insert)
    pk_vec = combine_committed_points(
        msgs, li_vec, local_key.t, new_n, use_device=config.device_ec,
    )

    # consistency gate absent from the reference: the decrypted share
    # must match the Feldman-committed public share, or the key would be
    # silently corrupted (e.g. by a plaintext wrap mod a too-small
    # Paillier modulus)
    if GENERATOR * new_share_fe != pk_vec[local_key.i - 1]:
        raise PublicShareValidationError()

    # zeroize the old dk, install the new one (reference :445-448)
    local_key.paillier_dk.zeroize()
    local_key.paillier_dk = new_dk

    local_key.keys_linear.x_i = new_share_fe
    local_key.keys_linear.y = GENERATOR * new_share_fe
    local_key.pk_vec = pk_vec


def combine_committed_points(
    refresh_messages: Sequence["RefreshMessage"],
    li_vec: Sequence[Scalar],
    t: int,
    n: int,
    use_device: bool = False,
) -> List[Point]:
    """X_i = sum_{j=0..t} lambda_j * S_i^{(j)} over the first t+1 senders'
    committed points — shared by refresh collect (reference :455-464) and
    join collect (reference `src/add_party_message.rs:203-212`).

    On the TPU backend this is one batched MSM (n groups of t+1 rows);
    the host path costs n*(t+1) ~2 ms scalar-muls (~65 s at n=256)."""
    if use_device:
        from ..ops.ec_batch import batch_msm

        scalars = [li.to_int() for li in li_vec[: t + 1]]
        return batch_msm(
            [
                [refresh_messages[j].points_committed_vec[i] for j in range(t + 1)]
                for i in range(n)
            ],
            [scalars] * n,
        )
    pk_vec = []
    for i in range(n):
        acc = refresh_messages[0].points_committed_vec[i] * li_vec[0]
        for j in range(1, t + 1):
            acc = acc + refresh_messages[j].points_committed_vec[i] * li_vec[j]
        pk_vec.append(acc)
    return pk_vec


