"""Error taxonomy, mirroring the reference's `FsDkrError`
(`/root/reference/src/error.rs:6-60`): every protocol failure names the
offending party where the reference does (identifiable abort).

The reference models errors as a serde-serializable enum; here each variant
is an exception subclass carrying the same fields, and `FsDkrError` is the
common base so callers can `except FsDkrError`.
"""

from __future__ import annotations


class FsDkrError(Exception):
    """Base class of all protocol errors (reference `FsDkrError`)."""


class PartiesThresholdViolation(FsDkrError):
    # reference: src/error.rs:9-14
    def __init__(self, threshold: int, refreshed_keys: int):
        self.threshold = threshold
        self.refreshed_keys = refreshed_keys
        super().__init__(
            f"Too many malicious parties detected! Threshold {threshold}, "
            f"number of refresh messages: {refreshed_keys}"
        )


class PublicShareValidationError(FsDkrError):
    # reference: src/error.rs:17
    def __init__(self) -> None:
        super().__init__("Shares did not pass verification.")


class SizeMismatchError(FsDkrError):
    # reference: src/error.rs:20-25
    def __init__(
        self,
        refresh_message_index: int,
        pdl_proof_len: int,
        points_committed_len: int,
        points_encrypted_len: int,
    ):
        self.refresh_message_index = refresh_message_index
        self.pdl_proof_len = pdl_proof_len
        self.points_committed_len = points_committed_len
        self.points_encrypted_len = points_encrypted_len
        super().__init__(
            f"Size mismatch for refresh message {refresh_message_index}: "
            f"pdl={pdl_proof_len} committed={points_committed_len} "
            f"encrypted={points_encrypted_len}"
        )


class PDLwSlackProofError(FsDkrError):
    """PDL-with-slack verification failure, with per-equation booleans
    (reference: src/error.rs:28-32)."""

    def __init__(self, is_u1_eq: bool, is_u2_eq: bool, is_u3_eq: bool):
        self.is_u1_eq = is_u1_eq
        self.is_u2_eq = is_u2_eq
        self.is_u3_eq = is_u3_eq
        super().__init__(
            f"PDLwSlack proof verification failed: u1=={is_u1_eq}, "
            f"u2=={is_u2_eq}, u3=={is_u3_eq}"
        )


class RingPedersenProofError(FsDkrError):
    # reference: src/error.rs:35
    def __init__(self) -> None:
        super().__init__("Ring Pedersen proof failed")


class RangeProofError(FsDkrError):
    # reference: src/error.rs:38
    def __init__(self, party_index: int):
        self.party_index = party_index
        super().__init__(f"Range proof failed for party: {party_index}")


class ModuliTooSmall(FsDkrError):
    # reference: src/error.rs:41-44
    def __init__(self, party_index: int, moduli_size: int):
        self.party_index = party_index
        self.moduli_size = moduli_size
        super().__init__(
            f"Paillier modulus of party {party_index} is {moduli_size} bits"
        )


class PaillierVerificationError(FsDkrError):
    # reference: src/error.rs:47
    def __init__(self, party_index: int):
        self.party_index = party_index
        super().__init__(f"Paillier correct-key proof failed for party {party_index}")


class NewPartyUnassignedIndexError(FsDkrError):
    # reference: src/error.rs:50
    def __init__(self) -> None:
        super().__init__("A new party did not receive a valid index.")


class BroadcastedPublicKeyError(FsDkrError):
    # reference: src/error.rs:53; party_index is an identifiable-abort
    # extension (None on the join path, where the culprit is unknowable)
    def __init__(self, party_index: "int | None" = None) -> None:
        self.party_index = party_index
        who = "" if party_index is None else f" (party {party_index})"
        super().__init__(
            f"Broadcast public keys are not all identical, aborting{who}"
        )


class DLogProofValidation(FsDkrError):
    # reference: src/error.rs:56
    def __init__(self, party_index: int):
        self.party_index = party_index
        super().__init__(f"Composite dlog proof failed for party {party_index}")


class RingPedersenProofValidation(FsDkrError):
    # reference: src/error.rs:59
    def __init__(self, party_index: int):
        self.party_index = party_index
        super().__init__(f"Ring Pedersen proof failed for party {party_index}")


class PrecomputeReuseError(FsDkrError):
    """A precompute pool entry was consumed twice (fsdkr_tpu/precompute).
    Entries are strictly single-use: a Paillier randomizer or sigma
    first-message nonce that enters two transcripts collapses the
    zero-knowledge property (two challenges over one commitment reveal
    the witness), so the second take aborts hard instead of returning
    the wiped value."""

    def __init__(self):
        super().__init__("precompute pool entry consumed twice (single-use)")


class CrtFaultError(FsDkrError):
    """A secret-CRT modexp leg failed its Bellcore fault check
    (backend/crt.py): the recombined value is withheld entirely — a
    faulted CRT output would let gcd(output - truth, N) recover a prime
    factor of the prover's key, so the engine aborts hard instead of
    ever emitting it. No detail beyond the failure itself is exposed
    (the faulty residues stay inside the engine)."""

    def __init__(self):
        super().__init__("secret-CRT modexp failed its fault check")
