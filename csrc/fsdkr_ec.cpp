// Native secp256k1 host core for the CPU-platform hot paths.
//
// The reference's EC layer is curv's pure-Rust secp256k1 backing the
// Feldman checks (/root/reference/src/refresh_message.rs:177-188) and
// the PDL u1 equation (/root/reference/src/zk_pdl_with_slack.rs:124-127).
// The rebuild's Python Jacobian oracle (fsdkr_tpu/core/secp256k1.py) is
// the semantic reference; this file is the same math in C++ for the
// host-routed paths, where interpreter overhead — not field math — is
// ~95% of the cost (measured 26 ms per Feldman check at t=128).
//
// Variable-time arithmetic, matching the Python oracle it replaces (and
// CPython int ops themselves): used on verification-side inputs, which
// are public broadcast values.
//
// ABI: plain C, ctypes-loaded (no pybind11 in this environment). Field
// elements are 4 little-endian u64 limbs; affine points are (x, y)
// limb pairs; (0, 0) encodes the identity (it is not on the curve).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

using u32 = uint32_t;
using u64 = uint64_t;
using u128 = __uint128_t;

namespace {

// Row parallelism (same contract as csrc/fsdkr_native.cpp): batch rows
// are independent point equations writing disjoint output slots, so a
// chunked row split is bit-identical to the serial loop at any thread
// count. The shared-inversion batch_to_affine pass stays serial — it is
// one field inversion plus ~5 muls per row, noise next to the per-row
// scalar ladders. Deliberately DUPLICATED from fsdkr_native.cpp rather
// than shared via a header: the loader builds and hash-tags exactly one
// source file per core (native/_loader.py), so an #include'd header
// would not participate in the .so cache tag and edits to it would load
// stale artifacts. Keep the two copies in lock-step.
std::atomic<int> g_threads{1};

template <class F>
void parallel_rows(int rows, const F &fn) {
  int nt = g_threads.load(std::memory_order_relaxed);
  if (nt > rows) nt = rows;
  if (nt <= 1 || rows <= 1) {
    fn(0, rows);
    return;
  }
  std::vector<std::thread> ts;
  ts.reserve(nt - 1);
  const int chunk = rows / nt, rem = rows % nt;
  int lo = 0;
  for (int i = 0; i < nt; i++) {
    const int hi = lo + chunk + (i < rem ? 1 : 0);
    if (i == nt - 1)
      fn(lo, hi);
    else
      ts.emplace_back([&fn, lo, hi] { fn(lo, hi); });
    lo = hi;
  }
  for (auto &t : ts) t.join();
}

// p = 2^256 - 0x1000003D1
const u64 PRIME[4] = {0xFFFFFFFEFFFFFC2FULL, 0xFFFFFFFFFFFFFFFFULL,
                      0xFFFFFFFFFFFFFFFFULL, 0xFFFFFFFFFFFFFFFFULL};
const u64 RED = 0x1000003D1ULL;  // 2^256 mod p

struct fe {
  u64 v[4];
};

inline bool fe_is_zero(const fe &a) {
  return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

inline int fe_cmp(const fe &a, const u64 b[4]) {
  for (int i = 3; i >= 0; --i) {
    if (a.v[i] < b[i]) return -1;
    if (a.v[i] > b[i]) return 1;
  }
  return 0;
}

// a -= p (caller guarantees a >= p, or a virtual 2^256 carry)
inline void fe_sub_p(fe &a) {
  u128 d = (u128)a.v[0] - PRIME[0];
  a.v[0] = (u64)d;
  u64 borrow = (d >> 64) ? 1 : 0;
  for (int i = 1; i < 4; ++i) {
    d = (u128)a.v[i] - PRIME[i] - borrow;
    a.v[i] = (u64)d;
    borrow = (d >> 64) ? 1 : 0;
  }
}

inline void fe_add(fe &r, const fe &a, const fe &b) {
  u128 c = 0;
  for (int i = 0; i < 4; ++i) {
    c += (u128)a.v[i] + b.v[i];
    r.v[i] = (u64)c;
    c >>= 64;
  }
  if (c || fe_cmp(r, PRIME) >= 0) fe_sub_p(r);
}

inline void fe_sub(fe &r, const fe &a, const fe &b) {
  u128 d = 0;
  u64 borrow = 0;
  for (int i = 0; i < 4; ++i) {
    d = (u128)a.v[i] - b.v[i] - borrow;
    r.v[i] = (u64)d;
    borrow = (d >> 64) ? 1 : 0;
  }
  if (borrow) {  // r += p
    u128 c = 0;
    for (int i = 0; i < 4; ++i) {
      c += (u128)r.v[i] + PRIME[i];
      r.v[i] = (u64)c;
      c >>= 64;
    }
  }
}

inline void fe_reduce512(fe &out, const u64 t[8]) {
  // fold hi*2^256 == hi*RED, twice, then one conditional subtract
  u128 c = 0;
  for (int i = 0; i < 4; ++i) {
    c += (u128)t[i] + (u128)t[i + 4] * RED;
    out.v[i] = (u64)c;
    c >>= 64;
  }
  while (c) {  // c <= ~2^34 after first fold; at most 2 rounds
    u128 d = (u128)out.v[0] + c * RED;
    out.v[0] = (u64)d;
    d >>= 64;
    for (int i = 1; i < 4; ++i) {
      d += out.v[i];
      out.v[i] = (u64)d;
      d >>= 64;
    }
    c = d;
  }
  if (fe_cmp(out, PRIME) >= 0) fe_sub_p(out);
}

inline void fe_mul(fe &r, const fe &a, const fe &b) {
  u64 t[8] = {0};
  for (int i = 0; i < 4; ++i) {
    u128 carry = 0;
    for (int j = 0; j < 4; ++j) {
      carry += (u128)a.v[i] * b.v[j] + t[i + j];
      t[i + j] = (u64)carry;
      carry >>= 64;
    }
    t[i + 4] = (u64)carry;
  }
  fe_reduce512(r, t);
}

inline void fe_sqr(fe &r, const fe &a) { fe_mul(r, a, a); }

void fe_inv(fe &r, const fe &a) {
  // Fermat: a^(p-2). Rarely called (once per output batch).
  u64 e[4] = {PRIME[0] - 2, PRIME[1], PRIME[2], PRIME[3]};
  fe acc{{1, 0, 0, 0}};
  fe base = a;
  for (int limb = 0; limb < 4; ++limb)
    for (int bit = 0; bit < 64; ++bit) {
      if ((e[limb] >> bit) & 1) fe_mul(acc, acc, base);
      fe_sqr(base, base);
    }
  r = acc;
}

struct jac {
  fe X, Y, Z;  // Z == 0 -> identity
};

inline bool jac_is_inf(const jac &p) { return fe_is_zero(p.Z); }

inline void jac_set_inf(jac &p) { std::memset(&p, 0, sizeof(p)); }

inline void jac_from_affine(jac &p, const fe &x, const fe &y) {
  p.X = x;
  p.Y = y;
  p.Z = fe{{1, 0, 0, 0}};
}

// dbl-2009-l (a = 0)
void jac_dbl(jac &r, const jac &p) {
  if (jac_is_inf(p) || fe_is_zero(p.Y)) {
    jac_set_inf(r);
    return;
  }
  fe A, B, C, D, E, F, t;
  fe_sqr(A, p.X);
  fe_sqr(B, p.Y);
  fe_sqr(C, B);
  fe_add(t, p.X, B);
  fe_sqr(t, t);
  fe_sub(t, t, A);
  fe_sub(t, t, C);
  fe_add(D, t, t);
  fe_add(E, A, A);
  fe_add(E, E, A);
  fe_sqr(F, E);
  fe X3, Y3, Z3;
  fe_sub(X3, F, D);
  fe_sub(X3, X3, D);
  fe_sub(t, D, X3);
  fe_mul(Y3, E, t);
  fe C8;
  fe_add(C8, C, C);
  fe_add(C8, C8, C8);
  fe_add(C8, C8, C8);
  fe_sub(Y3, Y3, C8);
  fe_mul(Z3, p.Y, p.Z);
  fe_add(Z3, Z3, Z3);
  r.X = X3;
  r.Y = Y3;
  r.Z = Z3;
}

// add-2007-bl (general jac + jac)
void jac_add(jac &r, const jac &p, const jac &q) {
  if (jac_is_inf(p)) {
    r = q;
    return;
  }
  if (jac_is_inf(q)) {
    r = p;
    return;
  }
  fe Z1Z1, Z2Z2, U1, U2, S1, S2, t;
  fe_sqr(Z1Z1, p.Z);
  fe_sqr(Z2Z2, q.Z);
  fe_mul(U1, p.X, Z2Z2);
  fe_mul(U2, q.X, Z1Z1);
  fe_mul(t, q.Z, Z2Z2);
  fe_mul(S1, p.Y, t);
  fe_mul(t, p.Z, Z1Z1);
  fe_mul(S2, q.Y, t);
  if (fe_cmp(U1, U2.v) == 0) {
    if (fe_cmp(S1, S2.v) != 0) {
      jac_set_inf(r);
      return;
    }
    jac_dbl(r, p);
    return;
  }
  fe H, I, J, rr, V;
  fe_sub(H, U2, U1);
  fe_add(I, H, H);
  fe_sqr(I, I);
  fe_mul(J, H, I);
  fe_sub(rr, S2, S1);
  fe_add(rr, rr, rr);
  fe_mul(V, U1, I);
  fe X3, Y3, Z3;
  fe_sqr(X3, rr);
  fe_sub(X3, X3, J);
  fe_sub(X3, X3, V);
  fe_sub(X3, X3, V);
  fe_sub(t, V, X3);
  fe_mul(Y3, rr, t);
  fe_mul(t, S1, J);
  fe_add(t, t, t);
  fe_sub(Y3, Y3, t);
  fe_add(Z3, p.Z, q.Z);
  fe_sqr(Z3, Z3);
  fe_sub(Z3, Z3, Z1Z1);
  fe_sub(Z3, Z3, Z2Z2);
  fe_mul(Z3, Z3, H);
  r.X = X3;
  r.Y = Y3;
  r.Z = Z3;
}

// madd-2007-bl (jac + affine), affine not identity
void jac_madd(jac &r, const jac &p, const fe &qx, const fe &qy) {
  if (jac_is_inf(p)) {
    jac_from_affine(r, qx, qy);
    return;
  }
  fe Z1Z1, U2, S2, t;
  fe_sqr(Z1Z1, p.Z);
  fe_mul(U2, qx, Z1Z1);
  fe_mul(t, p.Z, Z1Z1);
  fe_mul(S2, qy, t);
  if (fe_cmp(p.X, U2.v) == 0) {
    if (fe_cmp(p.Y, S2.v) != 0) {
      jac_set_inf(r);
      return;
    }
    jac_dbl(r, p);
    return;
  }
  fe H, HH, I, J, rr, V;
  fe_sub(H, U2, p.X);
  fe_sqr(HH, H);
  fe_add(I, HH, HH);
  fe_add(I, I, I);
  fe_mul(J, H, I);
  fe_sub(rr, S2, p.Y);
  fe_add(rr, rr, rr);
  fe_mul(V, p.X, I);
  fe X3, Y3, Z3;
  fe_sqr(X3, rr);
  fe_sub(X3, X3, J);
  fe_sub(X3, X3, V);
  fe_sub(X3, X3, V);
  fe_sub(t, V, X3);
  fe_mul(Y3, rr, t);
  fe_mul(t, p.Y, J);
  fe_add(t, t, t);
  fe_sub(Y3, Y3, t);
  fe_add(Z3, p.Z, H);
  fe_sqr(Z3, Z3);
  fe_sub(Z3, Z3, Z1Z1);
  fe_sub(Z3, Z3, HH);
  r.X = X3;
  r.Y = Y3;
  r.Z = Z3;
}

// r = k * p for a small scalar (double-and-add over k's bits)
void jac_mul_small(jac &r, const jac &p, u32 k) {
  if (k == 0 || jac_is_inf(p)) {
    jac_set_inf(r);
    return;
  }
  int top = 31;
  while (!((k >> top) & 1)) --top;
  jac acc = p;
  for (int i = top - 1; i >= 0; --i) {
    jac_dbl(acc, acc);
    if ((k >> i) & 1) jac_add(acc, acc, p);
  }
  r = acc;
}

// r = scalar (4 limbs LE) * affine point, 4-bit fixed window
void jac_mul(jac &r, const fe &px, const fe &py, const u64 s[4]) {
  bool zero = (s[0] | s[1] | s[2] | s[3]) == 0;
  if (zero) {
    jac_set_inf(r);
    return;
  }
  jac tbl[16];
  jac_set_inf(tbl[0]);
  jac_from_affine(tbl[1], px, py);
  for (int i = 2; i < 16; ++i) jac_madd(tbl[i], tbl[i - 1], px, py);
  jac acc;
  jac_set_inf(acc);
  for (int w = 63; w >= 0; --w) {
    int limb = w / 16;
    int shift = (w % 16) * 4;
    unsigned d = (unsigned)((s[limb] >> shift) & 0xF);
    if (!jac_is_inf(acc)) {
      jac_dbl(acc, acc);
      jac_dbl(acc, acc);
      jac_dbl(acc, acc);
      jac_dbl(acc, acc);
    }
    if (d) jac_add(acc, acc, tbl[d]);
  }
  r = acc;
}

// Batch Jacobian -> affine with one shared inversion (Montgomery trick).
// out: (x, y) pairs; identity -> (0, 0).
void batch_to_affine(const jac *pts, int n, u64 *out) {
  fe *prefix = new fe[n];
  fe acc{{1, 0, 0, 0}};
  for (int i = 0; i < n; ++i) {
    prefix[i] = acc;
    if (!jac_is_inf(pts[i])) fe_mul(acc, acc, pts[i].Z);
  }
  fe inv;
  fe_inv(inv, acc);
  for (int i = n - 1; i >= 0; --i) {
    u64 *o = out + (size_t)i * 8;
    if (jac_is_inf(pts[i])) {
      std::memset(o, 0, 64);
      continue;
    }
    fe zinv;
    fe_mul(zinv, inv, prefix[i]);
    fe_mul(inv, inv, pts[i].Z);
    fe zi2, zi3, x, y;
    fe_sqr(zi2, zinv);
    fe_mul(zi3, zi2, zinv);
    fe_mul(x, pts[i].X, zi2);
    fe_mul(y, pts[i].Y, zi3);
    std::memcpy(o, x.v, 32);
    std::memcpy(o + 4, y.v, 32);
  }
  delete[] prefix;
}

inline void load_fe(fe &r, const u64 *p) { std::memcpy(r.v, p, 32); }

inline bool load_affine_jac(jac &r, const u64 *p) {
  // returns false for the (0,0) identity encoding
  fe x, y;
  load_fe(x, p);
  load_fe(y, p + 4);
  if (fe_is_zero(x) && fe_is_zero(y)) {
    jac_set_inf(r);
    return false;
  }
  jac_from_affine(r, x, y);
  return true;
}

}  // namespace

extern "C" {

// Thread-count control (FSDKR_THREADS bridge). Returns the applied count.
int fsdkr_ec_set_threads(int n) {
  if (n <= 0) {
    unsigned hc = std::thread::hardware_concurrency();
    n = hc ? (int)hc : 1;
  }
  g_threads.store(n, std::memory_order_relaxed);
  return n;
}

// out[j] = sum_k A_k * idx[j]^k, Horner over the shared commitment
// vector (t1 affine points, A_0 first). The Feldman check's exact
// evaluation order (core/vss.py validate_share_public).
int fsdkr_ec_horner_batch(const u64 *commits, int t1, const u32 *idxs,
                          int m, u64 *out) {
  if (t1 <= 0 || m <= 0) return 1;
  jac *res = new jac[m];
  parallel_rows(m, [&](int lo, int hi) {
    for (int j = lo; j < hi; ++j) {
      jac acc;
      load_affine_jac(acc, commits + (size_t)(t1 - 1) * 8);
      for (int k = t1 - 2; k >= 0; --k) {
        jac t;
        jac_mul_small(t, acc, idxs[j]);
        const u64 *ak = commits + (size_t)k * 8;
        fe x, y;
        load_fe(x, ak);
        load_fe(y, ak + 4);
        if (fe_is_zero(x) && fe_is_zero(y)) {
          acc = t;  // identity commitment: acc*idx + 0
        } else {
          jac_madd(acc, t, x, y);
        }
      }
      res[j] = acc;
    }
  });
  batch_to_affine(res, m, out);
  delete[] res;
  return 0;
}

// out[i] = scalars[i] * points[i] (scalars reduced mod group order by
// the caller; variable-time)
int fsdkr_ec_scalar_mul_batch(const u64 *points, const u64 *scalars, int n,
                              u64 *out) {
  if (n <= 0) return 1;
  jac *res = new jac[n];
  parallel_rows(n, [&](int lo, int hi) {
    for (int i = lo; i < hi; ++i) {
      fe x, y;
      load_fe(x, points + (size_t)i * 8);
      load_fe(y, points + (size_t)i * 8 + 4);
      if (fe_is_zero(x) && fe_is_zero(y)) {
        jac_set_inf(res[i]);
      } else {
        jac_mul(res[i], x, y, scalars + (size_t)i * 4);
      }
    }
  });
  batch_to_affine(res, n, out);
  delete[] res;
  return 0;
}

// out[i] = a[i]*P[i] + b[i]*Q[i] — the PDL u1 shape (s1*G + (q-e)*Q)
int fsdkr_ec_lincomb2_batch(const u64 *P, const u64 *a, const u64 *Q,
                            const u64 *b, int n, u64 *out) {
  if (n <= 0) return 1;
  jac *res = new jac[n];
  parallel_rows(n, [&](int lo, int hi) {
    for (int i = lo; i < hi; ++i) {
      jac pa, qb;
      fe x, y;
      load_fe(x, P + (size_t)i * 8);
      load_fe(y, P + (size_t)i * 8 + 4);
      if (fe_is_zero(x) && fe_is_zero(y))
        jac_set_inf(pa);
      else
        jac_mul(pa, x, y, a + (size_t)i * 4);
      load_fe(x, Q + (size_t)i * 8);
      load_fe(y, Q + (size_t)i * 8 + 4);
      if (fe_is_zero(x) && fe_is_zero(y))
        jac_set_inf(qb);
      else
        jac_mul(qb, x, y, b + (size_t)i * 4);
      jac_add(res[i], pa, qb);
    }
  });
  batch_to_affine(res, n, out);
  delete[] res;
  return 0;
}

}  // extern "C"
