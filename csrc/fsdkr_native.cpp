// Native host bignum core for fsdkr_tpu.
//
// The reference's host-serial native layer is GMP (C) underneath
// curv/kzen-paillier — e.g. the 2048-bit Paillier keygen at
// /root/reference/src/refresh_message.rs:118 and the ring-Pedersen setup at
// src/ring_pedersen_proof.rs:48-74 are GMP prime generation and modexp.
// This file is the rebuild's equivalent: fixed-width Montgomery arithmetic
// over 64-bit limbs (unsigned __int128 partial products), exposed as a
// plain C ABI loaded from Python via ctypes (no pybind11 in this
// environment). It serves the host-serial paths the TPU cannot batch:
// Miller-Rabin prime generation, the comb kernel's host power ladder, and
// the host-backend oracle's modular exponentiation.
//
// All numbers are little-endian uint64 limb arrays of a caller-chosen
// width; moduli must be odd. Maximum width 64 limbs = 4096 bits (the
// protocol's widest modulus class, N^2 for 2048-bit Paillier N).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <dlfcn.h>
#include <new>
#include <thread>
#include <vector>

typedef uint64_t u64;
typedef unsigned __int128 u128;

static const int MAXL = 64; // 4096 bits

// ---------------------------------------------------------------------------
// Row parallelism. Every batch entry point below iterates over rows that
// are mathematically independent (per-row modulus, per-row output slice),
// so splitting the row range across threads is bit-identical to the
// serial loop at any thread count — the per-row computation is exactly
// the same code, and no row reads another row's state. The count is set
// from Python (FSDKR_THREADS; 0 = auto from hardware_concurrency, 1 =
// serial). Threads are spawned per call: batch calls are
// milliseconds-to-seconds of work, so spawn cost (~tens of us) is noise,
// and no pool lifecycle can leak across fork or library reload.

static std::atomic<int> g_threads{1};

template <class F>
static void parallel_rows(int rows, const F &fn) {
  int nt = g_threads.load(std::memory_order_relaxed);
  if (nt > rows)
    nt = rows;
  if (nt <= 1 || rows <= 1) {
    fn(0, rows);
    return;
  }
  std::vector<std::thread> ts;
  ts.reserve(nt - 1);
  const int chunk = rows / nt, rem = rows % nt;
  int lo = 0;
  for (int i = 0; i < nt; i++) {
    const int hi = lo + chunk + (i < rem ? 1 : 0);
    if (i == nt - 1)
      fn(lo, hi); // run the last chunk on the calling thread
    else
      ts.emplace_back([&fn, lo, hi] { fn(lo, hi); });
    lo = hi;
  }
  for (auto &t : ts)
    t.join();
}

extern "C" {

// Thread-count control (FSDKR_THREADS bridge). Returns the applied count.
int fsdkr_set_threads(int n) {
  if (n <= 0) {
    unsigned hc = std::thread::hardware_concurrency();
    n = hc ? (int)hc : 1;
  }
  g_threads.store(n, std::memory_order_relaxed);
  return n;
}

int fsdkr_get_threads(void) {
  return g_threads.load(std::memory_order_relaxed);
}

} // extern "C" (reopened below; the mpn backend plumbing is C++)

// ---------------------------------------------------------------------------
// Optional GMP mpn backend for the Montgomery inner loop. The system
// libgmp (the reference's own bigint backend, already a runtime
// dependency of the ctypes bridge in native/gmp.py) carries asm
// basecase multiplication and Karatsuba above ~30 limbs: at the
// protocol's 64-limb (n^2, 4096-bit) width its mul+REDC-1 is ~2.4x the
// portable u128 CIOS loop below, and ~2x at 32 limbs. The backend is
// resolved at RUNTIME with dlopen/dlsym (no GMP headers in this image;
// mp_limb_t == uint64_t on every LP64 target this builds for), and
// every mont_mul/mont_sqr call dispatches on one relaxed atomic load:
// results are BIT-IDENTICAL either way (same canonical residue < n), so
// the switch (FSDKR_MPN via fsdkr_set_mpn, auto-on when libgmp
// resolves) is a pure speed A/B, pinned by the parity suites.

typedef u64 (*mpn_addmul_1_fn)(u64 *, const u64 *, long, u64);
typedef void (*mpn_mul_n_fn)(u64 *, const u64 *, const u64 *, long);
typedef void (*mpn_sqr_fn)(u64 *, const u64 *, long);
typedef u64 (*mpn_sub_n_fn)(u64 *, const u64 *, const u64 *, long);
typedef int (*mpn_cmp_fn)(const u64 *, const u64 *, long);
typedef u64 (*mpn_redc_1_fn)(u64 *, u64 *, const u64 *, long, u64);

static mpn_addmul_1_fn g_mpn_addmul_1 = nullptr;
static mpn_mul_n_fn g_mpn_mul_n = nullptr;
static mpn_sqr_fn g_mpn_sqr = nullptr;
static mpn_sub_n_fn g_mpn_sub_n = nullptr;
static mpn_cmp_fn g_mpn_cmp = nullptr;
// internal-but-exported asm REDC (GMP keeps mpn symbols stable within a
// soname); optional — nullptr falls back to the addmul_1 loop, which is
// the same algorithm ~10% slower
static mpn_redc_1_fn g_mpn_redc_1 = nullptr;
static std::atomic<int> g_use_mpn{0};
static std::atomic<int> g_mpn_probed{0};

static int mpn_probe() { // idempotent; races only re-store identical values
  if (g_mpn_probed.load(std::memory_order_acquire))
    return g_mpn_addmul_1 != nullptr;
  void *h = dlopen("libgmp.so.10", RTLD_NOW | RTLD_LOCAL);
  if (!h)
    h = dlopen("libgmp.so", RTLD_NOW | RTLD_LOCAL);
  if (h) {
    mpn_addmul_1_fn am = (mpn_addmul_1_fn)dlsym(h, "__gmpn_addmul_1");
    mpn_mul_n_fn mn = (mpn_mul_n_fn)dlsym(h, "__gmpn_mul_n");
    mpn_sqr_fn sq = (mpn_sqr_fn)dlsym(h, "__gmpn_sqr");
    mpn_sub_n_fn sb = (mpn_sub_n_fn)dlsym(h, "__gmpn_sub_n");
    mpn_cmp_fn cp = (mpn_cmp_fn)dlsym(h, "__gmpn_cmp");
    if (am && mn && sq && sb && cp) {
      g_mpn_mul_n = mn;
      g_mpn_sqr = sq;
      g_mpn_sub_n = sb;
      g_mpn_cmp = cp;
      g_mpn_redc_1 = (mpn_redc_1_fn)dlsym(h, "__gmpn_redc_1"); // optional
      g_mpn_addmul_1 = am; // published last: the dispatch gates on it
    } // a partial symbol set stays on the portable core (never dlclose:
      // the handle must outlive every worker thread)
  }
  g_mpn_probed.store(1, std::memory_order_release);
  return g_mpn_addmul_1 != nullptr;
}

extern "C" {

// FSDKR_MPN bridge: n < 0 = auto (use mpn when libgmp resolves),
// 0 = force the portable u128 core, > 0 = request mpn (granted only if
// it resolves). Returns the active engine: 1 = mpn, 0 = portable.
// Release store: pairs with the dispatchers' acquire loads so a thread
// that observes g_use_mpn == 1 also observes the g_mpn_* pointer
// stores from mpn_probe (they are plain pointers, not atomics).
int fsdkr_set_mpn(int n) {
  int want = (n != 0) && mpn_probe();
  g_use_mpn.store(want ? 1 : 0, std::memory_order_release);
  return want ? 1 : 0;
}

// 1 = GMP mpn inner loop active, 0 = portable u128 CIOS core.
int fsdkr_engine_kind(void) {
  return g_use_mpn.load(std::memory_order_relaxed);
}

// ---------------------------------------------------------------------------
// limb helpers

// Volatile wipe that the optimizer cannot elide: secret-bearing limb
// buffers (exponents, secret-derived bases and their power tables, prime
// candidates) are zeroed before frames return — the native-side
// equivalent of the reference's zeroize discipline
// (/root/reference/src/refresh_message.rs:446-448).
static void secure_wipe(u64 *p, int L) {
  volatile u64 *vp = p;
  for (int i = 0; i < L; i++)
    vp[i] = 0;
}

static int cmp_limbs(const u64 *a, const u64 *b, int L) {
  for (int i = L - 1; i >= 0; i--) {
    if (a[i] < b[i])
      return -1;
    if (a[i] > b[i])
      return 1;
  }
  return 0;
}

static void sub_limbs(u64 *out, const u64 *a, const u64 *b, int L) {
  u64 borrow = 0;
  for (int i = 0; i < L; i++) {
    u64 bi = b[i] + borrow;
    u64 new_borrow = (bi < b[i]) || (a[i] < bi);
    out[i] = a[i] - bi;
    borrow = new_borrow;
  }
}

// -n^{-1} mod 2^64 by Newton iteration (n odd)
static u64 mont_n0inv(u64 n0) {
  u64 x = n0; // 3 correct bits
  for (int i = 0; i < 6; i++)
    x *= 2 - n0 * x; // doubles correct bits each round
  return (u64)0 - x;
}

// ---------------------------------------------------------------------------
// Montgomery CIOS multiplication: out = a * b * R^{-1} mod n, R = 2^(64 L)

static void mont_mul_cios(u64 *out, const u64 *a, const u64 *b, const u64 *n,
                          u64 n0inv, int L) {
  u64 t[MAXL + 2];
  std::memset(t, 0, sizeof(u64) * (L + 2));
  for (int i = 0; i < L; i++) {
    u128 carry = 0;
    const u64 ai = a[i];
    for (int j = 0; j < L; j++) {
      u128 cur = (u128)ai * b[j] + t[j] + carry;
      t[j] = (u64)cur;
      carry = cur >> 64;
    }
    u128 cur = (u128)t[L] + carry;
    t[L] = (u64)cur;
    t[L + 1] += (u64)(cur >> 64);

    const u64 m = t[0] * n0inv;
    carry = ((u128)m * n[0] + t[0]) >> 64;
    for (int j = 1; j < L; j++) {
      u128 cur2 = (u128)m * n[j] + t[j] + carry;
      t[j - 1] = (u64)cur2;
      carry = cur2 >> 64;
    }
    cur = (u128)t[L] + carry;
    t[L - 1] = (u64)cur;
    t[L] = t[L + 1] + (u64)(cur >> 64);
    t[L + 1] = 0;
  }
  if (t[L] != 0 || cmp_limbs(t, n, L) >= 0)
    sub_limbs(out, t, n, L); // t < 2n always, one subtract suffices
  else
    std::memcpy(out, t, sizeof(u64) * L);
}

// ---------------------------------------------------------------------------
// Dedicated Montgomery squaring: out = a * a * R^{-1} mod n. SOS layout —
// the symmetric half of the schoolbook product is computed once and
// doubled (L(L+1)/2 limb products instead of L^2), then a separate
// Montgomery reduction pass (L^2 products) finishes. Measured 0.66x the
// general mont_mul at 64 limbs, 0.69x at 32, 0.76x at 24 on this class
// of host — and every modexp ladder is ~4 squarings per multiply, so the
// squaring chain is where modexp wall-clock actually lives.

static void mont_sqr_sos(u64 *out, const u64 *a, const u64 *n, u64 n0inv,
                         int L) {
  u64 t[2 * MAXL + 1];
  std::memset(t, 0, sizeof(u64) * (2 * L + 1));
  // cross products a_i * a_j (i < j), each summed once. t[i+L] is
  // provably still zero when row i deposits its final carry there (rows
  // i' < i only reach position i'+L < i+L), so no carry-out can wrap.
  for (int i = 0; i < L; i++) {
    u128 carry = 0;
    const u64 ai = a[i];
    for (int j = i + 1; j < L; j++) {
      u128 cur = (u128)ai * a[j] + t[i + j] + carry;
      t[i + j] = (u64)cur;
      carry = cur >> 64;
    }
    t[i + L] += (u64)carry;
  }
  // double the cross half, then add the diagonal a_i^2 terms
  {
    u64 c = 0;
    for (int i = 0; i < 2 * L; i++) {
      u64 hi = t[i] >> 63;
      t[i] = (t[i] << 1) | c;
      c = hi;
    }
    t[2 * L] = c;
  }
  {
    u128 carry = 0;
    for (int i = 0; i < L; i++) {
      u128 cur = (u128)a[i] * a[i] + t[2 * i] + carry;
      t[2 * i] = (u64)cur;
      carry = cur >> 64;
      cur = (u128)t[2 * i + 1] + carry;
      t[2 * i + 1] = (u64)cur;
      carry = cur >> 64;
    }
    t[2 * L] += (u64)carry;
  }
  // Montgomery reduction of the 2L-word square
  for (int i = 0; i < L; i++) {
    const u64 m = t[i] * n0inv;
    u128 carry = 0;
    for (int j = 0; j < L; j++) {
      u128 cur = (u128)m * n[j] + t[i + j] + carry;
      t[i + j] = (u64)cur;
      carry = cur >> 64;
    }
    for (int j = i + L; carry && j <= 2 * L; j++) {
      u128 cur = (u128)t[j] + carry;
      t[j] = (u64)cur;
      carry = cur >> 64;
    }
  }
  // result in t[L..2L]; t[2L] in {0,1} and the value is < 2n. The stack
  // temp is left to be overwritten by the next call, matching mont_mul:
  // the wipe discipline lives in the calling frames' persistent buffers.
  if (t[2 * L] != 0 || cmp_limbs(t + L, n, L) >= 0)
    sub_limbs(out, t + L, n, L);
  else
    std::memcpy(out, t + L, sizeof(u64) * L);
}

// mpn-backed Montgomery product/square: schoolbook/Karatsuba product via
// mpn_mul_n / mpn_sqr, then textbook REDC-1 (L rounds of addmul_1 by
// m = t_i * n0inv, carries rippled into the high half), conditional
// subtract. The intermediate t < 2n * R always fits 2L+1 limbs, and the
// final residue is canonical (< n) exactly like the CIOS/SOS cores —
// the two engines are interchangeable mid-ladder.

static inline void mpn_redc(u64 *out, u64 *t, const u64 *n, u64 n0inv,
                            int L) {
  // t: 2L+1 limbs, t[2L] = 0 on entry; result < n into out
  if (g_mpn_redc_1) {
    u64 c = g_mpn_redc_1(out, t, n, L, n0inv);
    if (c || g_mpn_cmp(out, n, L) >= 0)
      g_mpn_sub_n(out, out, n, L);
    return;
  }
  for (int i = 0; i < L; i++) {
    const u64 m = t[i] * n0inv;
    u64 c = g_mpn_addmul_1(t + i, n, L, m);
    for (int j = i + L; c; j++) {
      u64 s = t[j] + c;
      c = s < c;
      t[j] = s;
    }
  }
  if (t[2 * L] != 0 || g_mpn_cmp(t + L, n, L) >= 0)
    g_mpn_sub_n(out, t + L, n, L);
  else
    std::memcpy(out, t + L, sizeof(u64) * L);
}

static void mont_mul_mpn(u64 *out, const u64 *a, const u64 *b, const u64 *n,
                         u64 n0inv, int L) {
  u64 t[2 * MAXL + 1];
  g_mpn_mul_n(t, a, b, L);
  t[2 * L] = 0;
  mpn_redc(out, t, n, n0inv, L);
}

static void mont_sqr_mpn(u64 *out, const u64 *a, const u64 *n, u64 n0inv,
                         int L) {
  u64 t[2 * MAXL + 1];
  g_mpn_sqr(t, a, L);
  t[2 * L] = 0;
  mpn_redc(out, t, n, n0inv, L);
}

// Every ladder below calls these dispatchers; one acquire load per
// Montgomery operation is noise against the ~L^2 limb products behind
// it (acquire pairs with fsdkr_set_mpn's release so the g_mpn_* pointer
// stores are visible whenever the flag reads 1, on any memory model).
static inline void mont_mul(u64 *out, const u64 *a, const u64 *b,
                            const u64 *n, u64 n0inv, int L) {
  if (g_use_mpn.load(std::memory_order_acquire))
    mont_mul_mpn(out, a, b, n, n0inv, L);
  else
    mont_mul_cios(out, a, b, n, n0inv, L);
}

static inline void mont_sqr(u64 *out, const u64 *a, const u64 *n, u64 n0inv,
                            int L) {
  if (g_use_mpn.load(std::memory_order_acquire))
    mont_sqr_mpn(out, a, n, n0inv, L);
  else
    mont_sqr_sos(out, a, n, n0inv, L);
}

// R mod n and R^2 mod n by doubling (L <= MAXL)
static void mont_constants(const u64 *n, int L, u64 *r_mod, u64 *r2_mod) {
  // r_mod = R mod n: start from 2^(64L - 1) mod n (top bit), double once
  u64 acc[MAXL];
  std::memset(acc, 0, sizeof(u64) * L);
  // set acc = 1, then double 64*L times mod n
  acc[0] = 1;
  for (int bit = 0; bit < 64 * L; bit++) {
    // acc = 2*acc mod n
    u64 carry = 0;
    for (int i = 0; i < L; i++) {
      u64 hi = acc[i] >> 63;
      acc[i] = (acc[i] << 1) | carry;
      carry = hi;
    }
    if (carry || cmp_limbs(acc, n, L) >= 0)
      sub_limbs(acc, acc, n, L);
  }
  std::memcpy(r_mod, acc, sizeof(u64) * L);
  // r2_mod = R^2 mod n: double 64*L more times
  for (int bit = 0; bit < 64 * L; bit++) {
    u64 carry = 0;
    for (int i = 0; i < L; i++) {
      u64 hi = acc[i] >> 63;
      acc[i] = (acc[i] << 1) | carry;
      carry = hi;
    }
    if (carry || cmp_limbs(acc, n, L) >= 0)
      sub_limbs(acc, acc, n, L);
  }
  std::memcpy(r2_mod, acc, sizeof(u64) * L);
}

// ---------------------------------------------------------------------------
// modexp: out = base^exp mod n. n odd, L limbs; exp EL limbs.
// Fixed wbits-wide window (4..8, caller-chosen by exponent width: wider
// windows trade table-build multiplies for fewer per-window lookups, so
// w=6 wins for full-width exponents and w=4 for short ones), MSB-first.

// Core ladder against caller-owned Montgomery constants (n0inv, one_m,
// r2). Wipes every temporary it creates (reduced base, Montgomery base,
// window table, accumulator) but NOT the constants — the CRT leg batch
// amortizes one mont_constants over a run of equal-modulus rows and
// wipes them once per run.
static int modexp_core(const u64 *base, const u64 *exp, const u64 *n,
                       u64 n0inv, const u64 *one_m, const u64 *r2, u64 *out,
                       int L, int EL, int wbits) {
  // wbits capped at 6: the 2^wbits-entry stack table is 32 KB there, and
  // the build-vs-lookup tradeoff already tips back past w=6 for every
  // protocol exponent width
  if (L <= 0 || L > MAXL || EL <= 0 || wbits < 1 || wbits > 6 ||
      !(n[0] & 1))
    return -1;

  // reduce base below n (base < 2^(64L); subtract n a few times if needed —
  // callers pass base < n, this is just a guard)
  u64 b[MAXL];
  std::memcpy(b, base, sizeof(u64) * L);
  while (cmp_limbs(b, n, L) >= 0)
    sub_limbs(b, b, n, L);

  u64 base_m[MAXL];
  mont_mul(base_m, b, r2, n, n0inv, L);

  // window table: t[d] = base^d in Montgomery form (even entries are
  // squares of earlier entries — cheaper than a multiply)
  const int D = 1 << wbits;
  u64 table[64][MAXL];
  std::memcpy(table[0], one_m, sizeof(u64) * L);
  std::memcpy(table[1], base_m, sizeof(u64) * L);
  for (int d = 2; d < D; d++) {
    if (d % 2 == 0)
      mont_sqr(table[d], table[d / 2], n, n0inv, L);
    else
      mont_mul(table[d], table[d - 1], base_m, n, n0inv, L);
  }

  // top set window
  int top_bit = -1;
  for (int i = EL - 1; i >= 0 && top_bit < 0; i--)
    if (exp[i])
      for (int bit = 63; bit >= 0; bit--)
        if ((exp[i] >> bit) & 1) {
          top_bit = i * 64 + bit;
          break;
        }
  u64 acc[MAXL];
  u64 onev[MAXL];
  std::memset(onev, 0, sizeof(u64) * L);
  onev[0] = 1;
  if (top_bit < 0) { // exp == 0
    std::memcpy(out, one_m, sizeof(u64) * L);
    mont_mul(out, out, onev, n, n0inv, L); // leave Montgomery domain -> 1
    secure_wipe(b, L);
    secure_wipe(base_m, L);
    secure_wipe(&table[0][0], D * MAXL);
    return 0;
  }

  int nwin = top_bit / wbits; // highest window index
  const u64 mask = (u64)D - 1;
  std::memcpy(acc, one_m, sizeof(u64) * L);
  for (int w = nwin; w >= 0; w--) {
    for (int s = 0; s < wbits; s++)
      mont_sqr(acc, acc, n, n0inv, L);
    int bit0 = w * wbits; // windows may straddle a 64-bit limb
    u64 d = exp[bit0 / 64] >> (bit0 % 64);
    if (bit0 % 64 + wbits > 64 && bit0 / 64 + 1 < EL)
      d |= exp[bit0 / 64 + 1] << (64 - bit0 % 64);
    d &= mask;
    mont_mul(acc, acc, table[d], n, n0inv, L);
  }

  mont_mul(out, acc, onev, n, n0inv, L);
  secure_wipe(b, L);
  secure_wipe(base_m, L);
  secure_wipe(&table[0][0], D * MAXL);
  secure_wipe(acc, L);
  return 0;
}

int fsdkr_modexp_w(const u64 *base, const u64 *exp, const u64 *n, u64 *out,
                   int L, int EL, int wbits) {
  if (L <= 0 || L > MAXL || EL <= 0 || wbits < 1 || wbits > 6 ||
      !(n[0] & 1))
    return -1;
  const u64 n0inv = mont_n0inv(n[0]);
  u64 one_m[MAXL], r2[MAXL];
  mont_constants(n, L, one_m, r2);
  int rc = modexp_core(base, exp, n, n0inv, one_m, r2, out, L, EL, wbits);
  // one_m/r2 reconstruct the modulus (secret on the Paillier-decrypt
  // path where n = p^2): gcd(R - one_m, R^2 - r2) recovers it
  secure_wipe(one_m, L);
  secure_wipe(r2, L);
  return rc;
}

// ABI-stable 4-bit-window entry point
int fsdkr_modexp(const u64 *base, const u64 *exp, const u64 *n, u64 *out,
                 int L, int EL) {
  return fsdkr_modexp_w(base, exp, n, out, L, EL, 4);
}

// ---------------------------------------------------------------------------
// Miller-Rabin: 1 = probable prime, 0 = composite, -1 = bad input.
// Witness bases are caller-provided (sampled with a CSPRNG in Python) so
// the native side stays deterministic and testable.

int fsdkr_miller_rabin(const u64 *n, int L, const u64 *witnesses, int rounds) {
  if (L <= 0 || L > MAXL || !(n[0] & 1))
    return -1;

  const u64 n0inv = mont_n0inv(n[0]);
  u64 one_m[MAXL], r2[MAXL];
  mont_constants(n, L, one_m, r2);

  // n1 = n - 1 = 2^r * d
  u64 n1[MAXL], d[MAXL];
  u64 onev[MAXL];
  std::memset(onev, 0, sizeof(u64) * L);
  onev[0] = 1;
  sub_limbs(n1, n, onev, L);
  std::memcpy(d, n1, sizeof(u64) * L);
  int r = 0;
  while (!(d[0] & 1)) {
    for (int i = 0; i < L - 1; i++)
      d[i] = (d[i] >> 1) | (d[i + 1] << 63);
    d[L - 1] >>= 1;
    r++;
  }

  u64 n1_m[MAXL]; // n-1 in Montgomery form, for comparisons
  mont_mul(n1_m, n1, r2, n, n0inv, L);

  // Rounds are independent (each witness runs its own power chain from
  // shared read-only constants), so they split across threads; the
  // verdict is "composite iff ANY round found a witness", which is
  // order-independent — identical at every thread count. A found
  // witness short-circuits the remaining rounds on every thread.
  std::atomic<bool> composite{false};
  parallel_rows(rounds, [&](int lo, int hi) {
    u64 a_m[MAXL];
    u64 ared[MAXL];
    u64 x[MAXL];
    for (int round = lo; round < hi; round++) {
      if (composite.load(std::memory_order_relaxed))
        break;
      const u64 *a = witnesses + (size_t)round * L;
      std::memcpy(ared, a, sizeof(u64) * L);
      while (cmp_limbs(ared, n, L) >= 0)
        sub_limbs(ared, ared, n, L);
      mont_mul(a_m, ared, r2, n, n0inv, L);

      // x = a^d mod n (Montgomery domain, square-and-multiply MSB-first)
      int top_bit = -1;
      for (int i = L - 1; i >= 0 && top_bit < 0; i--)
        if (d[i])
          for (int bit = 63; bit >= 0; bit--)
            if ((d[i] >> bit) & 1) {
              top_bit = i * 64 + bit;
              break;
            }
      std::memcpy(x, one_m, sizeof(u64) * L);
      for (int bit = top_bit; bit >= 0; bit--) {
        mont_sqr(x, x, n, n0inv, L);
        if ((d[bit / 64] >> (bit % 64)) & 1)
          mont_mul(x, x, a_m, n, n0inv, L);
      }

      if (cmp_limbs(x, one_m, L) == 0 || cmp_limbs(x, n1_m, L) == 0)
        continue;
      bool witness = true;
      for (int i = 0; i < r - 1; i++) {
        mont_sqr(x, x, n, n0inv, L);
        if (cmp_limbs(x, n1_m, L) == 0) {
          witness = false;
          break;
        }
      }
      if (witness)
        composite.store(true, std::memory_order_relaxed);
    }
    // witness-power state derives from the secret prime candidate
    secure_wipe(x, MAXL);
    secure_wipe(a_m, MAXL);
    secure_wipe(ared, MAXL);
  });
  secure_wipe(d, L);
  secure_wipe(n1, L);
  secure_wipe(n1_m, L);
  // one_m/r2 are R mod n and R^2 mod n with R public: n is recoverable
  // from either (gcd(R - one_m, R^2 - r2)), so they are as secret as
  // the prime candidate itself
  secure_wipe(one_m, L);
  secure_wipe(r2, L);
  return composite.load() ? 0 : 1;
}

// Batched modexp over a column of rows (independent moduli): the host
// backend's powm shape. Returns 0 on success, -1 on any bad row input.
int fsdkr_modexp_batch_w(const u64 *bases, const u64 *exps, const u64 *mods,
                         u64 *outs, int rows, int L, int EL, int wbits) {
  // Rows are independent; a bad row on any thread fails the whole batch
  // (the Python bridge discards every output and falls back row-wise, so
  // which rows were written before the failure is unobservable).
  std::atomic<int> rc{0};
  parallel_rows(rows, [&](int lo, int hi) {
    for (int i = lo; i < hi; i++) {
      if (rc.load(std::memory_order_relaxed) != 0)
        return;
      int r = fsdkr_modexp_w(bases + (size_t)i * L, exps + (size_t)i * EL,
                             mods + (size_t)i * L, outs + (size_t)i * L, L,
                             EL, wbits);
      if (r != 0)
        rc.store(r, std::memory_order_relaxed);
    }
  });
  return rc.load();
}

int fsdkr_modexp_batch(const u64 *bases, const u64 *exps, const u64 *mods,
                       u64 *outs, int rows, int L, int EL) {
  return fsdkr_modexp_batch_w(bases, exps, mods, outs, rows, L, EL, 4);
}

// ---------------------------------------------------------------------------
// Secret-CRT leg batch: the prover-owned-modulus engine's half-width
// modexp legs (backend/crt.py). Rows are the p/q legs of CRT-decomposed
// exponentiations — base and exponent already reduced by the Python
// planner (base mod p*r, exponent mod lcm(p-1, r-1) with r the fresh
// 64-bit fault-check prime), so every operand here is SECRET-DERIVED:
// the modulus itself contains a factor of the prover's key. Semantics
// are row-wise modexp exactly like fsdkr_modexp_batch_w, with one
// difference exploited by the planner's row layout: Montgomery
// constants (the ~60-montmul doubling ladder of mont_constants) are
// computed once per RUN of equal consecutive moduli instead of once per
// row — CRT legs arrive grouped per context (a correct-key proof
// submits `rounds` consecutive rows mod the same p*r), so constants
// amortize over each group. Thread-chunk boundaries recompute the run
// constants at their first row, so the split is bit-identical to the
// serial loop. Constants are wiped at every run boundary (they
// reconstruct the secret leg modulus via gcd(R - one_m, R^2 - r2)).

int fsdkr_crt_modexp_batch(const u64 *bases, const u64 *exps, const u64 *mods,
                           u64 *outs, int rows, int L, int EL, int wbits) {
  if (L <= 0 || L > MAXL || EL <= 0 || rows <= 0 || wbits < 1 || wbits > 6)
    return -1;
  for (int r = 0; r < rows; r++)
    if (!(mods[(size_t)r * L] & 1))
      return -1;
  std::atomic<int> rc{0};
  parallel_rows(rows, [&](int lo, int hi) {
    u64 one_m[MAXL], r2[MAXL];
    const u64 *cur_n = nullptr;
    u64 n0inv = 0;
    for (int i = lo; i < hi; i++) {
      if (rc.load(std::memory_order_relaxed) != 0)
        break;
      const u64 *n = mods + (size_t)i * L;
      if (cur_n == nullptr || std::memcmp(n, cur_n, sizeof(u64) * L) != 0) {
        if (cur_n != nullptr) { // run boundary: old constants are secret
          secure_wipe(one_m, L);
          secure_wipe(r2, L);
        }
        n0inv = mont_n0inv(n[0]);
        mont_constants(n, L, one_m, r2);
        cur_n = n;
      }
      int r = modexp_core(bases + (size_t)i * L, exps + (size_t)i * EL, n,
                          n0inv, one_m, r2, outs + (size_t)i * L, L, EL,
                          wbits);
      if (r != 0)
        rc.store(r, std::memory_order_relaxed);
    }
    secure_wipe(one_m, MAXL);
    secure_wipe(r2, MAXL);
  });
  return rc.load();
}

// ---------------------------------------------------------------------------
// Row-parallel Miller-Rabin batch: the prime-generation shape (many
// candidates, each with its own CSPRNG witnesses) — candidates split
// across the FSDKR_THREADS row pool, rounds run serially per candidate
// with composite short-circuit. verdicts[i]: 1 probable prime, 0
// composite. The single-candidate entry point (fsdkr_miller_rabin,
// round-parallel) stays for the confirmation call on one candidate;
// this one kills the per-candidate bridge overhead of the generation
// loop (one staging + one native call for a whole sieve window).

static int mr_test_row(const u64 *n, int L, const u64 *wits, int rounds) {
  if (!(n[0] & 1))
    return -1;
  // n == 1 would make d = n-1 = 0 and spin the shift loop below forever;
  // the ABI entry validates nothing beyond oddness, so guard here
  bool gt_one = n[0] > 1;
  for (int i = 1; i < L && !gt_one; i++)
    gt_one = n[i] != 0;
  if (!gt_one)
    return -1;
  const u64 n0inv = mont_n0inv(n[0]);
  u64 one_m[MAXL], r2[MAXL];
  mont_constants(n, L, one_m, r2);

  u64 n1[MAXL], d[MAXL], onev[MAXL];
  std::memset(onev, 0, sizeof(u64) * L);
  onev[0] = 1;
  sub_limbs(n1, n, onev, L);
  std::memcpy(d, n1, sizeof(u64) * L);
  int r = 0;
  while (!(d[0] & 1)) {
    for (int i = 0; i < L - 1; i++)
      d[i] = (d[i] >> 1) | (d[i + 1] << 63);
    d[L - 1] >>= 1;
    r++;
  }
  u64 n1_m[MAXL];
  mont_mul(n1_m, n1, r2, n, n0inv, L);

  int top_bit = -1;
  for (int i = L - 1; i >= 0 && top_bit < 0; i--)
    if (d[i])
      for (int bit = 63; bit >= 0; bit--)
        if ((d[i] >> bit) & 1) {
          top_bit = i * 64 + bit;
          break;
        }

  bool composite = false;
  u64 a_m[MAXL], ared[MAXL], x[MAXL];
  for (int round = 0; round < rounds && !composite; round++) {
    const u64 *a = wits + (size_t)round * L;
    std::memcpy(ared, a, sizeof(u64) * L);
    while (cmp_limbs(ared, n, L) >= 0)
      sub_limbs(ared, ared, n, L);
    mont_mul(a_m, ared, r2, n, n0inv, L);
    std::memcpy(x, one_m, sizeof(u64) * L);
    for (int bit = top_bit; bit >= 0; bit--) {
      mont_sqr(x, x, n, n0inv, L);
      if ((d[bit / 64] >> (bit % 64)) & 1)
        mont_mul(x, x, a_m, n, n0inv, L);
    }
    if (cmp_limbs(x, one_m, L) == 0 || cmp_limbs(x, n1_m, L) == 0)
      continue;
    bool witness = true;
    for (int i = 0; i < r - 1; i++) {
      mont_sqr(x, x, n, n0inv, L);
      if (cmp_limbs(x, n1_m, L) == 0) {
        witness = false;
        break;
      }
    }
    if (witness)
      composite = true;
  }
  // every temporary derives from the secret prime candidate
  secure_wipe(x, MAXL);
  secure_wipe(a_m, MAXL);
  secure_wipe(ared, MAXL);
  secure_wipe(d, L);
  secure_wipe(n1, L);
  secure_wipe(n1_m, L);
  secure_wipe(one_m, L);
  secure_wipe(r2, L);
  return composite ? 0 : 1;
}

int fsdkr_miller_rabin_batch(const u64 *ns, const u64 *witnesses,
                             int *verdicts, int rows, int L, int rounds) {
  if (L <= 0 || L > MAXL || rows <= 0 || rounds <= 0)
    return -1;
  std::atomic<int> rc{0};
  parallel_rows(rows, [&](int lo, int hi) {
    for (int i = lo; i < hi; i++) {
      if (rc.load(std::memory_order_relaxed) != 0)
        return;
      int v = mr_test_row(ns + (size_t)i * L,
                          L, witnesses + (size_t)i * rounds * L, rounds);
      if (v < 0)
        rc.store(-1, std::memory_order_relaxed);
      else
        verdicts[i] = v;
    }
  });
  return rc.load();
}

// Fixed-base comb: out[m] = base^exps[m] mod n for M exponents sharing
// one (base, modulus) — the dominant column shape of the O(n^2) verify
// loop (every receiver checks the same sender's h1/h2/T bases;
// reference loop: src/refresh_message.rs:330-365). Per wbits-wide window
// position w the 2^wbits-entry table holds (base^((2^wbits)^w))^d, so
// each row costs only ~ebits/wbits multiplies and the squaring ladder is
// paid once in the precompute, amortized over M. The window width is a
// caller choice: wider windows cut the per-row multiplies ~linearly but
// grow the per-group table build by 2^wbits, so the bridge picks wbits
// by rows-per-group (w=6 beats w=4 by ~22% at the ring-Pedersen M=256
// shape; w=4 stays optimal for the n-row pair groups).
// Comb geometry validation shared by precompute/apply/one-shot.
// EL is capped: verify-side exponents are adversary-supplied proof
// integers, and the comb table is (64 EL / wbits)*2^wbits*L words — an
// unbounded EL would let one malicious proof force a huge (or throwing)
// allocation where the generic kernel merely computes slowly. 2*MAXL
// limbs = 8192 bits covers every protocol exponent incl. range slack.
static int comb_windows(int L, int EL, int wbits, const u64 *n) {
  if (L <= 0 || L > MAXL || EL <= 0 || EL > 2 * MAXL || wbits < 1 ||
      wbits > 8 || !(n[0] & 1))
    return -1;
  return (EL * 64 + wbits - 1) / wbits;
}

// Words needed for a comb window table of this geometry (Python sizes
// the cacheable buffer with this; -1 on bad geometry). Fits int: the
// EL/wbits caps bound the table at (8192/8)*2^8*64 < 2^25 words.
int fsdkr_comb_table_words(int L, int EL, int wbits) {
  u64 odd = 1;
  int W = comb_windows(L, EL, wbits, &odd);
  if (W < 0)
    return -1;
  return W * (1 << wbits) * L;
}

// Build the comb window table for one (base, modulus) into a
// caller-owned buffer of fsdkr_comb_table_words words: per window w the
// 2^wbits entries (base^((2^wbits)^w))^d in Montgomery form. The table
// derives ONLY from (base, modulus, geometry) — no exponent ever enters
// it — so callers may cache it across calls for PUBLIC bases/moduli
// (ring-Pedersen h1/h2/T); secret-base callers must stay on the
// one-shot fsdkr_modexp_shared_w, which wipes the table before free.
int fsdkr_comb_precompute(const u64 *base, const u64 *n, u64 *table, int L,
                          int EL, int wbits) {
  const int W = comb_windows(L, EL, wbits, n);
  if (W < 0)
    return -1;
  const int D = 1 << wbits;
  const u64 n0inv = mont_n0inv(n[0]);
  u64 one_m[MAXL], r2[MAXL];
  mont_constants(n, L, one_m, r2);

  u64 b[MAXL];
  std::memcpy(b, base, sizeof(u64) * L);
  while (cmp_limbs(b, n, L) >= 0)
    sub_limbs(b, b, n, L);

  auto T = [&](int w, int d) { return table + ((size_t)w * D + d) * L; };
  u64 pw[MAXL];  // base^((2^wbits)^w) in Montgomery form
  mont_mul(pw, b, r2, n, n0inv, L);
  for (int w = 0; w < W; w++) {
    std::memcpy(T(w, 0), one_m, sizeof(u64) * L);
    std::memcpy(T(w, 1), pw, sizeof(u64) * L);
    for (int d = 2; d < D; d++) {
      if (d % 2 == 0)
        mont_sqr(T(w, d), T(w, d / 2), n, n0inv, L);
      else
        mont_mul(T(w, d), T(w, d - 1), pw, n, n0inv, L);
    }
    if (w + 1 < W)  // pw <- pw^(2^wbits) = (pw^(2^(wbits-1)))^2
      mont_sqr(pw, T(w, D / 2), n, n0inv, L);
  }
  secure_wipe(b, L);
  secure_wipe(pw, L);
  secure_wipe(one_m, L);
  secure_wipe(r2, L);
  return 0;
}

// Run M rows against a prebuilt comb table (fsdkr_comb_precompute with
// the same geometry). Rows are independent and split across threads.
int fsdkr_comb_apply(const u64 *table, const u64 *exps, const u64 *n,
                     u64 *outs, int M, int L, int EL, int wbits) {
  const int W = comb_windows(L, EL, wbits, n);
  if (W < 0 || M <= 0)
    return -1;
  const int D = 1 << wbits;
  const u64 n0inv = mont_n0inv(n[0]);
  const u64 *one_m = table;  // T(0, 0) is the Montgomery one
  auto T = [&](int w, int d) { return table + ((size_t)w * D + d) * L; };
  const u64 mask = (u64)D - 1;
  parallel_rows(M, [&](int lo, int hi) {
    u64 acc[MAXL];
    u64 onev[MAXL];
    std::memset(onev, 0, sizeof(u64) * MAXL);
    onev[0] = 1;
    for (int m = lo; m < hi; m++) {
      const u64 *e = exps + (size_t)m * EL;
      std::memcpy(acc, one_m, sizeof(u64) * L);
      // one multiply per window unconditionally (d == 0 hits the one_m
      // entry): prover-side exponents are secret key shares and nonces,
      // and a zero-digit skip would make wall time a function of their
      // contents — the generic kernel is uniform per window for the
      // same reason
      for (int w = 0; w < W; w++) {
        int bit0 = w * wbits;  // windows may straddle a 64-bit limb
        u64 d = e[bit0 / 64] >> (bit0 % 64);
        if (bit0 % 64 + wbits > 64 && bit0 / 64 + 1 < EL)
          d |= e[bit0 / 64 + 1] << (64 - bit0 % 64);
        d &= mask;
        mont_mul(acc, acc, T(w, (int)d), n, n0inv, L);
      }
      mont_mul(outs + (size_t)m * L, acc, onev, n, n0inv, L);
    }
    secure_wipe(acc, MAXL);  // exponent-derived accumulator state
  });
  return 0;
}

int fsdkr_modexp_shared_w(const u64 *base, const u64 *exps, const u64 *n,
                          u64 *outs, int M, int L, int EL, int wbits) {
  const int W = comb_windows(L, EL, wbits, n);
  if (W < 0 || M <= 0)
    return -1;
  const int D = 1 << wbits;
  u64 *table = new (std::nothrow) u64[(size_t)W * D * L];
  if (!table)
    return -1;
  int rc = fsdkr_comb_precompute(base, n, table, L, EL, wbits);
  if (rc == 0)
    rc = fsdkr_comb_apply(table, exps, n, outs, M, L, EL, wbits);
  // same wipe discipline as fsdkr_modexp: the table can reconstruct
  // base/modulus state (secret on prover-side uses of this one-shot)
  secure_wipe(table, W * D * L);
  delete[] table;
  return rc;
}

// ABI-stable 4-bit-window entry point (older bridges / capture tooling)
int fsdkr_modexp_shared(const u64 *base, const u64 *exps, const u64 *n,
                        u64 *outs, int M, int L, int EL) {
  return fsdkr_modexp_shared_w(base, exps, n, outs, M, L, EL, 4);
}

// ---------------------------------------------------------------------------
// Digit extraction on a fixed wbits grid from little-endian limbs
// (windows may straddle a 64-bit limb).

static inline u64 exp_digit(const u64 *e, int EL, int w, int wbits) {
  long bit0 = (long)w * wbits;
  int li = (int)(bit0 / 64), sh = (int)(bit0 % 64);
  if (li >= EL)
    return 0;
  u64 d = e[li] >> sh;
  if (sh + wbits > 64 && li + 1 < EL)
    d |= e[li + 1] << (64 - sh);
  return d & (((u64)1 << wbits) - 1);
}

// ---------------------------------------------------------------------------
// Shared-exponent ladder: outs[r] = bases[r]^exp * aux_bases[r]^aux_exps[r]
// mod n — the Alice-range u-power column shape (src/range_proofs.rs:141-148):
// every row of a receiver's s^n column carries the SAME public exponent
// (the receiver's Paillier modulus n) over the SAME modulus n^2, with an
// optional per-row short second term (c^{-e}, the 256-bit challenge power)
// riding the same squaring chain Straus-style. ONE sliding-window
// schedule is derived from the shared exponent — per-bit squarings with
// odd-digit multiplies at the precomputed window ends — and replayed for
// every row; the aux term fires at fixed 4-bit grid positions of the
// same per-bit chain (both terms' multiplies commute at a given bit
// position, so the interleave is exact). Rows split across the
// FSDKR_THREADS pool (independent per-row state -> bit-identical at any
// thread count).
//
// Cost per row: ~top_bit squarings + ~top_bit/(wbits+1) odd-window
// multiplies + 2^(wbits-1) odd-power table builds (+ 64 aux lookups and
// a 14-multiply aux table when the aux term is present) — against TWO
// independent full ladders for the split columns, and the
// schedule/constants are amortized batch-wide. Zero-digit skipping and
// the sliding schedule are data-dependent by design: this is a VERIFIER
// engine over public wire integers and the public modulus (see
// SECURITY.md "Range-opt verifier engines"); secret exponents must keep
// to the uniform-schedule kernels (modexp_core / fsdkr_comb_apply).
//
// aux_bases/aux_exps may be NULL (AEL = 0): plain shared-exponent batch.
// Callers stage bases/aux_bases already reduced below n.

static inline int exp_bit(const u64 *e, int EL, int b) {
  return b >= 0 && b < EL * 64 ? (int)((e[b / 64] >> (b % 64)) & 1) : 0;
}

int fsdkr_shared_exp_powm(const u64 *bases, const u64 *exp, const u64 *n,
                          const u64 *aux_bases, const u64 *aux_exps,
                          u64 *outs, int rows, int L, int EL, int AEL,
                          int wbits) {
  if (L <= 0 || L > MAXL || EL <= 0 || EL > 2 * MAXL || AEL < 0 ||
      AEL > 2 * MAXL || rows <= 0 || wbits < 1 || wbits > 8 || !(n[0] & 1))
    return -1;
  const bool aux = aux_bases != nullptr && aux_exps != nullptr && AEL > 0;
  const int D2 = 1 << (wbits - 1); // odd-power main table entries

  // shared sliding-window schedule: main_at[b] = odd digit whose window
  // ENDS at bit b (0 = no multiply here), windows never wider than wbits
  int top_bit = -1;
  for (int i = EL - 1; i >= 0 && top_bit < 0; i--)
    if (exp[i])
      for (int bit = 63; bit >= 0; bit--)
        if ((exp[i] >> bit) & 1) {
          top_bit = i * 64 + bit;
          break;
        }
  const int aux_bits = aux ? AEL * 64 : 0;
  const int H = top_bit > aux_bits - 1 ? top_bit : aux_bits - 1;
  std::vector<u64> main_at(top_bit + 1 > 0 ? top_bit + 1 : 0, 0);
  for (int b = top_bit; b >= 0;) {
    if (!exp_bit(exp, EL, b)) {
      b--;
      continue;
    }
    int j = b - wbits + 1;
    if (j < 0)
      j = 0;
    while (!exp_bit(exp, EL, j))
      j++; // window ends on a set bit -> odd digit
    u64 d = 0;
    for (int k = b; k >= j; k--)
      d = (d << 1) | (u64)exp_bit(exp, EL, k);
    main_at[j] = d;
    b = j - 1;
  }

  const u64 n0inv = mont_n0inv(n[0]);
  u64 one_m[MAXL], r2[MAXL];
  mont_constants(n, L, one_m, r2);
  if (H < 0) { // exp == 0 and no aux: every row is 1
    for (int r = 0; r < rows; r++) {
      std::memset(outs + (size_t)r * L, 0, sizeof(u64) * L);
      outs[(size_t)r * L] = 1;
    }
    return 0;
  }

  std::atomic<int> rc{0};
  parallel_rows(rows, [&](int lo, int hi) {
    // T_odd[k] = base^(2k+1); A[d] = aux_base^d (4-bit grid, both
    // parities — aux digits are per-row data, the table build is 14
    // multiplies against 64 lookups)
    u64 *table = new (std::nothrow) u64[((size_t)D2 + (aux ? 16 : 0)) * MAXL];
    if (!table) {
      rc.store(-1, std::memory_order_relaxed);
      return;
    }
    u64 *atab = table + (size_t)D2 * MAXL;
    auto T = [&](int k) { return table + (size_t)k * MAXL; };
    auto A = [&](int d) { return atab + (size_t)d * MAXL; };
    u64 b[MAXL], base_m[MAXL], base2[MAXL], acc[MAXL], onev[MAXL];
    std::memset(onev, 0, sizeof(u64) * MAXL);
    onev[0] = 1;
    for (int r = lo; r < hi; r++) {
      // main-term odd-power table (base already reduced by the bridge)
      std::memcpy(b, bases + (size_t)r * L, sizeof(u64) * L);
      while (cmp_limbs(b, n, L) >= 0)
        sub_limbs(b, b, n, L);
      mont_mul(base_m, b, r2, n, n0inv, L);
      std::memcpy(T(0), base_m, sizeof(u64) * L);
      if (D2 > 1) {
        mont_sqr(base2, base_m, n, n0inv, L);
        for (int k = 1; k < D2; k++)
          mont_mul(T(k), T(k - 1), base2, n, n0inv, L);
      }
      const u64 *ae = aux ? aux_exps + (size_t)r * AEL : nullptr;
      bool has_aux = false;
      if (aux) {
        for (int i = 0; i < AEL && !has_aux; i++)
          has_aux = ae[i] != 0;
        if (has_aux) {
          std::memcpy(b, aux_bases + (size_t)r * L, sizeof(u64) * L);
          while (cmp_limbs(b, n, L) >= 0)
            sub_limbs(b, b, n, L);
          mont_mul(base_m, b, r2, n, n0inv, L);
          std::memcpy(A(0), one_m, sizeof(u64) * L);
          std::memcpy(A(1), base_m, sizeof(u64) * L);
          for (int d = 2; d < 16; d++) {
            if (d % 2 == 0)
              mont_sqr(A(d), A(d / 2), n, n0inv, L);
            else
              mont_mul(A(d), A(d - 1), base_m, n, n0inv, L);
          }
        }
      }
      // per-bit chain: squarings every bit, main multiply where a
      // window ends, aux multiply at 4-aligned positions — same-bit
      // multiplies commute, so the interleave equals the two ladders
      bool started = false;
      for (int bi = H; bi >= 0; bi--) {
        if (started)
          mont_sqr(acc, acc, n, n0inv, L);
        const u64 dm = bi <= top_bit ? main_at[bi] : 0;
        if (dm) {
          if (!started) {
            std::memcpy(acc, T((int)(dm >> 1)), sizeof(u64) * L);
            started = true;
          } else
            mont_mul(acc, acc, T((int)(dm >> 1)), n, n0inv, L);
        }
        if (has_aux && (bi & 3) == 0 && bi < aux_bits) {
          const u64 da = exp_digit(ae, AEL, bi / 4, 4);
          if (da) {
            if (!started) {
              std::memcpy(acc, A((int)da), sizeof(u64) * L);
              started = true;
            } else
              mont_mul(acc, acc, A((int)da), n, n0inv, L);
          }
        }
      }
      if (!started)
        std::memcpy(acc, one_m, sizeof(u64) * L);
      mont_mul(outs + (size_t)r * L, acc, onev, n, n0inv, L);
    }
    secure_wipe(acc, MAXL); // consistency with the other frames; all
    secure_wipe(b, MAXL);   // operands here are public wire data
    secure_wipe(base_m, MAXL);
    secure_wipe(base2, MAXL);
    secure_wipe(table, (D2 + (aux ? 16 : 0)) * MAXL);
    delete[] table;
  });
  return rc.load();
}

// ---------------------------------------------------------------------------
// Fused two-table comb apply: outs[m] = T1-base^exps1[m] * T2-base^exps2[m]
// mod n — the h1^s1 * h2^s2 mod N~ shape of the range/PDL mod-N~ equations
// (src/range_proofs.rs:133-139), as ONE pass per row over BOTH persistent
// window tables (fsdkr_comb_precompute geometry, cached cross-epoch in the
// Python LRU for public bases) with a single Montgomery exit — eliminating
// the separate columns and the recombination modmul. Tables may carry
// different geometries (EL, wbits). Zero digits skip (public wire
// exponents; see fsdkr_shared_exp_powm's note). Rows split across the
// FSDKR_THREADS pool.

int fsdkr_comb2_apply(const u64 *table1, const u64 *exps1, int EL1, int w1,
                      const u64 *table2, const u64 *exps2, int EL2, int w2,
                      const u64 *n, u64 *outs, int M, int L) {
  const int W1 = comb_windows(L, EL1, w1, n);
  const int W2 = comb_windows(L, EL2, w2, n);
  if (W1 < 0 || W2 < 0 || M <= 0)
    return -1;
  const int D1 = 1 << w1, D2 = 1 << w2;
  const u64 n0inv = mont_n0inv(n[0]);
  const u64 *one_m = table1; // T(0, 0) is the Montgomery one
  auto T1 = [&](int w, int d) { return table1 + ((size_t)w * D1 + d) * L; };
  auto T2 = [&](int w, int d) { return table2 + ((size_t)w * D2 + d) * L; };
  parallel_rows(M, [&](int lo, int hi) {
    u64 acc[MAXL], onev[MAXL];
    std::memset(onev, 0, sizeof(u64) * MAXL);
    onev[0] = 1;
    for (int m = lo; m < hi; m++) {
      const u64 *e1 = exps1 + (size_t)m * EL1;
      const u64 *e2 = exps2 + (size_t)m * EL2;
      std::memcpy(acc, one_m, sizeof(u64) * L);
      for (int w = 0; w < W1; w++) {
        u64 d = exp_digit(e1, EL1, w, w1);
        if (d)
          mont_mul(acc, acc, T1(w, (int)d), n, n0inv, L);
      }
      for (int w = 0; w < W2; w++) {
        u64 d = exp_digit(e2, EL2, w, w2);
        if (d)
          mont_mul(acc, acc, T2(w, (int)d), n, n0inv, L);
      }
      mont_mul(outs + (size_t)m * L, acc, onev, n, n0inv, L);
    }
    secure_wipe(acc, MAXL);
  });
  return 0;
}

// ---------------------------------------------------------------------------
// Joint (Straus/Shamir) multi-exponentiation: rows of k terms sharing one
// modulus per row,
//
//   outs[r] = prod_t bases[r*k+t] ^ exps[r*k+t]  mod mods[r].
//
// One interleaved windowed ladder per row: the squaring chain — the
// dominant cost of a full-width modexp — is paid ONCE for the whole
// product instead of once per term, and each wbits-wide window costs one
// table multiply per *active* term. ebits[t] (k entries, launch-wide)
// caps term t's window count: widths are column-level shape information
// (bucketed by the caller from public wire-domain bounds), so the
// schedule is data-independent — every row performs the identical
// multiply sequence, and a zero window digit multiplies by the
// Montgomery one (constant cost), same discipline as the comb kernel.
//
// Layout: bases rows*k*L, exps rows*k*EL (uniform EL, little-endian),
// mods/outs rows*L. k <= MAXK; EL capped like the comb (adversarial
// widths are gated upstream; this is the allocation backstop).
//
// k is NOT limited to a handful of terms: the RLC aggregated groups
// (backend.rlc) submit n-term rows — one 128-384-bit exponent per
// folded proof row plus the merged shared-base terms — so the per-term
// window tables live on the heap (k * 2^wbits * L words, ~1 MB per
// thread at the n=256 ring-Pedersen shape) and MAXK is only the
// allocation backstop against adversarially huge launches.

static const int MAXK = 4096;

int fsdkr_multi_modexp_batch(const u64 *bases, const u64 *exps,
                             const u64 *mods, u64 *outs, const int *ebits,
                             int rows, int k, int L, int EL, int wbits) {
  if (L <= 0 || L > MAXL || EL <= 0 || EL > 2 * MAXL || rows <= 0 ||
      k <= 0 || k > MAXK || wbits < 1 || wbits > 6)
    return -1;
  const int D = 1 << wbits;
  int W = 0;                // shared chain depth: max window count over terms
  std::vector<int> Wt(k);   // per-term window counts (k is runtime-sized)
  for (int t = 0; t < k; t++) {
    if (ebits[t] <= 0 || ebits[t] > EL * 64)
      return -1;
    Wt[t] = (ebits[t] + wbits - 1) / wbits;
    if (Wt[t] > W)
      W = Wt[t];
  }
  for (int r = 0; r < rows; r++)
    if (!(mods[(size_t)r * L] & 1))
      return -1;

  // Rows split across threads; each thread owns a private per-term table
  // allocation and temporaries, so the per-row work is byte-identical to
  // the serial loop. A failed allocation on any thread fails the batch.
  std::atomic<int> rc{0};
  parallel_rows(rows, [&](int lo, int hi) {
    u64 *table = new (std::nothrow) u64[(size_t)k * D * L];
    if (!table) {
      rc.store(-1, std::memory_order_relaxed);
      return;
    }
    auto T = [&](int t, int d) { return table + ((size_t)t * D + d) * L; };

    u64 one_m[MAXL], r2[MAXL], b[MAXL], base_m[MAXL], acc[MAXL], onev[MAXL];
    std::memset(onev, 0, sizeof(u64) * MAXL);
    onev[0] = 1;
    for (int r = lo; r < hi; r++) {
      if (rc.load(std::memory_order_relaxed) != 0)
        break;
      const u64 *n = mods + (size_t)r * L;
      const u64 n0inv = mont_n0inv(n[0]);
      mont_constants(n, L, one_m, r2);

      for (int t = 0; t < k; t++) {
        std::memcpy(b, bases + ((size_t)r * k + t) * L, sizeof(u64) * L);
        while (cmp_limbs(b, n, L) >= 0)
          sub_limbs(b, b, n, L);
        mont_mul(base_m, b, r2, n, n0inv, L);
        std::memcpy(T(t, 0), one_m, sizeof(u64) * L);
        std::memcpy(T(t, 1), base_m, sizeof(u64) * L);
        for (int d = 2; d < D; d++) {
          if (d % 2 == 0)
            mont_sqr(T(t, d), T(t, d / 2), n, n0inv, L);
          else
            mont_mul(T(t, d), T(t, d - 1), base_m, n, n0inv, L);
        }
      }

      const u64 mask = (u64)D - 1;
      std::memcpy(acc, one_m, sizeof(u64) * L);
      for (int w = W - 1; w >= 0; w--) {
        if (w != W - 1) // acc is still one at the top window
          for (int s = 0; s < wbits; s++)
            mont_sqr(acc, acc, n, n0inv, L);
        for (int t = 0; t < k; t++) {
          if (w >= Wt[t])
            continue; // static per-launch schedule (ebits), not data
          const u64 *e = exps + ((size_t)r * k + t) * EL;
          int bit0 = w * wbits; // windows may straddle a 64-bit limb
          u64 d = e[bit0 / 64] >> (bit0 % 64);
          if (bit0 % 64 + wbits > 64 && bit0 / 64 + 1 < EL)
            d |= e[bit0 / 64 + 1] << (64 - bit0 % 64);
          d &= mask;
          mont_mul(acc, acc, T(t, (int)d), n, n0inv, L);
        }
      }
      mont_mul(outs + (size_t)r * L, acc, onev, n, n0inv, L);
    }

    secure_wipe(table, k * D * L);
    delete[] table;
    secure_wipe(b, MAXL);
    secure_wipe(base_m, MAXL);
    secure_wipe(acc, MAXL);
    secure_wipe(one_m, MAXL); // one_m/r2 reconstruct the modulus
    secure_wipe(r2, MAXL);
  });
  return rc.load();
}

// ---------------------------------------------------------------------------
// Batched modular multiplication: outs[r] = a[r] * b[r] mod mods[r].
// Two Montgomery products per row (enter with a*R^2, exit against b),
// with the expensive mont_constants computed once per RUN of equal
// consecutive moduli — the Python bridge sorts rows by modulus, and the
// collect() recombination columns carry at most one modulus per
// receiver, so constants amortize over the receiver's whole row group.
// Rows split across threads (each thread rebuilds constants at its
// chunk's first row, so chunk boundaries cannot change any row's math).

int fsdkr_modmul_batch(const u64 *a, const u64 *b, const u64 *mods,
                       u64 *outs, int rows, int L) {
  if (L <= 0 || L > MAXL || rows <= 0)
    return -1;
  for (int r = 0; r < rows; r++)
    if (!(mods[(size_t)r * L] & 1))
      return -1;
  parallel_rows(rows, [&](int lo, int hi) {
    u64 one_m[MAXL], r2[MAXL], ar[MAXL], br[MAXL], a_m[MAXL];
    const u64 *cur_n = nullptr;
    u64 n0inv = 0;
    for (int r = lo; r < hi; r++) {
      const u64 *n = mods + (size_t)r * L;
      if (cur_n == nullptr || std::memcmp(n, cur_n, sizeof(u64) * L) != 0) {
        n0inv = mont_n0inv(n[0]);
        mont_constants(n, L, one_m, r2);
        cur_n = n;
      }
      std::memcpy(ar, a + (size_t)r * L, sizeof(u64) * L);
      while (cmp_limbs(ar, n, L) >= 0)
        sub_limbs(ar, ar, n, L);
      std::memcpy(br, b + (size_t)r * L, sizeof(u64) * L);
      while (cmp_limbs(br, n, L) >= 0)
        sub_limbs(br, br, n, L);
      mont_mul(a_m, ar, r2, n, n0inv, L);  // a*R mod n
      mont_mul(outs + (size_t)r * L, a_m, br, n, n0inv, L);  // a*b mod n
    }
    // operands can be secret (share recombination factors); same wipe
    // discipline as the modexp frames
    secure_wipe(ar, MAXL);
    secure_wipe(br, MAXL);
    secure_wipe(a_m, MAXL);
    secure_wipe(one_m, MAXL);
    secure_wipe(r2, MAXL);
  });
  return 0;
}

// ---------------------------------------------------------------------------
// Batch limb pack/unpack for the device staging path (ops/limbs.py).
// The kernels' host staging is bigint -> LE bytes -> uint16 limbs ->
// uint32 lanes; the widen/narrow passes below replace two numpy passes
// (astype + canonicality check) with one threaded pass each, so tile
// staging overlaps engine execution on spare cores.

// u16 -> u32 widen, threaded. count = total limbs.
int fsdkr_limbs_widen_u16(const uint16_t *in, uint32_t *out,
                          long long count) {
  if (count < 0)
    return -1;
  const long long CHUNK = 1 << 20;
  int chunks = (int)((count + CHUNK - 1) / CHUNK);
  if (chunks <= 0)
    return 0;
  parallel_rows(chunks, [&](int lo, int hi) {
    for (long long i = (long long)lo * CHUNK;
         i < (long long)hi * CHUNK && i < count; i++)
      out[i] = in[i];
  });
  return 0;
}

// u32 -> u16 narrow with a fused canonicality check: any limb with high
// bits set (a pending carry — a kernel bug, never valid data) fails the
// whole batch with -2, matching limbs_to_ints' ValueError.
int fsdkr_limbs_narrow_u16(const uint32_t *in, uint16_t *out,
                           long long count) {
  if (count < 0)
    return -1;
  const long long CHUNK = 1 << 20;
  int chunks = (int)((count + CHUNK - 1) / CHUNK);
  if (chunks <= 0)
    return 0;
  std::atomic<int> rc{0};
  parallel_rows(chunks, [&](int lo, int hi) {
    uint32_t pending = 0;
    for (long long i = (long long)lo * CHUNK;
         i < (long long)hi * CHUNK && i < count; i++) {
      pending |= in[i] >> 16;
      out[i] = (uint16_t)in[i];
    }
    if (pending)
      rc.store(-2, std::memory_order_relaxed);
  });
  return rc.load();
}

} // extern "C"
