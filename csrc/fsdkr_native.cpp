// Native host bignum core for fsdkr_tpu.
//
// The reference's host-serial native layer is GMP (C) underneath
// curv/kzen-paillier — e.g. the 2048-bit Paillier keygen at
// /root/reference/src/refresh_message.rs:118 and the ring-Pedersen setup at
// src/ring_pedersen_proof.rs:48-74 are GMP prime generation and modexp.
// This file is the rebuild's equivalent: fixed-width Montgomery arithmetic
// over 64-bit limbs (unsigned __int128 partial products), exposed as a
// plain C ABI loaded from Python via ctypes (no pybind11 in this
// environment). It serves the host-serial paths the TPU cannot batch:
// Miller-Rabin prime generation, the comb kernel's host power ladder, and
// the host-backend oracle's modular exponentiation.
//
// All numbers are little-endian uint64 limb arrays of a caller-chosen
// width; moduli must be odd. Maximum width 64 limbs = 4096 bits (the
// protocol's widest modulus class, N^2 for 2048-bit Paillier N).

#include <cstdint>
#include <cstring>
#include <new>

typedef uint64_t u64;
typedef unsigned __int128 u128;

static const int MAXL = 64; // 4096 bits

extern "C" {

// ---------------------------------------------------------------------------
// limb helpers

// Volatile wipe that the optimizer cannot elide: secret-bearing limb
// buffers (exponents, secret-derived bases and their power tables, prime
// candidates) are zeroed before frames return — the native-side
// equivalent of the reference's zeroize discipline
// (/root/reference/src/refresh_message.rs:446-448).
static void secure_wipe(u64 *p, int L) {
  volatile u64 *vp = p;
  for (int i = 0; i < L; i++)
    vp[i] = 0;
}

static int cmp_limbs(const u64 *a, const u64 *b, int L) {
  for (int i = L - 1; i >= 0; i--) {
    if (a[i] < b[i])
      return -1;
    if (a[i] > b[i])
      return 1;
  }
  return 0;
}

static void sub_limbs(u64 *out, const u64 *a, const u64 *b, int L) {
  u64 borrow = 0;
  for (int i = 0; i < L; i++) {
    u64 bi = b[i] + borrow;
    u64 new_borrow = (bi < b[i]) || (a[i] < bi);
    out[i] = a[i] - bi;
    borrow = new_borrow;
  }
}

// -n^{-1} mod 2^64 by Newton iteration (n odd)
static u64 mont_n0inv(u64 n0) {
  u64 x = n0; // 3 correct bits
  for (int i = 0; i < 6; i++)
    x *= 2 - n0 * x; // doubles correct bits each round
  return (u64)0 - x;
}

// ---------------------------------------------------------------------------
// Montgomery CIOS multiplication: out = a * b * R^{-1} mod n, R = 2^(64 L)

static void mont_mul(u64 *out, const u64 *a, const u64 *b, const u64 *n,
                     u64 n0inv, int L) {
  u64 t[MAXL + 2];
  std::memset(t, 0, sizeof(u64) * (L + 2));
  for (int i = 0; i < L; i++) {
    u128 carry = 0;
    const u64 ai = a[i];
    for (int j = 0; j < L; j++) {
      u128 cur = (u128)ai * b[j] + t[j] + carry;
      t[j] = (u64)cur;
      carry = cur >> 64;
    }
    u128 cur = (u128)t[L] + carry;
    t[L] = (u64)cur;
    t[L + 1] += (u64)(cur >> 64);

    const u64 m = t[0] * n0inv;
    carry = ((u128)m * n[0] + t[0]) >> 64;
    for (int j = 1; j < L; j++) {
      u128 cur2 = (u128)m * n[j] + t[j] + carry;
      t[j - 1] = (u64)cur2;
      carry = cur2 >> 64;
    }
    cur = (u128)t[L] + carry;
    t[L - 1] = (u64)cur;
    t[L] = t[L + 1] + (u64)(cur >> 64);
    t[L + 1] = 0;
  }
  if (t[L] != 0 || cmp_limbs(t, n, L) >= 0)
    sub_limbs(out, t, n, L); // t < 2n always, one subtract suffices
  else
    std::memcpy(out, t, sizeof(u64) * L);
}

// ---------------------------------------------------------------------------
// Dedicated Montgomery squaring: out = a * a * R^{-1} mod n. SOS layout —
// the symmetric half of the schoolbook product is computed once and
// doubled (L(L+1)/2 limb products instead of L^2), then a separate
// Montgomery reduction pass (L^2 products) finishes. Measured 0.66x the
// general mont_mul at 64 limbs, 0.69x at 32, 0.76x at 24 on this class
// of host — and every modexp ladder is ~4 squarings per multiply, so the
// squaring chain is where modexp wall-clock actually lives.

static void mont_sqr(u64 *out, const u64 *a, const u64 *n, u64 n0inv, int L) {
  u64 t[2 * MAXL + 1];
  std::memset(t, 0, sizeof(u64) * (2 * L + 1));
  // cross products a_i * a_j (i < j), each summed once. t[i+L] is
  // provably still zero when row i deposits its final carry there (rows
  // i' < i only reach position i'+L < i+L), so no carry-out can wrap.
  for (int i = 0; i < L; i++) {
    u128 carry = 0;
    const u64 ai = a[i];
    for (int j = i + 1; j < L; j++) {
      u128 cur = (u128)ai * a[j] + t[i + j] + carry;
      t[i + j] = (u64)cur;
      carry = cur >> 64;
    }
    t[i + L] += (u64)carry;
  }
  // double the cross half, then add the diagonal a_i^2 terms
  {
    u64 c = 0;
    for (int i = 0; i < 2 * L; i++) {
      u64 hi = t[i] >> 63;
      t[i] = (t[i] << 1) | c;
      c = hi;
    }
    t[2 * L] = c;
  }
  {
    u128 carry = 0;
    for (int i = 0; i < L; i++) {
      u128 cur = (u128)a[i] * a[i] + t[2 * i] + carry;
      t[2 * i] = (u64)cur;
      carry = cur >> 64;
      cur = (u128)t[2 * i + 1] + carry;
      t[2 * i + 1] = (u64)cur;
      carry = cur >> 64;
    }
    t[2 * L] += (u64)carry;
  }
  // Montgomery reduction of the 2L-word square
  for (int i = 0; i < L; i++) {
    const u64 m = t[i] * n0inv;
    u128 carry = 0;
    for (int j = 0; j < L; j++) {
      u128 cur = (u128)m * n[j] + t[i + j] + carry;
      t[i + j] = (u64)cur;
      carry = cur >> 64;
    }
    for (int j = i + L; carry && j <= 2 * L; j++) {
      u128 cur = (u128)t[j] + carry;
      t[j] = (u64)cur;
      carry = cur >> 64;
    }
  }
  // result in t[L..2L]; t[2L] in {0,1} and the value is < 2n. The stack
  // temp is left to be overwritten by the next call, matching mont_mul:
  // the wipe discipline lives in the calling frames' persistent buffers.
  if (t[2 * L] != 0 || cmp_limbs(t + L, n, L) >= 0)
    sub_limbs(out, t + L, n, L);
  else
    std::memcpy(out, t + L, sizeof(u64) * L);
}

// R mod n and R^2 mod n by doubling (L <= MAXL)
static void mont_constants(const u64 *n, int L, u64 *r_mod, u64 *r2_mod) {
  // r_mod = R mod n: start from 2^(64L - 1) mod n (top bit), double once
  u64 acc[MAXL];
  std::memset(acc, 0, sizeof(u64) * L);
  // set acc = 1, then double 64*L times mod n
  acc[0] = 1;
  for (int bit = 0; bit < 64 * L; bit++) {
    // acc = 2*acc mod n
    u64 carry = 0;
    for (int i = 0; i < L; i++) {
      u64 hi = acc[i] >> 63;
      acc[i] = (acc[i] << 1) | carry;
      carry = hi;
    }
    if (carry || cmp_limbs(acc, n, L) >= 0)
      sub_limbs(acc, acc, n, L);
  }
  std::memcpy(r_mod, acc, sizeof(u64) * L);
  // r2_mod = R^2 mod n: double 64*L more times
  for (int bit = 0; bit < 64 * L; bit++) {
    u64 carry = 0;
    for (int i = 0; i < L; i++) {
      u64 hi = acc[i] >> 63;
      acc[i] = (acc[i] << 1) | carry;
      carry = hi;
    }
    if (carry || cmp_limbs(acc, n, L) >= 0)
      sub_limbs(acc, acc, n, L);
  }
  std::memcpy(r2_mod, acc, sizeof(u64) * L);
}

// ---------------------------------------------------------------------------
// modexp: out = base^exp mod n. n odd, L limbs; exp EL limbs.
// Fixed wbits-wide window (4..8, caller-chosen by exponent width: wider
// windows trade table-build multiplies for fewer per-window lookups, so
// w=6 wins for full-width exponents and w=4 for short ones), MSB-first.

int fsdkr_modexp_w(const u64 *base, const u64 *exp, const u64 *n, u64 *out,
                   int L, int EL, int wbits) {
  // wbits capped at 6: the 2^wbits-entry stack table is 32 KB there, and
  // the build-vs-lookup tradeoff already tips back past w=6 for every
  // protocol exponent width
  if (L <= 0 || L > MAXL || EL <= 0 || wbits < 1 || wbits > 6 ||
      !(n[0] & 1))
    return -1;

  const u64 n0inv = mont_n0inv(n[0]);
  u64 one_m[MAXL], r2[MAXL];
  mont_constants(n, L, one_m, r2);

  // reduce base below n (base < 2^(64L); subtract n a few times if needed —
  // callers pass base < n, this is just a guard)
  u64 b[MAXL];
  std::memcpy(b, base, sizeof(u64) * L);
  while (cmp_limbs(b, n, L) >= 0)
    sub_limbs(b, b, n, L);

  u64 base_m[MAXL];
  mont_mul(base_m, b, r2, n, n0inv, L);

  // window table: t[d] = base^d in Montgomery form (even entries are
  // squares of earlier entries — cheaper than a multiply)
  const int D = 1 << wbits;
  u64 table[64][MAXL];
  std::memcpy(table[0], one_m, sizeof(u64) * L);
  std::memcpy(table[1], base_m, sizeof(u64) * L);
  for (int d = 2; d < D; d++) {
    if (d % 2 == 0)
      mont_sqr(table[d], table[d / 2], n, n0inv, L);
    else
      mont_mul(table[d], table[d - 1], base_m, n, n0inv, L);
  }

  // top set window
  int top_bit = -1;
  for (int i = EL - 1; i >= 0 && top_bit < 0; i--)
    if (exp[i])
      for (int bit = 63; bit >= 0; bit--)
        if ((exp[i] >> bit) & 1) {
          top_bit = i * 64 + bit;
          break;
        }
  u64 acc[MAXL];
  if (top_bit < 0) { // exp == 0
    std::memcpy(out, one_m, sizeof(u64) * L);
    u64 onev[MAXL];
    std::memset(onev, 0, sizeof(u64) * L);
    onev[0] = 1;
    mont_mul(out, out, onev, n, n0inv, L); // leave Montgomery domain -> 1
    secure_wipe(b, L);
    secure_wipe(base_m, L);
    secure_wipe(&table[0][0], D * MAXL);
    // one_m/r2 reconstruct the modulus (secret on the Paillier-decrypt
    // path where n = p^2): gcd(R - one_m, R^2 - r2) recovers it
    secure_wipe(one_m, L);
    secure_wipe(r2, L);
    return 0;
  }

  int nwin = top_bit / wbits; // highest window index
  const u64 mask = (u64)D - 1;
  std::memcpy(acc, one_m, sizeof(u64) * L);
  for (int w = nwin; w >= 0; w--) {
    for (int s = 0; s < wbits; s++)
      mont_sqr(acc, acc, n, n0inv, L);
    int bit0 = w * wbits; // windows may straddle a 64-bit limb
    u64 d = exp[bit0 / 64] >> (bit0 % 64);
    if (bit0 % 64 + wbits > 64 && bit0 / 64 + 1 < EL)
      d |= exp[bit0 / 64 + 1] << (64 - bit0 % 64);
    d &= mask;
    mont_mul(acc, acc, table[d], n, n0inv, L);
  }

  u64 onev[MAXL];
  std::memset(onev, 0, sizeof(u64) * L);
  onev[0] = 1;
  mont_mul(out, acc, onev, n, n0inv, L);
  secure_wipe(b, L);
  secure_wipe(base_m, L);
  secure_wipe(&table[0][0], D * MAXL);
  secure_wipe(acc, L);
  secure_wipe(one_m, L); // see exp==0 branch: these reconstruct n
  secure_wipe(r2, L);
  return 0;
}

// ABI-stable 4-bit-window entry point
int fsdkr_modexp(const u64 *base, const u64 *exp, const u64 *n, u64 *out,
                 int L, int EL) {
  return fsdkr_modexp_w(base, exp, n, out, L, EL, 4);
}

// ---------------------------------------------------------------------------
// Miller-Rabin: 1 = probable prime, 0 = composite, -1 = bad input.
// Witness bases are caller-provided (sampled with a CSPRNG in Python) so
// the native side stays deterministic and testable.

int fsdkr_miller_rabin(const u64 *n, int L, const u64 *witnesses, int rounds) {
  if (L <= 0 || L > MAXL || !(n[0] & 1))
    return -1;

  const u64 n0inv = mont_n0inv(n[0]);
  u64 one_m[MAXL], r2[MAXL];
  mont_constants(n, L, one_m, r2);

  // n1 = n - 1 = 2^r * d
  u64 n1[MAXL], d[MAXL];
  u64 onev[MAXL];
  std::memset(onev, 0, sizeof(u64) * L);
  onev[0] = 1;
  sub_limbs(n1, n, onev, L);
  std::memcpy(d, n1, sizeof(u64) * L);
  int r = 0;
  while (!(d[0] & 1)) {
    for (int i = 0; i < L - 1; i++)
      d[i] = (d[i] >> 1) | (d[i + 1] << 63);
    d[L - 1] >>= 1;
    r++;
  }

  u64 n1_m[MAXL]; // n-1 in Montgomery form, for comparisons
  mont_mul(n1_m, n1, r2, n, n0inv, L);

  u64 a_m[MAXL];
  u64 ared[MAXL];
  u64 x[MAXL];
  for (int round = 0; round < rounds; round++) {
    const u64 *a = witnesses + (size_t)round * L;
    std::memcpy(ared, a, sizeof(u64) * L);
    while (cmp_limbs(ared, n, L) >= 0)
      sub_limbs(ared, ared, n, L);
    mont_mul(a_m, ared, r2, n, n0inv, L);

    // x = a^d mod n (Montgomery domain, square-and-multiply MSB-first)
    int top_bit = -1;
    for (int i = L - 1; i >= 0 && top_bit < 0; i--)
      if (d[i])
        for (int bit = 63; bit >= 0; bit--)
          if ((d[i] >> bit) & 1) {
            top_bit = i * 64 + bit;
            break;
          }
    std::memcpy(x, one_m, sizeof(u64) * L);
    for (int bit = top_bit; bit >= 0; bit--) {
      mont_sqr(x, x, n, n0inv, L);
      if ((d[bit / 64] >> (bit % 64)) & 1)
        mont_mul(x, x, a_m, n, n0inv, L);
    }

    if (cmp_limbs(x, one_m, L) == 0 || cmp_limbs(x, n1_m, L) == 0)
      continue;
    bool witness = true;
    for (int i = 0; i < r - 1; i++) {
      mont_sqr(x, x, n, n0inv, L);
      if (cmp_limbs(x, n1_m, L) == 0) {
        witness = false;
        break;
      }
    }
    if (witness) {
      secure_wipe(d, L);
      secure_wipe(n1, L);
      secure_wipe(n1_m, L);
      secure_wipe(x, L);
      secure_wipe(a_m, L);
      secure_wipe(ared, L);
      // one_m/r2 are R mod n and R^2 mod n with R public: n is
      // recoverable from either (gcd(R - one_m, R^2 - r2)), so they are
      // as secret as the prime candidate itself
      secure_wipe(one_m, L);
      secure_wipe(r2, L);
      return 0; // composite
    }
  }
  secure_wipe(d, L);
  secure_wipe(n1, L);
  secure_wipe(n1_m, L);
  secure_wipe(x, L);
  secure_wipe(a_m, L);
  secure_wipe(ared, L);
  secure_wipe(one_m, L);
  secure_wipe(r2, L);
  return 1; // probable prime
}

// Batched modexp over a column of rows (independent moduli): the host
// backend's powm shape. Returns 0 on success, -1 on any bad row input.
int fsdkr_modexp_batch_w(const u64 *bases, const u64 *exps, const u64 *mods,
                         u64 *outs, int rows, int L, int EL, int wbits) {
  for (int i = 0; i < rows; i++) {
    int rc = fsdkr_modexp_w(bases + (size_t)i * L, exps + (size_t)i * EL,
                            mods + (size_t)i * L, outs + (size_t)i * L, L,
                            EL, wbits);
    if (rc != 0)
      return rc;
  }
  return 0;
}

int fsdkr_modexp_batch(const u64 *bases, const u64 *exps, const u64 *mods,
                       u64 *outs, int rows, int L, int EL) {
  return fsdkr_modexp_batch_w(bases, exps, mods, outs, rows, L, EL, 4);
}

// Fixed-base comb: out[m] = base^exps[m] mod n for M exponents sharing
// one (base, modulus) — the dominant column shape of the O(n^2) verify
// loop (every receiver checks the same sender's h1/h2/T bases;
// reference loop: src/refresh_message.rs:330-365). Per wbits-wide window
// position w the 2^wbits-entry table holds (base^((2^wbits)^w))^d, so
// each row costs only ~ebits/wbits multiplies and the squaring ladder is
// paid once in the precompute, amortized over M. The window width is a
// caller choice: wider windows cut the per-row multiplies ~linearly but
// grow the per-group table build by 2^wbits, so the bridge picks wbits
// by rows-per-group (w=6 beats w=4 by ~22% at the ring-Pedersen M=256
// shape; w=4 stays optimal for the n-row pair groups).
int fsdkr_modexp_shared_w(const u64 *base, const u64 *exps, const u64 *n,
                          u64 *outs, int M, int L, int EL, int wbits) {
  // EL is capped: verify-side exponents are adversary-supplied proof
  // integers, and the comb table is (64 EL / wbits)*2^wbits*L words — an
  // unbounded EL would let one malicious proof force a huge (or
  // throwing) allocation where the generic kernel merely computes
  // slowly. 2*MAXL limbs = 8192 bits covers every protocol exponent
  // incl. range slack.
  if (L <= 0 || L > MAXL || EL <= 0 || EL > 2 * MAXL || M <= 0 ||
      wbits < 1 || wbits > 8 || !(n[0] & 1))
    return -1;

  const u64 n0inv = mont_n0inv(n[0]);
  u64 one_m[MAXL], r2[MAXL];
  mont_constants(n, L, one_m, r2);

  u64 b[MAXL];
  std::memcpy(b, base, sizeof(u64) * L);
  while (cmp_limbs(b, n, L) >= 0)
    sub_limbs(b, b, n, L);

  const int D = 1 << wbits;             // table entries per window
  const int W = (EL * 64 + wbits - 1) / wbits;  // windows over the limbs
  u64 *table = new (std::nothrow) u64[(size_t)W * D * L];
  if (!table)
    return -1;
  auto T = [&](int w, int d) { return table + ((size_t)w * D + d) * L; };

  u64 pw[MAXL];  // base^((2^wbits)^w) in Montgomery form
  mont_mul(pw, b, r2, n, n0inv, L);
  for (int w = 0; w < W; w++) {
    std::memcpy(T(w, 0), one_m, sizeof(u64) * L);
    std::memcpy(T(w, 1), pw, sizeof(u64) * L);
    for (int d = 2; d < D; d++) {
      if (d % 2 == 0)
        mont_sqr(T(w, d), T(w, d / 2), n, n0inv, L);
      else
        mont_mul(T(w, d), T(w, d - 1), pw, n, n0inv, L);
    }
    if (w + 1 < W)  // pw <- pw^(2^wbits) = (pw^(2^(wbits-1)))^2
      mont_sqr(pw, T(w, D / 2), n, n0inv, L);
  }

  u64 onev[MAXL];
  std::memset(onev, 0, sizeof(u64) * L);
  onev[0] = 1;
  u64 acc[MAXL];
  const u64 mask = (u64)D - 1;
  for (int m = 0; m < M; m++) {
    const u64 *e = exps + (size_t)m * EL;
    std::memcpy(acc, one_m, sizeof(u64) * L);
    // one multiply per window unconditionally (d == 0 hits the one_m
    // entry): prover-side exponents are secret key shares and nonces,
    // and a zero-digit skip would make wall time a function of their
    // contents — the generic kernel is uniform per window for the same
    // reason
    for (int w = 0; w < W; w++) {
      int bit0 = w * wbits;  // windows may straddle a 64-bit limb
      u64 d = e[bit0 / 64] >> (bit0 % 64);
      if (bit0 % 64 + wbits > 64 && bit0 / 64 + 1 < EL)
        d |= e[bit0 / 64 + 1] << (64 - bit0 % 64);
      d &= mask;
      mont_mul(acc, acc, T(w, (int)d), n, n0inv, L);
    }
    mont_mul(outs + (size_t)m * L, acc, onev, n, n0inv, L);
  }

  // same wipe discipline as fsdkr_modexp: the table and constants can
  // reconstruct base/modulus state (secret on prover-side uses)
  secure_wipe(table, W * D * L);
  delete[] table;
  secure_wipe(b, L);
  secure_wipe(pw, L);
  secure_wipe(acc, L);
  secure_wipe(one_m, L);
  secure_wipe(r2, L);
  return 0;
}

// ABI-stable 4-bit-window entry point (older bridges / capture tooling)
int fsdkr_modexp_shared(const u64 *base, const u64 *exps, const u64 *n,
                        u64 *outs, int M, int L, int EL) {
  return fsdkr_modexp_shared_w(base, exps, n, outs, M, L, EL, 4);
}

// ---------------------------------------------------------------------------
// Joint (Straus/Shamir) multi-exponentiation: rows of k terms sharing one
// modulus per row,
//
//   outs[r] = prod_t bases[r*k+t] ^ exps[r*k+t]  mod mods[r].
//
// One interleaved windowed ladder per row: the squaring chain — the
// dominant cost of a full-width modexp — is paid ONCE for the whole
// product instead of once per term, and each wbits-wide window costs one
// table multiply per *active* term. ebits[t] (k entries, launch-wide)
// caps term t's window count: widths are column-level shape information
// (bucketed by the caller from public wire-domain bounds), so the
// schedule is data-independent — every row performs the identical
// multiply sequence, and a zero window digit multiplies by the
// Montgomery one (constant cost), same discipline as the comb kernel.
//
// Layout: bases rows*k*L, exps rows*k*EL (uniform EL, little-endian),
// mods/outs rows*L. k <= MAXK; EL capped like the comb (adversarial
// widths are gated upstream; this is the allocation backstop).

static const int MAXK = 8;

int fsdkr_multi_modexp_batch(const u64 *bases, const u64 *exps,
                             const u64 *mods, u64 *outs, const int *ebits,
                             int rows, int k, int L, int EL, int wbits) {
  if (L <= 0 || L > MAXL || EL <= 0 || EL > 2 * MAXL || rows <= 0 ||
      k <= 0 || k > MAXK || wbits < 1 || wbits > 6)
    return -1;
  const int D = 1 << wbits;
  int W = 0;       // shared chain depth: max window count over terms
  int Wt[MAXK];    // per-term window counts
  for (int t = 0; t < k; t++) {
    if (ebits[t] <= 0 || ebits[t] > EL * 64)
      return -1;
    Wt[t] = (ebits[t] + wbits - 1) / wbits;
    if (Wt[t] > W)
      W = Wt[t];
  }
  for (int r = 0; r < rows; r++)
    if (!(mods[(size_t)r * L] & 1))
      return -1;

  u64 *table = new (std::nothrow) u64[(size_t)k * D * L];
  if (!table)
    return -1;
  auto T = [&](int t, int d) { return table + ((size_t)t * D + d) * L; };

  u64 one_m[MAXL], r2[MAXL], b[MAXL], base_m[MAXL], acc[MAXL], onev[MAXL];
  std::memset(onev, 0, sizeof(u64) * MAXL);
  onev[0] = 1;
  for (int r = 0; r < rows; r++) {
    const u64 *n = mods + (size_t)r * L;
    const u64 n0inv = mont_n0inv(n[0]);
    mont_constants(n, L, one_m, r2);

    for (int t = 0; t < k; t++) {
      std::memcpy(b, bases + ((size_t)r * k + t) * L, sizeof(u64) * L);
      while (cmp_limbs(b, n, L) >= 0)
        sub_limbs(b, b, n, L);
      mont_mul(base_m, b, r2, n, n0inv, L);
      std::memcpy(T(t, 0), one_m, sizeof(u64) * L);
      std::memcpy(T(t, 1), base_m, sizeof(u64) * L);
      for (int d = 2; d < D; d++) {
        if (d % 2 == 0)
          mont_sqr(T(t, d), T(t, d / 2), n, n0inv, L);
        else
          mont_mul(T(t, d), T(t, d - 1), base_m, n, n0inv, L);
      }
    }

    const u64 mask = (u64)D - 1;
    std::memcpy(acc, one_m, sizeof(u64) * L);
    for (int w = W - 1; w >= 0; w--) {
      if (w != W - 1) // acc is still one at the top window
        for (int s = 0; s < wbits; s++)
          mont_sqr(acc, acc, n, n0inv, L);
      for (int t = 0; t < k; t++) {
        if (w >= Wt[t])
          continue; // static per-launch schedule (ebits), not data
        const u64 *e = exps + ((size_t)r * k + t) * EL;
        int bit0 = w * wbits; // windows may straddle a 64-bit limb
        u64 d = e[bit0 / 64] >> (bit0 % 64);
        if (bit0 % 64 + wbits > 64 && bit0 / 64 + 1 < EL)
          d |= e[bit0 / 64 + 1] << (64 - bit0 % 64);
        d &= mask;
        mont_mul(acc, acc, T(t, (int)d), n, n0inv, L);
      }
    }
    mont_mul(outs + (size_t)r * L, acc, onev, n, n0inv, L);
  }

  secure_wipe(table, k * D * L);
  delete[] table;
  secure_wipe(b, MAXL);
  secure_wipe(base_m, MAXL);
  secure_wipe(acc, MAXL);
  secure_wipe(one_m, MAXL); // one_m/r2 reconstruct the modulus
  secure_wipe(r2, MAXL);
  return 0;
}

} // extern "C"
