#!/usr/bin/env python
"""Kernel-level sweep: CIOS vs RNS (XLA chain vs fused Pallas MontMul),
generic vs fixed-base comb, across modulus widths and batch sizes, on
the real chip. Produces the measured numbers that set the powm router
thresholds (FSDKR_RNS_MIN_ROWS & friends, backend/powm.py) and the
BASELINE.md kernel table.

Usage: python scripts/bench_kernels.py [quick|full]
Output: one human table to stderr + JSON lines to stdout, one per
measured point:
  {"kernel": "...", "bits": N, "exp_bits": N, "rows": N, "seconds": S,
   "modexp_per_s": X}
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


class PointTimeout(Exception):
    pass


class point_deadline:
    """Deadline around one measurement point. Two layers:

    - SIGALRM at T seconds: raises PointTimeout if the interpreter is
      running Python bytecode (slow but live point -> skip gracefully);
    - a monitor thread at 1.5*T: os._exit(75) for hangs stuck inside a
      C-level device call (a dead TPU tunnel never returns, and Python
      signals cannot interrupt it). Already-printed JSON lines are
      flushed, so completed points survive the exit.

    T via FSDKR_POINT_TIMEOUT, default 600.
    """

    def __init__(self):
        self.seconds = int(os.environ.get("FSDKR_POINT_TIMEOUT", "600"))

    def __enter__(self):
        if self.seconds <= 0:  # 0 disables the deadline entirely
            self._done = None
            return
        import signal
        import threading

        def _raise(signum, frame):
            raise PointTimeout(f"point exceeded {self.seconds}s")

        self._old = signal.signal(signal.SIGALRM, _raise)
        signal.alarm(self.seconds)
        self._done = threading.Event()

        def _hard_exit():
            if not self._done.wait(self.seconds * 1.5):
                log(f"point hung past {self.seconds * 1.5:.0f}s; exiting 75")
                os._exit(75)

        self._mon = threading.Thread(target=_hard_exit, daemon=True)
        self._mon.start()

    def __exit__(self, *exc):
        if self._done is None:
            return False
        import signal

        signal.alarm(0)
        signal.signal(signal.SIGALRM, self._old)
        self._done.set()
        return False


def _workload(bits, exp_bits, rows, seed=0):
    import random

    rng = random.Random(seed)
    moduli = [
        rng.getrandbits(bits) | (1 << (bits - 1)) | 1 for _ in range(rows)
    ]
    bases = [rng.getrandbits(bits - 1) for _ in range(rows)]
    exps = [rng.getrandbits(exp_bits) | (1 << (exp_bits - 1)) for _ in range(rows)]
    return bases, exps, moduli


def _grouped_workload(bits, exp_bits, groups, rows_per_group, seed=0):
    import random

    rng = random.Random(seed)
    gmods = [rng.getrandbits(bits) | (1 << (bits - 1)) | 1 for _ in range(groups)]
    gbases = [rng.getrandbits(bits - 1) for _ in range(groups)]
    gexps = [
        [rng.getrandbits(exp_bits) | (1 << (exp_bits - 1)) for _ in range(rows_per_group)]
        for _ in range(groups)
    ]
    return gbases, gexps, gmods


def _time(fn, warmups=1, reps=2):
    for _ in range(warmups):
        fn()
    t0 = time.time()
    for _ in range(reps):
        fn()
    return (time.time() - t0) / reps


def measure_generic(kind, bits, exp_bits, rows, spot_check=True):
    from fsdkr_tpu.ops.limbs import limbs_for_bits
    from fsdkr_tpu.ops.montgomery import BatchModExp
    from fsdkr_tpu.ops import rns

    bases, exps, moduli = _workload(bits, exp_bits, rows)
    if kind == "cios":
        ctx = BatchModExp(moduli, limbs_for_bits(bits))
        run = lambda: ctx.modexp(bases, exps)
    elif kind in ("rns", "rns-pallas"):
        os.environ["FSDKR_PALLAS"] = "1" if kind == "rns-pallas" else "0"
        run = lambda: rns.rns_modexp(bases, exps, moduli, bits)
    else:
        raise ValueError(kind)
    out = run()  # correctness + compile
    if spot_check:
        for i in (0, rows // 2, rows - 1):
            assert out[i] == pow(bases[i] % moduli[i], exps[i], moduli[i]), (
                f"{kind} wrong at row {i}"
            )
    dt = _time(run)
    rec = {
        "kernel": kind,
        "bits": bits,
        "exp_bits": exp_bits,
        "rows": rows,
        "seconds": round(dt, 4),
        "modexp_per_s": round(rows / dt, 1),
    }
    print(json.dumps(rec), flush=True)
    log(f"  {kind:12s} bits={bits} e={exp_bits} rows={rows}: "
        f"{dt:.3f}s -> {rows / dt:.0f}/s")
    return rec


def measure_comb(kind, bits, exp_bits, groups, rows_per_group, spot_check=True):
    from fsdkr_tpu.ops.limbs import limbs_for_bits
    from fsdkr_tpu.ops.montgomery import shared_base_modexp
    from fsdkr_tpu.ops import rns

    gbases, gexps, gmods = _grouped_workload(bits, exp_bits, groups, rows_per_group)
    if kind == "comb-cios":
        run = lambda: shared_base_modexp(
            gbases, gexps, gmods, limbs_for_bits(bits)
        )
    elif kind in ("comb-rns", "comb-rns-pallas"):
        os.environ["FSDKR_PALLAS"] = "1" if kind == "comb-rns-pallas" else "0"
        run = lambda: rns.rns_modexp_shared(gbases, gexps, gmods, bits)
    else:
        raise ValueError(kind)
    out = run()
    if spot_check:
        g = groups // 2
        assert out[g][0] == pow(
            gbases[g] % gmods[g], gexps[g][0], gmods[g]
        ), f"{kind} wrong"
    dt = _time(run)
    rows = groups * rows_per_group
    rec = {
        "kernel": kind,
        "bits": bits,
        "exp_bits": exp_bits,
        "rows": rows,
        "groups": groups,
        "seconds": round(dt, 4),
        "modexp_per_s": round(rows / dt, 1),
    }
    print(json.dumps(rec), flush=True)
    log(f"  {kind:16s} bits={bits} e={exp_bits} G={groups}xM={rows_per_group}: "
        f"{dt:.3f}s -> {rows / dt:.0f}/s")
    return rec


def measure_shared_exp(kind, bits, exp_bits, rows, spot_check=True):
    """Shared-exponent engines (FSDKR_RANGEOPT): ONE public exponent and
    modulus, per-row bases — the Alice-range s^n column shape. kinds:
    sharedexp-cios (rows x limbs device kernel, digit schedule as a
    dynamic vector) and sharedexp-native (host shared-schedule engine,
    GMP mpn inner loop when present)."""
    import random

    from fsdkr_tpu.ops.limbs import limbs_for_bits

    rng = random.Random(17)
    mod = rng.getrandbits(bits) | (1 << (bits - 1)) | 1
    exp = rng.getrandbits(exp_bits) | (1 << (exp_bits - 1))
    bases = [rng.getrandbits(bits - 1) for _ in range(rows)]
    if kind == "sharedexp-cios":
        from fsdkr_tpu.ops.montgomery import shared_exp_modexp

        run = lambda: shared_exp_modexp(
            bases, exp, mod, limbs_for_bits(bits)
        )
    elif kind == "sharedexp-native":
        from fsdkr_tpu import native

        run = lambda: native.shared_exp_powm(bases, exp, mod)
    else:
        raise ValueError(kind)
    out = run()  # correctness + compile
    if spot_check:
        for i in (0, rows // 2, rows - 1):
            assert out[i] == pow(bases[i] % mod, exp, mod), (
                f"{kind} wrong at row {i}"
            )
    dt = _time(run)
    rec = {
        "kernel": kind,
        "bits": bits,
        "exp_bits": exp_bits,
        "rows": rows,
        "seconds": round(dt, 4),
        "modexp_per_s": round(rows / dt, 1),
    }
    print(json.dumps(rec), flush=True)
    log(f"  {kind:16s} bits={bits} e={exp_bits} rows={rows}: "
        f"{dt:.3f}s -> {rows / dt:.0f}/s")
    return rec


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "quick"
    import jax

    try:
        from bench import _jax_cache_dir

        jax.config.update("jax_compilation_cache_dir", _jax_cache_dir())
    except Exception:
        pass
    log(f"devices: {jax.devices()}  backend: {jax.default_backend()}")

    if mode == "sharedexp":
        # single-kernel micro-step for the armed tunnel-window battery
        # (ROADMAP item 2 discipline: <= 15 s per point, persisted
        # per-point via JSON lines before any full bench): the
        # shared-exponent device kernel at the warm n=16 collect shape
        # (4096-bit modulus, 2048-bit public exponent, one receiver
        # group of 16 rows), plus the host engine as the same-shape
        # reference point.
        for kind in ("sharedexp-cios", "sharedexp-native"):
            try:
                with point_deadline():
                    measure_shared_exp(kind, 4096, 2048, 16)
            except Exception as ex:
                log(f"  {kind}: FAILED {ex}")
        return

    # the collect() shapes that matter: 2048-bit (N~, ring-Pedersen N) and
    # 4096-bit (Paillier N^2) moduli; 256-bit challenges, ~2048-bit secret
    # exponents, 2304/2816-bit slack-range exponents
    if mode == "quick":
        generic_points = [
            (2048, 256, 1024),
            (2048, 2048, 1024),
            (4096, 256, 1024),
            (4096, 2048, 512),
        ]
        comb_points = [
            (2048, 2048, 16, 256),  # ring-Pedersen @ n=16
            (2048, 256, 16, 64),
        ]
        batch_sweep = [64, 128, 512, 2048, 8192]
    else:
        generic_points = [
            (2048, 256, 1024),
            (2048, 2048, 1024),
            (2048, 2560, 1024),
            (4096, 256, 1024),
            (4096, 2048, 512),
            (4096, 3072, 512),
        ]
        comb_points = [
            (2048, 2048, 16, 256),
            (2048, 2048, 256, 256),  # ring-Pedersen @ n=256
            (4096, 2048, 64, 64),
            (2048, 256, 64, 64),
        ]
        batch_sweep = [128, 256, 512, 1024, 2048, 4096, 8192, 16384]

    # FSDKR_NO_PALLAS=1: the battery preflight found the Pallas kernels
    # unlowerable for TPU — measuring them would die at compile on chip
    no_pallas = os.environ.get("FSDKR_NO_PALLAS") == "1"
    kinds = ["cios", "rns"]
    if jax.default_backend() == "tpu" and not no_pallas:
        kinds.append("rns-pallas")

    log("== generic kernels ==")
    for bits, e, rows in generic_points:
        for kind in kinds:
            try:
                with point_deadline():
                    measure_generic(kind, bits, e, rows)
            except Exception as ex:
                log(f"  {kind} bits={bits} e={e} rows={rows}: FAILED {ex}")

    log("== batch-size sweep (2048-bit, 2048-bit exp) ==")
    for rows in batch_sweep:
        for kind in kinds:
            try:
                with point_deadline():
                    measure_generic(kind, 2048, 2048, rows)
            except Exception as ex:
                log(f"  {kind} rows={rows}: FAILED {ex}")

    log("== comb kernels ==")
    comb_kinds = ["comb-cios", "comb-rns"]
    if jax.default_backend() == "tpu" and not no_pallas:
        comb_kinds.append("comb-rns-pallas")
    for bits, e, g, m in comb_points:
        for kind in comb_kinds:
            try:
                with point_deadline():
                    measure_comb(kind, bits, e, g, m)
            except Exception as ex:
                log(f"  {kind} bits={bits} e={e} G={g} M={m}: FAILED {ex}")

    log("== shared-exponent kernels (FSDKR_RANGEOPT) ==")
    se_points = (
        [(4096, 2048, 64)] if mode == "quick" else [(4096, 2048, 240)]
    )
    for bits, e, rows in se_points:
        for kind in ("sharedexp-cios", "sharedexp-native"):
            try:
                with point_deadline():
                    measure_shared_exp(kind, bits, e, rows)
            except Exception as ex:
                log(f"  {kind} bits={bits} e={e} rows={rows}: FAILED {ex}")


if __name__ == "__main__":
    main()
