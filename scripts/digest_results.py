#!/usr/bin/env python
"""Digest bench_results/ into markdown tables for BASELINE.md.

Reads every m_*.json the battery produced (collect configs: one JSON
object; kernel sweep: JSON lines) and prints two markdown tables to
stdout: the collect()/config table and the kernel sweep table, plus a
per-phase breakdown for each traced config. Purely offline — safe to run
any time.

Usage: python scripts/digest_results.py [bench_results_dir]
"""

import json
import pathlib
import re
import sys

# very-large-report guards (ISSUE 10: n=256 full-width bench JSONs carry
# multi-megabyte telemetry/trace blocks): refuse to slurp a file past
# the hard cap, and never re-attempt json.loads per line on huge broken
# lines (the old fallback re-parsed a failed multi-MB line once per
# line, quadratic on corrupt big reports)
_MAX_FILE_BYTES = 512 * (1 << 20)
_MAX_LINE_BYTES = 64 * (1 << 20)


def load(path):
    try:
        if path.stat().st_size > _MAX_FILE_BYTES:
            print(
                f"digest: skipping {path} "
                f"({path.stat().st_size >> 20} MB > cap)",
                file=sys.stderr,
            )
            return []
    except OSError:
        return []
    text = path.read_text()
    # whole-file object first (pretty-printed reports); JSON-lines after
    try:
        rec = json.loads(text)
        return [rec] if isinstance(rec, dict) else []
    except json.JSONDecodeError:
        pass
    recs = []
    for line in text.splitlines():
        line = line.strip()
        if not line or len(line) > _MAX_LINE_BYTES:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict):
            recs.append(rec)
    return recs


def is_structural_proxy(rec) -> bool:
    """True when a collect-config record was measured at reduced
    parameters (the cpu_scale_n256* 768-bit/M=32 runs) or self-declares
    as structural — such rows must never read as full-parameter
    (2048-bit/M=256) numbers. A dry-run memory-plan report is also a
    proxy: it planned, it did not verify."""
    metric = str(rec.get("metric", ""))
    if "[structural" in metric or "dry-run" in metric or rec.get("dry_run"):
        return True
    m = re.search(r"(\d+)-bit", metric)
    return bool(m) and int(m.group(1)) < 2048


def main():
    root = pathlib.Path(sys.argv[1] if len(sys.argv) > 1 else "bench_results")
    configs, kernels, traces, ec_ab = [], [], {}, []
    mfu, other_kernel_recs = [], 0
    serving, chaos, storms, net_storms = [], [], [], []
    # serving reports live both as battery steps (m_serve_*.json) and as
    # the loadgen's own serving_*.json artifacts; the cpu_scale_* /
    # cpu_full_* structural and full-width runs digest too (ISSUE 10),
    # with reduced-parameter rows labeled as proxies below; chaos_*.json
    # are the fault-injection runs (ISSUE 11)
    paths = (
        sorted(root.glob("m_*.json"))
        + sorted(root.glob("serving_*.json"))
        + sorted(root.glob("chaos_*.json"))
        + sorted(root.glob("crash_storm*.json"))
        + sorted(root.glob("net_storm*.json"))
        + sorted(root.glob("cpu_scale_*.json"))
        + sorted(root.glob("cpu_full_*.json"))
        + sorted(root.glob("amortization_*.json"))
        + sorted(root.glob("delegate_ab*.json"))
        + sorted(root.glob("net_full_param*.json"))
    )
    for path in paths:
        name = path.stem[2:] if path.stem.startswith("m_") else path.stem
        for rec in load(path):
            if "kernel" in rec and "seconds" in rec:
                kernels.append(rec)  # bench_kernels.py sweep rows
            elif "kernel" in rec and "mfu_wall" in rec:
                mfu.append(rec)  # profile_mfu.py rows
            elif "kernel" in rec:
                # preflight lowering records ({kernel, ok, mosaic}) and
                # mfu error rows carry no timings: count, don't tabulate
                other_kernel_recs += 1
            elif "shape" in rec:  # scripts/bench_ec.py A/B records
                ec_ab.append(rec)
            elif rec.get("metric") == "serve_sustained":
                # the same run exists twice on disk (the battery's
                # m_serve_*.json stdout capture AND loadgen's own
                # serving_*.json) — dedup by run content, not file name
                fp = tuple(
                    (rec.get(k) if not isinstance(rec.get(k), dict)
                     else tuple(sorted(rec[k].items())))
                    for k in ("committees", "window_s", "arrivals",
                              "sessions_done", "offered_rate_hz",
                              "latency_s")
                )
                if not any(f == fp for _n, _r, f in serving):
                    serving.append((name, rec, fp))
            elif rec.get("metric") == "serve_chaos":
                chaos.append((name, rec))
            elif rec.get("metric") == "serve_crash_storm":
                storms.append((name, rec))
            elif rec.get("metric") == "serve_net_storm":
                net_storms.append((name, rec))
            elif "metric" in rec:
                configs.append((name, rec))
                if rec.get("trace"):
                    traces[f"{name} (warm collect)"] = (
                        rec["trace"],
                        rec.get("mfu") or {},
                    )
                if rec.get("trace_distribute"):
                    traces[f"{name} (distribute, incl. compiles)"] = (
                        rec["trace_distribute"],
                        rec.get("mfu_distribute") or {},
                    )

    if configs:
        print("### collect() configurations\n")
        print("| step | metric | platform | proofs/s | warm s | cold s | vs native C++ | vs CPython |")
        print("|---|---|---|---|---|---|---|---|")
        any_proxy = False
        for name, r in configs:
            plat = r.get("platform") or "—"
            if r.get("fallback_note"):
                plat += " (FALLBACK)"
            step = name
            if is_structural_proxy(r):
                step = f"proxy: {name}"
                any_proxy = True
            print(
                f"| {step} | {r['metric']} | {plat} | {r.get('value', 0)} "
                f"| {r.get('collect_warm_s', '—')} | {r.get('collect_cold_s', '—')} "
                f"| {r.get('vs_baseline', '—')}x | {r.get('vs_cpython', '—')}x |"
            )
            if "error" in r:
                print(f"|  | ERROR: {r['error'][:90]} | | | | | | |")
            if r.get("fallback_note"):
                print(f"|  | note: {r['fallback_note'][:110]} | | | | | | |")
        if any_proxy:
            print(
                "\n`proxy:` rows are reduced-parameter structural runs "
                "(e.g. 768-bit/M=32 cpu_scale_n256*) or plan-only dry "
                "runs — NOT full-parameter (2048-bit/M=256) numbers."
            )
        print()

    amort = [(name, r) for name, r in configs if r.get("curve")]
    if amort:
        # cross-session amortization sweeps (ISSUE 17, BENCH_AMORTIZE):
        # one committee, fused collect_sessions at each S — the reduced-
        # parameter sweeps label as proxies like every other config row
        print("### cross-session amortization "
              "(bench.py BENCH_AMORTIZE, fused collect_sessions)\n")
        for name, r in amort:
            proxy = (
                " — proxy: reduced parameters"
                if is_structural_proxy(r) else ""
            )
            print(f"#### {name}: {r['metric']}{proxy}\n")
            print("| S | warm s | s/session | proofs/s | vs S=1 "
                  "| groups | fullwidth ladders | rows folded "
                  "| deduped | ladder cache hit/miss |")
            print("|---|---|---|---|---|---|---|---|---|---|")
            for pt in r["curve"]:
                print(
                    f"| {pt.get('sessions')} | {pt.get('collect_warm_s')} "
                    f"| {pt.get('per_session_warm_s')} "
                    f"| {pt.get('proofs_per_s')} "
                    f"| {pt.get('amortization_x', '—')}x "
                    f"| {pt.get('rlc_groups')} "
                    f"| {pt.get('fullwidth_ladders')} "
                    f"| {pt.get('rows_folded')} "
                    f"| {pt.get('xsession_rows_deduped')} "
                    f"| {pt.get('ladder_cache_hits')}/"
                    f"{pt.get('ladder_cache_misses')} |"
                )
            print()

    delegated = [
        (name, r) for name, r in configs if "delegated_measured_ops" in r
    ]
    if delegated:
        # FSDKR_DELEGATE acceptance A/Bs (ISSUE 17): parity verdicts and
        # the measured-vs-model group-op counts
        print("### Feldman MSM delegation A/B "
              "(bench.py BENCH_DELEGATE_AB)\n")
        print("| step | shape | parity honest/tampered | delegated ops "
              "| honest model ops | ratio | warm s honest/delegated "
              "| schemes/rows by cert |")
        print("|---|---|---|---|---|---|---|---|")
        for name, r in delegated:
            d = r.get("delegate") or {}
            step = f"proxy: {name}" if is_structural_proxy(r) else name
            print(
                f"| {step} | {r['metric']} "
                f"| {r.get('verdict_parity_honest')}/"
                f"{r.get('verdict_parity_tampered')} "
                f"| {r.get('delegated_measured_ops')} "
                f"| {r.get('honest_model_ops')} | {r.get('ops_ratio')} "
                f"| {r.get('collect_warm_honest_s')}/"
                f"{r.get('collect_warm_delegated_s')} "
                f"| {d.get('schemes_delegated')}/"
                f"{d.get('rows_delegated')} |"
            )
        print()

    for name, (tr, mfu) in traces.items():
        print(f"### per-phase breakdown: {name}\n")
        print("| phase | seconds | GMACs | mfu |")
        print("|---|---|---|---|")
        # cap the table for very large reports (an n=256 full-width
        # trace carries every tile's sub-phases): top 25 by time, with
        # the tail summarized instead of silently dropped
        rows_t = sorted(tr.items(), key=lambda kv: -kv[1])
        for phase, secs in rows_t[:25]:
            m = mfu.get(phase, {})
            print(
                f"| {phase} | {secs} | {m.get('gmacs', '—')} "
                f"| {m.get('mfu', '—')} |"
            )
        if len(rows_t) > 25:
            rest = round(sum(s for _, s in rows_t[25:]), 3)
            print(f"| ({len(rows_t) - 25} more phases) | {rest} | — | — |")
        print()
        # verify_pairs sub-phase view (ISSUE 8): the pair-loop wall and
        # its removal must be visible WITHOUT opening the Chrome trace —
        # break collect.verify_pairs into its engine sub-phases (the
        # range.* shared-exponent/comb/z columns, the pdl.* fold columns
        # and bisection phases) with their share of the family total.
        pairs_total = tr.get("collect.verify_pairs")
        if pairs_total:
            sub = {
                p: s for p, s in tr.items()
                if p.startswith(("range.", "pdl.", "pairs."))
            }
            if sub:
                print(
                    f"#### verify_pairs sub-phases "
                    f"({pairs_total}s family total)\n"
                )
                print("| sub-phase | seconds | % of verify_pairs |")
                print("|---|---|---|")
                for p, s in sorted(sub.items(), key=lambda kv: -kv[1]):
                    pct = round(100.0 * s / pairs_total, 1)
                    print(f"| {p} | {s} | {pct}% |")
                accounted = sum(
                    s for p, s in sub.items()
                    if not p.startswith("pairs.")  # container span
                )
                print(
                    f"| (glue / unattributed) | "
                    f"{round(max(0.0, pairs_total - accounted), 3)} | "
                    f"{round(100.0 * max(0.0, pairs_total - accounted) / pairs_total, 1)}% |"
                )
                print()

    # unified telemetry blocks (ISSUE 6): newer bench JSONs embed the
    # schema-versioned registry snapshot under "telemetry" — phase
    # latency percentiles and the pool/producer gauges. Old
    # BENCH_r0*.json files without the block still digest through the
    # legacy rlc/crt/precompute keys handled above.
    for name, rec in configs:
        tel = rec.get("telemetry")
        if not tel or "metrics" not in tel:
            continue
        metrics = tel["metrics"]
        print(
            f"### telemetry: {name} "
            f"(schema {tel.get('schema', '?')})\n"
        )
        hist = metrics.get("fsdkr_phase_seconds")
        if hist and hist.get("values"):
            print("| phase | calls | total s | p50 | p95 | p99 |")
            print("|---|---|---|---|---|---|")
            rows = sorted(
                hist["values"], key=lambda v: -v.get("sum", 0)
            )[:15]
            for v in rows:
                print(
                    f"| {v['labels'].get('phase', '?')} | {v['count']} "
                    f"| {round(v['sum'], 3)} | {v['p50']} | {v['p95']} "
                    f"| {v['p99']} |"
                )
            print()
        mem = rec.get("mem")
        if mem:
            print("| memory plan | value |")
            print("|---|---|")
            for k in (
                "budget_bytes", "peak_resident_bytes", "rss_peak_bytes",
                "bytes_staged", "tiles", "plan_enabled",
            ):
                if k in mem:
                    v = mem[k]
                    if isinstance(v, int) and v >= 1 << 20 and k != "tiles":
                        v = f"{v} ({v >> 20} MB)"
                    print(f"| {k} | {v} |")
            print()
        gauge_rows = []
        for gname in (
            "fsdkr_pool_depth", "fsdkr_pool_bytes", "fsdkr_pool_count",
            "fsdkr_producer_occupancy", "fsdkr_producer_steps",
            "fsdkr_mem_budget_bytes", "fsdkr_mem_peak_resident_bytes",
            "fsdkr_mem_rss_peak_bytes", "fsdkr_mem_tile_rows",
            "fsdkr_mem_plan_rows",
        ):
            for v in metrics.get(gname, {}).get("values", []):
                labels = ",".join(
                    f"{k}={x}" for k, x in v["labels"].items()
                )
                gauge_rows.append(
                    (gname + (f"{{{labels}}}" if labels else ""),
                     v["value"])
                )
        if gauge_rows:
            print("| gauge | value |")
            print("|---|---|")
            for g, v in gauge_rows:
                print(f"| {g} | {v} |")
            print()

    if serving:
        # serving sustained-load report (ISSUE 9, scripts/loadgen.py)
        print("### serving: sustained multi-committee load (loadgen)\n")
        print("| step | platform | committees | n | bits | window s "
              "| offered/s | done/s (win) | p50 s | p95 s | p99 s "
              "| dry rate | aborted |")
        print("|---|---|---|---|---|---|---|---|---|---|---|---|---|")
        for name, r, _fp in serving:
            lat = r.get("latency_s") or {}
            pool = r.get("pool") or {}
            print(
                f"| {name} | {r.get('platform', '—')} "
                f"| {r.get('committees', '—')} | {r.get('n', '—')} "
                f"| {r.get('paillier_bits', '—')} "
                f"| {r.get('window_s', '—')} "
                f"| {r.get('offered_rate_hz', '—')} "
                f"| {r.get('sessions_per_s', '—')} "
                f"| {lat.get('p50', '—')} | {lat.get('p95', '—')} "
                f"| {lat.get('p99', '—')} "
                f"| {pool.get('dry_fallback_rate', '—')} "
                f"| {r.get('sessions_aborted', '—')} |"
            )
        print()
        for name, r, _fp in serving:
            # pool occupancy / dry-fallback table per run
            metrics = (r.get("telemetry") or {}).get("metrics") or {}
            depth = {
                v["labels"].get("kind", "?"): v["value"]
                for v in metrics.get("fsdkr_pool_depth", {}).get("values", [])
            }
            events = {}
            for v in metrics.get("fsdkr_pool_events", {}).get("values", []):
                k = v["labels"].get("kind", "?")
                events.setdefault(k, {})[v["labels"].get("event", "?")] = int(
                    v["value"]
                )
            if not depth and not events:
                continue
            print(f"#### pool occupancy / dry fallbacks: {name}\n")
            print("| kind | pooled now | produced | consumed "
                  "| dry fallbacks | wiped |")
            print("|---|---|---|---|---|---|")
            for kind in sorted(set(depth) | set(events)):
                ev = events.get(kind, {})
                print(
                    f"| {kind} | {int(depth.get(kind, 0))} "
                    f"| {ev.get('produced', 0)} | {ev.get('consumed', 0)} "
                    f"| {ev.get('dry_fallbacks', 0)} "
                    f"| {ev.get('wiped', 0)} |"
                )
            print()

    if chaos:
        # chaos-hardening runs (ISSUE 11, scripts/loadgen.py --chaos)
        print("### chaos: serving under fault injection (loadgen --chaos)\n")
        print("| step | arrivals | done | recovered | aborted (blame/transient) "
              "| timed out (named) | rejected | wedged | wrong verdicts "
              "| healthy p99 |")
        print("|---|---|---|---|---|---|---|---|---|---|")
        for name, r in chaos:
            ch = r.get("chaos") or {}
            out = ch.get("outcomes") or {}
            p99h = ch.get("p99_healthy_done_s")
            bnd = ch.get("p99_bound_s")
            p99s = (
                f"{p99h}s (bound {bnd}s: "
                f"{'ok' if ch.get('p99_within_bound') else 'OVER'})"
                if p99h is not None else "—"
            )
            print(
                f"| {name} | {r.get('arrivals', '—')} "
                f"| {r.get('sessions_done', '—')} "
                f"| {out.get('recovered', '—')} "
                f"| {out.get('aborted_blame', 0)}/"
                f"{out.get('aborted_transient', 0)} "
                f"| {out.get('timed_out', 0)} "
                f"({out.get('timed_out_named', 0)}) "
                f"| {ch.get('service_rejected_total', r.get('rejected', 0))} "
                f"| {ch.get('wedged', '—')} "
                f"| {ch.get('wrong_verdicts', '—')} "
                f"| {p99s} |"
            )
        print()
        for name, r in chaos:
            ch = r.get("chaos") or {}
            inj = ch.get("injected") or {}
            if inj:
                print(f"#### injected faults: {name}\n")
                print("| site | fired |")
                print("|---|---|")
                for site in sorted(inj):
                    print(f"| {site} | {inj[site]} |")
                print()
            curve = ch.get("tamper_curve") or []
            if curve:
                print(f"#### tamper rate vs bisection cost: {name} "
                      f"(ROADMAP 5b economics)\n")
                print("| tamper rate | sessions | aborted | rejected "
                      "| bisect fallbacks | s/session |")
                print("|---|---|---|---|---|---|")
                for pt in curve:
                    print(
                        f"| {pt.get('tamper_rate')} | {pt.get('sessions')} "
                        f"| {pt.get('aborted')} | {pt.get('rejected')} "
                        f"| {pt.get('bisect_fallbacks')} "
                        f"| {pt.get('s_per_session')} |"
                    )
                print()

    if storms:
        # crash-storm / shard-kill recovery runs (ISSUE 12,
        # scripts/loadgen.py --crash-storm)
        print("### crash storm: durable sessions under shard kills "
              "(loadgen --crash-storm)\n")
        print("| step | shards | kills | epochs | clean | recovered "
              "| transient | lost | wrong | wedged | MTTR mean/max "
              "| bystander p99 | gates |")
        print("|---|---|---|---|---|---|---|---|---|---|---|---|---|")
        for name, r in storms:
            out = r.get("outcomes") or {}
            mttr = r.get("mttr_s") or {}
            gates = r.get("gates") or {}
            gate_s = "ok" if gates and all(gates.values()) else ",".join(
                k for k, v in gates.items() if not v
            ) or "—"
            print(
                f"| {name} | {r.get('shards', '—')} "
                f"| {r.get('kills_injected', '—')} "
                f"| {r.get('epochs_submitted', '—')} "
                f"| {out.get('done_clean', '—')} "
                f"| {out.get('recovered', '—')} "
                f"| {out.get('aborted_transient', 0)} "
                f"| {r.get('lost_broadcast_sessions', '—')} "
                f"| {r.get('wrong_verdicts', '—')} "
                f"| {r.get('wedged', '—')} "
                f"| {mttr.get('mean', '—')}/{mttr.get('max', '—')}s "
                f"| {r.get('bystander_p99_s', '—')}s "
                f"| {gate_s} |"
            )
        print()
        for name, r in storms:
            fos = r.get("failovers") or []
            if not fos:
                continue
            print(f"#### failover / journal-replay detail: {name}\n")
            print("| failover | dead -> peer | committees moved "
                  "| replayed terminal | resumed | transient "
                  "| torn tails | MTTR |")
            print("|---|---|---|---|---|---|---|---|")
            for fo in fos:
                rec2 = fo.get("recovery") or {}
                print(
                    f"| gen {fo.get('gen')} "
                    f"| {fo.get('dead')} -> {fo.get('peer')} "
                    f"| {fo.get('committees', '—')} "
                    f"| {rec2.get('replayed_terminal', '—')} "
                    f"| {rec2.get('resumed', '—')} "
                    f"| {rec2.get('aborted_transient', '—')} "
                    f"| {rec2.get('torn_tails', '—')} "
                    f"| {fo.get('mttr_s', '—')}s |"
                )
            print()
            jagg = (r.get("aggregate") or {}).get("journal") or {}
            if jagg:
                print(
                    f"journal aggregate: {int(jagg.get('records', 0))} "
                    f"records, {int(jagg.get('bytes', 0))} bytes, "
                    f"{int(jagg.get('segments', 0))} segments, "
                    f"{int(jagg.get('fsyncs', 0))} fsyncs\n"
                )

    if net_storms:
        # network-fed serving storms (ISSUE 13, scripts/loadgen.py --net)
        print("### network storm: socket-fed serving under net chaos "
              "(loadgen --net)\n")
        print("| step | shards | clients | kills | epochs | clean "
              "| recovered | transient | timed out | lost | wrong "
              "| wedged | bystander p99 | net /s (per core) "
              "| in-proc /s | gates |")
        print("|---|---|---|---|---|---|---|---|---|---|---|---|---|"
              "---|---|---|")
        for name, r in net_storms:
            out = r.get("outcomes") or {}
            gates = r.get("gates") or {}
            gate_s = "ok" if gates and all(gates.values()) else ",".join(
                k for k, v in gates.items() if not v
            ) or "—"
            base = r.get("in_process_baseline") or {}
            print(
                f"| {name} | {r.get('shards', '—')} "
                f"| {r.get('clients', '—')} "
                f"| {r.get('kills_injected', 0)} "
                f"| {r.get('epochs_submitted', '—')} "
                f"| {out.get('done_clean', '—')} "
                f"| {out.get('recovered', '—')} "
                f"| {out.get('aborted_transient', 0)} "
                f"| {out.get('timed_out', 0)} "
                f"| {r.get('lost_broadcast_sessions', '—')} "
                f"| {r.get('wrong_verdicts', '—')} "
                f"| {r.get('wedged', '—')} "
                f"| {r.get('bystander_p99_s', '—')}s "
                f"| {r.get('net_sessions_per_s', '—')} "
                f"({r.get('net_sessions_per_s_per_core', '—')}) "
                f"| {base.get('sessions_per_s', '—')} "
                f"| {gate_s} |"
            )
        print()
        for name, r in net_storms:
            ing = (r.get("aggregate") or {}).get("ingress") or {}
            if not ing:
                continue
            print(f"#### ingress rollup: {name} (shard heartbeats)\n")
            print("| counter | value |")
            print("|---|---|")
            for k in ("connections", "frames", "bytes",
                      "frames_rejected", "paused_reads"):
                for lk, v in sorted((ing.get(k) or {}).items()):
                    print(f"| {k}{{{lk}}} | {int(v)} |")
            print(f"| peer_rate_shed | {int(ing.get('peer_rate_shed', 0))} |")
            cc = r.get("client_counters") or {}
            for k in sorted(cc):
                print(f"| clients.{k} | {int(cc[k])} |")
            print()

    if kernels:
        print("### kernel sweep (modexp rows/s, real chip)\n")
        print("| kernel | bits | exp bits | rows | groups | seconds | modexp/s |")
        print("|---|---|---|---|---|---|---|")
        for r in kernels:
            print(
                f"| {r['kernel']} | {r['bits']} | {r['exp_bits']} | {r['rows']} "
                f"| {r.get('groups', '—')} | {r['seconds']} | {r['modexp_per_s']} |"
            )
        print()

    if mfu:
        print("### profiler-measured MFU (scripts/profile_mfu.py)\n")
        print("| kernel | bits | rows | wall s | device s | modexp/s "
              "| MFU(wall) | MFU(device) | occupancy |")
        print("|---|---|---|---|---|---|---|---|---|")
        for r in mfu:
            print(
                f"| {r['kernel']} | {r['bits']} | {r['rows']} "
                f"| {r['wall_s']} | {r.get('device_s', '—')} "
                f"| {r['modexp_per_s']} | {r['mfu_wall']} "
                f"| {r.get('mfu_device', '—')} | {r.get('occupancy', '—')} |"
            )
        print()

    if ec_ab:
        print("### EC device-vs-host A/B (scripts/bench_ec.py)\n")
        print("| shape | n | rows | platform | host s | device warm s | device speedup |")
        print("|---|---|---|---|---|---|---|")
        for r in ec_ab:
            speedup = r.get("device_speedup_warm")
            print(
                f"| {r['shape']} | {r['n']} | {r['rows']} | {r['platform']} "
                f"| {r.get('host_s') or '—'} | {r.get('device_warm_s') or '—'} "
                f"| {f'{speedup}x' if speedup is not None else '—'} |"
            )
        print()


if __name__ == "__main__":
    main()
