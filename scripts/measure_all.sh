#!/bin/bash
# On-chip measurement battery: run as soon as the TPU tunnel is up.
# Produces bench_results/m_*.json + logs; each step tolerates failure.
# The tunnel is flaky (it died mid-run twice in rounds 1-3): steps are
# ordered most-valuable-first, and a health probe runs between steps so a
# dead tunnel pauses the battery instead of burning each step's timeout.
cd /root/repo
R=/root/repo/bench_results
mkdir -p "$R"
echo $$ > "$R/.battery.pid"
# wait_healthy already gates every step on the tunnel: keep bench.py
# fail-hard here so a step that races a mid-run outage errors out
# instead of silently burning its timeout on the CPU platform
export BENCH_CPU_FALLBACK=0

probe() {  # 0 = healthy
  timeout 120 python - <<'EOF' > /dev/null 2>&1
import jax, jax.numpy as jnp
assert jax.devices()[0].platform != "cpu"
assert float((jnp.arange(8.0) * 2).sum()) == 56.0
EOF
}

wait_healthy() {
  until probe; do
    echo "[$(date +%H:%M:%S)] tunnel down; waiting" >> "$R/battery_run.log"
    sleep 180
  done
}

run() {  # name, timeout, [VAR=V ...] cmd args...   (no '--': env treats
  # everything up to the first non-assignment word as the command)
  name=$1; to=$2; shift 2
  # only a completed run (rc=0, marked .ok) counts as measured: a killed
  # or failed step may leave partial stdout that must not be skipped over
  if [ -e "$R/m_$name.ok" ] && [ -s "$R/m_$name.json" ]; then
    echo "=== $name already measured, skipping ==="
    return
  fi
  wait_healthy
  echo "=== $name ($(date +%H:%M:%S)) ==="
  timeout "$to" env "$@" > "$R/m_$name.json" 2> "$R/m_$name.log"
  rc=$?
  # bench.py exits 0 even when it degrades to an annotated error line, so
  # rc alone is not "measured" — an "error" key in the JSON is a failure
  if [ "$rc" = 0 ] && ! grep -q '"error"' "$R/m_$name.json"; then
    touch "$R/m_$name.ok"
  else
    mv "$R/m_$name.json" "$R/m_$name.json.failed"
    [ "$rc" = 0 ] && rc=error-in-json
  fi
  echo "rc=$rc tail:"; tail -3 "$R/m_$name.log"; cat "$R/m_$name.json" 2>/dev/null
}

run_local() {  # like run, but never touches the tunnel: for host-path
  # steps (BENCH_PLATFORM=cpu) that must proceed through an outage
  name=$1; to=$2; shift 2
  if [ -e "$R/m_$name.ok" ] && [ -s "$R/m_$name.json" ]; then
    echo "=== $name already measured, skipping ==="
    return
  fi
  echo "=== $name ($(date +%H:%M:%S)) ==="
  timeout "$to" env "$@" > "$R/m_$name.json" 2> "$R/m_$name.log"
  rc=$?
  if [ "$rc" = 0 ] && ! grep -q '"error"' "$R/m_$name.json"; then
    touch "$R/m_$name.ok"
  else
    mv "$R/m_$name.json" "$R/m_$name.json.failed"
    [ "$rc" = 0 ] && rc=error-in-json
  fi
  echo "rc=$rc tail:"; tail -3 "$R/m_$name.log"; cat "$R/m_$name.json" 2>/dev/null
}

# Chipless AOT preflight before any tunnel time: every jitted call a
# refresh makes must lower for TPU (Mosaic included). Two round-5
# hardware-only compile failures motivated this. On failure, degrade
# the battery to the XLA chain (FSDKR_PALLAS=0) instead of letting the
# first bench step die at compile.
degrade() {  # $1: provenance label recorded by bench.py per step
  echo "degrading to the XLA chain ($1)"
  export FSDKR_PALLAS=0      # bench steps use the XLA chain
  export FSDKR_NO_PALLAS=1   # sweep/mfu skip their *-pallas points
  export BENCH_DEGRADED="$1" # so degraded numbers can never read as
                             # the nominal Pallas configuration
}
if [ -e "$R/m_preflight.failed" ]; then
  degrade xla-chain  # decided on a previous launch; don't re-pay 20 min
elif [ -e "$R/onchip_degraded" ]; then
  degrade xla-chain-onchip  # a previous launch hit a Mosaic backend error
elif [ ! -e "$R/m_preflight.ok" ]; then
  echo "=== preflight ($(date +%H:%M:%S)) ==="
  if timeout 1200 python scripts/preflight_tpu.py > "$R/preflight.json" 2> "$R/preflight.log"; then
    touch "$R/m_preflight.ok"
  else
    touch "$R/m_preflight.failed"
    degrade xla-chain
  fi
  tail -2 "$R/preflight.log"
fi

# judge-facing collect() configs first (known-good kernel families at
# n=16 as of round 2; RNS engages at >=512-row columns)
run n16 2400 FSDKR_TRACE=1 python bench.py
# AOT lowering cannot see Mosaic *backend* failures (VMEM budgeting,
# register allocation): if the first on-chip step died with a
# compile-class error — and the battery is not already degraded — keep
# the evidence, degrade, and retry once instead of burning every later
# step's timeout on the same failure. Only DETERMINISTIC compile-class
# errors (NotImplementedError / Mosaic lowering) write the persistent
# `onchip_degraded` marker: a RESOURCE_EXHAUSTED / VMEM / OOM can be a
# transient co-tenancy or shape-specific condition, so it degrades this
# launch only and the next battery relaunch retries the Pallas chain.
# Transient tunnel deaths (timeouts, connection losses) match neither
# pattern and retry un-degraded.
if [ -z "$BENCH_DEGRADED" ] && [ ! -e "$R/m_n16.ok" ]; then
  if grep -qE "NotImplementedError|[Mm]osaic" "$R/m_n16.log" 2>/dev/null; then
    echo "n16 died with a deterministic compile error: degrading persistently"
    cp "$R/m_n16.log" "$R/n16_pallas_fail.log"  # keep the compile error
    [ -e "$R/m_n16.json.failed" ] && cp "$R/m_n16.json.failed" "$R/n16_pallas_fail.json"
    touch "$R/onchip_degraded"
    degrade xla-chain-onchip
    run n16 2400 FSDKR_TRACE=1 python bench.py
  elif grep -qE "RESOURCE_EXHAUSTED|VMEM|out of memory" "$R/m_n16.log" 2>/dev/null; then
    echo "n16 died with a resource error: degrading THIS launch only"
    cp "$R/m_n16.log" "$R/n16_resource_fail.log"
    degrade xla-chain-resource
    run n16 2400 FSDKR_TRACE=1 python bench.py
  fi
fi
run n64 3600 BENCH_N=64 BENCH_T=32 FSDKR_TRACE=1 python bench.py
run join32 2400 BENCH_N=32 BENCH_T=15 BENCH_JOIN=2 python bench.py
run sessions16 4800 BENCH_SESSIONS=16 BENCH_N=16 BENCH_T=8 python bench.py
run n128 6000 BENCH_N=128 BENCH_T=64 FSDKR_TRACE=1 python bench.py
run n256 9000 BENCH_N=256 BENCH_T=128 FSDKR_TRACE=1 python bench.py
# kernel-level sweep (sets router thresholds; experimental points last)
run sweep_quick 3600 python scripts/bench_kernels.py quick
# EC device-vs-host crossover on the real chip (routes config.device_ec;
# the CPU-platform points are bench_results/ec_ab_cpu.json)
run ec_ab 4800 BENCH_EC_NS=16,64,256 python scripts/bench_ec.py
# profiler-measured MFU (device-track busy time from a real xprof dump,
# not the analytic meter) for the three kernel families
run mfu 3600 python scripts/profile_mfu.py quick
# fallback datapoint if the RNS path misbehaves on the real chip —
# also disables tree-comb, i.e. exactly the round-2 known-good kernels
run n16_cios 2400 FSDKR_RNS_MIN_ROWS=999999999 FSDKR_COMB_TREE=0 FSDKR_TRACE=1 python bench.py
# and the inverse A/B: RNS everywhere but sequential comb ladders
run n16_notree 2400 FSDKR_COMB_TREE=0 FSDKR_TRACE=1 python bench.py
# forced-host-EC A/B of a full collect at n=64 (isolates the EC columns)
run n64_hostec 3600 BENCH_N=64 BENCH_T=32 FSDKR_DEVICE_EC=0 FSDKR_TRACE=1 python bench.py
# joint multi-exponentiation A/B (isolates the Straus planner: =0 runs
# the per-term column path on identical kernels; CPU-platform pair is in
# BASELINE.md round 6)
run n16_nomultiexp 2400 FSDKR_MULTIEXP=0 FSDKR_TRACE=1 python bench.py
# cross-proof randomized batch verification A/B (FSDKR_RLC: =0 reverts
# the verifier to per-row columns; =1 is the default fold — the nominal
# n16 step above already measures it and emits the fold statistics
# {rlc_groups, rows_folded, bisect_fallbacks, fullwidth_ladders} as the
# bench JSON's "rlc" field)
run n16_norlc 2400 FSDKR_RLC=0 FSDKR_TRACE=1 python bench.py
# secret-CRT prover engine A/B (FSDKR_CRT: =0 reverts the ring-Pedersen
# / correct-key / Paillier-decrypt provers to full-width modexp; =1 is
# the default — the nominal n16 step above measures it and emits the
# "crt" stats block plus the per-phase prover deltas in
# trace_distribute / trace_distribute_warm and distribute_warm_s; this
# step is the off arm at the same n=16 full-2048-bit shape, mirroring
# the n16_norlc pattern). The CPU-platform acceptance pair is
# bench_results/crt_ab_n16_{on,off}.json.
run n16_nocrt 2400 FSDKR_CRT=0 FSDKR_TRACE=1 python bench.py
# range-opt A/B (FSDKR_RANGEOPT: =0 reverts the Alice-range family to
# the per-row joint/column path and verify_pairs to the single fused
# sequential launch set; =1 is the default — shared-exponent ladders
# for the s^n mod n^2 column, joint fixed-base comb apply for
# h1^s1*h2^s2 mod N~, concurrent column scheduler. The nominal n16
# step above measures the on arm and its trace carries the range.*
# sub-phases; this is the off arm at the same shape, mirroring the
# n16_norlc pattern). The CPU-platform acceptance pair is
# bench_results/rangeopt_ab_n16_{on,off}.json.
run n16_norangeopt 2400 FSDKR_RANGEOPT=0 FSDKR_TRACE=1 python bench.py
# single-kernel micro-step for the shared-exponent device kernel
# (<= 15 s per point, persisted before any full bench — ROADMAP item 2
# tunnel-window discipline; step 0 smoke + probe cadence as above)
run sharedexp_kernel 120 python scripts/bench_kernels.py sharedexp
# precompute offline/online split A/B (FSDKR_PRECOMPUTE: =0 reverts
# distribute() to the inline path — no pools, no prefill; =1 is the
# default — the nominal n16 step above measures it and emits
# distribute_online_s / precompute_offline_s plus the "precompute"
# stats block {produced, consumed, dry_fallbacks, wiped, bytes_pooled};
# this step is the off arm at the same n=16 full-2048-bit shape,
# mirroring the n16_nocrt pattern). The CPU-platform acceptance pair is
# bench_results/precompute_ab_n16_{on,off}.json.
run n16_noprecompute 2400 FSDKR_PRECOMPUTE=0 FSDKR_TRACE=1 python bench.py
# memory-plan A/B (ISSUE 10, FSDKR_MEM_PLAN: =0 restores the monolithic
# all-rows-resident gather/stage/verify path; =1 is the default — the
# nominal n16 step above measures it and emits the "mem" stat block
# {budget_bytes, bytes_staged, peak_resident_bytes, rss_peak_bytes,
# tiles}; this step is the off arm at the same shape, mirroring the
# n16_norlc pattern). At n=16 the default budget fits one tile, so the
# two arms must match within noise; the multi-tile path is measured by
# the n256_full / n64_fullwidth steps below. The CPU-platform acceptance
# pair is bench_results/memplan_ab_n16_{on,off}.json.
run n16_nomemplan 2400 FSDKR_MEM_PLAN=0 FSDKR_TRACE=1 python bench.py

# telemetry trace-overhead A/B (ISSUE 6): one traced bench run that adds
# an extra warm collect with the tracer forced OFF in the same process —
# the JSON carries collect_warm_s (traced), collect_warm_notrace_s
# (disabled path), and trace_overhead_pct. The disabled arm is the one
# under the 2%-of-baseline budget; the CPU-platform acceptance pair is
# bench_results/trace_ab_n16.json. Trace/metrics artifacts land next to
# the JSON so a timeline of this exact run is always on disk.
run n16_trace_overhead 2400 FSDKR_TRACE=1 BENCH_TRACE_AB=1 \
  FSDKR_TRACE_OUT="$R/n16_trace_overhead.trace.json" \
  FSDKR_METRICS_DUMP="$R/n16_trace_overhead.prom" python bench.py

# host-engine thread scaling (FSDKR_THREADS row pool; 1 = the historical
# serial loop, auto = all cores). Pinned to the CPU platform + host
# routes so the series isolates the native engines and survives a tunnel
# outage; the warm collect's powm_cache field in each JSON shows the
# persistent-table hit counts (second collect of the same committee must
# show the table builds eliminated).
# On a single-core host the 1/4/8 series is SKIPPED, not measured: every
# point would time the same serial loop and the resulting flat "1x
# scaling" would read as a thread-pool regression. The skip is annotated
# in a marker JSON; only the auto point runs — it doubles as the
# canonical host datapoint below and self-describes its real pool size
# via the fsdkr_threads field.
if [ "$(nproc)" -gt 1 ]; then
  rm -f "$R/m_threads_scaling_skipped.json"
  for T in 1 4 8; do
    run_local "n16_host_t$T" 3600 BENCH_PLATFORM=cpu FSDKR_THREADS=$T \
      FSDKR_DEVICE_POWM=0 FSDKR_DEVICE_EC=0 FSDKR_TRACE=1 python bench.py
  done
else
  echo "single-core host: skipping the FSDKR_THREADS scaling series"
  printf '{"skipped": "FSDKR_THREADS 1/4/8 scaling series", "reason": "nproc=1: every point would measure the identical serial loop and report a misleading 1x scaling figure", "nproc": 1}\n' \
    > "$R/m_threads_scaling_skipped.json"
fi
run_local "n16_host_tauto" 3600 BENCH_PLATFORM=cpu FSDKR_THREADS=auto \
  FSDKR_DEVICE_POWM=0 FSDKR_DEVICE_EC=0 FSDKR_TRACE=1 python bench.py

# serving sustained load (ISSUE 9): the refresh-as-a-service acceptance
# shape — >=200 concurrent committees, >=60 s measured window of Poisson
# arrivals through RefreshService (streaming collect, coalesced fused
# finalize launches, SLO-driven pool capacity planning). Pinned to the
# host platform (run_local) so the step survives a tunnel outage; the
# loadgen also writes bench_results/serving_sustained.json itself, and
# digest_results.py renders the sessions/sec + latency-percentile +
# pool-occupancy tables from either copy.
run_local serve_sustained 3000 JAX_PLATFORMS=cpu \
  python scripts/loadgen.py --committees 200 --bases 4 --window 60 \
  --prefill-wait 90 --tag sustained

# cross-session amortization curve (ISSUE 17): fused S=1/2/4/8/16
# full-parameter sessions of ONE committee through collect_sessions —
# per-S proofs/s, ladders-per-launch (must equal merged groups, never
# groups x S), dedup counts, fold-ladder cache hits. Host-pinned so a
# tunnel outage cannot eat the sweep; the acceptance gate is S=8
# aggregate proofs/s >= 1.3x the S=1 rate.
run_local amortization_curve 7200 BENCH_PLATFORM=cpu BENCH_N=16 \
  BENCH_T=8 BENCH_AMORTIZE=1,2,4,8,16 python bench.py
[ -e "$R/m_amortization_curve.ok" ] && \
  cp "$R/m_amortization_curve.json" "$R/amortization_curve.json"

# Feldman MSM-delegation acceptance A/B (ISSUE 17): FSDKR_DELEGATE=0/1
# on the same fused S=4 full-parameter launch — bit-identical verdicts
# on honest AND tampered transcripts, delegated measured group ops
# strictly below the honest arm's op model.
run_local delegate_ab 7200 BENCH_PLATFORM=cpu BENCH_N=16 BENCH_T=8 \
  BENCH_DELEGATE_AB=1 BENCH_SESSIONS=4 python bench.py
[ -e "$R/m_delegate_ab.ok" ] && \
  cp "$R/m_delegate_ab.json" "$R/delegate_ab_full.json"

# full-parameter committees over the socket ingress (ISSUE 17
# satellite): the net storm harness at 2048-bit/M=256, n=16 — the
# fused amortizing path fed by real TCP clients; sessions/s-per-core
# lands next to the in-process baseline in the same report.
run_local net_full_param 7200 JAX_PLATFORMS=cpu \
  python scripts/loadgen.py --net --committees 4 --bases 2 --shards 2 \
  --clients 2 --window 60 --rate 0.15 --baseline-window 45 \
  --deadline 300 --kills 0 --seed 42 --drain-timeout 900 \
  --bits 2048 --m-security 256 --n 16 --t 8 \
  --out "$R/net_full_param.json"

# north-star shape at FULL parameters (ISSUE 10 / ROADMAP item 3): the
# n=256 / 2048-bit / M=256 end-to-end run under the memory plan. Pinned
# to the host platform (run_local) so a tunnel outage cannot eat the
# multi-hour step; FSDKR_MEM_BUDGET_MB=256 forces the multi-tile
# streaming path at this shape (the pair plan estimates ~1.6 GB
# all-resident), and BENCH_HOST_PAIRS caps the serial host-baseline
# subsample so the step's wall-clock is the measured run, not the
# oracle. DOCUMENTED FALLBACK: if the step times out or fails on this
# host (single-core n=256 full width is hours), the battery degrades to
# (a) the n=64 full-width end-to-end run under a deliberately tight
# budget — the tiled path at full width, just a smaller committee — and
# (b) the n=256 memory-plan dry-run report (scripts/memplan_report.py,
# plan-only, labeled a proxy by digest_results.py). Together they pin
# what the full run would: the plan bounds the shape, the tiles verify
# at full width.
run_local n256_full 28800 BENCH_PLATFORM=cpu BENCH_N=256 BENCH_T=128 \
  BENCH_HOST_PAIRS=64 FSDKR_MEM_BUDGET_MB=256 FSDKR_TRACE=1 python bench.py
if [ -e "$R/m_n256_full.ok" ] && [ -s "$R/m_n256_full.json" ]; then
  cp "$R/m_n256_full.json" "$R/cpu_full_n256.json"
  echo "n256_full -> cpu_full_n256.json"
else
  echo "n256_full unavailable: degrading to the documented n=64 fallback"
  run_local n64_fullwidth 7200 BENCH_PLATFORM=cpu BENCH_N=64 BENCH_T=32 \
    BENCH_HOST_PAIRS=64 FSDKR_MEM_BUDGET_MB=16 FSDKR_TRACE=1 python bench.py
  [ -e "$R/m_n64_fullwidth.ok" ] && \
    cp "$R/m_n64_fullwidth.json" "$R/cpu_full_n64_fullwidth.json"
  python scripts/memplan_report.py --out "$R/cpu_full_n256.json" \
    > "$R/cpu_full_n256.log" 2>&1 || true
fi

# canonical BENCH datapoint from the battery, copied to the repo root so
# the round's bench trajectory is populated even if the driver never
# runs bench.py itself: prefer the on-chip n16 step, fall back to the
# host-path auto-thread step
for src in n16 n16_host_tauto; do
  if [ -e "$R/m_$src.ok" ] && [ -s "$R/m_$src.json" ]; then
    cp "$R/m_$src.json" /root/repo/BENCH_battery.json
    echo "canonical datapoint: $src -> BENCH_battery.json"
    break
  fi
done
echo "=== battery done ==="
