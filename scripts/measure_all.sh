#!/bin/bash
# On-chip measurement battery: run as soon as the TPU tunnel is up.
# Produces /tmp/m_*.json + logs; each step tolerates failure.
cd /root/repo
R=/root/repo/bench_results
mkdir -p "$R"
run() {  # name, timeout, [VAR=V ...] cmd args...   (no '--': env treats
  # everything up to the first non-assignment word as the command)
  name=$1; to=$2; shift 2
  echo "=== $name ($(date +%H:%M:%S)) ==="
  timeout "$to" env "$@" > "$R/m_$name.json" 2> "$R/m_$name.log"
  echo "rc=$? tail:"; tail -3 "$R/m_$name.log"; cat "$R/m_$name.json"
}
run sweep_quick 2400 python scripts/bench_kernels.py quick
run n16 2400 FSDKR_TRACE=1 python bench.py
run join32 2400 BENCH_N=32 BENCH_T=15 BENCH_JOIN=2 python bench.py
run n64 3000 BENCH_N=64 BENCH_T=32 FSDKR_TRACE=1 python bench.py
run n128 4800 BENCH_N=128 BENCH_T=64 FSDKR_TRACE=1 python bench.py
run n256 9000 BENCH_N=256 BENCH_T=128 FSDKR_TRACE=1 python bench.py
run sessions16 4800 BENCH_SESSIONS=16 BENCH_N=16 BENCH_T=8 python bench.py
echo "=== battery done ==="
