#!/bin/bash
# Start the measurement battery once the single core is free of test
# runs. Tunnel health is handled inside measure_all.sh (it probes before
# every step and waits out tunnel outages), so the watchdog only guards
# against CPU contention and the manual pause switch.
cd /root/repo
R=/root/repo/bench_results
mkdir -p "$R"
log() { echo "[$(date +%H:%M:%S)] $*" >> "$R/watchdog.log"; }
log "watchdog start"
while [ -f /tmp/fsdkr_no_bench ] || pgrep -f pytest > /dev/null; do
  sleep 60
done
log "starting battery"
bash scripts/measure_all.sh >> "$R/battery_run.log" 2>&1
log "battery finished rc=$?"
