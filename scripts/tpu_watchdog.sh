#!/bin/bash
# Start the measurement battery once the single core is free of test
# runs. Tunnel health is handled inside measure_all.sh (it probes before
# every step and waits out tunnel outages), so the watchdog only guards
# against CPU contention and the manual pause switch.
cd /root/repo
R=/root/repo/bench_results
mkdir -p "$R"
echo $$ > "$R/.watchdog.pid"
log() { echo "[$(date +%H:%M:%S)] $*" >> "$R/watchdog.log"; }
log "watchdog start"
# anchored: match actual pytest processes only — `python -m pytest`,
# `pytest`, or `python /path/to/pytest` — not other long-running
# processes on this box that merely mention pytest in their argv
PYTEST_PAT='^[^ ]*python[0-9.]* (-m )?([^ ]*/)?pytest|^([^ ]*/)?pytest( |$)'
while [ -f /tmp/fsdkr_no_bench ] || pgrep -f "$PYTEST_PAT" > /dev/null; do
  sleep 60
done
log "starting battery"
bash scripts/measure_all.sh >> "$R/battery_run.log" 2>&1
log "battery finished rc=$?"
