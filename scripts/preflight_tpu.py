#!/usr/bin/env python
"""Pre-flight the whole protocol's TPU compile surface — no chip needed.

Runs a tiny full refresh round (keygen -> distribute -> collect) on the
CPU platform with device EC forced on, once per Pallas mode, while
recording every jitted call the protocol actually makes (via
fsdkr_tpu.utils.aot_check.capture_jitted over every kernel-bearing
module). Each distinct (function, shapes) call is then AOT-lowered for
platform "tpu".

Run this before spending tunnel time on a bench: a kernel that cannot
lower dies here in seconds instead of inside the first on-chip bench
step (which is how round 5 lost its first tunnel window).

Exit status: 0 = every captured call lowers for TPU; 1 = failures
(listed on stderr, one JSON line each on stdout).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def run_tiny_refresh(pallas_mode: str, mesh_shape=None, multiexp: str = "1"):
    """One n=4 refresh at TEST_CONFIG size; returns captured calls."""
    os.environ["FSDKR_PALLAS"] = pallas_mode
    # both planner modes must lower: =1 launches the joint multi-exp
    # kernels (CIOS + RNS), =0 the per-term column kernels
    os.environ["FSDKR_MULTIEXP"] = multiexp
    # force the TPU-platform routing: auto would send EC and modexp to
    # the host engines on this CPU host and the capture would never
    # reach the device kernels the preflight exists to lower
    os.environ["FSDKR_DEVICE_EC"] = "1"
    os.environ["FSDKR_DEVICE_POWM"] = "1"
    # force the batched-device columns even at tiny row counts so the
    # RNS/comb kernels are reached the way a full-size collect reaches them
    os.environ.setdefault("FSDKR_RNS_MIN_ROWS", "1")

    from fsdkr_tpu.config import TEST_CONFIG
    from fsdkr_tpu.ops import ec_batch, montgomery, pallas_rns, rns
    from fsdkr_tpu.parallel import shard_kernels, sharded_verify
    from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen
    from fsdkr_tpu.utils.aot_check import capture_jitted

    # the batched device path, exactly as a TPU-platform session routes it
    import dataclasses

    cfg = dataclasses.replace(
        TEST_CONFIG.with_backend("tpu"), mesh_shape=mesh_shape
    )

    modules = [
        ec_batch, montgomery, pallas_rns, rns, shard_kernels, sharded_verify,
    ]
    calls = []
    n, t = 4, 1
    with capture_jitted(modules, calls):
        keys = simulate_keygen(t, n, cfg)
        results = [RefreshMessage.distribute(k.i, k, n, cfg) for k in keys]
        msgs = [m for m, _ in results]
        # one collect exercises the full verify surface; the other
        # parties' collects would capture identical geometry
        RefreshMessage.collect(msgs, keys[0], results[0][1], [], cfg)
    return calls


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from fsdkr_tpu.utils.aot_check import lower_for_tpu

    all_calls = []
    for mode, mesh, multiexp in (
        ("0", None, "1"),
        ("1", None, "1"),
        ("0", (1,), "1"),
        ("0", None, "0"),
    ):
        log(
            f"--- capture pass: FSDKR_PALLAS={mode} mesh={mesh} "
            f"multiexp={multiexp}"
        )
        calls = run_tiny_refresh(mode, mesh_shape=mesh, multiexp=multiexp)
        log(f"    {len(calls)} jitted calls recorded")
        all_calls.extend(calls)
    os.environ.pop("FSDKR_MULTIEXP", None)
    # The mesh pass executes the shard_map wrappers (API surface, e.g.
    # the __wrapped__ unwrap) but those wrappers are factory-built, not
    # module-level jits, so they are not re-lowered here: their Mosaic
    # content is the same inner kernels captured above, and the
    # sharding/collective layer is validated by dryrun_multichip.
    log("note: sharded wrappers exercised via the mesh pass; "
        "their inner kernels are lowered below")

    # dedup by (name, full signature): one lowering per distinct geometry
    # AND static configuration — scalar kwargs like pallas_mode or
    # exp_bits select different kernel bodies, so they must stay in the
    # key (an array leaf contributes its aval, anything else its repr)
    def leaf_sig(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return (tuple(x.shape), str(x.dtype))
        return repr(x)

    seen = {}
    for name, fn, args, kwargs in all_calls:
        key = (name, str(jax.tree_util.tree_structure((args, kwargs))),
               str(jax.tree_util.tree_map(leaf_sig, (args, kwargs))))
        seen.setdefault(key, (name, fn, args, kwargs))

    log(f"--- lowering {len(seen)} distinct calls for platform tpu")
    failures = 0
    for name, fn, args, kwargs in seen.values():
        try:
            text = lower_for_tpu(fn, args, kwargs)
            rec = {"kernel": name, "ok": True,
                   "mosaic": "tpu_custom_call" in text}
        except Exception as e:
            failures += 1
            rec = {"kernel": name, "ok": False,
                   "error": f"{type(e).__name__}: {str(e)[:300]}"}
            log(f"FAIL {name}: {rec['error']}")
        print(json.dumps(rec), flush=True)

    log(f"--- preflight {'FAILED' if failures else 'ok'}: "
        f"{len(seen) - failures}/{len(seen)} lowered")
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
