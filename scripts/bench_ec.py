#!/usr/bin/env python
"""EC device-vs-host A/B: measures the crossover of the batched device EC
path (`fsdkr_tpu.ops.ec_batch`) against the host Jacobian oracle
(`fsdkr_tpu.core.secp256k1`) on the shapes collect()/distribute() actually
launch (VERDICT r4 item 3 — the EC columns displace the reference's serial
point math at `src/zk_pdl_with_slack.rs:124-127` and
`src/refresh_message.rs:177-188`).

Shapes measured, per committee size n (t = n/2):
- genmul:   s_i * G fan-out, rows = n^2       (distribute.commit_points)
- u1msm:    ONE group of 2*n^2+1 points, the random-linear-combination
            combined check                     (pdl.ec_u1)
- u1host:   the per-row host equivalent (2 muls/row) it replaces
- feldman:  n groups of (n + t + 1) points     (collect.validate_feldman)
- feldhost: host validate_share_public on the same rows

Each device measurement runs twice: first includes compile, second is the
warm number. Prints one JSON object per (shape, n) to stdout and a summary
table to stderr. BENCH_EC_NS overrides the committee sizes (comma list).

Usage:  [BENCH_PLATFORM=cpu] python scripts/bench_ec.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    import jax

    platform = jax.devices()[0].platform
    log(f"platform: {platform}")

    import secrets

    from fsdkr_tpu.core.secp256k1 import GENERATOR, N, Scalar
    from fsdkr_tpu.core.vss import ShamirSecretSharing, VerifiableSS
    from fsdkr_tpu.ops.ec_batch import batch_msm, batch_scalar_mul

    ns = [int(x) for x in os.environ.get("BENCH_EC_NS", "16,64,256").split(",")]
    # BENCH_EC_SHAPES=feldman (comma list) restricts to a subset — the
    # u1msm device shape at n=256 costs ~40 min on the CPU platform
    shapes = set(
        os.environ.get("BENCH_EC_SHAPES", "genmul,u1msm,feldman").split(",")
    )
    results = []

    def emit(shape, n, rows, host_s, dev_cold, dev_warm):
        rec = {
            "shape": shape,
            "n": n,
            "rows": rows,
            "platform": platform,
            "host_s": round(host_s, 3) if host_s is not None else None,
            "device_cold_s": round(dev_cold, 3),
            "device_warm_s": round(dev_warm, 3),
            "device_speedup_warm": (
                round(host_s / dev_warm, 3) if host_s else None
            ),
        }
        results.append(rec)
        print(json.dumps(rec), flush=True)

    for n in ns:
        t = n // 2
        rows = n * n

        host_pts = None
        if shapes & {"genmul", "u1msm"}:
            scalars = [secrets.randbelow(N) for _ in range(rows)]
            t0 = time.time()
            host_pts = [GENERATOR * Scalar.from_int(s) for s in scalars]
            host_s = time.time() - t0

        # --- genmul: s*G fan-out ---------------------------------------
        if "genmul" in shapes:
            t0 = time.time()
            dev_pts = batch_scalar_mul([GENERATOR] * rows, scalars)
            cold = time.time() - t0
            t0 = time.time()
            dev_pts = batch_scalar_mul([GENERATOR] * rows, scalars)
            warm = time.time() - t0
            assert dev_pts == host_pts, f"genmul mismatch at n={n}"
            emit("genmul", n, rows, host_s, cold, warm)
            log(f"n={n} genmul: host {host_s:.2f}s dev {warm:.2f}s")

        # --- u1: combined RLC check vs per-row host --------------------
        if "u1msm" in shapes:
            # device: one group of 2*rows+1 points, 256-bit scalars
            pts = host_pts[:rows] + host_pts[:rows] + [GENERATOR]
            scs = [secrets.randbelow(N) for _ in range(2 * rows + 1)]
            t0 = time.time()
            (comb,) = batch_msm([pts], [scs])
            cold = time.time() - t0
            t0 = time.time()
            (comb2,) = batch_msm([pts], [scs])
            warm = time.time() - t0
            assert comb == comb2
            # host equivalent: 2 scalar muls + 1 add per row
            sample = min(rows, 512)
            t0 = time.time()
            for i in range(sample):
                _ = host_pts[i] * Scalar.from_int(scs[i]) + host_pts[i] * Scalar.from_int(scs[rows + i])
            host_s = (time.time() - t0) / sample * rows
            emit("u1msm", n, 2 * rows + 1, host_s, cold, warm)
            log(f"n={n} u1: host(2muls/row, extrap) {host_s:.2f}s dev-msm {warm:.2f}s")

        if "feldman" not in shapes:
            continue
        # --- feldman: n groups of (n + t + 1) --------------------------
        params = ShamirSecretSharing(t, n)
        scheme = VerifiableSS(
            params, [GENERATOR * Scalar.from_int(i + 2) for i in range(t + 1)]
        )
        share_pts = (
            host_pts[:n]
            if host_pts is not None
            else [
                GENERATOR * Scalar.from_int(secrets.randbelow(N))
                for _ in range(n)
            ]
        )
        groups_pts, groups_scs = [], []
        for _ in range(n):
            rho = [secrets.randbits(128) for _ in range(n)]
            c_vec = [secrets.randbelow(N) for _ in range(t + 1)]
            groups_pts.append(share_pts + list(scheme.commitments))
            groups_scs.append(rho + c_vec)
        t0 = time.time()
        dev_out = batch_msm(groups_pts, groups_scs)
        cold = time.time() - t0
        t0 = time.time()
        dev_out2 = batch_msm(groups_pts, groups_scs)
        warm = time.time() - t0
        assert dev_out == dev_out2
        # host equivalent: validate_share_public per (msg, i) row
        sample = min(n * n, 256)
        done = 0
        t0 = time.time()
        for _ in range(n):
            for i in range(n):
                if done >= sample:
                    break
                scheme.validate_share_public(share_pts[i], i + 1)
                done += 1
        host_s = (time.time() - t0) / done * n * n
        emit("feldman", n, n * n, host_s, cold, warm)
        log(f"n={n} feldman: host(extrap) {host_s:.2f}s dev {warm:.2f}s")

    log("summary:")
    for r in results:
        log(
            f"  {r['shape']:8s} n={r['n']:<4d} host {r['host_s']}s "
            f"dev {r['device_warm_s']}s speedup {r['device_speedup_warm']}"
        )


if __name__ == "__main__":
    main()
