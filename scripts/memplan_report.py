#!/usr/bin/env python
"""Memory-plan dry run for a collect() shape (ISSUE 10): compute the
bytes-budgeted streaming verification plan — tile sizes, tile counts,
planned in-flight staged bytes — for a given (n, paillier_bits) shape
WITHOUT running the protocol. The plan is a pure function of public row
counts and width buckets (backend.memplan), so no keys are generated and
the report costs milliseconds.

This is the documented fallback artifact for the north-star n=256
full-parameter run: when the host cannot finish the end-to-end run
inside a battery window (measure_all.sh `n256_full`), the dry-run report
plus the n=64 full-width end-to-end run (`cpu_full_n64_fullwidth.json`)
together pin (a) that the planner bounds the n=256 shape under the
budget and (b) that the tiled path actually verifies at full width.
The record is marked `"dry_run": true` and its metric says so —
digest_results.py labels it a proxy, never a full-parameter number.

Usage:
  python scripts/memplan_report.py [--n 256] [--t 128] [--bits 2048]
      [--m 256] [--out bench_results/cpu_full_n256.json]
"""

import argparse
import json
import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--n", type=int, default=256)
    p.add_argument("--t", type=int, default=128)
    p.add_argument("--bits", type=int, default=2048)
    p.add_argument("--m", type=int, default=256)
    p.add_argument("--out", default=None)
    args = p.parse_args()

    from fsdkr_tpu.backend import memplan

    try:
        import jax

        platform = jax.devices()[0].platform
    except Exception:
        platform = "unknown"

    n, bits = args.n, args.bits
    pair_rows = n * n  # one PDL + one range row per (sender, receiver)
    feld_rows = n * n
    nn_bits = 2 * bits  # mod n^2 width
    nt_bits = bits
    row_b = memplan.pair_row_bytes(nn_bits, nt_bits)
    plan = memplan.plan_rows(pair_rows, row_b, label="pairs")
    feld_plan = memplan.plan_rows(
        feld_rows, memplan.ec_row_bytes(), label="feldman"
    )

    def plan_block(pl):
        if pl is None:
            return {"enabled": False}
        return {
            "rows": pl.rows,
            "row_bytes": pl.row_bytes,
            "tile_rows": pl.tile_rows,
            "tiles": len(pl.tiles),
            "inflight": pl.inflight,
            # in-flight staged bytes: inflight tiles, capped by the
            # whole row set (a single-tile plan peaks at rows, not 2x)
            "planned_peak_bytes": pl.tile_bytes(
                min(pl.rows, pl.tile_rows * pl.inflight)
            ),
            "budget_bytes": pl.budget,
            "monolithic_estimate_bytes": pl.rows * pl.row_bytes,
        }

    pairs = plan_block(plan)
    rec = {
        "metric": (
            f"memory-plan dry run @ n={n},t={args.t},{bits}-bit,"
            f"M={args.m} [plan only — see cpu_full_n64_fullwidth.json "
            f"for the end-to-end full-width run]"
        ),
        "dry_run": True,
        "value": 0,
        "unit": "proofs/s",
        "vs_baseline": 0,
        "platform": platform,
        "n": n,
        "t": args.t,
        "paillier_bits": bits,
        "m_security": args.m,
        "budget_mb": os.environ.get("FSDKR_MEM_BUDGET_MB", "256"),
        "pair_plan": pairs,
        "feldman_plan": plan_block(feld_plan),
        "mem": memplan.mem_stats(),
    }
    if pairs.get("tiles"):
        # the headline claim: bounded in-flight staged bytes vs the
        # monolithic all-rows-resident estimate
        rec["resident_reduction_x"] = round(
            pairs["monolithic_estimate_bytes"]
            / max(1, pairs["planned_peak_bytes"]),
            2,
        )
    out = args.out or "bench_results/cpu_full_n256.json"
    pathlib.Path(out).parent.mkdir(parents=True, exist_ok=True)
    pathlib.Path(out).write_text(json.dumps(rec, indent=1) + "\n")
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
