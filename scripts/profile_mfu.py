#!/usr/bin/env python
"""Measured MFU for the modexp kernel families (on-chip ground truth).

bench_kernels.py reports wall-clock modexp/s; the roofline meter
(fsdkr_tpu/utils/roofline.py) prices each launch in analytic u16 MACs.
This script closes the loop the round-4 verdict flagged ("until xprof
runs on chip, even the MFU numbers are a model"): it wraps timed reps in
a real `jax.profiler.trace`, then parses the dumped Perfetto
trace.json.gz and sums device-track op durations, giving

  mfu_wall   = macs / wall_s   / peak      (what the tracer reports)
  mfu_device = macs / device_s / peak      (profiler-measured busy time)
  occupancy  = device_s / wall_s           (host/dispatch overhead share)

Reference workload being priced: the collect() verify loop,
/root/reference/src/refresh_message.rs:321-467 (n^2 x ~11 modexps).

Usage: python scripts/profile_mfu.py [quick|full]
Output: JSON lines to stdout; xprof traces under bench_results/xprof/.
"""

import glob
import gzip
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

R = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "bench_results")


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _merge_intervals_us(intervals):
    """Total covered time of [start, end) microsecond intervals."""
    total = 0.0
    end = None
    for s, e in sorted(intervals):
        if end is None or s > end:
            total += e - s
            end = e
        elif e > end:
            total += e - end
            end = e
    return total


def _leaf_intervals(intervals):
    """Drop container intervals: trace events nest, and the umbrella
    "step"/module events SPAN the ops they contain — including the
    host-side gaps between launches, which are not device-busy time.
    Keeping only leaves (intervals that contain no other interval) drops
    the umbrellas wherever real op events exist, while a dump with only
    umbrella events keeps them (they are leaves then)."""
    ivs = sorted(intervals, key=lambda se: (se[0], -se[1]))
    out = []
    stack = []  # [start, end, has_child]

    def flush(node):
        if not node[2]:
            out.append((node[0], node[1]))

    for s, e in ivs:
        while stack and stack[-1][1] <= s:
            flush(stack.pop())
        if stack:
            stack[-1][2] = True  # current nests (or overlaps) into top
        stack.append([s, e, False])
    while stack:
        flush(stack.pop())
    return out


def _parse_device_busy_s(trace_dir):
    """Busy time of the device tracks of the newest Perfetto dump.

    The profiler writes <dir>/plugins/profile/<run>/*.trace.json.gz with
    one process per hardware unit. Device tracks are the ones whose
    process name mentions the TPU core ("/device:TPU" or "TensorCore");
    host/python threads are excluded. Busy time is the UNION of the LEAF
    op intervals: xprof dumps interleave umbrella "step"/module events
    that span the ops they contain (including host gaps between
    launches) — different xprof versions put them on different tids, so
    the old tid==0 heuristic either double-counted (steps on another
    tid) or dropped real op time (ops on tid 0). Dropping containers
    (_leaf_intervals) then merging overlaps (_merge_intervals_us) is
    correct under any nesting/track layout and degrades to the plain sum
    when nothing nests or overlaps (ops serialize per core)."""
    dumps = sorted(
        glob.glob(os.path.join(trace_dir, "plugins", "profile", "*",
                               "*.trace.json.gz")),
        key=os.path.getmtime,
    )
    if not dumps:
        return None
    with gzip.open(dumps[-1], "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    device_pids = set()
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pname = ev.get("args", {}).get("name", "")
            if "TPU" in pname or "TensorCore" in pname:
                device_pids.add(ev["pid"])
    intervals = []
    for ev in events:
        if ev.get("ph") == "X" and ev.get("pid") in device_pids:
            ts = float(ev.get("ts", 0.0))
            dur = float(ev.get("dur", 0.0))
            if dur > 0:
                intervals.append((ts, ts + dur))
    busy_us = _merge_intervals_us(_leaf_intervals(intervals))
    return busy_us / 1e6 if busy_us else None


def _workload(bits, exp_bits, rows, seed=0):
    import random

    rng = random.Random(seed)
    moduli = [rng.getrandbits(bits) | (1 << (bits - 1)) | 1 for _ in range(rows)]
    bases = [rng.getrandbits(bits - 1) for _ in range(rows)]
    exps = [rng.getrandbits(exp_bits) | (1 << (exp_bits - 1)) for _ in range(rows)]
    return bases, exps, moduli


def profile_point(kind, bits, exp_bits, rows, reps=2):
    from fsdkr_tpu.ops.limbs import limbs_for_bits
    from fsdkr_tpu.ops.montgomery import BatchModExp
    from fsdkr_tpu.ops import rns
    from fsdkr_tpu.utils import roofline

    bases, exps, moduli = _workload(bits, exp_bits, rows)
    if kind == "cios":
        ctx = BatchModExp(moduli, limbs_for_bits(bits))
        run = lambda: ctx.modexp(bases, exps)
    elif kind in ("rns", "rns-pallas"):
        os.environ["FSDKR_PALLAS"] = "1" if kind == "rns-pallas" else "0"
        run = lambda: rns.rns_modexp(bases, exps, moduli, bits)
    else:
        raise ValueError(kind)

    out = run()  # compile + correctness
    for i in (0, rows - 1):
        assert out[i] == pow(bases[i] % moduli[i], exps[i], moduli[i]), (
            f"{kind} wrong at row {i}"
        )
    run()  # warm

    import jax

    trace_dir = os.path.join(R, "xprof", f"{kind}_{bits}b_e{exp_bits}_r{rows}")
    os.makedirs(trace_dir, exist_ok=True)
    # time only the rep loop: profiler start/stop and the Perfetto dump
    # on context exit must not be charged to the kernel
    with jax.profiler.trace(trace_dir):
        t0 = time.time()
        for _ in range(reps):
            run()
        wall = (time.time() - t0) / reps

    device_s = _parse_device_busy_s(trace_dir)
    if device_s is not None:
        device_s /= reps

    # analytic MAC count for the same launch geometry the tracer prices
    if kind == "cios":
        k = limbs_for_bits(bits)
    else:
        k = rns.rns_bases_for_bits(bits, limbs_for_bits(bits)).k
    macs = roofline.generic_modexp_macs(rows, exp_bits, k)
    peak = roofline.peak_macs()
    rec = {
        "kernel": kind,
        "bits": bits,
        "exp_bits": exp_bits,
        "rows": rows,
        "wall_s": round(wall, 4),
        "device_s": round(device_s, 4) if device_s else None,
        "modexp_per_s": round(rows / wall, 1),
        "analytic_macs": macs,
        "mac_per_s_wall": round(macs / wall, 3),
        "mfu_wall": round(macs / wall / peak, 5),
        "mfu_device": (
            round(macs / device_s / peak, 5) if device_s else None
        ),
        "occupancy": round(device_s / wall, 4) if device_s else None,
        "trace_dir": os.path.relpath(trace_dir, R),
    }
    print(json.dumps(rec), flush=True)
    log(f"{kind} {bits}b e={exp_bits} rows={rows}: wall {wall:.3f}s, "
        f"device {device_s if device_s else float('nan'):.3f}s, "
        f"MFU(wall) {rec['mfu_wall']:.2%}"
        + (f", MFU(device) {rec['mfu_device']:.2%}" if device_s else ""))
    return rec


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "quick"
    import jax

    platform = jax.devices()[0].platform
    log(f"platform: {platform}")
    if platform == "cpu":
        log("WARNING: CPU platform — numbers are not chip MFU")

    points = [
        ("rns-pallas", 2048, 2048, 1024),
        ("rns", 2048, 2048, 1024),
        ("cios", 2048, 256, 1024),
    ]
    if mode == "full":
        points += [
            ("rns-pallas", 2048, 256, 1024),
            ("rns-pallas", 4096, 2048, 512),
            ("rns", 4096, 2048, 512),
            ("cios", 2048, 2048, 512),
        ]
    if os.environ.get("FSDKR_NO_PALLAS") == "1":  # see bench_kernels.py
        points = [p for p in points if "pallas" not in p[0]]
    for kind, bits, eb, rows in points:
        try:
            profile_point(kind, bits, eb, rows)
        except Exception as e:  # keep later points alive past one failure
            print(json.dumps({
                "kernel": kind, "bits": bits, "exp_bits": eb, "rows": rows,
                "error": f"{type(e).__name__}: {e}",
            }), flush=True)
            log(f"{kind} {bits}b FAILED: {e}")


if __name__ == "__main__":
    main()
