#!/usr/bin/env python
"""Minimal static lint for an image without pyflakes/ruff: flags unused
imports, per file, via the ast module. Conservative by design —
`__all__` entries, re-export modules (__init__.py), names starting with
'_', and names referenced from quoted string annotations are exempt.

Also enforces LAYERING rules (ISSUE 9): `fsdkr_tpu/serving` is an
orchestration layer and must reach the cryptography only through the
protocol surface — importing `proofs`, `backend`, `ops`, `native`, or
`core` internals from serving (absolute or relative) is a finding, so a
violation fails ci.sh instead of fossilizing.

Usage: python scripts/lint_imports.py [paths...]   (default: fsdkr_tpu)
Exit code 1 if any finding (ci.sh lint gate).
"""

import ast
import pathlib
import sys

# package-dir -> module prefixes its files must not import. Checked for
# every *.py under the directory, __init__.py included.
LAYERING_RULES = {
    "fsdkr_tpu/serving": (
        "fsdkr_tpu.proofs",
        "fsdkr_tpu.backend",
        "fsdkr_tpu.ops",
        "fsdkr_tpu.native",
        "fsdkr_tpu.core",
    ),
}


def _abs_module(node, path: pathlib.Path):
    """Absolute dotted module of an ImportFrom, resolving relative
    imports against the file's package (CPython semantics: __package__
    is the containing package for BOTH regular modules and __init__.py,
    and level N strips N-1 trailing components from it)."""
    if node.level == 0:
        return node.module or ""
    parts = path.resolve().parts
    try:
        root = parts.index("fsdkr_tpu")
    except ValueError:
        return node.module or ""
    pkg = list(parts[root:-1])  # the module's package path
    base = pkg[: len(pkg) - (node.level - 1)] if node.level > 1 else pkg
    return ".".join(base + ([node.module] if node.module else []))


def check_layering(path: pathlib.Path, tree) -> list:
    rel = path.as_posix()
    rules = [
        banned
        for prefix, banned in LAYERING_RULES.items()
        if f"/{prefix}/" in f"/{rel}" or rel.startswith(prefix + "/")
    ]
    if not rules:
        return []
    banned = tuple(b for rule in rules for b in rule)
    findings = []
    for node in ast.walk(tree):
        mods = []
        if isinstance(node, ast.Import):
            mods = [a.name for a in node.names]
        elif isinstance(node, ast.ImportFrom):
            mods = [_abs_module(node, path)]
        for mod in mods:
            for b in banned:
                if mod == b or mod.startswith(b + "."):
                    findings.append(
                        f"{path}:{node.lineno}: layering violation: "
                        f"serving must not import {mod!r} (use the "
                        f"protocol surface)"
                    )
    return findings


def check_file(path: pathlib.Path):
    tree = ast.parse(path.read_text(), filename=str(path))
    layering = check_layering(path, tree)
    if path.name == "__init__.py":
        return layering  # re-export wiring: imports are the point

    exported = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    try:
                        exported = set(ast.literal_eval(node.value))
                    except ValueError:
                        pass

    imported = {}  # name -> lineno
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                imported[name] = node.lineno
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue  # compiler directives, not names
            for a in node.names:
                if a.name == "*":
                    continue
                imported[a.asname or a.name] = node.lineno

    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            # quoted annotations ('-> "ProtocolConfig"', TYPE_CHECKING
            # uses) reference names as strings: count their roots as used
            try:
                sub = ast.parse(node.value, mode="eval")
            except SyntaxError:
                continue
            for n in ast.walk(sub):
                if isinstance(n, ast.Name):
                    used.add(n.id)
        elif isinstance(node, ast.Attribute):
            # record the root of dotted access: jax.numpy -> jax
            n = node
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                used.add(n.id)

    findings = layering
    for name, lineno in sorted(imported.items(), key=lambda kv: kv[1]):
        if name in used or name in exported or name.startswith("_"):
            continue
        findings.append(f"{path}:{lineno}: unused import {name!r}")
    return findings


def main():
    roots = [pathlib.Path(p) for p in (sys.argv[1:] or ["fsdkr_tpu"])]
    findings = []
    for root in roots:
        if not root.exists():
            # a renamed/misspelled root must fail the gate, not silently
            # shrink its coverage to nothing
            print(f"lint_imports: no such path: {root}", file=sys.stderr)
            return 1
        files = [root] if root.is_file() else sorted(root.rglob("*.py"))
        for f in files:
            findings += check_file(f)
    for line in findings:
        print(line)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
