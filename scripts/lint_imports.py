#!/usr/bin/env python
"""Back-compat shim (ISSUE 14): the unused-import + layering rules now
live in the fsdkr-lint framework (`fsdkr_tpu/analysis/imports.py`,
driver `scripts/fsdkr_lint.py`). This entry point keeps the old CLI —
same paths, same exit-code contract — and runs exactly the imports
pass.

Usage: python scripts/lint_imports.py [paths...]   (default: fsdkr_tpu)
Exit code 1 if any finding.
"""

import sys

from fsdkr_lint import main as _lint_main


def main() -> int:
    paths = sys.argv[1:] or ["fsdkr_tpu"]
    return _lint_main(["--passes", "imports", "-q"] + paths)


if __name__ == "__main__":
    sys.exit(main())
