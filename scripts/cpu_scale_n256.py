#!/usr/bin/env python
"""Config-4-at-reduced-parameters structural run: collect() at n=256,
t=128 end-to-end on whatever platform JAX has (VERDICT r4 item 2 — the
first execution of the north-star shape anywhere; reference loop
`/root/reference/src/refresh_message.rs:321-467`).

Reduced parameters (768-bit moduli, M=32, 3 correct-key rounds) keep the
single-core wall-clock in hours instead of days while exercising exactly
what the item asks: the 131,072-row pair gather, the per-family fused
launches, shape bucketing, and the memory plan at n=256. The series is
comparable to bench_results/cpu_scale_n64.json (same parameters, n=64).

One collect (not cold+warm): on the fallback platform the point is
structural proof, not steady-state throughput; the trace splits compile
from compute via the persistent cache delta. A small host-baseline
subsample (HOST_PAIRS rows) gives the extrapolated vs_baseline.

Writes ONE JSON line to stdout; progress to stderr.
"""

import faulthandler
import json
import os
import sys
import time

# a fatal signal (e.g. SIGILL from a stale cross-machine XLA AOT cache
# entry — the silent death mode of the first attempt) must leave a trace
faulthandler.enable(file=sys.stderr)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    # self-written pidfile: `$!` after `setsid nohup ... &` records the
    # short-lived wrapper, not this process (see memory: box-quirks)
    _repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        with open(
            os.path.join(_repo, "bench_results", ".cpu_scale.pid"), "w"
        ) as f:
            f.write(str(os.getpid()))
    except OSError:
        pass

    n = int(os.environ.get("BENCH_N", "256"))
    t = int(os.environ.get("BENCH_T", str(n // 2)))
    bits = int(os.environ.get("BENCH_BITS", "768"))
    m_sec = int(os.environ.get("BENCH_M", "32"))
    ck_rounds = int(os.environ.get("BENCH_CK", "3"))
    host_pairs = int(os.environ.get("HOST_PAIRS", "128"))

    plat = os.environ.get("BENCH_PLATFORM", "cpu")
    import jax

    if plat:
        jax.config.update("jax_platforms", plat)
    from bench import _jax_cache_dir  # single source for the cache path

    cache_dir = _jax_cache_dir()
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception:
        pass
    platform = jax.devices()[0].platform
    log(f"platform: {platform} n={n} t={t} bits={bits} M={m_sec}")

    os.environ.setdefault("FSDKR_TRACE", "1")
    from fsdkr_tpu.config import ProtocolConfig
    from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen
    from fsdkr_tpu.utils.trace import get_tracer

    cfg = ProtocolConfig(
        paillier_bits=bits, m_security=m_sec, correct_key_rounds=ck_rounds
    )
    tpu_cfg = cfg.with_backend("tpu")

    t0 = time.time()
    keys = simulate_keygen(t, n, cfg)
    t_keygen = time.time() - t0
    log(f"keygen: {t_keygen:.1f}s")

    get_tracer().reset()
    t0 = time.time()
    results = RefreshMessage.distribute_batch(
        [(key.i, key) for key in keys], n, tpu_cfg
    )
    t_distribute = time.time() - t0
    msgs = [m for m, _ in results]
    dks = [dk for _, dk in results]
    dist_stats = get_tracer().stats()
    trace_distribute = {
        name: round(st.seconds, 3)
        for name, st in dist_stats.items()
        if name.startswith("distribute.")
    }
    log(f"distribute_batch: {t_distribute:.1f}s {trace_distribute}")

    cache_before = len(os.listdir(cache_dir)) if os.path.isdir(cache_dir) else 0
    get_tracer().reset()
    log("starting collect ...")
    t0 = time.time()
    RefreshMessage.collect(msgs, keys[0].clone(), dks[0], (), tpu_cfg)
    t_collect = time.time() - t0
    cache_after = len(os.listdir(cache_dir)) if os.path.isdir(cache_dir) else 0
    stats = get_tracer().stats()
    trace = {name: round(st.seconds, 3) for name, st in stats.items()}
    proofs = 2 * n * n + 2 * n
    log(
        f"collect: {t_collect:.1f}s -> {proofs / t_collect:.1f} proofs/s "
        f"({cache_after - cache_before} fresh compiles)"
    )
    log(get_tracer().report())

    # host baseline on a small subsample of the pair loop
    from fsdkr_tpu.backend.batch_verifier import HostBatchVerifier
    from fsdkr_tpu.core.secp256k1 import GENERATOR
    from fsdkr_tpu.proofs.pdl_slack import PDLwSlackStatement

    host = HostBatchVerifier(cfg.hash_alg)
    key = keys[1]
    pdl_items, range_items = [], []
    for msg in msgs:
        for i in range(n):
            if len(pdl_items) >= host_pairs:
                break
            st = PDLwSlackStatement(
                ciphertext=msg.points_encrypted_vec[i],
                ek=key.paillier_key_vec[i],
                Q=msg.points_committed_vec[i],
                G=GENERATOR,
                h1=key.h1_h2_n_tilde_vec[i].g,
                h2=key.h1_h2_n_tilde_vec[i].ni,
                N_tilde=key.h1_h2_n_tilde_vec[i].N,
            )
            pdl_items.append((msg.pdl_proof_vec[i], st))
            range_items.append(
                (
                    msg.range_proofs[i],
                    msg.points_encrypted_vec[i],
                    key.paillier_key_vec[i],
                    key.h1_h2_n_tilde_vec[i],
                )
            )
        if len(pdl_items) >= host_pairs:
            break
    t0 = time.time()
    ok_pdl = all(v is None for v in host.verify_pdl(pdl_items))
    ok_rng = all(host.verify_range(range_items))
    per_pair = (time.time() - t0) / len(pdl_items)
    if not (ok_pdl and ok_rng):
        raise RuntimeError("host baseline rejected a valid proof")
    t_host = n * n * per_pair  # pair loop only (dominant term)
    log(
        f"host baseline: {per_pair * 1e3:.1f} ms/pair -> ~{t_host:.0f}s "
        f"extrapolated pair loop"
    )

    print(
        json.dumps(
            {
                "metric": f"collect() @ n={n},t={t},{bits}-bit,M={m_sec} "
                f"[structural, {platform}]",
                "value": round(proofs / t_collect, 2),
                "unit": "proofs/s",
                "vs_baseline": round(t_host / t_collect, 2),
                "collect_s": round(t_collect, 2),
                "distribute_batch_s": round(t_distribute, 2),
                "keygen_s": round(t_keygen, 2),
                "fresh_compiles": cache_after - cache_before,
                "host_pair_ms": round(per_pair * 1e3, 2),
                "platform": platform,
                "trace": trace,
                "trace_distribute": trace_distribute,
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
