#!/usr/bin/env python
"""Serving load generator (ISSUE 9): Poisson refresh arrivals across
hundreds-to-thousands of concurrent committees through RefreshService,
reporting sustained sessions/sec + exact end-to-end latency percentiles
+ pool economics into bench_results/serving_*.json.

Phases:
  1. keygen `--bases` distinct committees at the serve parameters and
     clone them out to `--committees` (cloned committees share auxiliary
     mod-N~ parameters until their first epoch rotates every Paillier
     key, after which all pool keys are genuinely per-committee; the
     clone count is reported, never hidden).
  2. admit everything, run one unmeasured seed epoch per committee
     (registers each committee's SLO-derived pool targets keyed by its
     post-seed key material and warms the persistent engine caches).
  3. prefill wait: let the background producer fill the planned depth
     targets (bounded by --prefill-wait).
  4. the measured window (--window seconds): open-loop Poisson arrivals
     at --rate sessions/sec over uniformly random committees, then
     drain. Pool dry-fallback counters are snapshotted at the window
     edges so the steady-state dry rate excludes setup.

Honesty rules (matching bench.py): the JSON carries the platform tag,
every knob that shaped the run, offered vs completed rate, shed
arrivals (backlog cap), and the full telemetry snapshot. Exact
percentiles come from per-session wall clocks, not histogram
interpolation.

Usage (acceptance shape, fallback platform):
  python scripts/loadgen.py --committees 200 --window 60
Smoke (ci.sh):
  python scripts/loadgen.py --committees 8 --bases 2 --window 5 --rate 2
"""

import argparse
import json
import os
import pathlib
import random
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--committees", type=int, default=200)
    p.add_argument("--bases", type=int, default=4,
                   help="distinct keygen committees cloned out to --committees")
    p.add_argument("--n", type=int, default=3, help="committee size")
    p.add_argument("--t", type=int, default=1, help="threshold")
    p.add_argument("--bits", type=int, default=640,
                   help="Paillier modulus bits (640 = smallest exact-recovery size)")
    p.add_argument("--m-security", type=int, default=8)
    p.add_argument("--ck-rounds", type=int, default=2)
    p.add_argument("--backend", default="tpu",
                   help="protocol backend (tpu = batched engines, auto-routed)")
    p.add_argument("--window", type=float, default=60.0,
                   help="measured window seconds")
    p.add_argument("--rate", type=float, default=0.0,
                   help="offered sessions/sec (0 = auto: ~70%% of calibrated capacity)")
    p.add_argument("--seed-epochs", type=int, default=1)
    p.add_argument("--prefill-wait", type=float, default=60.0)
    p.add_argument("--drain-timeout", type=float, default=300.0)
    p.add_argument("--max-backlog", type=int, default=64,
                   help="arrivals shed (not queued) beyond this in-flight count")
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--tag", default="sustained")
    p.add_argument("--out", default=None,
                   help="report path (default bench_results/serving_<tag>.json)")
    return p.parse_args()


def _mem_block():
    """Memory-plan stat block for the serving report (matches bench.py's
    `mem` field): budget, staged/peak bytes, process VmHWM, tiles."""
    from fsdkr_tpu.backend import memplan

    return memplan.mem_stats()


def percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return round(sorted_vals[idx], 4)


def main():
    args = parse_args()
    t_start = time.time()

    from fsdkr_tpu import precompute
    from fsdkr_tpu.config import ProtocolConfig
    from fsdkr_tpu.protocol import simulate_keygen
    from fsdkr_tpu.serving import RefreshService, SLO, enabled as serve_enabled
    from fsdkr_tpu.telemetry import export as tel_export

    config = ProtocolConfig(
        paillier_bits=args.bits,
        m_security=args.m_security,
        correct_key_rounds=args.ck_rounds,
        backend=args.backend,
    )
    try:
        import jax

        platform = jax.devices()[0].platform
    except Exception:
        platform = "unknown"

    rng = random.Random(args.seed)

    # ---- phase 1: committees -----------------------------------------
    log = lambda *a: print(*a, file=sys.stderr, flush=True)
    log(f"[loadgen] keygen {args.bases} base committees "
        f"(n={args.n}, t={args.t}, {args.bits}-bit)")
    t0 = time.time()
    keygen = getattr(simulate_keygen, "uncached", simulate_keygen)
    bases = [keygen(args.t, args.n, config) for _ in range(args.bases)]
    committees = {
        cid: [k.clone() for k in bases[cid % args.bases]]
        for cid in range(args.committees)
    }
    keygen_s = time.time() - t0
    log(f"[loadgen] keygen {keygen_s:.1f}s; admitting {args.committees} committees")

    svc = RefreshService()
    # per-committee rate: the offered total spread uniformly
    per_rate = (args.rate or 1.0) / max(1, args.committees)
    for cid, keys in committees.items():
        svc.admit(cid, keys, config, SLO(arrival_rate_hz=per_rate))
    svc.start()

    # ---- phase 2: seed epochs ----------------------------------------
    t0 = time.time()
    for _epoch in range(args.seed_epochs):
        for cid in committees:
            svc.submit(cid)
        if not svc.drain(timeout=max(args.drain_timeout, 12 * args.committees)):
            log("[loadgen] WARNING: seed epoch did not drain; continuing")
    seed_s = time.time() - t0
    st = svc.stats()
    seed_done = st["sessions_done"]
    log(f"[loadgen] seeded {seed_done} sessions in {seed_s:.1f}s "
        f"({seed_done / seed_s:.2f}/s single-stream)")

    # auto rate: ~70% of the calibrated closed-loop capacity so the
    # producer has idle time to keep pools at depth (open-loop at or
    # above capacity is a queueing divergence, not a steady state)
    rate = args.rate
    if rate <= 0:
        rate = max(0.1, 0.7 * seed_done / seed_s) if seed_s > 0 else 1.0
        log(f"[loadgen] auto rate: {rate:.2f} sessions/s")

    # ---- phase 3: prefill wait ---------------------------------------
    t0 = time.time()
    precompute.kick()
    deficit0 = precompute.deficit_total()
    while time.time() - t0 < args.prefill_wait:
        if precompute.deficit_total() == 0:
            break
        time.sleep(0.25)
    prefill_s = time.time() - t0
    deficit_left = precompute.deficit_total()
    log(f"[loadgen] prefill {prefill_s:.1f}s "
        f"(deficit {deficit0} -> {deficit_left})")

    # ---- phase 4: measured window ------------------------------------
    from fsdkr_tpu.serving import metrics as smetrics

    smetrics.phase_histogram().reset()
    smetrics.sessions_counter().reset()
    smetrics.batch_histogram().reset()
    pool0 = precompute.precompute_stats()
    win_ids = []
    shed = 0
    cids = list(committees)
    t_win = time.monotonic()
    next_arrival = t_win
    while True:
        now = time.monotonic()
        if now - t_win >= args.window:
            break
        if now < next_arrival:
            time.sleep(min(0.005, next_arrival - now))
            continue
        next_arrival += rng.expovariate(rate)
        if svc.stats()["inflight"] >= args.max_backlog:
            shed += 1
            continue
        win_ids.append(svc.submit(rng.choice(cids)))
    window_s = time.monotonic() - t_win
    drained = svc.drain(timeout=args.drain_timeout)
    drain_s = time.monotonic() - t_win - window_s
    pool1 = precompute.precompute_stats()

    sessions = [svc.wait(sid, 0) for sid in win_ids]
    done = [s for s in sessions if s.state == "done"]
    aborted = [s for s in sessions if s.state == "aborted"]
    # completed-inside-window throughput (the sustained figure) plus the
    # drain-inclusive one (total work the window's offered load produced)
    done_in_window = [
        s for s in done if s.finalized_at - t_win <= args.window
    ]
    lat = sorted(s.finalized_at - s.submitted_at for s in done)
    consumed = pool1["consumed"] - pool0["consumed"]
    dry = pool1["dry_fallbacks"] - pool0["dry_fallbacks"]
    takes = consumed + dry
    dry_rate = round(dry / takes, 4) if takes else None

    prod = {}
    for rec in tel_export.snapshot()["metrics"].get(
        "fsdkr_producer_occupancy", {}
    ).get("values", []):
        prod["occupancy"] = round(rec["value"], 4)

    report = {
        "metric": "serve_sustained",
        "platform": platform,
        "fsdkr_serve": serve_enabled(),
        "committees": args.committees,
        "distinct_bases": args.bases,
        "n": args.n,
        "t": args.t,
        "paillier_bits": args.bits,
        "m_security": args.m_security,
        "correct_key_rounds": args.ck_rounds,
        "window_s": round(window_s, 2),
        "drain_s": round(drain_s, 2),
        "drained": drained,
        "offered_rate_hz": round(rate, 4),
        "arrivals": len(win_ids),
        "shed": shed,
        "sessions_done": len(done),
        "sessions_done_in_window": len(done_in_window),
        "sessions_aborted": len(aborted),
        "abort_errors": sorted({s.error for s in aborted})[:5],
        "sessions_per_s": round(len(done_in_window) / window_s, 4),
        "sessions_per_s_incl_drain": (
            round(len(done) / (window_s + drain_s), 4)
            if window_s + drain_s > 0 else None
        ),
        "latency_s": {
            "p50": percentile(lat, 0.50),
            "p95": percentile(lat, 0.95),
            "p99": percentile(lat, 0.99),
            "mean": round(sum(lat) / len(lat), 4) if lat else None,
            "max": round(lat[-1], 4) if lat else None,
        },
        "pool": {
            "consumed": consumed,
            "dry_fallbacks": dry,
            "dry_fallback_rate": dry_rate,
            "produced": pool1["produced"] - pool0["produced"],
            "bytes_pooled": pool1["bytes_pooled"],
            "entries_pooled": pool1["entries"],
            "pools": pool1["pools"],
            "prefill_deficit_left": deficit_left,
        },
        "producer": prod,
        # per-process memory accounting (ISSUE 10): VmHWM ground truth +
        # the memory-plan block — the serving loop's bounded-per-session
        # claim is checkable from the report alone
        "mem": _mem_block(),
        "setup": {
            "keygen_s": round(keygen_s, 1),
            "seed_epochs": args.seed_epochs,
            "seed_s": round(seed_s, 1),
            "seed_sessions_per_s": (
                round(seed_done / seed_s, 3) if seed_s > 0 else None
            ),
            "prefill_s": round(prefill_s, 1),
        },
        "knobs": {
            "FSDKR_SERVE_BATCH": svc.policy.max_sessions,
            "FSDKR_SERVE_LINGER_MS": round(svc.policy.linger_s * 1000, 1),
            "FSDKR_SERVE_WORKERS": svc.workers,
            "FSDKR_SERVE_HORIZON_S": svc.planner.horizon_s,
            "FSDKR_SERVE_MAX_AHEAD": svc.planner.max_ahead,
            "FSDKR_POOL_DEPTH": os.environ.get("FSDKR_POOL_DEPTH", "64"),
            "max_backlog": args.max_backlog,
        },
        "telemetry": tel_export.snapshot(),
    }
    svc.stop()
    precompute.stop_background()

    out = args.out or f"bench_results/serving_{args.tag}.json"
    pathlib.Path(out).parent.mkdir(parents=True, exist_ok=True)
    pathlib.Path(out).write_text(json.dumps(report, indent=1) + "\n")
    log(f"[loadgen] report -> {out} (total wall {time.time() - t_start:.0f}s)")
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
