#!/usr/bin/env python
"""Serving load generator (ISSUE 9; chaos mode ISSUE 11): Poisson
refresh arrivals across hundreds-to-thousands of concurrent committees
through RefreshService, reporting sustained sessions/sec + exact
end-to-end latency percentiles + pool economics into
bench_results/serving_*.json — and, with --chaos, the same Poisson
window under a deterministic fault plan (FSDKR_FAULTS spec) with
verdict-correctness accounting into bench_results/chaos_*.json.

Phases:
  1. keygen `--bases` distinct committees at the serve parameters and
     clone them out to `--committees` (cloned committees share auxiliary
     mod-N~ parameters until their first epoch rotates every Paillier
     key, after which all pool keys are genuinely per-committee; the
     clone count is reported, never hidden).
  2. admit everything, run one unmeasured seed epoch per committee
     (registers each committee's SLO-derived pool targets keyed by its
     post-seed key material and warms the persistent engine caches).
  3. prefill wait: let the background producer fill the planned depth
     targets (bounded by --prefill-wait).
  4. the measured window (--window seconds): open-loop Poisson arrivals
     at --rate sessions/sec over uniformly random committees, then
     drain. Pool dry-fallback counters are snapshotted at the window
     edges so the steady-state dry rate excludes setup.

Chaos mode (--chaos) inserts between 3 and 4:
  3b. a fault-free BASELINE window (--baseline-window) for the healthy
      p99 the chaos p99 is gated against, then installs the fault plan
      and runs the measured window under injection. Every session's
      outcome is classified against the faults that actually hit it:
      zero wedged sessions and zero wrong verdicts (no healthy session
      aborted with blame, no tampered session finished clean) are hard
      report fields, not prose.
  5.  the tamper-economics curve (--curve, default 0/1/5%): closed-loop
      bursts at each malicious-traffic rate, reporting RLC bisection
      fallbacks and wall cost per session — the ROADMAP 5b measurement
      of what tampered traffic costs a shard under the bisection-depth
      budget (--bisect-budget arms the admission guard).

Honesty rules (matching bench.py): the JSON carries the platform tag,
every knob that shaped the run, offered vs completed rate, shed
arrivals (backlog cap), and the full telemetry snapshot. Exact
percentiles come from per-session wall clocks, not histogram
interpolation.

Usage (acceptance shape, fallback platform):
  python scripts/loadgen.py --committees 200 --window 60
Chaos storm (ISSUE 11 acceptance):
  python scripts/loadgen.py --chaos --committees 24 --window 30
Smoke (ci.sh):
  python scripts/loadgen.py --committees 8 --bases 2 --window 5 --rate 2
"""

import argparse
import json
import os
import pathlib
import random
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# rates chosen so a short smoke window still fires every class at least
# once (per-message sites roll n times per session); seed is appended
DEFAULT_FAULTS = (
    "worker_crash=0.3,finalize_exc=0.25,pool_dry=0.05,msg_delay=0.15,"
    "msg_drop=0.12,msg_dup=0.15,msg_tamper=0.15,mem_squeeze=0.5,"
    "delay_s=0.4,squeeze_factor=0.25"
)

# network-chaos storm (ISSUE 13): per-frame rates at the ingress — a
# session exchanges ~8-10 frames, so a few percent per frame hits a
# large fraction of sessions with at least one dropped connection,
# torn response, duplicated response, or delayed answer
DEFAULT_NET_FAULTS = (
    "conn_drop=0.04,frame_truncate=0.02,net_delay=0.08,net_dup=0.06,"
    "delay_s=0.3"
)


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--committees", type=int, default=200)
    p.add_argument("--bases", type=int, default=4,
                   help="distinct keygen committees cloned out to --committees")
    p.add_argument("--n", type=int, default=3, help="committee size")
    p.add_argument("--t", type=int, default=1, help="threshold")
    p.add_argument("--bits", type=int, default=640,
                   help="Paillier modulus bits (640 = smallest exact-recovery size)")
    p.add_argument("--m-security", type=int, default=8)
    p.add_argument("--ck-rounds", type=int, default=2)
    p.add_argument("--backend", default="tpu",
                   help="protocol backend (tpu = batched engines, auto-routed)")
    p.add_argument("--window", type=float, default=60.0,
                   help="measured window seconds")
    p.add_argument("--rate", type=float, default=0.0,
                   help="offered sessions/sec (0 = auto: ~70%% of calibrated capacity)")
    p.add_argument("--seed-epochs", type=int, default=1)
    p.add_argument("--prefill-wait", type=float, default=60.0)
    p.add_argument("--drain-timeout", type=float, default=300.0)
    p.add_argument("--max-backlog", type=int, default=64,
                   help="arrivals shed (not queued) beyond this in-flight count")
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--tag", default=None,
                   help="report tag (default: sustained, or storm with --chaos)")
    p.add_argument("--out", default=None,
                   help="report path (default bench_results/serving_<tag>.json "
                        "or chaos_<tag>.json)")
    # ---- chaos mode (ISSUE 11) ---------------------------------------
    p.add_argument("--chaos", action="store_true",
                   help="run the measured window under a fault plan and "
                        "emit the chaos report")
    p.add_argument("--faults", default=None,
                   help="FSDKR_FAULTS spec (default: the storm spec with "
                        "--seed appended)")
    p.add_argument("--deadline", type=float, default=0.0,
                   help="per-session deadline seconds (chaos default 15; "
                        "0 keeps FSDKR_SERVE_DEADLINE_S)")
    p.add_argument("--retries", type=int, default=None,
                   help="transient-failure retries (default FSDKR_SERVE_RETRIES)")
    p.add_argument("--baseline-window", type=float, default=0.0,
                   help="fault-free baseline window seconds (chaos; default "
                        "min(window, 20))")
    p.add_argument("--curve", default="0,0.01,0.05",
                   help="tamper-rate curve for the bisection-economics "
                        "measurement ('' disables)")
    p.add_argument("--curve-sessions", type=int, default=18,
                   help="closed-loop sessions per curve point")
    p.add_argument("--bisect-budget", type=int, default=0,
                   help="per-committee RLC bisection budget per window "
                        "(0 = guard off; arms FSDKR_SERVE_BISECT_BUDGET)")
    p.add_argument("--p99-bound", type=float, default=3.0,
                   help="chaos gate: healthy-traffic p99 must stay within "
                        "this factor of the fault-free baseline")
    # ---- crash-storm mode (ISSUE 12) ---------------------------------
    p.add_argument("--crash-storm", action="store_true",
                   help="Poisson window over a multi-process shard "
                        "supervisor with periodic SIGKILLs; emits "
                        "bench_results/crash_storm.json")
    p.add_argument("--shards", type=int, default=4,
                   help="shard processes under the supervisor")
    p.add_argument("--kills", type=int, default=None,
                   help="shard SIGKILLs injected across the window "
                        "(the shard_kill fault site; default 3 for "
                        "--crash-storm, 0 for --net — network chaos "
                        "composes with kills only when asked)")
    p.add_argument("--journal-root", default=None,
                   help="journal root directory (default: a temp dir; "
                        "journals hold PUBLIC data only)")
    p.add_argument("--journal-dir", default=None,
                   help="journal THIS run's single service to the given "
                        "directory (durability A/B for sustained/chaos "
                        "windows; the report gains a `journal` block)")
    # ---- network mode (ISSUE 13) -------------------------------------
    p.add_argument("--net", action="store_true",
                   help="multi-process network storm: client processes "
                        "speak the wire protocol over real TCP sockets "
                        "against an ingress-enabled ShardSupervisor; "
                        "emits bench_results/net_storm.json (combine "
                        "with --kills N for the crash x network storm)")
    p.add_argument("--clients", type=int, default=2,
                   help="wire-protocol client processes (--net)")
    p.add_argument("--net-faults", default=None,
                   help="server-side network fault spec armed in every "
                        "shard (conn_drop/frame_truncate/net_delay/"
                        "net_dup; default: the net storm spec with "
                        "--seed appended; '' = no network chaos)")
    p.add_argument("--max-attempts", type=int, default=5,
                   help="client resubmit attempts per epoch before it "
                        "counts as unresolved/wedged (--net)")
    p.add_argument("--net-client", action="store_true",
                   help=argparse.SUPPRESS)  # internal: client worker
    return p.parse_args()


def _mem_block():
    """Memory-plan stat block for the serving report (matches bench.py's
    `mem` field): budget, staged/peak bytes, process VmHWM, tiles."""
    from fsdkr_tpu.backend import memplan

    return memplan.mem_stats()


def percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return round(sorted_vals[idx], 4)


def run_window(svc, cids, rng, rate, window_s, max_backlog, drain_timeout,
               backlog_shed_inline):
    """One open-loop Poisson window. Returns (session ids, inline-shed
    count, service-rejected count, window wall, drained, drain wall)."""
    from fsdkr_tpu.serving import ServeRejected

    win_ids, shed, rejected = [], 0, 0
    t_win = time.monotonic()
    next_arrival = t_win
    while True:
        now = time.monotonic()
        if now - t_win >= window_s:
            break
        if now < next_arrival:
            time.sleep(min(0.005, next_arrival - now))
            continue
        next_arrival += rng.expovariate(rate)
        if backlog_shed_inline and svc.stats()["inflight"] >= max_backlog:
            shed += 1
            continue
        try:
            win_ids.append(svc.submit(rng.choice(cids)))
        except ServeRejected:
            rejected += 1
    window_wall = time.monotonic() - t_win
    drained = svc.drain(timeout=drain_timeout)
    drain_wall = time.monotonic() - t_win - window_wall
    return win_ids, shed, rejected, window_wall, drained, drain_wall, t_win


def collect_sessions(svc, win_ids):
    """wait(sid, 0) per id; a TimeoutError is a WEDGED session — the
    exact failure class the chaos gate exists to catch."""
    sessions, wedged = [], 0
    for sid in win_ids:
        try:
            sessions.append(svc.wait(sid, 0))
        except TimeoutError:
            wedged += 1
    return sessions, wedged


def classify_chaos(sessions):
    """Per-session verdict-correctness accounting against the faults
    that hit each session. Wrong verdicts: a session with NO disruptive
    fault aborted with identifiable blame, or a tampered session
    finished clean."""
    out = {
        "done_clean": 0, "recovered": 0, "aborted_blame": 0,
        "aborted_transient": 0, "timed_out": 0,
        "timed_out_named": 0, "wrong_verdicts": 0,
        "wrong_detail": [],
    }
    for s in sessions:
        tampered = any(f.startswith("msg_tamper") for f in s.faults)
        dropped = any(f.startswith("msg_drop") for f in s.faults)
        transient = s.retries > 0 or any(
            f in ("worker_crash", "finalize_exc") for f in s.faults
        )
        if s.state == "done":
            out["recovered" if (transient or tampered) else "done_clean"] += 1
            if tampered:
                out["wrong_verdicts"] += 1
                out["wrong_detail"].append(
                    f"session {s.session_id}: tampered but finished clean"
                )
        elif s.state == "aborted":
            out["aborted_blame" if s.blame else "aborted_transient"] += 1
            if s.blame and not tampered:
                out["wrong_verdicts"] += 1
                out["wrong_detail"].append(
                    f"session {s.session_id}: healthy but blamed: {s.error}"
                )
        elif s.state == "timed_out":
            out["timed_out"] += 1
            if "missing senders" in (s.error or ""):
                out["timed_out_named"] += 1
            elif dropped and "state 'collecting'" in (s.error or ""):
                # a collecting-state timeout always knows its drops
                # (fault decisions are rolled before distribute); a
                # timeout while still queued/distributing legitimately
                # has no senders to name
                out["wrong_verdicts"] += 1
                out["wrong_detail"].append(
                    f"session {s.session_id}: dropped-message timeout did "
                    f"not name senders: {s.error}"
                )
    out["wrong_detail"] = out["wrong_detail"][:8]
    return out


def run_tamper_curve(svc, cids, rates, sessions_per_rate, seed, drain_timeout,
                     log):
    """ROADMAP 5b economics: closed-loop bursts at each tamper rate;
    bisection fallbacks + wall cost per session, plus admission
    rejections when the bisect guard is armed."""
    from fsdkr_tpu.serving import ServeRejected, faults, metrics as smetrics

    curve = []
    for rate in rates:
        svc.guard.reset()  # each point starts with a clean budget window
        spec = f"seed={seed},msg_tamper={rate}" if rate > 0 else f"seed={seed}"
        plan = faults.configure(spec)
        bisect0 = smetrics.rlc_bisect_count()
        t0 = time.monotonic()
        ids, rejected = [], 0
        for k in range(sessions_per_rate):
            # closed-loop burst: wait out OVERLOAD rejections (the curve
            # measures verify cost, not admission); a bisection-budget
            # rejection IS the measurement — the guard shedding the
            # tampering committee — so count it and move on
            while True:
                try:
                    ids.append(svc.submit(cids[k % len(cids)]))
                    break
                except ServeRejected as e:
                    if "bisection" in e.reason:
                        rejected += 1
                        break
                    time.sleep(min(0.5, e.retry_after_s))
        svc.drain(timeout=drain_timeout)
        wall = time.monotonic() - t0
        sessions, wedged = collect_sessions(svc, ids)
        aborted = sum(s.state == "aborted" for s in sessions)
        point = {
            "tamper_rate": rate,
            "sessions": len(ids),
            "rejected": rejected,
            "aborted": aborted,
            "wedged": wedged,
            "tamper_injected": plan.injected().get("msg_tamper", 0),
            "bisect_fallbacks": smetrics.rlc_bisect_count() - bisect0,
            "wall_s": round(wall, 2),
            "s_per_session": round(wall / max(1, len(ids)), 4),
        }
        faults.reset()
        curve.append(point)
        log(f"[loadgen] curve tamper={rate}: {point['bisect_fallbacks']} "
            f"bisects, {point['s_per_session']}s/session, "
            f"{aborted} aborted, {rejected} rejected")
    return curve


def run_net_client():
    """Internal worker for --net (spawned as `loadgen.py --net-client`):
    one wire-protocol client process. Reads its spec as one JSON line on
    stdin, prints `{"ev": "ready"}`, waits for a `go` line, runs a
    Poisson window of refresh epochs over its assigned committees
    ENTIRELY over TCP (submit -> receive the broadcast set -> re-deliver
    every broadcast -> wait for the verdict), and prints one result JSON
    line. The client IS the broadcast channel: it retries through
    redirects, rejections, dropped connections, and torn frames —
    reconnect + idempotent resubmit — and classifies what it observed."""
    import threading

    from fsdkr_tpu.serving.ingress import IngressClient
    from fsdkr_tpu.serving.supervisor import shard_for

    spec = json.loads(sys.stdin.readline())
    ports = [int(p) for p in spec["ports"].values()]
    port_of_shard = {int(k): int(v) for k, v in spec["ports"].items()}
    n_shards = int(spec["shards"])
    committees = list(spec["committees"])
    epochs = {int(c): int(e) for c, e in spec["epochs"]}
    rate = float(spec["rate_hz"])
    window_s = float(spec["window_s"])
    deadline_s = float(spec["deadline_s"])
    max_attempts = int(spec["max_attempts"])
    op_timeout = float(spec.get("op_timeout_s", 30.0))
    rng = random.Random(int(spec["seed"]))
    counters = {"reconnects": 0, "redirects": 0, "rejected": 0,
                "unknown_committee_retries": 0, "sessions_started": 0}
    clock = {"lock": threading.Lock()}

    def count(k, n=1):
        with clock["lock"]:
            counters[k] = counters.get(k, 0) + n

    def run_epoch(cid, epoch, out):
        t0 = time.monotonic()
        attempts = reconnects = redirects = 0
        # first dial: the fingerprint owner (failover may override —
        # the redirect response re-routes us)
        port = port_of_shard.get(shard_for(cid, n_shards), ports[0])
        ports_cycle = [port] + [p for p in ports if p != port]
        cycle_i = 0
        cli = None
        outcome = None
        budget = t0 + deadline_s * (max_attempts + 1) + 60.0
        while outcome is None and attempts < max_attempts \
                and time.monotonic() < budget:
            attempts += 1
            try:
                if cli is None:
                    cli = IngressClient("127.0.0.1", port,
                                        timeout=op_timeout)
                r = cli.submit(cid, epoch, timeout=op_timeout)
                typ = r.get("type")
                if typ == "redirect":
                    redirects += 1
                    count("redirects")
                    attempts -= 1  # routing, not a failed attempt
                    hint = r.get("hint")
                    new_port = int(hint) if hint else None
                    if new_port is None or new_port == port:
                        pp = [int(v) for v in (r.get("ports") or {}).values()]
                        alt = [p for p in (pp or ports) if p != port]
                        new_port = alt[0] if alt else port
                    port = new_port
                    cli.close()
                    cli = None
                    continue
                if typ == "rejected":
                    count("rejected")
                    attempts -= 1  # shed is an answer, not an attempt
                    time.sleep(min(1.0, float(r.get("retry_after_s", 0.1))))
                    continue
                if typ == "error":
                    if r.get("error") == "unknown_committee":
                        # failover in flight: the committee is between
                        # shards — rotate ports until someone owns it
                        # (routing churn, not a protocol attempt; the
                        # wall-clock budget bounds the loop)
                        count("unknown_committee_retries")
                        attempts -= 1
                        cycle_i += 1
                        port = ports_cycle[cycle_i % len(ports_cycle)]
                        cli.close()
                        cli = None
                        time.sleep(0.2)
                        continue
                    time.sleep(0.2)
                    continue
                sid = r["sid"]
                count("sessions_started")
                if r.get("state") in ("done", "aborted", "timed_out"):
                    # idempotent dedupe handed back a finished epoch
                    # (e.g. replayed after failover): that IS the verdict
                    outcome = {"state": r["state"],
                               "blame": bool(r.get("blame")),
                               "error": r.get("error")}
                    break
                bcasts = r.get("broadcasts")
                if bcasts is None:
                    f = cli.fetch(sid, timeout=op_timeout)
                    while f.get("type") in ("pending", "rejected") \
                            and time.monotonic() < budget:
                        # pending: the session is ALIVE, distribute
                        # just hasn't finished; rejected: the limiter
                        # shed this re-fetch — either way retry the
                        # fetch (honoring retry_after_s so the limiter
                        # isn't hammered into the close verdict);
                        # resubmitting would burn attempts on a live
                        # session
                        if f.get("type") == "rejected":
                            count("rejected")
                        time.sleep(max(
                            0.1, float(f.get("retry_after_s", 0.0))
                        ))
                        f = cli.fetch(sid, timeout=op_timeout)
                    if f.get("type") in ("pending", "rejected"):
                        continue  # wall budget expired first: no
                        # broadcasts were delivered, so waiting for a
                        # verdict is futile — let the outer budget
                        # guard end the epoch
                    bcasts = f.get("broadcasts") or []
                rng.shuffle(bcasts)  # arrival order must not matter
                resubmit = False
                for snd, wire in bcasts:
                    ack = cli.broadcast(sid, wire, timeout=op_timeout)
                    if ack.get("type") != "broadcast_ack":
                        resubmit = True
                        break
                    if ack.get("result") == "unknown":
                        # the session died with its shard: start over
                        resubmit = True
                        break
                if resubmit:
                    continue
                term = cli.wait(sid, deadline_s + 10.0)
                if term.get("type") == "error" \
                        and term.get("error") == "timeout":
                    term = cli.wait(sid, deadline_s + 10.0)  # once more
                if term.get("type") != "terminal":
                    continue
                st = term["state"]
                outcome = {"state": st, "blame": bool(term.get("blame")),
                           "error": term.get("error"),
                           "server_latency_s": term.get("latency_s")}
                if st == "done" or (st == "aborted" and outcome["blame"]):
                    break  # verdicts are final; transients retry
                outcome = None if attempts < max_attempts else outcome
            except (ConnectionError, OSError):
                # a network failure is NOT a protocol attempt: rotate
                # ports and redial (the wall-clock budget bounds a
                # fully-dead fleet; attempts bound protocol retries —
                # burning them on a refused dial would wedge an epoch
                # behind one failover's connection churn)
                attempts -= 1
                reconnects += 1
                count("reconnects")
                if cli is not None:
                    cli.close()
                    cli = None
                cycle_i += 1
                port = ports_cycle[cycle_i % len(ports_cycle)]
                time.sleep(min(1.0, 0.05 * (reconnects + attempts)))
        if cli is not None:
            cli.close()
        if outcome is None:
            outcome = {"state": "unresolved", "blame": False,
                       "error": "client attempts exhausted"}
        outcome.update(
            cid=cid, epoch=epoch, attempts=attempts,
            reconnects=reconnects, redirects=redirects,
            latency_s=round(time.monotonic() - t0, 4),
        )
        out.append(outcome)

    print(json.dumps({"ev": "ready"}), flush=True)
    go = sys.stdin.readline()  # parent's start barrier
    if not go:
        return 1
    outcomes = []
    busy = {}
    threads = []
    t_win = time.monotonic()
    next_arrival = t_win
    while time.monotonic() - t_win < window_s:
        now = time.monotonic()
        if now < next_arrival:
            time.sleep(min(0.01, next_arrival - now))
            continue
        next_arrival += rng.expovariate(rate)
        idle = [c for c in committees
                if not (busy.get(c) and busy[c].is_alive())]
        if not idle:
            continue  # every committee has an epoch in flight
        cid = rng.choice(idle)
        epoch = epochs[cid]
        epochs[cid] = epoch + 1
        th = threading.Thread(
            target=run_epoch, args=(cid, epoch, outcomes), daemon=True
        )
        busy[cid] = th
        threads.append(th)
        th.start()
    join_deadline = time.monotonic() + deadline_s * (max_attempts + 1) + 90
    for th in threads:
        th.join(timeout=max(1.0, join_deadline - time.monotonic()))
    still = sum(th.is_alive() for th in threads)
    print(json.dumps({
        "ev": "result",
        "client_id": spec.get("client_id"),
        "window_s": round(time.monotonic() - t_win, 2),
        "outcomes": outcomes,
        "counters": counters,
        "threads_unjoined": still,
    }, default=str), flush=True)
    return 0


def run_crash_storm(args):
    """ISSUE 12 acceptance harness: Poisson refresh arrivals over a
    multi-process ShardSupervisor while the `shard_kill` fault site
    SIGKILLs shards mid-window. Every submitted epoch is classified
    (done_clean / recovered after failover-replay-resubmit /
    aborted_transient / rejected / LOST), and the report gates on zero
    lost accepted broadcasts, zero wrong verdicts, zero wedged
    sessions, with MTTR per failover and the healthy-bystander p99
    (committees whose shard never died)."""
    import tempfile

    from fsdkr_tpu.config import ProtocolConfig
    from fsdkr_tpu.protocol import simulate_keygen
    from fsdkr_tpu.serving import faults, recovery
    from fsdkr_tpu.serving.supervisor import ShardSupervisor
    from fsdkr_tpu.telemetry import export as tel_export

    if args.kills is None:
        args.kills = 3  # the crash storm's whole point
    log = lambda *a: print(*a, file=sys.stderr, flush=True)
    t_start = time.time()
    config = ProtocolConfig(
        paillier_bits=args.bits,
        m_security=args.m_security,
        correct_key_rounds=args.ck_rounds,
        backend=args.backend,
    )
    rng = random.Random(args.seed)
    rate = args.rate or 1.0
    deadline_s = args.deadline or 8.0
    root = args.journal_root or tempfile.mkdtemp(prefix="fsdkr_storm_")

    # the kill schedule is seed-deterministic through the fault plan:
    # evenly spaced ticks across the window, each consulted against the
    # shard_kill site (rate 1.0, capped at --kills)
    plan = faults.configure(
        f"seed={args.seed},shard_kill=1.0,shard_kill_max={args.kills}"
    )

    log(f"[storm] keygen {args.bases} base committees "
        f"(n={args.n}, t={args.t}, {args.bits}-bit)")
    t0 = time.time()
    keygen = getattr(simulate_keygen, "uncached", simulate_keygen)
    bases = [keygen(args.t, args.n, config) for _ in range(args.bases)]
    committees = {
        cid: [k.clone() for k in bases[cid % args.bases]]
        for cid in range(args.committees)
    }
    keygen_s = time.time() - t0

    sup = ShardSupervisor(
        shards=args.shards,
        root=root,
        deadline_s=deadline_s,
        retries=args.retries if args.retries is not None else 2,
        hb_interval=0.3,
    )
    t0 = time.time()
    sup.start()
    log(f"[storm] {args.shards} shards ready in {time.time() - t0:.1f}s "
        f"(journals under {root})")
    for cid, keys in committees.items():
        sup.admit(cid, keys, config)

    # seed epoch 0 everywhere (unmeasured; warms shard engine caches)
    t0 = time.time()
    epoch_of = {}
    for cid in committees:
        sup.submit(cid, 0)
        epoch_of[cid] = 1
    if not sup.drain(timeout=max(args.drain_timeout, 10 * args.committees)):
        log(f"[storm] WARNING: seed epoch did not drain: {sup.pending}")
    seed_s = time.time() - t0
    seed_outcomes = list(sup.outcomes)
    sup.outcomes.clear()
    log(f"[storm] seeded {len(seed_outcomes)} epochs in {seed_s:.1f}s")

    # ---- measured window: Poisson arrivals + the kill schedule -------
    kill_ticks = [
        (i + 1) * args.window / (args.kills + 1) for i in range(args.kills)
    ]
    kills_done, killed_shards = 0, []
    t_win = time.monotonic()
    next_arrival = t_win
    while True:
        now = time.monotonic()
        if now - t_win >= args.window:
            break
        while kill_ticks and now - t_win >= kill_ticks[0]:
            tick = kill_ticks.pop(0)
            if plan.fire("shard_kill", (round(tick, 3),)):
                # prefer a victim with sessions IN FLIGHT (mid-window
                # kill is the point), then any committee owner;
                # kill_shard refuses to take the last shard
                alive = [h for h in sup.shards if h.alive]
                busy_idx = {p["shard"] for p in sup.pending.values()}
                busy = [h for h in alive if h.idx in busy_idx]
                owners = [h for h in alive if h.committees]
                victim = rng.choice(busy or owners or alive)
                k = sup.kill_shard(victim.idx)
                if k is not None:
                    kills_done += 1
                    killed_shards.append(k)
                    log(f"[storm] t+{now - t_win:.1f}s SIGKILL shard {k}")
        if now >= next_arrival:
            next_arrival += rng.expovariate(rate)
            cid = rng.choice(list(committees))
            sup.submit(cid, epoch_of[cid])
            epoch_of[cid] += 1
        sup.pump(0.02)
    window_wall = time.monotonic() - t_win
    drained = sup.drain(timeout=args.drain_timeout)
    drain_wall = time.monotonic() - t_win - window_wall
    faults.reset()

    # ---- classification ----------------------------------------------
    outcomes = list(sup.outcomes)
    agg = sup.aggregate()
    failovers = agg["failovers"]
    moved_cids = {c for fo in failovers for c in fo.get("moved", [])}
    cls = {"done_clean": 0, "recovered": 0, "aborted_transient": 0,
           "timed_out": 0, "rejected": 0, "aborted_blame": 0}
    wrong = []
    for o in outcomes:
        if o["state"] == "done":
            cls["recovered" if (o["via"] != "primary" or o["resubmits"])
                else "done_clean"] += 1
        elif o["state"] == "rejected":
            cls["rejected"] += 1
        elif o["state"] == "timed_out":
            cls["timed_out"] += 1
        elif o["blame"]:
            # no tampering is injected in the storm: any blame verdict
            # is a wrong verdict by construction
            cls["aborted_blame"] += 1
            wrong.append(f"{o['cid']}/{o['epoch']}: blamed: {o['error']}")
        else:
            cls["aborted_transient"] += 1
    wedged = len(sup.pending)

    # ---- zero-lost-broadcast audit across every journal --------------
    # every session that ever ACCEPTED a broadcast must be accounted:
    # a terminal record in its own journal, or its journal was adopted
    # by a recovery (whose report settles every non-terminal session)
    recovered_dirs = {fo["journal_dir"] for fo in failovers
                      if fo.get("recovery")}
    lost_sessions = []
    scanned = {"journals": 0, "sessions": 0, "broadcast_records": 0,
               "terminal_records": 0}
    for shard_dir in sorted(pathlib.Path(root).glob("shard*")):
        sessions, _coms = recovery.load_state(shard_dir)
        scanned["journals"] += 1
        scanned["sessions"] += len(sessions)
        for sid, js in sessions.items():
            scanned["broadcast_records"] += len(js.broadcasts)
            scanned["terminal_records"] += js.terminal is not None
            if js.broadcasts and js.terminal is None \
                    and str(shard_dir) not in recovered_dirs:
                lost_sessions.append(f"{shard_dir.name}:{sid}")
    mttrs = [fo["mttr_s"] for fo in failovers if fo.get("mttr_s")]
    recovers = [fo["recover_s"] for fo in failovers if fo.get("recover_s")]
    bystander_lat = sorted(
        o["latency_s"] for o in outcomes
        if o["state"] == "done" and o["via"] == "primary"
        and o["cid"] not in moved_cids and o["latency_s"] is not None
    )

    report = {
        "metric": "serve_crash_storm",
        "platform": "host-shards",
        "committees": args.committees,
        "distinct_bases": args.bases,
        "n": args.n,
        "t": args.t,
        "paillier_bits": args.bits,
        "m_security": args.m_security,
        "shards": args.shards,
        "window_s": round(window_wall, 2),
        "drain_s": round(drain_wall, 2),
        "drained": drained,
        "offered_rate_hz": rate,
        "deadline_s": deadline_s,
        "seed": args.seed,
        "fault_spec": plan.spec(),
        "kills_injected": kills_done,
        "killed_shards": killed_shards,
        "epochs_submitted": len(outcomes) + wedged,
        "outcomes": cls,
        "wrong_verdicts": len(wrong),
        "wrong_detail": wrong[:8],
        "wedged": wedged,
        "wedged_detail": [f"{c}/{e}" for (c, e) in list(sup.pending)[:8]],
        "lost_broadcast_sessions": len(lost_sessions),
        "lost_detail": lost_sessions[:8],
        "journal_audit": scanned,
        "mttr_s": {
            "per_failover": mttrs,
            "mean": round(sum(mttrs) / len(mttrs), 3) if mttrs else None,
            "max": round(max(mttrs), 3) if mttrs else None,
        },
        # death detection -> journal replay adopted on the peer (the
        # floor every failover pays, measured even when no epoch was
        # interrupted; MTTR above additionally includes the first
        # interrupted epoch completing)
        "recover_s": {
            "per_failover": recovers,
            "mean": (
                round(sum(recovers) / len(recovers), 3) if recovers else None
            ),
            "max": round(max(recovers), 3) if recovers else None,
        },
        "bystander_p99_s": percentile(bystander_lat, 0.99),
        "bystander_done": len(bystander_lat),
        "failovers": failovers,
        "aggregate": {k: agg[k] for k in ("serving", "journal", "alive")},
        "setup": {
            "keygen_s": round(keygen_s, 1),
            "seed_s": round(seed_s, 1),
            "seed_epochs_done": sum(
                o["state"] == "done" for o in seed_outcomes
            ),
        },
        "gates": {
            "zero_lost_broadcasts": len(lost_sessions) == 0,
            "zero_wrong_verdicts": len(wrong) == 0,
            "zero_wedged": wedged == 0,
            # the ISSUE 12 acceptance storm wants >= 3; a smaller
            # --kills run gates against its own configuration
            "kills_injected": kills_done >= min(3, args.kills),
        },
    }
    report["telemetry"] = tel_export.snapshot()
    sup.stop()

    out = args.out or "bench_results/crash_storm.json"
    pathlib.Path(out).parent.mkdir(parents=True, exist_ok=True)
    pathlib.Path(out).write_text(json.dumps(report, indent=1, default=str)
                                 + "\n")
    log(f"[storm] {kills_done} kills, outcomes {cls}, "
        f"MTTR mean {report['mttr_s']['mean']}s, "
        f"bystander p99 {report['bystander_p99_s']}s, "
        f"lost {len(lost_sessions)}, wrong {len(wrong)}, wedged {wedged}")
    log(f"[storm] report -> {out} (total wall {time.time() - t_start:.0f}s)")
    print(json.dumps(report, default=str))
    return 0 if all(report["gates"].values()) else 1


def run_net_storm(args):
    """ISSUE 13 acceptance harness: multi-process wire-protocol clients
    over real TCP sockets against an ingress-enabled ShardSupervisor,
    under server-side network chaos (conn_drop / frame_truncate /
    net_delay / net_dup) and — with --kills — composed with shard
    SIGKILLs. Gates: zero wrong verdicts (no tampering injected -> any
    blame is wrong), zero wedged sessions (client attempts exhausted),
    zero lost ACCEPTED broadcasts (every journal audited), and the
    healthy-bystander p99 under the stated bound. Also documents the
    networked sessions/s-per-core against the in-process (pipe-fed)
    baseline window — the ROADMAP item 3 done-criterion."""
    import subprocess
    import tempfile
    import threading

    from fsdkr_tpu.config import ProtocolConfig
    from fsdkr_tpu.protocol import simulate_keygen
    from fsdkr_tpu.serving import faults, recovery
    from fsdkr_tpu.serving.supervisor import ShardSupervisor

    if args.kills is None:
        args.kills = 0  # kills compose with network chaos only by request
    log = lambda *a: print(*a, file=sys.stderr, flush=True)
    t_start = time.time()
    config = ProtocolConfig(
        paillier_bits=args.bits,
        m_security=args.m_security,
        correct_key_rounds=args.ck_rounds,
        backend=args.backend,
    )
    rng = random.Random(args.seed)
    rate = args.rate or 1.0
    deadline_s = args.deadline or 8.0
    root = args.journal_root or tempfile.mkdtemp(prefix="fsdkr_net_")
    net_spec = args.net_faults
    if net_spec is None:
        net_spec = f"{DEFAULT_NET_FAULTS},seed={args.seed}"
    kill_plan = None
    if args.kills > 0:
        kill_plan = faults.configure(
            f"seed={args.seed},shard_kill=1.0,shard_kill_max={args.kills}"
        )

    log(f"[net] keygen {args.bases} base committees "
        f"(n={args.n}, t={args.t}, {args.bits}-bit)")
    t0 = time.time()
    keygen = getattr(simulate_keygen, "uncached", simulate_keygen)
    bases = [keygen(args.t, args.n, config) for _ in range(args.bases)]
    committees = {
        cid: [k.clone() for k in bases[cid % args.bases]]
        for cid in range(args.committees)
    }
    keygen_s = time.time() - t0

    # shards carry the NETWORK fault plan via env — the sites act only
    # at the ingress, so the pipe-fed seed/baseline stays chaos-free
    env = {"FSDKR_FAULTS": net_spec} if net_spec else {}
    sup = ShardSupervisor(
        shards=args.shards,
        root=root,
        deadline_s=deadline_s,
        retries=args.retries if args.retries is not None else 2,
        hb_interval=0.3,
        ingress=True,
        env=env,
    )
    t0 = time.time()
    sup.start()
    ports = sup.ingress_ports()
    log(f"[net] {args.shards} shards ready in {time.time() - t0:.1f}s, "
        f"ingress ports {ports} (journals under {root})")
    for cid, keys in committees.items():
        sup.admit(cid, keys, config)

    # seed epoch 0 via the pipes (warms shard engine caches)
    t0 = time.time()
    epoch_of = {cid: 0 for cid in committees}
    for cid in committees:
        sup.submit(cid, 0)
        epoch_of[cid] = 1
    if not sup.drain(timeout=max(args.drain_timeout, 10 * args.committees)):
        log(f"[net] WARNING: seed epoch did not drain: {sup.pending}")
    seed_s = time.time() - t0
    sup.outcomes.clear()

    # ---- in-process baseline window (pipe path, no sockets) ----------
    bw = args.baseline_window or min(args.window, 20.0)
    log(f"[net] in-process baseline window {bw:.0f}s at {rate}/s")
    t_base = time.monotonic()
    next_arrival = t_base
    while time.monotonic() - t_base < bw:
        now = time.monotonic()
        if now >= next_arrival:
            next_arrival += rng.expovariate(rate)
            cid = rng.choice(list(committees))
            sup.submit(cid, epoch_of[cid])
            epoch_of[cid] += 1
        sup.pump(0.02)
    base_window = time.monotonic() - t_base
    sup.drain(timeout=args.drain_timeout)
    base_outcomes = list(sup.outcomes)
    sup.outcomes.clear()
    base_lat = sorted(o["latency_s"] for o in base_outcomes
                      if o["state"] == "done" and o["latency_s"] is not None)
    baseline = {
        "window_s": round(base_window, 2),
        "sessions_done": len(base_lat),
        "sessions_per_s": round(len(base_lat) / base_window, 4),
        "p50": percentile(base_lat, 0.50),
        "p99": percentile(base_lat, 0.99),
    }
    log(f"[net] baseline: {baseline['sessions_per_s']}/s, "
        f"p99 {baseline['p99']}s ({len(base_lat)} done in-process)")

    # ---- spawn the wire-protocol client processes --------------------
    n_clients = max(1, args.clients)
    assignment = {i: [] for i in range(n_clients)}
    for j, cid in enumerate(sorted(committees)):
        assignment[j % n_clients].append(cid)
    clients = []
    for i in range(n_clients):
        spec = {
            "client_id": i,
            "ports": {str(k): v for k, v in ports.items()},
            "shards": args.shards,
            "committees": assignment[i],
            "epochs": [[c, epoch_of[c]] for c in assignment[i]],
            "rate_hz": rate / n_clients,
            "window_s": args.window,
            "deadline_s": deadline_s,
            "max_attempts": args.max_attempts,
            "seed": args.seed * 1000 + i,
        }
        cenv = dict(os.environ)
        cenv.setdefault("JAX_PLATFORMS", "cpu")
        cenv.pop("FSDKR_FAULTS", None)  # chaos is server-side only
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--net-client"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=sys.stderr, text=True, env=cenv,
        )
        proc.stdin.write(json.dumps(spec) + "\n")
        proc.stdin.flush()
        lines = []
        threading.Thread(
            target=lambda p=proc, ls=lines: ls.extend(p.stdout),
            daemon=True,
        ).start()
        clients.append({"proc": proc, "lines": lines, "spec": spec})

    # start barrier: every client finished importing before the window
    spawn_deadline = time.monotonic() + 300
    for c in clients:
        while time.monotonic() < spawn_deadline:
            if any('"ready"' in ln for ln in c["lines"]):
                break
            if c["proc"].poll() is not None:
                raise RuntimeError(
                    f"net client {c['spec']['client_id']} died at startup"
                )
            time.sleep(0.1)
    for c in clients:
        c["proc"].stdin.write("go\n")
        c["proc"].stdin.flush()
    log(f"[net] {n_clients} clients started; window {args.window:.0f}s"
        + (f" with {args.kills} shard kills" if args.kills else ""))

    # ---- measured window: pump heartbeats + the kill schedule --------
    kill_ticks = [
        (i + 1) * args.window / (args.kills + 1) for i in range(args.kills)
    ]
    kills_done, killed_shards = 0, []
    t_win = time.monotonic()
    while any(c["proc"].poll() is None for c in clients):
        now = time.monotonic() - t_win
        while kill_plan and kill_ticks and now >= kill_ticks[0]:
            tick = kill_ticks.pop(0)
            if kill_plan.fire("shard_kill", (round(tick, 3),)):
                alive = [h for h in sup.shards if h.alive]
                owners = [h for h in alive if h.committees]
                victim = rng.choice(owners or alive)
                k = sup.kill_shard(victim.idx)
                if k is not None:
                    kills_done += 1
                    killed_shards.append(k)
                    log(f"[net] t+{now:.1f}s SIGKILL shard {k}")
        sup.pump(0.1)
        if now > args.window + deadline_s * (args.max_attempts + 1) + 180:
            log("[net] WARNING: clients overran the window budget")
            break
    window_wall = time.monotonic() - t_win
    faults.reset()

    results = []
    for c in clients:
        try:
            c["proc"].wait(timeout=30)
        except subprocess.TimeoutExpired:
            c["proc"].kill()
    time.sleep(0.5)  # let the stdout reader threads hit EOF
    for c in clients:
        for ln in c["lines"]:
            try:
                obj = json.loads(ln)
            except ValueError:
                continue
            if obj.get("ev") == "result":
                results.append(obj)
    if len(results) != n_clients:
        log(f"[net] WARNING: {n_clients - len(results)} clients "
            f"returned no result")

    # let in-flight deadline reaps settle, then read the fleet's last
    # word (aggregation satellite: serving/journal/ingress roll up from
    # SHARD heartbeats + CLIENT processes only — the parent's own
    # registry saw keygen, not serving, and must not leak into the sums).
    # quiescence counts ALIVE shards: a SIGKILLed shard's final
    # heartbeat can freeze a nonzero inflight forever
    def _alive_inflight():
        return sum(
            (h.last_stats or {}).get("inflight", 0)
            for h in sup.shards if h.alive
        )

    quiesce_deadline = time.monotonic() + deadline_s + 15
    while time.monotonic() < quiesce_deadline:
        sup.pump(0.2)
        if _alive_inflight() == 0:
            break
    agg = sup.aggregate()

    # ---- classification ----------------------------------------------
    outcomes = [o for r in results for o in r["outcomes"]]
    moved_cids = {c for fo in agg["failovers"] for c in fo.get("moved", [])}
    cls = {"done_clean": 0, "recovered": 0, "aborted_blame": 0,
           "aborted_transient": 0, "timed_out": 0, "unresolved": 0}
    wrong = []
    bystander_lat = []
    for o in outcomes:
        disturbed = (o["attempts"] > 1 or o["reconnects"] > 0
                     or o["redirects"] > 0 or o["cid"] in moved_cids)
        if o["state"] == "done":
            cls["recovered" if disturbed else "done_clean"] += 1
            if not disturbed:
                bystander_lat.append(o["latency_s"])
        elif o["state"] == "aborted" and o["blame"]:
            # no tampering injected anywhere: blame is wrong by
            # construction
            cls["aborted_blame"] += 1
            wrong.append(f"{o['cid']}/{o['epoch']}: blamed: {o['error']}")
        elif o["state"] == "aborted":
            cls["aborted_transient"] += 1
        elif o["state"] == "timed_out":
            cls["timed_out"] += 1
        else:
            cls["unresolved"] += 1
    wedged = cls["unresolved"] + sum(
        int(r.get("threads_unjoined", 0)) for r in results
    )
    bystander_lat.sort()

    # ---- zero-lost-accepted-broadcast audit (every journal) ----------
    recovered_dirs = {fo["journal_dir"] for fo in agg["failovers"]
                      if fo.get("recovery")}
    lost_sessions = []
    scanned = {"journals": 0, "sessions": 0, "broadcast_records": 0,
               "terminal_records": 0}
    for shard_dir in sorted(pathlib.Path(root).glob("shard*")):
        sessions, _coms = recovery.load_state(shard_dir)
        scanned["journals"] += 1
        scanned["sessions"] += len(sessions)
        for sid, js in sessions.items():
            scanned["broadcast_records"] += len(js.broadcasts)
            scanned["terminal_records"] += js.terminal is not None
            if js.broadcasts and js.terminal is None \
                    and str(shard_dir) not in recovered_dirs:
                lost_sessions.append(f"{shard_dir.name}:{sid}")

    client_counters = {}
    for r in results:
        for k, v in (r.get("counters") or {}).items():
            client_counters[k] = client_counters.get(k, 0) + v
    done_total = cls["done_clean"] + cls["recovered"]
    cores = os.cpu_count() or 1
    p99_by = percentile(bystander_lat, 0.99)
    bound_s = (
        round(deadline_s + args.p99_bound * baseline["p99"], 3)
        if baseline["p99"] else None
    )

    report = {
        "metric": "serve_net_storm",
        "platform": "host-shards-tcp",
        "committees": args.committees,
        "distinct_bases": args.bases,
        "n": args.n,
        "t": args.t,
        "paillier_bits": args.bits,
        "m_security": args.m_security,
        "shards": args.shards,
        "clients": n_clients,
        "window_s": args.window,
        "window_wall_s": round(window_wall, 2),
        "offered_rate_hz": rate,
        "deadline_s": deadline_s,
        "seed": args.seed,
        "net_fault_spec": net_spec or None,
        "kill_fault_spec": kill_plan.spec() if kill_plan else None,
        "kills_injected": kills_done,
        "killed_shards": killed_shards,
        "epochs_submitted": len(outcomes),
        "outcomes": cls,
        "wrong_verdicts": len(wrong),
        "wrong_detail": wrong[:8],
        "wedged": wedged,
        "lost_broadcast_sessions": len(lost_sessions),
        "lost_detail": lost_sessions[:8],
        "journal_audit": scanned,
        "client_counters": client_counters,
        "in_process_baseline": baseline,
        "net_sessions_per_s": round(done_total / window_wall, 4)
        if window_wall > 0 else None,
        "net_sessions_per_s_per_core": round(
            done_total / window_wall / cores, 4
        ) if window_wall > 0 else None,
        "in_process_sessions_per_s_per_core": round(
            baseline["sessions_per_s"] / cores, 4
        ),
        "cores": cores,
        "bystander_p99_s": p99_by,
        "bystander_done": len(bystander_lat),
        "p99_bound": args.p99_bound,
        "p99_bound_s": bound_s,
        "p99_bound_stated": "deadline_s + p99_bound * in_process_p99",
        "failovers": agg["failovers"],
        # satellite (ISSUE 13): serving/journal/ingress sums come from
        # shard heartbeats + client processes ONLY — never the parent
        # registry, which would double-count nothing real but pollute
        # the rollup with the parent's keygen-phase counters
        "aggregate": {k: agg[k] for k in ("serving", "journal",
                                          "ingress", "alive")},
        "aggregation": "shard heartbeats + client results; "
                       "parent registry excluded",
        "setup": {
            "keygen_s": round(keygen_s, 1),
            "seed_s": round(seed_s, 1),
        },
        "knobs": {
            "FSDKR_INGRESS_MAX_FRAME_MB": os.environ.get(
                "FSDKR_INGRESS_MAX_FRAME_MB", "8"),
            "FSDKR_INGRESS_INFLIGHT_MB": os.environ.get(
                "FSDKR_INGRESS_INFLIGHT_MB", "32"),
            "FSDKR_INGRESS_IDLE_S": os.environ.get(
                "FSDKR_INGRESS_IDLE_S", "60"),
            "FSDKR_INGRESS_PEER_RPS": os.environ.get(
                "FSDKR_INGRESS_PEER_RPS", "0"),
            "max_attempts": args.max_attempts,
        },
        "gates": {
            "zero_lost_broadcasts": len(lost_sessions) == 0,
            "zero_wrong_verdicts": len(wrong) == 0,
            "zero_wedged": wedged == 0,
            "fleet_quiesced": _alive_inflight() == 0,
            "p99_within_bound": (
                p99_by is not None and bound_s is not None
                and p99_by <= bound_s
            ) or not bystander_lat,
            "kills_injected": kills_done >= min(3, args.kills),
        },
    }
    sup.stop()

    out = args.out or "bench_results/net_storm.json"
    pathlib.Path(out).parent.mkdir(parents=True, exist_ok=True)
    pathlib.Path(out).write_text(
        json.dumps(report, indent=1, default=str) + "\n"
    )
    log(f"[net] outcomes {cls} | wrong {len(wrong)} | wedged {wedged} | "
        f"lost {len(lost_sessions)} | bystander p99 {p99_by}s "
        f"(bound {bound_s}s) | net {report['net_sessions_per_s']}/s vs "
        f"in-process {baseline['sessions_per_s']}/s")
    log(f"[net] report -> {out} (total wall {time.time() - t_start:.0f}s)")
    print(json.dumps(report, default=str))
    return 0 if all(report["gates"].values()) else 1


def main():
    args = parse_args()
    if args.net_client:
        return run_net_client()
    if args.net:
        return run_net_storm(args)
    if args.crash_storm:
        return run_crash_storm(args)
    t_start = time.time()
    tag = args.tag or ("storm" if args.chaos else "sustained")

    from fsdkr_tpu import precompute
    from fsdkr_tpu.config import ProtocolConfig
    from fsdkr_tpu.protocol import simulate_keygen
    from fsdkr_tpu.serving import (
        BisectGuard, OverloadPolicy, RefreshService, ServeRejected, SLO,
        faults, enabled as serve_enabled,
    )
    from fsdkr_tpu.telemetry import export as tel_export

    config = ProtocolConfig(
        paillier_bits=args.bits,
        m_security=args.m_security,
        correct_key_rounds=args.ck_rounds,
        backend=args.backend,
    )
    try:
        import jax

        platform = jax.devices()[0].platform
    except Exception:
        platform = "unknown"

    rng = random.Random(args.seed)

    # ---- phase 1: committees -----------------------------------------
    log = lambda *a: print(*a, file=sys.stderr, flush=True)
    log(f"[loadgen] keygen {args.bases} base committees "
        f"(n={args.n}, t={args.t}, {args.bits}-bit)")
    t0 = time.time()
    keygen = getattr(simulate_keygen, "uncached", simulate_keygen)
    bases = [keygen(args.t, args.n, config) for _ in range(args.bases)]
    committees = {
        cid: [k.clone() for k in bases[cid % args.bases]]
        for cid in range(args.committees)
    }
    keygen_s = time.time() - t0
    log(f"[loadgen] keygen {keygen_s:.1f}s; admitting {args.committees} committees")

    deadline_s = args.deadline
    if args.chaos and deadline_s <= 0:
        deadline_s = 15.0
    if args.chaos:
        # chaos admission control lives in the SERVICE (explicit
        # `rejected` outcomes with retry-after), not the inline backlog
        # check; the bisect guard arms the ROADMAP 5b budget
        svc = RefreshService(
            deadline_s=deadline_s,
            retries=args.retries,
            overload=OverloadPolicy(max_queue=args.max_backlog,
                                    shed_p99_factor=0.0),
            guard=BisectGuard(budget=args.bisect_budget),
            journal=args.journal_dir,
        )
    else:
        svc = RefreshService(
            deadline_s=deadline_s or None, retries=args.retries,
            journal=args.journal_dir,
        )
    # per-committee rate: the offered total spread uniformly
    per_rate = (args.rate or 1.0) / max(1, args.committees)
    for cid, keys in committees.items():
        svc.admit(cid, keys, config, SLO(arrival_rate_hz=per_rate))
    svc.start()

    # ---- phase 2: seed epochs ----------------------------------------
    t0 = time.time()
    for _epoch in range(args.seed_epochs):
        for cid in committees:
            # seeding is closed-loop setup, not measured load: honor a
            # chaos-mode admission rejection by waiting out the hint
            while True:
                try:
                    svc.submit(cid)
                    break
                except ServeRejected as e:
                    time.sleep(min(1.0, e.retry_after_s))
        if not svc.drain(timeout=max(args.drain_timeout, 12 * args.committees)):
            log("[loadgen] WARNING: seed epoch did not drain; continuing")
    seed_s = time.time() - t0
    st = svc.stats()
    seed_done = st["sessions_done"]
    log(f"[loadgen] seeded {seed_done} sessions in {seed_s:.1f}s "
        f"({seed_done / seed_s:.2f}/s single-stream)")

    # auto rate: ~70% of the calibrated closed-loop capacity so the
    # producer has idle time to keep pools at depth (open-loop at or
    # above capacity is a queueing divergence, not a steady state)
    rate = args.rate
    if rate <= 0:
        rate = max(0.1, 0.7 * seed_done / seed_s) if seed_s > 0 else 1.0
        log(f"[loadgen] auto rate: {rate:.2f} sessions/s")

    # ---- phase 3: prefill wait ---------------------------------------
    t0 = time.time()
    precompute.kick()
    deficit0 = precompute.deficit_total()
    while time.time() - t0 < args.prefill_wait:
        if precompute.deficit_total() == 0:
            break
        time.sleep(0.25)
    prefill_s = time.time() - t0
    deficit_left = precompute.deficit_total()
    log(f"[loadgen] prefill {prefill_s:.1f}s "
        f"(deficit {deficit0} -> {deficit_left})")

    # ---- phase 3b (chaos): fault-free baseline window ----------------
    baseline = None
    fault_plan = None
    if args.chaos:
        bw = args.baseline_window or min(args.window, 20.0)
        log(f"[loadgen] chaos baseline window {bw:.0f}s (no faults)")
        ids, _shed, _rej, bwall, bdrained, _bd, _t0 = run_window(
            svc, list(committees), rng, rate, bw, args.max_backlog,
            args.drain_timeout, backlog_shed_inline=False,
        )
        bsessions, bwedged = collect_sessions(svc, ids)
        blat = sorted(
            s.finalized_at - s.submitted_at
            for s in bsessions if s.state == "done"
        )
        baseline = {
            "window_s": round(bwall, 2),
            "sessions_done": len(blat),
            "drained": bdrained,
            "wedged": bwedged,
            "p50": percentile(blat, 0.50),
            "p99": percentile(blat, 0.99),
        }
        log(f"[loadgen] baseline p99 {baseline['p99']}s "
            f"({len(blat)} sessions)")
        spec = args.faults or f"{DEFAULT_FAULTS},seed={args.seed}"
        fault_plan = faults.configure(spec)
        log(f"[loadgen] fault plan armed: {fault_plan.spec()}")

    # ---- phase 4: measured window ------------------------------------
    from fsdkr_tpu.serving import metrics as smetrics

    smetrics.phase_histogram().reset()
    smetrics.sessions_counter().reset()
    smetrics.batch_histogram().reset()
    pool0 = precompute.precompute_stats()
    dry0 = _dry_by_cause()
    rejected0 = svc.sessions_rejected
    win_ids, shed, rejected, window_s, drained, drain_s, t_win = run_window(
        svc, list(committees), rng, rate, args.window, args.max_backlog,
        args.drain_timeout, backlog_shed_inline=not args.chaos,
    )
    pool1 = precompute.precompute_stats()
    dry1 = _dry_by_cause()

    sessions, wedged = collect_sessions(svc, win_ids)
    done = [s for s in sessions if s.state == "done"]
    aborted = [s for s in sessions if s.state == "aborted"]
    timed_out = [s for s in sessions if s.state == "timed_out"]
    # completed-inside-window throughput (the sustained figure) plus the
    # drain-inclusive one (total work the window's offered load produced)
    done_in_window = [
        s for s in done if s.finalized_at - t_win <= args.window
    ]
    lat = sorted(s.finalized_at - s.submitted_at for s in done)
    consumed = pool1["consumed"] - pool0["consumed"]
    dry = pool1["dry_fallbacks"] - pool0["dry_fallbacks"]
    takes = consumed + dry
    dry_rate = round(dry / takes, 4) if takes else None

    prod = {}
    for rec in tel_export.snapshot()["metrics"].get(
        "fsdkr_producer_occupancy", {}
    ).get("values", []):
        prod["occupancy"] = round(rec["value"], 4)

    report = {
        "metric": "serve_chaos" if args.chaos else "serve_sustained",
        "platform": platform,
        "fsdkr_serve": serve_enabled(),
        "committees": args.committees,
        "distinct_bases": args.bases,
        "n": args.n,
        "t": args.t,
        "paillier_bits": args.bits,
        "m_security": args.m_security,
        "correct_key_rounds": args.ck_rounds,
        "window_s": round(window_s, 2),
        "drain_s": round(drain_s, 2),
        "drained": drained,
        "offered_rate_hz": round(rate, 4),
        "arrivals": len(win_ids),
        "shed": shed,
        "rejected": rejected,
        "sessions_done": len(done),
        "sessions_done_in_window": len(done_in_window),
        "sessions_aborted": len(aborted),
        "sessions_timed_out": len(timed_out),
        "sessions_wedged": wedged,
        "abort_errors": sorted({s.error for s in aborted if s.error})[:5],
        "sessions_per_s": round(len(done_in_window) / window_s, 4),
        "sessions_per_s_incl_drain": (
            round(len(done) / (window_s + drain_s), 4)
            if window_s + drain_s > 0 else None
        ),
        "latency_s": {
            "p50": percentile(lat, 0.50),
            "p95": percentile(lat, 0.95),
            "p99": percentile(lat, 0.99),
            "mean": round(sum(lat) / len(lat), 4) if lat else None,
            "max": round(lat[-1], 4) if lat else None,
        },
        "pool": {
            "consumed": consumed,
            "dry_fallbacks": dry,
            "dry_fallback_rate": dry_rate,
            "dry_by_cause": {
                k: dry1.get(k, 0) - dry0.get(k, 0)
                for k in set(dry0) | set(dry1)
            },
            "produced": pool1["produced"] - pool0["produced"],
            "bytes_pooled": pool1["bytes_pooled"],
            "entries_pooled": pool1["entries"],
            "pools": pool1["pools"],
            "prefill_deficit_left": deficit_left,
        },
        "producer": prod,
        # per-process memory accounting (ISSUE 10): VmHWM ground truth +
        # the memory-plan block — the serving loop's bounded-per-session
        # claim is checkable from the report alone
        "mem": _mem_block(),
        # durability accounting (ISSUE 12): present when --journal-dir
        # put a write-ahead log under this run
        "journal": svc.journal_stats(),
        "setup": {
            "keygen_s": round(keygen_s, 1),
            "seed_epochs": args.seed_epochs,
            "seed_s": round(seed_s, 1),
            "seed_sessions_per_s": (
                round(seed_done / seed_s, 3) if seed_s > 0 else None
            ),
            "prefill_s": round(prefill_s, 1),
        },
        "knobs": {
            "FSDKR_SERVE_BATCH": svc.policy.max_sessions,
            "FSDKR_SERVE_LINGER_MS": round(svc.policy.linger_s * 1000, 1),
            "FSDKR_SERVE_WORKERS": svc.workers,
            "FSDKR_SERVE_HORIZON_S": svc.planner.horizon_s,
            "FSDKR_SERVE_MAX_AHEAD": svc.planner.max_ahead,
            "FSDKR_SERVE_DEADLINE_S": svc.deadline_s,
            "FSDKR_SERVE_RETRIES": svc.retries,
            "FSDKR_POOL_DEPTH": os.environ.get("FSDKR_POOL_DEPTH", "64"),
            "max_backlog": args.max_backlog,
        },
    }

    # ---- chaos accounting + tamper-economics curve -------------------
    if args.chaos:
        from fsdkr_tpu.serving import faults as faults_mod

        outcomes = classify_chaos(sessions)
        injected = fault_plan.injected()
        faults_mod.reset()
        # the p99 gate reads HEALTHY traffic: sessions no DISRUPTIVE
        # fault hit (crash/finalize/delay/drop/tamper change the
        # session's own path; pool_dry/mem_squeeze/msg_dup are absorbed
        # invisibly by design — inline fallback, tighter tiles, ignored
        # duplicate) and that completed first try. This measures what
        # injection costs BYSTANDERS — queueing behind storm-hit
        # siblings — not what the faulted sessions themselves paid.
        disruptive = ("worker_crash", "finalize_exc", "msg_delay",
                      "msg_drop", "msg_tamper")
        healthy_lat = sorted(
            s.finalized_at - s.submitted_at
            for s in done
            if s.retries == 0
            and not any(f.startswith(d) for f in s.faults for d in disruptive)
        )
        p99_healthy = percentile(healthy_lat, 0.99)
        p99_base = baseline["p99"] if baseline else None
        ratio = (
            round(p99_healthy / p99_base, 3)
            if p99_healthy and p99_base and p99_base > 0 else None
        )
        # the STATED bound: one in-flight session per committee means a
        # healthy arrival can inherit at most ONE doomed sibling's
        # deadline of queue wait, plus bounded (p99_bound x baseline)
        # service — so the gate is deadline + bound x baseline, not a
        # bare ratio (which a single sibling-deadline inheritance would
        # dominate at any storm intensity)
        bound_s = (
            round(deadline_s + args.p99_bound * p99_base, 3)
            if p99_base else None
        )
        report["chaos"] = {
            "fault_spec": fault_plan.spec(),
            "injected": injected,
            "injected_classes": sorted(injected),
            "outcomes": outcomes,
            "wedged": wedged,
            "wrong_verdicts": outcomes["wrong_verdicts"],
            "service_rejected_total": svc.sessions_rejected - rejected0,
            "workers_respawned": svc.stats()["workers_respawned"],
            "baseline": baseline,
            "healthy_done": len(healthy_lat),
            "p99_healthy_done_s": p99_healthy,
            "p99_all_done_s": report["latency_s"]["p99"],
            "p99_vs_baseline": ratio,
            "p99_bound": args.p99_bound,
            "p99_bound_s": bound_s,
            "p99_bound_stated": "deadline_s + p99_bound * baseline_p99",
            "p99_within_bound": (
                p99_healthy is not None
                and bound_s is not None
                and p99_healthy <= bound_s
            ),
        }
        rates = [float(x) for x in args.curve.split(",") if x.strip()] \
            if args.curve else []
        if rates:
            report["chaos"]["tamper_curve"] = run_tamper_curve(
                svc, list(committees), rates, args.curve_sessions,
                args.seed, args.drain_timeout, log,
            )

    report["telemetry"] = tel_export.snapshot()
    svc.stop()
    precompute.stop_background()

    prefix = "chaos" if args.chaos else "serving"
    out = args.out or f"bench_results/{prefix}_{tag}.json"
    pathlib.Path(out).parent.mkdir(parents=True, exist_ok=True)
    pathlib.Path(out).write_text(json.dumps(report, indent=1) + "\n")
    log(f"[loadgen] report -> {out} (total wall {time.time() - t_start:.0f}s)")
    print(json.dumps(report))
    return 0


def _dry_by_cause():
    """Snapshot of the cause-labeled dry counter (ISSUE 11 satellite):
    {'real': n, 'injected': m} summed over pool kinds."""
    from fsdkr_tpu.telemetry import registry

    out = {}
    m = registry.get_registry().get("fsdkr_pool_dry")
    if m is None:
        return out
    for rec in m.snapshot_values():
        cause = rec["labels"].get("cause", "?")
        out[cause] = out.get(cause, 0) + int(rec["value"])
    return out


if __name__ == "__main__":
    sys.exit(main())
