#!/bin/bash
# Per-commit gate, mirroring the reference's pipeline
# (/root/reference/.github/workflows/pull_request.yml: check, test, fmt,
# clippy) with the tools this image has:
#   check  -> byte-compile every source tree + package import
#   test   -> the smoke tier: quick suite minus `heavy` kernel
#             differentials (pytest.ini already excludes `slow`);
#             session-scoped keygen caching makes this the <3 min gate
#   lint   -> compileall + scripts/fsdkr_lint.py (ISSUE 14: four AST
#             passes — secret-flow taint, lock discipline, knob drift,
#             unused-imports/layering — plus a planted-fixture gate
#             proof; scripts/lint_imports.py survives as a shim)
# Full suite on demand: pytest tests/ -m "not slow" (quick) or
# pytest tests/ -m "" (everything, ~hours on this box).
set -e
cd "$(dirname "$0")/.."

echo "== check: byte-compile =="
python -m compileall -q fsdkr_tpu tests scripts bench.py __graft_entry__.py

echo "== check: package import =="
python - <<'EOF'
import fsdkr_tpu
from fsdkr_tpu.protocol import RefreshMessage, JoinMessage  # API surface
from fsdkr_tpu import config, errors
print("import ok:", fsdkr_tpu.__name__)
EOF

echo "== lint: fsdkr-lint static analysis (taint + locks + knobs + imports) =="
# the four-pass gate (ISSUE 14): secret-flow taint, lock discipline,
# knob drift, and the old import/layering rules (scripts/lint_imports.py
# is now a shim over the imports pass). Whole tree, no jax import, ~5 s.
python scripts/fsdkr_lint.py

echo "== lint: gate proof (planted violations must fail the driver) =="
# a static gate that cannot catch a planted violation is a green light
# painted on a wall: one fixture per pass, each run through the REAL
# driver in a subprocess, each required to exit 1 naming the right rule
python - <<'EOF'
import pathlib, shutil, subprocess, sys, tempfile, textwrap

# rule -> (pass to run, fixture). Each fixture runs ONLY its own pass
# (exit 1 is then attributable to it, not to unrelated-pass noise) and
# must produce a finding line naming the fixture file AND the rule.
fixtures = {
    "secret-flow": ("taint",
                    "def f(journal, dk):\n"
                    "    journal.append({'p': dk.p})\n"),
    "lock-order": ("locks", textwrap.dedent("""
        import threading
        A = threading.Lock()
        B = threading.Lock()
        def ab():
            with A:
                with B: pass
        def ba():
            with B:
                with A: pass
    """)),
    "lock-blocking-call": ("locks", textwrap.dedent("""
        import os, threading
        L = threading.Lock()
        def f(fh):
            with L:
                os.fsync(fh.fileno())
    """)),
    "knob-undeclared": ("knobs",
                        "import os\n"
                        "X = os.environ.get('FSDKR_BOGUS_KNOB', '0')\n"),
}
tmp = pathlib.Path(tempfile.mkdtemp(prefix="fsdkr_lint_proof_"))
try:
    for rule, (passes, src) in fixtures.items():
        f = tmp / f"planted_{rule.replace('-', '_')}.py"
        f.write_text(src)
        p = subprocess.run(
            [sys.executable, "scripts/fsdkr_lint.py", "--passes", passes,
             str(f)],
            capture_output=True, text=True,
        )
        assert p.returncode == 1, f"{rule}: gate did not fail\n{p.stdout}{p.stderr}"
        hit = [ln for ln in p.stdout.splitlines()
               if ln.startswith(str(f)) and f"[{rule}]" in ln]
        assert hit, f"{rule} not reported against the fixture:\n{p.stdout}"
        print(f"gate proof ok: planted {rule} -> exit 1 ({passes} pass)")
finally:
    shutil.rmtree(tmp, ignore_errors=True)
EOF

echo "== test: smoke tier =="
python -m pytest tests/ -q -m "not slow and not heavy" -p no:cacheprovider

echo "== test: thread parity (row pool forced >1) =="
# the smoke tier above already ran these files at the default thread
# setting; this pass forces an 8-wide native row pool so the concurrent
# path is exercised on every commit, not just on many-core bench hosts
FSDKR_THREADS=8 python -m pytest tests/test_thread_parity.py \
  tests/test_cache_isolation.py -q -m "not slow and not heavy" \
  -p no:cacheprovider

echo "== test: FSDKR_RLC=0 leg (per-row column path) =="
# the smoke tier above ran with the default FSDKR_RLC=1 (randomized
# batch verification, bisection fallback); this leg forces the per-row
# column path on the verifier-facing suites so the fallback the
# bisection depends on cannot rot unexercised
FSDKR_RLC=0 python -m pytest tests/test_rlc.py tests/test_tamper.py \
  tests/test_join_tamper.py tests/test_tpu_backend.py -q \
  -m "not slow and not heavy" -p no:cacheprovider

echo "== test: FSDKR_RANGEOPT=0 leg (per-row range column path) =="
# the smoke tier above ran with the default FSDKR_RANGEOPT=1 (shared-
# exponent ladders, joint comb apply, concurrent column scheduler); this
# leg forces the per-row joint/column range path — the fallback the A/B
# identity depends on — plus FSDKR_MPN=0 so the portable u128 Montgomery
# core keeps coverage alongside the GMP mpn inner loop
FSDKR_RANGEOPT=0 FSDKR_MPN=0 python -m pytest tests/test_range_engines.py \
  tests/test_tamper.py tests/test_tpu_backend.py -q \
  -m "not slow and not heavy" -p no:cacheprovider

echo "== test: FSDKR_CRT=0 + FSDKR_GMP=0 leg (full-width prover path) =="
# the smoke tier above ran with the default FSDKR_CRT=1 (secret-CRT
# prover engine) and the GMP bridge active where present; this leg
# forces the full-width prover path AND the own native engines on the
# prover-facing suites so neither fallback can rot unexercised (same
# pattern as the FSDKR_RLC=0 leg)
FSDKR_CRT=0 FSDKR_GMP=0 python -m pytest tests/test_crt.py \
  tests/test_proofs.py tests/test_native.py tests/test_thread_parity.py \
  -q -m "not slow and not heavy" -p no:cacheprovider

echo "== test: telemetry export leg (FSDKR_TRACE=1 + dumps) =="
# the smoke tier above ran untraced; this leg turns on span tracing AND
# both export paths (Chrome trace, Prometheus dump, flight recorder) on
# the telemetry-facing suites, then drives one tiny traced refresh and
# asserts the three artifacts actually materialize — so the export
# paths cannot rot (same pattern as the A/B legs above)
rm -f /tmp/fsdkr_ci_trace.json /tmp/fsdkr_ci_metrics.prom /tmp/fsdkr_ci_flight.json
FSDKR_TRACE=1 python -m pytest tests/test_telemetry.py tests/test_trace.py \
  -q -m "not slow and not heavy" -p no:cacheprovider
FSDKR_TRACE=1 FSDKR_TRACE_OUT=/tmp/fsdkr_ci_trace.json \
  FSDKR_METRICS_DUMP=/tmp/fsdkr_ci_metrics.prom \
  FSDKR_FLIGHT=/tmp/fsdkr_ci_flight.json \
  python - <<'EOF'
import json, os
from fsdkr_tpu.config import TEST_CONFIG
from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen
from fsdkr_tpu import telemetry

keys = simulate_keygen(1, 3, TEST_CONFIG)
results = RefreshMessage.distribute_batch([(k.i, k) for k in keys], 3, TEST_CONFIG)
RefreshMessage.collect([m for m, _ in results], keys[0].clone(),
                       results[0][1], (), TEST_CONFIG)
telemetry.get_tracer().write_chrome_trace(os.environ["FSDKR_TRACE_OUT"])
telemetry.export.dump_metrics(os.environ["FSDKR_METRICS_DUMP"])
telemetry.flight.dump(reason="ci")
trace = json.load(open(os.environ["FSDKR_TRACE_OUT"]))
spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
assert any(e["name"] == "collect" for e in spans), "no collect span"
assert any(e["name"].startswith("distribute") for e in spans)
assert any("parent_id" in e["args"] for e in spans), "no nesting"
prom = open(os.environ["FSDKR_METRICS_DUMP"]).read()
assert "fsdkr_phase_seconds_bucket" in prom
flight = json.load(open(os.environ["FSDKR_FLIGHT"]))
assert flight["events"], "flight ring empty"
print("telemetry export leg ok:", len(spans), "spans")
EOF

echo "== test: memory-plan leg (tiny budget, multi-tile path) =="
# the smoke tier above ran with the default FSDKR_MEM_BUDGET_MB=256,
# where every test-size batch fits one tile and verify_pairs takes the
# monolithic path; this leg forces a deliberately tiny budget so a real
# refresh runs the multi-tile streaming path (running per-group RLC
# partial folds, per-tile range/EC verification, stage/release
# accounting) on every commit — the path the n=256 full-width run
# depends on cannot rot between batteries
FSDKR_MEM_BUDGET_MB=0.02 python -m pytest tests/test_memplan.py -q \
  -m "not slow and not heavy" -p no:cacheprovider
FSDKR_MEM_BUDGET_MB=0.01 python - <<'EOF'
from fsdkr_tpu.config import TEST_CONFIG
from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen
from fsdkr_tpu.backend import memplan, rlc

keys = simulate_keygen(1, 3, TEST_CONFIG)
cfg = TEST_CONFIG.with_backend("tpu")
out = RefreshMessage.distribute_batch([(k.i, k) for k in keys], 3, cfg)
rlc.stats_reset()
RefreshMessage.collect([m for m, _ in out], keys[0].clone(),
                       out[0][1], (), cfg)
mem = memplan.mem_stats()
assert mem["tiles"] > 1, f"tiny budget did not tile: {mem}"
assert rlc.stats()["stream_tiles"] > 1, rlc.stats()
assert rlc.stats()["bisect_fallbacks"] == 0, rlc.stats()
assert mem["peak_resident_bytes"] > 0
print("memory-plan leg ok:", mem["tiles"], "tiles, peak",
      mem["peak_resident_bytes"], "bytes under budget",
      mem["budget_bytes"])
EOF

echo "== test: cross-session fusion leg (fused S=4 identity + delegate A/B) =="
# the smoke tier runs tests/test_xsession.py and tests/test_delegate.py
# at TEST_CONFIG; this leg pins the ISSUE 17 acceptance invariants at a
# fast 640-bit shape on every commit: a fused S=4 launch's verdicts,
# blame, and adopted state are bit-identical to independent collects
# (honest + one-tampered-of-four), full-width ladders run once per
# merged group (not per session), and FSDKR_DELEGATE=0/1 agree on a
# fixed honest AND tampered transcript with certs on the wire
python - <<'EOF'
import dataclasses, os
from fsdkr_tpu.config import ProtocolConfig
from fsdkr_tpu.backend import rlc
from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen
from fsdkr_tpu.protocol.serialization import local_key_to_json

cfg = ProtocolConfig(
    paillier_bits=640, m_security=32, correct_key_rounds=3
).with_backend("tpu")
os.environ["FSDKR_DELEGATE"] = "1"  # certs on the wire for the A/B
keys = simulate_keygen(1, 3, cfg)
out = RefreshMessage.distribute_batch([(k.i, k) for k in keys], 3, cfg)
msgs = [m for m, _ in out]
dk = out[0][1]
os.environ["FSDKR_DELEGATE"] = "0"

def collect_fused(use_msgs_per_s):
    ks = [keys[0].clone() for _ in use_msgs_per_s]
    errs = RefreshMessage.collect_sessions(
        [(m, k, dk, ()) for m, k in zip(use_msgs_per_s, ks)], cfg
    )
    return errs, [local_key_to_json(k) for k in ks]

# solo reference + fused honest S=4: verdicts and state bit-identical
ref_errs, ref_states = collect_fused([msgs])
assert ref_errs == [None], ref_errs
rlc.stats_reset()
errs, states = collect_fused([msgs] * 4)
st = rlc.stats()
assert errs == [None] * 4, errs
assert states == ref_states * 4, "fused state diverged from solo collect"
assert st["fullwidth_ladders"] == st["rlc_groups"] > 0, st
assert st["xsession_rows_deduped"] > 0, st

# one-tampered-of-four blames exactly the guilty session, bit-identical
bad_pv = list(msgs[1].pdl_proof_vec)
bad_pv[0] = dataclasses.replace(bad_pv[0], u2=bad_pv[0].u2 + 1)
msgs_bad = list(msgs)
msgs_bad[1] = dataclasses.replace(msgs[1], pdl_proof_vec=bad_pv)
solo_err = collect_fused([msgs_bad])[0][0]
errs, _ = collect_fused([msgs, msgs_bad, msgs, msgs])
assert [e is None for e in errs] == [True, False, True, True], errs
assert type(errs[1]) is type(solo_err) and str(errs[1]) == str(solo_err)

# FSDKR_DELEGATE A/B on the same transcripts: verdict + state parity
from fsdkr_tpu.proofs import msm_delegate
assert all(m.coefficients_committed_vec.delegate_cert is not None
           for m in msgs)
os.environ["FSDKR_DELEGATE"] = "1"
msm_delegate.stats_reset()
errs_on, states_on = collect_fused([msgs] * 4)
dstats = msm_delegate.stats()
errs_bad_on, _ = collect_fused([msgs_bad])
os.environ["FSDKR_DELEGATE"] = "0"
assert errs_on == [None] * 4 and states_on == states, "delegate arm diverged"
assert dstats["schemes_delegated"] == len(msgs), dstats
assert dstats["rows_delegated"] > 0 and dstats["certs_rejected"] == 0, dstats
assert type(errs_bad_on[0]) is type(solo_err)
assert str(errs_bad_on[0]) == str(solo_err)
print("cross-session fusion leg ok:", st["rlc_groups"], "merged groups,",
      st["xsession_rows_deduped"], "rows deduped,",
      dstats["rows_delegated"], "rows by certificate")
EOF

echo "== test: FSDKR_PRECOMPUTE=0 leg (inline prover path) =="
# the smoke tier above ran with the default FSDKR_PRECOMPUTE=1 (pool
# consume-or-compute in distribute); this leg forces the inline path on
# the prover-facing suites so the no-pool code cannot rot unexercised
# (same pattern as the FSDKR_RLC=0 / FSDKR_CRT=0 legs)
FSDKR_PRECOMPUTE=0 python -m pytest tests/test_precompute.py \
  tests/test_protocol.py tests/test_proofs.py -q \
  -m "not slow and not heavy" -p no:cacheprovider

echo "== test: serving smoke leg (RefreshService loadgen) =="
# a short sustained run through the whole serving loop (admission ->
# distribute -> streaming collect -> coalesced fused finalize -> pool
# retarget): asserts sessions actually complete and the serving
# telemetry artifacts materialize, so the service cannot rot between
# the full measure_all serve_sustained runs
rm -f /tmp/fsdkr_ci_serving.json /tmp/fsdkr_ci_serving.prom
FSDKR_METRICS_DUMP=/tmp/fsdkr_ci_serving.prom \
  python scripts/loadgen.py --committees 8 --bases 2 --window 6 --rate 2 \
  --prefill-wait 15 --drain-timeout 180 --tag ci \
  --out /tmp/fsdkr_ci_serving.json > /dev/null
python - <<'EOF'
import json
rep = json.load(open("/tmp/fsdkr_ci_serving.json"))
assert rep["sessions_done"] > 0, "no serving sessions completed"
assert rep["sessions_aborted"] == 0, rep["abort_errors"]
assert rep["latency_s"]["p99"] is not None
tel = rep["telemetry"]["metrics"]
assert "fsdkr_serving_phase_seconds" in tel, "serving histogram missing"
assert "fsdkr_serving_sessions" in tel, "serving counter missing"
prom = open("/tmp/fsdkr_ci_serving.prom").read()
assert "fsdkr_serving_sessions" in prom, "prom exposition missing serving"
print("serving smoke leg ok:", rep["sessions_done"], "sessions, p99",
      rep["latency_s"]["p99"], "s, dry", rep["pool"]["dry_fallback_rate"])
EOF

echo "== test: chaos smoke leg (fault injection, verdict correctness) =="
# the serving smoke leg above ran perfectly healthy traffic; this leg
# replays a short Poisson window under a FIXED-SEED fault plan covering
# every fault class (worker crashes, finalize failures, pool-dry
# storms, delayed/dropped/duplicated/tampered broadcasts, memory
# squeezes) and asserts the ISSUE 11 hard invariants on every commit:
# every class actually injected, zero wedged sessions, zero wrong
# verdicts (no healthy session blamed, no tampered session clean),
# every drop-timeout names its missing senders, and the service drains
rm -f /tmp/fsdkr_ci_chaos.json
python scripts/loadgen.py --chaos --committees 8 --bases 2 \
  --window 10 --rate 2.5 --baseline-window 5 --prefill-wait 15 \
  --deadline 6 --drain-timeout 180 --curve "" --seed 42 \
  --faults "seed=42,worker_crash=0.35,finalize_exc=0.35,pool_dry=0.08,msg_delay=0.2,msg_drop=0.15,msg_dup=0.25,msg_tamper=0.2,mem_squeeze=0.6,delay_s=0.3,squeeze_factor=0.25" \
  --tag ci --out /tmp/fsdkr_ci_chaos.json > /dev/null
python - <<'EOF'
import json
rep = json.load(open("/tmp/fsdkr_ci_chaos.json"))
ch = rep["chaos"]
missing = [s for s in (
    "worker_crash", "finalize_exc", "pool_dry", "msg_delay", "msg_drop",
    "msg_dup", "msg_tamper", "mem_squeeze",
) if ch["injected"].get(s, 0) < 1]
assert not missing, f"fault classes never injected: {missing}"
assert ch["wrong_verdicts"] == 0, ch["outcomes"]["wrong_detail"]
assert ch["wedged"] == 0, "wedged sessions after drain"
assert rep["drained"], "service did not drain clean"
out = ch["outcomes"]
# wrong_verdicts==0 above already covers dropped-message timeouts that
# failed to name their missing senders; timeouts of sessions still
# QUEUED (stuck behind the storm) legitimately have no senders to name
assert rep["sessions_done"] + rep["sessions_aborted"] \
    + rep["sessions_timed_out"] == rep["arrivals"], rep["arrivals"]
dry = rep["pool"]["dry_by_cause"]
assert dry.get("injected", 0) >= 1, "injected pool-dry storms unlabeled"
print("chaos smoke leg ok:", dict(ch["injected"]),
      "| outcomes", {k: v for k, v in out.items() if isinstance(v, int)})
EOF

echo "== test: kill-recovery smoke leg (2-shard supervisor, SIGKILL + replay) =="
# the chaos leg above injects faults INSIDE one process; this leg kills
# a whole shard process mid-session (ISSUE 12): a 2-shard supervisor
# runs a healthy epoch on both committees, then SIGKILLs one shard with
# an epoch in flight and asserts the supervisor detects the death,
# replays the dead shard's journal on the peer (terminal verdicts
# restored verbatim), and the interrupted session COMPLETES with a
# verdict bit-identical to the uninterrupted control run on the
# surviving shard — plus MTTR measured and the dead shard's flight
# dump collected beside its journal
python - <<'EOF'
import json, pathlib, tempfile, time
from fsdkr_tpu.config import TEST_CONFIG
from fsdkr_tpu.protocol import simulate_keygen
from fsdkr_tpu.serving.supervisor import ShardSupervisor, shard_for

root = tempfile.mkdtemp(prefix="fsdkr_ci_killrec_")
sup = ShardSupervisor(shards=2, root=root, deadline_s=10.0, hb_interval=0.4)
sup.start()
cids, want, i = [], {0, 1}, 0
while want:  # one committee per shard under the fingerprint partition
    cid = f"com{i}"
    if shard_for(cid, 2) in want:
        want.discard(shard_for(cid, 2)); cids.append(cid)
    i += 1
keys = simulate_keygen(1, 3, TEST_CONFIG)
for cid in cids:
    sup.admit(cid, [k.clone() for k in keys], TEST_CONFIG)
for cid in cids:
    sup.submit(cid, 0)
assert sup.drain(240), f"epoch0 wedged: {sup.pending}"
victim, bystander = cids[0], cids[1]
# three epochs queue on the victim committee (one-in-flight-per-
# committee serializes them) so the SIGKILL is guaranteed to land with
# a session still in flight, however fast the box
for e in (1, 2, 3):
    sup.submit(victim, e)
sup.submit(bystander, 1)   # the uninterrupted control run
time.sleep(0.3)
killed = sup.kill_shard(sup.assignment[victim])
assert killed is not None, "no shard killed"
assert sup.drain(300), f"post-kill wedge: {sup.pending}"
by = {(o["cid"], o["epoch"]): o for o in sup.outcomes}
control = by[(bystander, 1)]
assert control["state"] == "done" and not control["blame"], control
# every interrupted epoch's verdict is bit-identical to the
# uninterrupted control (done, no blame, no error), and at least one
# crossed the failover/replay path
vias = set()
for e in (1, 2, 3):
    rec = by[(victim, e)]
    assert rec["state"] == "done" and not rec["blame"] \
        and rec["error"] is None, rec
    vias.add(rec["via"])
assert vias & {"failover", "resubmit"}, vias
rec = by[(victim, 1)]
agg = sup.aggregate()
fo = agg["failovers"][0]
assert fo["recovery"]["replayed_terminal"] >= 1, fo
assert fo["recovery"]["skipped"] == 0, fo
assert fo["mttr_s"] is not None and fo["mttr_s"] > 0, fo
assert fo["flight_dump"] and pathlib.Path(fo["flight_dump"]).exists(), fo
assert json.load(open(fo["flight_dump"]))["events"], "empty flight dump"
assert agg["journal"]["records"] > 0, agg
sup.stop()
print("kill-recovery smoke ok: killed shard", killed,
      "| MTTR", fo["mttr_s"], "s | replayed",
      fo["recovery"]["replayed_terminal"], "| recovered via", rec["via"])
EOF

echo "== test: ingress smoke leg (networked 2-shard supervisor + conn_drop storm) =="
# the kill-recovery leg above feeds shards over private pipes; this leg
# feeds them over REAL TCP sockets (ISSUE 13): a 2-shard supervisor
# with ingress ports, epoch 0 as the in-process (pipe-fed) control,
# epoch 1 driven through the wire protocol — dialing the WRONG shard
# first so the redirect path is exercised — asserting the socket-fed
# verdict matches the control; then a fixed-seed network-chaos storm
# (conn_drop / frame_truncate / net_delay / net_dup) through the
# multi-process client loadgen asserting zero wedged sessions, zero
# wrong verdicts, zero lost accepted broadcasts, and a clean drain
python - <<'EOF'
from fsdkr_tpu.config import TEST_CONFIG
from fsdkr_tpu.protocol import simulate_keygen
from fsdkr_tpu.serving.supervisor import ShardSupervisor, shard_for
from fsdkr_tpu.serving.ingress import IngressClient
import tempfile

root = tempfile.mkdtemp(prefix="fsdkr_ci_ingress_")
sup = ShardSupervisor(shards=2, root=root, deadline_s=20.0,
                      hb_interval=0.4, ingress=True)
sup.start()
ports = sup.ingress_ports()
assert len(ports) == 2, ports
cids, want, i = [], {0, 1}, 0
while want:  # one committee per shard under the fingerprint partition
    cid = f"com{i}"
    if shard_for(cid, 2) in want:
        want.discard(shard_for(cid, 2)); cids.append(cid)
    i += 1
keys = simulate_keygen(1, 3, TEST_CONFIG)
for cid in cids:
    sup.admit(cid, [k.clone() for k in keys], TEST_CONFIG)
for cid in cids:
    sup.submit(cid, 0)           # the in-process control epoch
assert sup.drain(240), f"control epoch wedged: {sup.pending}"
control = {(o["cid"], o["epoch"]): o for o in sup.outcomes}
assert all(o["state"] == "done" and not o["blame"]
           for o in control.values()), control
cid = cids[0]
owner = shard_for(cid, 2)
cli = IngressClient("127.0.0.1", ports[1 - owner])  # wrong shard first
r = cli.submit(cid, epoch=1)
assert r["type"] == "redirect" and r["hint"] == ports[owner], r
cli.close()
cli = IngressClient("127.0.0.1", int(r["hint"]))
r = cli.submit(cid, epoch=1)
assert r["type"] == "submitted", r
for snd, wire in r["broadcasts"]:
    assert cli.broadcast(r["sid"], wire)["result"] == "accepted"
term = cli.wait(r["sid"], 120)
assert term["state"] == "done" and not term["blame"], term
cli.close()
sup.pump(0.5)
agg = sup.aggregate()
assert agg["ingress"].get("frames", {}).get("in", 0) >= 5, agg["ingress"]
sup.stop()
print("ingress smoke ok: socket-fed verdict matches in-process control "
      "(done/no-blame), redirect exercised, ingress frames in heartbeats")
EOF
rm -f /tmp/fsdkr_ci_net.json
python scripts/loadgen.py --net --committees 4 --bases 2 --shards 2 \
  --clients 2 --window 8 --rate 1.5 --baseline-window 5 --deadline 8 \
  --kills 0 --seed 42 --drain-timeout 180 \
  --net-faults "seed=42,conn_drop=0.12,frame_truncate=0.05,net_delay=0.1,net_dup=0.1,delay_s=0.2" \
  --out /tmp/fsdkr_ci_net.json > /dev/null
python - <<'EOF'
import json
rep = json.load(open("/tmp/fsdkr_ci_net.json"))
g = rep["gates"]
assert g["zero_wedged"], rep["outcomes"]
assert g["zero_wrong_verdicts"], rep["wrong_detail"]
assert g["zero_lost_broadcasts"], rep["lost_detail"]
assert g["fleet_quiesced"], "fleet did not drain clean"
done = rep["outcomes"]["done_clean"] + rep["outcomes"]["recovered"]
assert done > 0, rep["outcomes"]
ing = rep["aggregate"]["ingress"]
assert ing.get("frames", {}).get("in", 0) > 0, ing
print("ingress conn_drop storm ok:", rep["outcomes"],
      "| client counters", rep["client_counters"],
      "| net", rep["net_sessions_per_s"], "/s vs in-process",
      rep["in_process_baseline"]["sessions_per_s"], "/s")
EOF

echo "== ci.sh: all gates green =="
