#!/usr/bin/env python
"""fsdkr-lint driver: the four-pass static-analysis gate (ISSUE 14).

Passes (all by default; select with --passes):

  taint     secret-flow: SECURITY.md's secret carriers must not reach
            journal/wire/telemetry/LRU/log/JSON sinks unsanitized
  locks     lock-order cycles + blocking calls under `with <lock>:`
  knobs     FSDKR_* declaration/README/dead/hot-read drift
  imports   unused imports + package layering

Inline suppression (reason REQUIRED — residuals stay documented):

    risky_call()  # fsdkr-lint: allow(lock-blocking-call) why it's ok

Usage:
  python scripts/fsdkr_lint.py [--passes taint,locks] [paths...]
  (default paths: fsdkr_tpu scripts tests bench.py __graft_entry__.py)

Exit code 1 on any finding — this is the ci.sh analysis gate.
"""

import argparse
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from fsdkr_tpu.analysis import PASSES, run_passes  # noqa: E402

DEFAULT_PATHS = ["fsdkr_tpu", "scripts", "tests", "bench.py",
                 "__graft_entry__.py"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: the whole tree)")
    ap.add_argument("--passes", default=",".join(PASSES),
                    help=f"comma-separated subset of: {', '.join(PASSES)}")
    ap.add_argument("--repo-root", default=str(REPO))
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="findings only, no summary line")
    args = ap.parse_args(argv)

    which = [p.strip() for p in args.passes.split(",") if p.strip()]
    # explicit paths resolve against the CALLER's cwd (the old
    # lint_imports contract); only then chdir to the repo root so
    # in-repo `rel` paths in findings are stable
    paths = [str(pathlib.Path(p).resolve()) for p in args.paths] \
        if args.paths else [
            str(pathlib.Path(args.repo_root) / p) for p in DEFAULT_PATHS
            if (pathlib.Path(args.repo_root) / p).exists()
        ]
    import os
    os.chdir(args.repo_root)

    try:
        result = run_passes(
            paths, which=which, repo_root=args.repo_root,
            # registry-wide knob reconciliation (dead/undocumented)
            # needs the whole tree's read surface: only the default
            # full path set provides it
            registry_checks=not args.paths,
        )
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 1

    for f in result["findings"]:
        print(f)
    if not args.quiet:
        print(
            f"fsdkr-lint: {len(result['findings'])} finding(s), "
            f"{result['suppressed']} suppressed, "
            f"{result['files']} files, passes: {', '.join(which)}",
            file=sys.stderr,
        )
    return 1 if result["findings"] else 0


if __name__ == "__main__":
    sys.exit(main())
