#!/usr/bin/env python
"""Phase profile of RefreshMessage.collect on the TPU backend.

Builds (or loads from .bench_cache/) a full-size refresh workload, then
times each batch-verifier family and the host-side glue separately.
Env: PROF_N, PROF_T, PROF_BITS, PROF_M (default full size n=16).
"""

import copy
import os
import pickle
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def load_workload(n, t, bits, m_sec, cfg):
    from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen

    cache_dir = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".bench_cache")
    os.makedirs(cache_dir, exist_ok=True)
    path = os.path.join(cache_dir, f"wl_{n}_{t}_{bits}_{m_sec}.pkl")
    if os.path.exists(path):
        log(f"loading cached workload {path}")
        with open(path, "rb") as f:
            return pickle.load(f)
    t0 = time.time()
    keys = simulate_keygen(t, n, cfg)
    log(f"keygen: {time.time()-t0:.1f}s")
    t0 = time.time()
    results = RefreshMessage.distribute_batch([(key.i, key) for key in keys], n, cfg)
    msgs = [m for m, _ in results]
    dks = [dk for _, dk in results]
    log(f"distribute_batch x{n}: {time.time()-t0:.1f}s")
    wl = (keys, msgs, dks)
    with open(path, "wb") as f:
        pickle.dump(wl, f)
    return wl


def main():
    n = int(os.environ.get("PROF_N", "16"))
    t = int(os.environ.get("PROF_T", "8"))
    bits = int(os.environ.get("PROF_BITS", "2048"))
    m_sec = int(os.environ.get("PROF_M", "256"))

    import jax

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        jax.config.update(
            "jax_compilation_cache_dir", os.path.join(repo_root, ".jax_cache")
        )
    except Exception:
        pass

    from fsdkr_tpu.config import ProtocolConfig
    from fsdkr_tpu.backend import tpu_verifier
    from fsdkr_tpu.protocol import RefreshMessage

    cfg = ProtocolConfig(paillier_bits=bits, m_security=m_sec, backend="tpu")
    keys, msgs, dks = load_workload(n, t, bits, m_sec, cfg)

    # wrap every verifier family with a timer
    times = {}
    verifier_cls = tpu_verifier.TpuBatchVerifier
    for name in (
        "verify_pdl",
        "verify_range",
        "verify_ring_pedersen",
        "verify_correct_key",
        "verify_composite_dlog",
        "validate_feldman",
    ):
        orig = getattr(verifier_cls, name)

        def wrap(orig=orig, name=name):
            def inner(self, *a, **kw):
                t0 = time.time()
                out = orig(self, *a, **kw)
                times[name] = times.get(name, 0.0) + time.time() - t0
                return out
            return inner

        setattr(verifier_cls, name, wrap())

    for run in ("cold", "warm"):
        times.clear()
        key = copy.deepcopy(keys[0])
        dk = dks[0]
        t0 = time.time()
        RefreshMessage.collect(list(msgs), key, dk, [], cfg)
        total = time.time() - t0
        log(f"--- {run}: collect total {total:.2f}s")
        acc = 0.0
        for name, dt in sorted(times.items(), key=lambda kv: -kv[1]):
            log(f"    {name:24s} {dt:7.2f}s")
            acc += dt
        log(f"    {'(host glue / other)':24s} {total-acc:7.2f}s")


if __name__ == "__main__":
    main()
