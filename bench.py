#!/usr/bin/env python
"""North-star benchmark (BASELINE.json): RefreshMessage.collect wall-clock,
reported as proofs verified per second, TPU batch backend vs the host
(native C++ Montgomery) baseline on the identical workload.

Prints exactly ONE JSON line to stdout:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
On any failure (including TPU backend init) the line still appears, with
an "error" field and value 0. All progress goes to stderr.

Default workload: a real full-size refresh (2048-bit Paillier, M=256
ring-Pedersen, 11 correct-key rounds) at committee n=16, t=8 — one
collecting party verifies 2*n^2 PDL+range proofs, n ring-Pedersen and n
correct-key proofs (plus n^2 Feldman EC checks). `vs_baseline` is the
speedup of the TPU backend over the host backend routed through the
native C++ Montgomery core (the repo's best CPU path — see
fsdkr_tpu/core/intops.py mod_pow); the CPython-only number is reported
separately as `vs_cpython` / stderr. Host cost is measured on a
subsample of >= 25% of the n^2 pair loop and extrapolated linearly.

Environment knobs: BENCH_N / BENCH_T / BENCH_BITS / BENCH_M override the
workload for experiments; defaults match BASELINE.md. BENCH_SESSIONS > 1
switches to the multi-session config (BASELINE.json config 5): S
independent (n, t) refresh sessions collected through ONE fused launch
set per proof family (RefreshMessage.collect_sessions), stacked on the
same batch axis and sharded over BENCH_MESH devices when set
(e.g. BENCH_SESSIONS=64 BENCH_MESH=8 on a v5e-8).
"""

import json
import os
import sys
import time


def log(msg):
    print(msg, file=sys.stderr, flush=True)


_PLATFORM = None  # set by init_jax_with_retry on successful backend init


def emit(result):
    # every line self-describes where it was measured; fallback runs are
    # tagged so a CPU-platform number can never read as a chip number.
    # NEVER touch jax here: on the fail-hard error path the backend was
    # never initialized and an in-process jax.devices() on a dead tunnel
    # hangs without printing the guaranteed JSON line (the round-1
    # failure mode the out-of-process probe exists to avoid).
    if _PLATFORM:
        result.setdefault("platform", _PLATFORM)
    note = os.environ.get("BENCH_FALLBACK_NOTE")
    if note:
        result.setdefault("fallback_note", note)
    print(json.dumps(result), flush=True)


def _metric(n, t, bits):
    return f"collect() proof verification throughput @ n={n},t={t},{bits}-bit"


def _probe_backend_subprocess(timeout=120.0) -> bool:
    """Probe the TPU backend in a THROWAWAY subprocess with a hard
    timeout. A dead tunnel makes jax.devices() hang inside a C call
    where Python signals never fire — probing in-process would hang
    this whole benchmark without ever emitting its JSON line (the
    round-1 failure mode). A killed subprocess just means 'down'."""
    import subprocess

    code = (
        "import jax, jax.numpy as jnp\n"
        "assert jax.devices()[0].platform != 'cpu'\n"
        "assert float((jnp.arange(8.0) * 2).sum()) == 56.0\n"
    )
    try:
        res = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout,
            capture_output=True,
        )
    except subprocess.TimeoutExpired:
        log("backend probe timed out (device call hung)")
        return False
    if res.returncode != 0:
        tail = res.stderr.decode(errors="replace").strip().splitlines()[-3:]
        log("backend probe failed: " + " | ".join(tail))
        return False
    return True


def init_jax_with_retry(attempts=4, delay=15.0):
    """TPU backend init is flaky on this platform (round-1 bench died on
    it; round-3 saw multi-hour tunnel outages where device calls hang).
    Probe out-of-process first, retry with backoff; raise only after all
    attempts fail — main() turns that into the error JSON line."""
    plat = os.environ.get("BENCH_PLATFORM")
    if not plat:  # real-chip run: never touch jax in-process until the
        # tunnel answers a disposable probe (a hang would eat the JSON)
        for i in range(attempts):
            if _probe_backend_subprocess():
                break
            log(f"backend probe {i + 1}/{attempts} failed; tunnel down")
            if i + 1 < attempts:
                time.sleep(delay)
        else:
            # Degrade to an honest CPU-platform measurement instead of a
            # zero datapoint (rounds 3 and 4 both recorded 0 proofs/s
            # through multi-hour tunnel outages). The emitted metric is
            # tagged with the platform and a fallback note
            # (BENCH_CPU_FALLBACK=0 restores the old fail-hard
            # behavior).
            if os.environ.get("BENCH_CPU_FALLBACK", "1") != "1":
                raise RuntimeError(
                    f"TPU backend unreachable after {attempts} probes"
                )
            log("tunnel down: falling back to the CPU platform")
            plat = "cpu"
            os.environ["BENCH_PLATFORM"] = "cpu"
            os.environ["BENCH_FALLBACK_NOTE"] = (
                f"TPU tunnel unreachable after {attempts} probes; measured "
                "on the XLA:CPU fallback platform (structural datapoint, "
                "not a chip number)"
            )
            # The fallback runs the NOMINAL shape (main()'s n=16, full
            # 2048-bit defaults): with the native host engines that is
            # ~6 min on this box, and the recorded metric stays directly
            # comparable to the on-chip rounds (same "n=16,t=8,2048-bit"
            # label, honest platform tag).

    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", _jax_cache_dir())
    except Exception:
        pass
    # BENCH_PLATFORM=cpu runs the bench flow off-chip (smoke-testing the
    # harness; the axon plugin ignores JAX_PLATFORMS, hence jax.config)
    if plat:
        try:
            jax.config.update("jax_platforms", plat)
        except Exception:
            pass

    # the probe said healthy, but init is still flaky (round-1 bench died
    # on it): retry raise-type failures in-process. A hang here remains
    # possible only in the probe-to-init window — the probe just answered,
    # so that race is narrow, and the step-level timeout still bounds it.
    last = None
    for i in range(attempts):
        try:
            devs = jax.devices()
            log(f"devices: {devs}")
            global _PLATFORM
            _PLATFORM = devs[0].platform
            return jax, devs
        except Exception as e:  # backend init failure is retriable
            last = e
            log(f"jax.devices() attempt {i + 1}/{attempts} failed: {e}")
            time.sleep(delay)
    raise RuntimeError(
        f"TPU backend unavailable after {attempts} attempts: {last}"
    )


def _host_cpu_tag() -> str:
    """Fingerprint of this host's CPU feature set. The persistent cache
    survives across VM instances of this environment whose CPUs differ
    slightly; XLA:CPU AOT entries compiled under one feature set can
    SIGILL (silently killing the bench, no JSON line) when loaded under
    another — the loader itself warns "could lead to execution errors
    such as SIGILL". Scoping the cache per feature set makes stale
    entries unloadable instead of fatal."""
    import hashlib

    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    feats = " ".join(sorted(line.split(":", 1)[1].split()))
                    return hashlib.sha256(feats.encode()).hexdigest()[:10]
    except OSError:
        pass
    import platform as _platform

    return _platform.machine()


def _jax_cache_dir() -> str:
    """Repo-relative persistent compilation cache (overridable via
    FSDKR_JAX_CACHE), derived from this file's location and scoped per
    host-CPU feature set (see _host_cpu_tag)."""
    base = os.environ.get(
        "FSDKR_JAX_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
    )
    return os.path.join(base, _host_cpu_tag())


def _jax_cache_entries() -> int:
    """Entry count of the persistent XLA compilation cache — cold-start
    accounting: cold-minus-warm is compile+upload overhead, and the
    entry delta says how many kernel shapes were NOT served by the
    cache (shape-bucketing regressions show up here)."""
    try:
        return len(os.listdir(_jax_cache_dir()))
    except OSError:
        return 0


def crt_fields():
    """Statistics of the secret-CRT prover engine (FSDKR_CRT,
    fsdkr_tpu.backend.crt), accumulated since the caller's stats_reset:
    rows routed / half-width legs computed / Bellcore fault checks run /
    full-width fallback rows / exponent bits saved by the leg-order
    reductions, plus the per-session secret store's counters. On an
    honest run fault_checks == legs and fallback_rows == 0."""
    from fsdkr_tpu.backend import crt

    return {
        "crt_enabled": crt.crt_enabled(),
        "crt": {**crt.crt_stats(), "store": crt.store_stats()},
    }


def precompute_fields():
    """Statistics of the precompute pool subsystem (FSDKR_PRECOMPUTE,
    fsdkr_tpu/precompute), accumulated since the caller's stats_reset:
    entries produced / consumed, dry-pool inline fallbacks, wiped
    entries, and current pooled bytes. On a prefilled online run
    dry_fallbacks == 0; on an FSDKR_PRECOMPUTE=0 run everything is 0."""
    from fsdkr_tpu import precompute

    return {
        "precompute_enabled": precompute.enabled(),
        "precompute": precompute.precompute_stats(),
    }


def mem_fields():
    """The memory-plan stat block (ISSUE 10, fsdkr_tpu.backend.memplan):
    the active FSDKR_MEM_BUDGET_MB budget, bytes staged through the
    limb encoder, the tracked peak of live staged tile bytes, the
    kernel's VmHWM ground truth, and how many tiles the streaming
    verification plan executed (0 = every batch fit its budget in one
    tile). Windowed alongside the rlc block (memplan_stats_reset before
    each measured section), except rss_peak_bytes, which is the
    process-lifetime VmHWM by kernel semantics. Per-family tile detail
    (rows/tile, plans) is in the telemetry snapshot's fsdkr_mem_*
    metrics."""
    from fsdkr_tpu.backend import memplan

    return {"mem": memplan.mem_stats()}


def memplan_stats_reset():
    from fsdkr_tpu.backend import memplan

    memplan.stats_reset()


def rlc_fields():
    """Fold statistics of the cross-proof randomized batch verifier
    (FSDKR_RLC, fsdkr_tpu.backend.rlc), accumulated since the caller's
    stats_reset — rlc_groups / rows_folded / fullwidth_ladders /
    bisect_fallbacks. The battery's A/B step reads fullwidth_ladders ==
    O(groups), not O(rows), off this field; on an honest transcript
    bisect_fallbacks must be 0."""
    from fsdkr_tpu.backend import rlc

    return {"rlc_enabled": rlc.rlc_enabled(), "rlc": rlc.stats()}


def telemetry_fields():
    """The unified telemetry block (ISSUE 6): ONE schema-versioned
    registry snapshot — per-phase latency histograms with interpolated
    p50/p95/p99, pool depth/occupancy gauges, producer occupancy, and
    the subsystem counters the legacy rlc/crt/precompute keys mirror
    (those stay for comparability with old BENCH_r0*.json files; this
    is the structured read going forward)."""
    from fsdkr_tpu.telemetry import export

    return {"telemetry": export.snapshot()}


def telemetry_artifacts():
    """Write the export artifacts when their env knobs ask for them:
    FSDKR_TRACE_OUT (Chrome-trace/Perfetto timeline of the recorded
    spans) and FSDKR_METRICS_DUMP (Prometheus text exposition). The
    package atexit hook would catch these too; writing here pins the
    artifacts even if the interpreter dies later."""
    from fsdkr_tpu.telemetry import export
    from fsdkr_tpu.utils.trace import get_tracer

    path = os.environ.get("FSDKR_TRACE_OUT")
    if path and get_tracer().spans():
        log(f"chrome trace -> {get_tracer().write_chrome_trace(path)}")
    dumped = export.maybe_dump_metrics()
    if dumped:
        log(f"metrics dump -> {dumped}")


def roofline_fields(t_warm, stats=None):
    """mfu/gmacs fields for a bench JSON, from tracer stats accumulated
    during the warm run (caller resets the tracer before it), or from an
    explicit stats dict. Empty when FSDKR_TRACE is off or no device
    modexp launched."""
    from fsdkr_tpu.utils.roofline import peak_macs
    from fsdkr_tpu.utils.trace import get_tracer

    tr = get_tracer()
    if not tr.enabled:
        return {}
    peak = peak_macs()
    if stats is None:
        stats = tr.stats()
    mfu = {
        name: {"gmacs": round(st.macs / 1e9, 2), "mfu": float(f"{st.mfu(peak):.3g}")}
        for name, st in stats.items()
        if st.macs > 0
    }
    if not mfu:
        return {}
    total = sum(st.macs for st in stats.values())
    return {
        "mfu": mfu,
        "mfu_collect": float(f"{total / (t_warm * peak):.3g}"),
        "peak_macs": peak,
    }


def bench_sessions(sessions_count, n, t, bits, m_sec):
    """Config-5 shape: S independent (n, t) sessions, one fused collect
    launch set (RefreshMessage.collect_sessions)."""
    import dataclasses

    from fsdkr_tpu.config import ProtocolConfig
    from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen

    cfg = ProtocolConfig(paillier_bits=bits, m_security=m_sec)
    mesh_env = os.environ.get("BENCH_MESH")
    mesh_shape = (int(mesh_env),) if mesh_env else None
    tpu_cfg = dataclasses.replace(cfg, backend="tpu", mesh_shape=mesh_shape)

    log(
        f"multi-session setup: {sessions_count} sessions of n={n} t={t} "
        f"bits={bits} M={m_sec} mesh={mesh_shape} ..."
    )
    t0 = time.time()
    built = []
    for _ in range(sessions_count):
        keys = simulate_keygen(t, n, cfg)
        results = RefreshMessage.distribute_batch(
            [(key.i, key) for key in keys], n, tpu_cfg
        )
        built.append(
            (keys, [m for m, _ in results], [dk for _, dk in results])
        )
    log(f"setup done in {time.time() - t0:.1f}s")

    proofs_per_session = 2 * n * n + 2 * n

    def run():
        sessions = [
            (msgs, keys[0].clone(), dks[0], ()) for keys, msgs, dks in built
        ]
        t0 = time.time()
        errs = RefreshMessage.collect_sessions(sessions, tpu_cfg)
        dt = time.time() - t0
        bad = [i for i, e in enumerate(errs) if e is not None]
        if bad:
            raise RuntimeError(f"sessions failed: {bad}: {errs[bad[0]]}")
        return dt

    t_cold = run()
    log(f"fused collect_sessions cold: {t_cold:.2f}s")
    from fsdkr_tpu.backend import rlc
    from fsdkr_tpu.utils.trace import get_tracer

    get_tracer().reset(keep_spans=True)
    rlc.stats_reset()
    memplan_stats_reset()
    t_warm = run()
    total_proofs = proofs_per_session * sessions_count
    log(
        f"fused collect_sessions warm: {t_warm:.2f}s -> "
        f"{total_proofs / t_warm:.1f} proofs/s"
    )
    emit(
        {
            "metric": (
                f"fused collect of {sessions_count} sessions @ n={n},t={t},"
                f"{bits}-bit (config 5)"
            ),
            "value": round(total_proofs / t_warm, 2),
            "unit": "proofs/s",
            "vs_baseline": 0,
            "collect_warm_s": round(t_warm, 2),
            "collect_cold_s": round(t_cold, 2),
            "sessions": sessions_count,
            "device_ec": tpu_cfg.device_ec,
            "device_powm": tpu_cfg.device_powm,
            "pallas": os.environ.get("FSDKR_PALLAS", "auto"),
            **({"degraded": os.environ["BENCH_DEGRADED"]}
               if os.environ.get("BENCH_DEGRADED") else {}),
            "mesh": mesh_shape,
            **rlc_fields(),
            **mem_fields(),
            **precompute_fields(),
            **roofline_fields(t_warm),
            **telemetry_fields(),
        }
    )
    telemetry_artifacts()


def bench_amortization(s_list, n, t, bits, m_sec):
    """Cross-session amortization curve (ISSUE 17 tentpole (d)): ONE
    committee, fused collect_sessions launches at S = s_list cloned
    sessions each. Same-committee sessions are the serving shape the
    fusion targets (S refresh requests against one broadcast), and the
    shape where the cross-session machinery all fires: merged fold
    groups run their full-width ladders once per GROUP per launch (not
    per session), value-identical pair rows dedup, and the fold-ladder
    cache (FSDKR_FOLD_CACHE) serves the shared-base comb tables warm
    after the first two launches. Emits ONE JSON line whose `curve`
    array carries per-S proofs/s, per-session warm seconds, and the
    ladders-per-launch accounting the acceptance gate reads
    (fullwidth_ladders == rlc_groups at every S; S=8 aggregate
    proofs/s >= 1.3x the S=1 rate)."""
    import dataclasses

    from fsdkr_tpu.backend import rlc
    from fsdkr_tpu.config import ProtocolConfig
    from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen
    from fsdkr_tpu.utils.trace import get_tracer

    cfg = ProtocolConfig(paillier_bits=bits, m_security=m_sec)
    mesh_env = os.environ.get("BENCH_MESH")
    mesh_shape = (int(mesh_env),) if mesh_env else None
    tpu_cfg = dataclasses.replace(cfg, backend="tpu", mesh_shape=mesh_shape)

    log(
        f"amortization sweep S={s_list}: one committee n={n} t={t} "
        f"bits={bits} M={m_sec} mesh={mesh_shape} ..."
    )
    t0 = time.time()
    keys = simulate_keygen(t, n, cfg)
    results = RefreshMessage.distribute_batch(
        [(key.i, key) for key in keys], n, tpu_cfg
    )
    msgs = [m for m, _ in results]
    dks = [dk for _, dk in results]
    log(f"setup done in {time.time() - t0:.1f}s")

    proofs_per_session = 2 * n * n + 2 * n

    def run(s_count):
        sessions = [
            (msgs, keys[0].clone(), dks[0], ()) for _ in range(s_count)
        ]
        t0 = time.time()
        errs = RefreshMessage.collect_sessions(sessions, tpu_cfg)
        dt = time.time() - t0
        bad = [i for i, e in enumerate(errs) if e is not None]
        if bad:
            raise RuntimeError(f"sessions failed: {bad}: {errs[bad[0]]}")
        return dt

    # two untimed launches: compiles + the fold-ladder cache's
    # mark -> build lifecycle, so every timed point below runs warm
    log(f"warmup launch 1 (cold/mark): {run(1):.2f}s")
    log(f"warmup launch 2 (table build): {run(1):.2f}s")

    curve = []
    rate_s1 = None
    for s_count in s_list:
        get_tracer().reset(keep_spans=True)
        rlc.stats_reset()
        memplan_stats_reset()
        dt = run(s_count)
        st = rlc.stats()
        total_proofs = proofs_per_session * s_count
        rate = total_proofs / dt
        if s_count == 1:
            rate_s1 = rate
        point = {
            "sessions": s_count,
            "collect_warm_s": round(dt, 2),
            "per_session_warm_s": round(dt / s_count, 3),
            "proofs_per_s": round(rate, 2),
            "amortization_x": (
                round(rate / rate_s1, 3) if rate_s1 else None
            ),
            "rlc_groups": st["rlc_groups"],
            "fullwidth_ladders": st["fullwidth_ladders"],
            "rows_folded": st["rows_folded"],
            "xsession_rows_deduped": st["xsession_rows_deduped"],
            "ladder_cache_hits": st["ladder_cache_hits"],
            "ladder_cache_misses": st["ladder_cache_misses"],
        }
        curve.append(point)
        log(
            f"S={s_count}: {dt:.2f}s, {rate:.1f} proofs/s "
            f"({point['amortization_x']}x vs S=1), ladders "
            f"{st['fullwidth_ladders']}/{st['rlc_groups']} groups, "
            f"deduped {st['xsession_rows_deduped']}"
        )
        # the amortization claim, checked at every S: full-width
        # ladders scale with merged groups, never with groups x S
        assert st["fullwidth_ladders"] == st["rlc_groups"], point

    emit(
        {
            "metric": (
                f"cross-session amortization curve @ n={n},t={t},"
                f"{bits}-bit,M={m_sec}"
            ),
            "value": curve[-1]["proofs_per_s"],
            "unit": "proofs/s",
            "vs_baseline": 0,
            "proofs_per_session": proofs_per_session,
            "curve": curve,
            "mesh": mesh_shape,
            "device_ec": tpu_cfg.device_ec,
            "device_powm": tpu_cfg.device_powm,
            **({"degraded": os.environ["BENCH_DEGRADED"]}
               if os.environ.get("BENCH_DEGRADED") else {}),
            **telemetry_fields(),
        }
    )
    telemetry_artifacts()


def bench_delegate_ab(n, t, bits, m_sec, s_count):
    """FSDKR_DELEGATE acceptance A/B (ISSUE 17 tentpole (c)): one
    committee distributed WITH certificates on the wire, then the same
    fused S-session collect in both knob positions — verdicts and
    adopted key state must be bit-identical on the honest transcript
    AND on a tampered one (same exception, both arms), and the
    delegated arm's MEASURED group ops must sit strictly below the
    honest arm's op model over the launch's Feldman rows. Emits one
    JSON line with both counts and the parity verdicts."""
    import dataclasses

    from fsdkr_tpu.config import ProtocolConfig
    from fsdkr_tpu.core.secp256k1 import GENERATOR
    from fsdkr_tpu.proofs import msm_delegate
    from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen
    from fsdkr_tpu.protocol.serialization import local_key_to_json

    cfg = ProtocolConfig(paillier_bits=bits, m_security=m_sec)
    tpu_cfg = cfg.with_backend("tpu")

    log(
        f"delegate A/B: n={n} t={t} bits={bits} M={m_sec} "
        f"S={s_count} fused sessions ..."
    )
    os.environ["FSDKR_DELEGATE"] = "1"  # certs on the wire
    t0 = time.time()
    keys = simulate_keygen(t, n, cfg)
    results = RefreshMessage.distribute_batch(
        [(key.i, key) for key in keys], n, tpu_cfg
    )
    msgs = [m for m, _ in results]
    dks = [dk for _, dk in results]
    log(f"setup done in {time.time() - t0:.1f}s")

    feld_items = [
        (msg.coefficients_committed_vec, msg.points_committed_vec[i], i + 1)
        for _ in range(s_count)
        for msg in msgs
        for i in range(n)
    ]
    model_ops = msm_delegate.honest_model_ops(feld_items)

    def collect(arm, use_msgs):
        os.environ["FSDKR_DELEGATE"] = arm
        sessions = [
            (use_msgs, keys[0].clone(), dks[0], ()) for _ in range(s_count)
        ]
        t0 = time.time()
        errs = RefreshMessage.collect_sessions(sessions, tpu_cfg)
        dt = time.time() - t0
        states = [local_key_to_json(k) for _, k, _, _ in sessions]
        return errs, states, dt

    collect("0", msgs)  # warmup: compiles + fold-cache mark
    errs_off, states_off, t_off0 = collect("0", msgs)
    _, _, t_off = collect("0", msgs)
    t_off = min(t_off0, t_off)
    msm_delegate.stats_reset()
    errs_on, states_on, t_on = collect("1", msgs)
    dstats = msm_delegate.stats()
    honest_ok = (
        errs_off == [None] * s_count
        and errs_on == [None] * s_count
        and states_on == states_off
    )
    measured = dstats["group_ops"]
    log(
        f"honest A/B: off {t_off:.2f}s on {t_on:.2f}s, parity={honest_ok}; "
        f"delegated ops {measured} vs honest model {model_ops} "
        f"({dstats['schemes_delegated']} schemes, "
        f"{dstats['rows_delegated']} rows by certificate)"
    )

    # tampered transcript: one commitment edited -> both arms must
    # raise the identical per-session error
    vss = msgs[1].coefficients_committed_vec
    bad_commits = list(vss.commitments)
    bad_commits[0] = bad_commits[0] + GENERATOR
    msgs_bad = list(msgs)
    msgs_bad[1] = dataclasses.replace(
        msgs[1],
        coefficients_committed_vec=dataclasses.replace(
            vss, commitments=bad_commits
        ),
    )
    errs_bad_off, _, _ = collect("0", msgs_bad)
    errs_bad_on, _, _ = collect("1", msgs_bad)
    tampered_ok = (
        all(e is not None for e in errs_bad_off)
        and [type(e) for e in errs_bad_on]
        == [type(e) for e in errs_bad_off]
        and [str(e) for e in errs_bad_on] == [str(e) for e in errs_bad_off]
    )
    os.environ["FSDKR_DELEGATE"] = "0"
    log(f"tampered A/B parity={tampered_ok}")

    emit(
        {
            "metric": (
                f"FSDKR_DELEGATE A/B @ n={n},t={t},{bits}-bit,"
                f"S={s_count} fused sessions"
            ),
            "value": measured,
            "unit": "delegated group ops (honest model "
                    f"{model_ops})",
            "vs_baseline": 0,
            "honest_model_ops": model_ops,
            "delegated_measured_ops": measured,
            "ops_ratio": round(measured / model_ops, 3) if model_ops else None,
            "verdict_parity_honest": honest_ok,
            "verdict_parity_tampered": tampered_ok,
            "collect_warm_honest_s": round(t_off, 2),
            "collect_warm_delegated_s": round(t_on, 2),
            "sessions": s_count,
            "delegate": dstats,
            **({"degraded": os.environ["BENCH_DEGRADED"]}
               if os.environ.get("BENCH_DEGRADED") else {}),
        }
    )
    telemetry_artifacts()


def bench_join(n, t, bits, m_sec, joins):
    """Config-3 shape (BASELINE.json): join/replace at (n, t) — ring-
    Pedersen + PDL batches plus the join-side correct-key/composite-dlog
    verifies, timed at one existing party's collect."""
    from fsdkr_tpu.config import ProtocolConfig
    from fsdkr_tpu.protocol import JoinMessage, RefreshMessage, simulate_keygen

    cfg = ProtocolConfig(paillier_bits=bits, m_security=m_sec)
    tpu_cfg = cfg.with_backend("tpu")
    n_existing = n - joins
    # the flow needs >= 2 existing parties (cold + warm collect use two
    # different collectors) and a valid (t, n_existing) Shamir setup
    if n_existing < max(t + 1, 2):
        raise ValueError(
            f"BENCH_JOIN={joins} leaves {n_existing} existing parties; "
            f"need at least max(t+1, 2) = {max(t + 1, 2)} for n={n}, t={t}"
        )

    log(f"join/replace setup: n={n} t={t} joins={joins} bits={bits} M={m_sec} ...")
    t0 = time.time()
    keys = simulate_keygen(t, n_existing, cfg)
    join_messages = []
    for idx in range(n_existing + 1, n + 1):
        jm, _pair = JoinMessage.distribute(cfg)
        jm.set_party_index(idx)
        join_messages.append(jm)
    t_keygen = time.time() - t0

    t0 = time.time()
    ident = {i: i for i in range(1, n_existing + 1)}
    msgs, dks = [], []
    for key in keys:
        m, dk = RefreshMessage.replace(join_messages, key, ident, n, tpu_cfg)
        msgs.append(m)
        dks.append(dk)
    t_replace = time.time() - t0
    log(f"setup done: keygen+join {t_keygen:.1f}s, replace(distribute) {t_replace:.1f}s")

    # per-collect proof instances: PDL+range over existing msgs x n slots,
    # ring-Pedersen + correct-key for refresh and join senders, 2 dlog
    # proofs per join
    proofs = 2 * n_existing * n + 2 * (n_existing + joins) + 2 * joins

    t0 = time.time()
    RefreshMessage.collect(msgs, keys[0].clone(), dks[0], join_messages, tpu_cfg)
    t_cold = time.time() - t0
    log(f"join collect cold: {t_cold:.2f}s")
    from fsdkr_tpu.backend import rlc
    from fsdkr_tpu.utils.trace import get_tracer

    get_tracer().reset(keep_spans=True)
    rlc.stats_reset()
    memplan_stats_reset()
    t0 = time.time()
    RefreshMessage.collect(msgs, keys[1].clone(), dks[1], join_messages, tpu_cfg)
    t_warm = time.time() - t0
    log(f"join collect warm: {t_warm:.2f}s -> {proofs / t_warm:.1f} proofs/s")
    emit(
        {
            "metric": (
                f"join/replace collect throughput @ n={n},t={t},"
                f"{joins} joins,{bits}-bit (config 3)"
            ),
            "value": round(proofs / t_warm, 2),
            "unit": "proofs/s",
            "vs_baseline": 0,
            "collect_warm_s": round(t_warm, 2),
            "collect_cold_s": round(t_cold, 2),
            "replace_s": round(t_replace, 2),
            **rlc_fields(),
            **mem_fields(),
            **precompute_fields(),
            "device_ec": tpu_cfg.device_ec,
            "device_powm": tpu_cfg.device_powm,
            "pallas": os.environ.get("FSDKR_PALLAS", "auto"),
            **({"degraded": os.environ["BENCH_DEGRADED"]}
               if os.environ.get("BENCH_DEGRADED") else {}),
            **roofline_fields(t_warm),
            **telemetry_fields(),
        }
    )
    telemetry_artifacts()


def main():
    # the background precompute producer must not time-share the
    # measured sections' cores: the offline/online split is measured
    # explicitly below (prefill = offline, warm distribute = online).
    # setdefault so an overlap experiment can force =1 from outside.
    os.environ.setdefault("FSDKR_PRECOMPUTE_BG", "0")
    jax, _ = init_jax_with_retry()

    # read the workload AFTER init: a tunnel-down fallback annotates the
    # parameters via environment defaults set inside the retry helper
    n = int(os.environ.get("BENCH_N", "16"))
    t = int(os.environ.get("BENCH_T", "8"))
    bits = int(os.environ.get("BENCH_BITS", "2048"))
    m_sec = int(os.environ.get("BENCH_M", "256"))
    sessions_count = int(os.environ.get("BENCH_SESSIONS", "1"))
    joins = int(os.environ.get("BENCH_JOIN", "0"))

    from fsdkr_tpu.config import ProtocolConfig
    from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen

    amortize = os.environ.get("BENCH_AMORTIZE")
    if amortize:
        bench_amortization(
            [int(x) for x in amortize.split(",") if x.strip()],
            n, t, bits, m_sec,
        )
        return
    if os.environ.get("BENCH_DELEGATE_AB") == "1":
        bench_delegate_ab(
            n, t, bits, m_sec, sessions_count if sessions_count > 1 else 4
        )
        return
    if sessions_count > 1:
        bench_sessions(sessions_count, n, t, bits, m_sec)
        return
    if joins > 0:
        bench_join(n, t, bits, m_sec, joins)
        return

    cfg = ProtocolConfig(paillier_bits=bits, m_security=m_sec)
    tpu_cfg = cfg.with_backend("tpu")

    log(f"setup: keygen + distribute, n={n} t={t} bits={bits} M={m_sec} ...")
    t0 = time.time()
    keys = simulate_keygen(t, n, cfg)
    t_keygen = time.time() - t0

    from fsdkr_tpu.core import primes as primes_mod

    primes_mod.gen_stats_reset()
    t0 = time.time()
    results = RefreshMessage.distribute_batch(
        [(key.i, key) for key in keys], n, tpu_cfg
    )
    keygen_work_cold = primes_mod.gen_stats()
    msgs = [m for m, _ in results]
    dks = [dk for _, dk in results]
    t_distribute = time.time() - t0
    log(f"setup done: keygen {t_keygen:.1f}s, distribute {t_distribute:.1f}s")

    from fsdkr_tpu.utils.trace import get_tracer

    # prover-side phase split (includes first-launch compiles), now with
    # the stage-1 sub-phases (sample / enc+beta wall / mod-N~ columns)
    dist_stats = get_tracer().stats()
    trace_distribute = {
        name: round(st.seconds, 3)
        for name, st in dist_stats.items()
        if name.startswith("distribute.")
    } or None
    mfu_distribute = roofline_fields(
        t_distribute,
        {k: v for k, v in dist_stats.items() if k.startswith("distribute.")},
    ).get("mfu")

    # --- offline precompute fill (FSDKR_PRECOMPUTE): produced here off
    # the critical path, consumed by the warm distribute below — so the
    # warm number IS the online critical path of the offline/online
    # split (distribute_online_s), and precompute_offline_s is what a
    # serving system pays between rounds. =0 makes prefill a no-op and
    # the warm run measures the inline path unchanged.
    from fsdkr_tpu import precompute

    precompute.stats_reset()
    # with tracing on, run the background producer ALONGSIDE the
    # synchronous prefill (both race to fill the same bounded pools):
    # the trace timeline then shows genuine producer-THREAD spans, the
    # occupancy gauge reads non-zero, and the measured sections below
    # are untouched (BG is forced back off before any of them)
    bg_for_trace = get_tracer().enabled and precompute.enabled()
    bg_user = os.environ["FSDKR_PRECOMPUTE_BG"]  # setdefault'd in main()
    if bg_for_trace:
        os.environ["FSDKR_PRECOMPUTE_BG"] = "1"
        precompute.register_committee(keys[0], n, n, tpu_cfg)
        precompute.kick()
    t0 = time.time()
    pre_produced = precompute.prefill(keys[0], n, n, tpu_cfg)
    t_offline = time.time() - t0
    if bg_for_trace:
        # restore the caller's knob: an explicit FSDKR_PRECOMPUTE_BG=1
        # keeps the producer running through the measured sections (an
        # overlap experiment); only the bench's own default of 0 stops it
        os.environ["FSDKR_PRECOMPUTE_BG"] = bg_user
        from fsdkr_tpu.precompute.producer import background_enabled

        if not background_enabled():
            precompute.stop_background()
    log(
        f"precompute offline fill: {pre_produced} entries in "
        f"{t_offline:.2f}s (enabled={precompute.enabled()}, "
        f"bg_overlap={bg_for_trace})"
    )

    # --- WARM-epoch distribute: proactive refresh re-runs on the same
    # committee, so the persistent (h1/h2, N~) comb tables are hot and
    # precompute pools are full — this is the ONLINE prover number the
    # round-9 acceptance A/B compares (precompute_ab_n16_{on,off}; the
    # round-8 pair was crt_ab_n16_{on,off}). The extra run re-mutates
    # each key's vss_scheme exactly like a next epoch would; collect
    # below verifies the COLD run's messages, which carry their own
    # committed schemes.
    from fsdkr_tpu.backend import crt as crt_mod
    from fsdkr_tpu.backend.powm import powm_cache_stats

    get_tracer().reset(keep_spans=True)
    crt_mod.stats_reset()
    primes_mod.gen_stats_reset()
    cache_d0 = powm_cache_stats()
    t0 = time.time()
    RefreshMessage.distribute_batch([(key.i, key) for key in keys], n, tpu_cfg)
    t_distribute_warm = time.time() - t0
    keygen_work_warm = primes_mod.gen_stats()
    cache_d1 = powm_cache_stats()
    log(
        f"distribute warm: {t_distribute_warm:.2f}s (cold {t_distribute:.2f}s; "
        f"prover comb cache +{cache_d1['hits'] - cache_d0['hits']} hits, "
        f"+{cache_d1['misses'] - cache_d0['misses']} misses)"
    )
    trace_distribute_warm = {
        name: round(st.seconds, 3)
        for name, st in get_tracer().stats().items()
        if name.startswith("distribute.")
    } or None
    crt_out = crt_fields()
    pre_out = precompute_fields()

    # --- keygen-anomaly pin (round 9). BENCH_r07 recorded warm keygen
    # 2.19s vs cold 1.38s; root cause: prime search is a randomized
    # algorithm with geometric-tail work, so two keygen walls are i.i.d.
    # draws and their difference is measurement noise, not a warm-path
    # regression (isolated repeated keygen_batch is flat at ~1.29s).
    # The pin therefore compares time-per-MR-round over the prime-search
    # phases (keygen + ring_pedersen_gen): work variance moves rounds
    # and wall together and passes; a genuine warm-path slowdown moves
    # the per-work rate and trips. With precompute on, the warm phases
    # consume pooled bundles and do ~no MR work — then the pin is that
    # the consume path stays pool-pop cheap.
    keygen_work = {"cold": keygen_work_cold, "warm": keygen_work_warm}

    def _gen_seconds(tr):
        return (tr or {}).get("distribute.keygen", 0.0) + (tr or {}).get(
            "distribute.ring_pedersen_gen", 0.0
        )

    gs_cold, gs_warm = _gen_seconds(trace_distribute), _gen_seconds(
        trace_distribute_warm
    )
    if trace_distribute_warm is not None:
        if keygen_work_warm["mr_rounds"] >= 64:
            if keygen_work_cold["mr_rounds"] >= 64 and gs_cold > 0:
                rate_c = gs_cold / keygen_work_cold["mr_rounds"]
                rate_w = gs_warm / keygen_work_warm["mr_rounds"]
                assert rate_w <= 2.5 * rate_c, (
                    f"warm-path keygen regression: {1e3 * rate_w:.4f} ms/MR-"
                    f"round warm vs {1e3 * rate_c:.4f} cold (walls "
                    f"{gs_warm:.2f}s/{gs_cold:.2f}s alone are NOT comparable:"
                    " prime-search work is randomized)"
                )
        else:
            assert gs_warm < 1.0, (
                f"pooled warm keygen took {gs_warm:.2f}s — the consume path"
                " regressed to inline work without counting MR rounds"
            )
    # prover-side comb cache counters (hits/misses across the warm
    # distribute): misses_warm == 0 means every stage-1 fixed-base table
    # was served from the persistent LRU
    powm_cache_distribute = {
        "hits_warm": cache_d1["hits"] - cache_d0["hits"],
        "misses_warm": cache_d1["misses"] - cache_d0["misses"],
    }

    # proof instances verified by one collect (excluding n^2 Feldman EC
    # checks and 2 joins' dlog proofs, which are zero here)
    proofs = 2 * n * n + 2 * n

    # --- TPU backend: warm-up (compiles), then timed run ----------------
    log("tpu collect: warm-up (compiles cached to .jax_cache) ...")
    cache_before = _jax_cache_entries()
    t0 = time.time()
    RefreshMessage.collect(msgs, keys[0].clone(), dks[0], (), tpu_cfg)
    t_tpu_cold = time.time() - t0
    cache_after = _jax_cache_entries()
    log(
        f"tpu collect cold: {t_tpu_cold:.2f}s "
        f"(persistent cache {cache_before} -> {cache_after} entries; "
        f"{cache_after - cache_before} fresh compiles)"
    )

    from fsdkr_tpu.backend import rlc
    from fsdkr_tpu.backend.powm import powm_cache_stats

    cache_cold = powm_cache_stats()
    get_tracer().reset(keep_spans=True)
    rlc.stats_reset()
    memplan_stats_reset()
    t0 = time.time()
    RefreshMessage.collect(msgs, keys[1].clone(), dks[1], (), tpu_cfg)
    t_tpu = time.time() - t0
    cache_warm = powm_cache_stats()
    log(
        f"tpu collect warm: {t_tpu:.2f}s -> {proofs / t_tpu:.1f} proofs/s "
        f"(precompute cache: +{cache_warm['hits'] - cache_cold['hits']} hits, "
        f"+{cache_warm['misses'] - cache_cold['misses']} misses warm)"
    )
    trace_out = None
    rf = {}
    if get_tracer().enabled:  # FSDKR_TRACE=1: per-family breakdown
        log(get_tracer().report())
        stats = get_tracer().stats()
        trace_out = {
            name: round(st.seconds, 3) for name, st in stats.items()
        }
        rf = roofline_fields(t_tpu, stats)
    # snapshot the warm-collect stat windows BEFORE the trace A/B below
    # runs extra collects in this process — the legacy rlc block and the
    # telemetry snapshot must describe ONE warm collect, same as every
    # other BENCH_*.json (old-BENCH comparability)
    rlc_out = rlc_fields()
    telemetry_out = telemetry_fields()

    # --- trace-overhead A/B (BENCH_TRACE_AB=1): one more warm collect
    # with the tracer forced OFF, same workload, same process. The
    # tentpole's perf budget is on the DISABLED path: with no tracing,
    # this collect must stay within BENCH_TRACE_GATE_PCT (default 2%)
    # of the pre-PR warm-collect baseline when BENCH_BASELINE_WARM_S
    # hands one in (e.g. collect_warm_s from the last pre-telemetry
    # BENCH). trace_overhead_pct reports what tracing itself costs.
    trace_ab = {}
    if os.environ.get("BENCH_TRACE_AB") == "1":
        tr = get_tracer()
        was_enabled = tr.enabled
        tr.disable()
        # two untraced runs, min taken: single warm collects on this box
        # scatter +/-2-3% run to run (the traced arm has measured FASTER
        # than the untraced one), so one sample cannot support a 2% gate
        notrace_runs = []
        for _ in range(2):
            t0 = time.time()
            RefreshMessage.collect(msgs, keys[1].clone(), dks[1], (), tpu_cfg)
            notrace_runs.append(time.time() - t0)
        t_notrace = min(notrace_runs)
        if was_enabled:
            tr.enable()
        log(
            f"trace A/B: warm collect {t_tpu:.2f}s traced vs "
            f"{t_notrace:.2f}s untraced (runs: "
            f"{', '.join(f'{x:.2f}' for x in notrace_runs)})"
        )
        trace_ab = {
            "collect_warm_notrace_s": round(t_notrace, 2),
            "trace_overhead_pct": round(100 * (t_tpu - t_notrace) / t_notrace, 2),
        }
        base = os.environ.get("BENCH_BASELINE_WARM_S")
        if base:
            gate = float(os.environ.get("BENCH_TRACE_GATE_PCT", "2.0"))
            base_s = float(base)
            delta_pct = 100 * (t_notrace - base_s) / base_s
            trace_ab["notrace_vs_baseline_pct"] = round(delta_pct, 2)
            assert delta_pct <= gate, (
                f"disabled-telemetry warm collect {t_notrace:.2f}s is "
                f"{delta_pct:.1f}% over the pre-PR baseline {base_s:.2f}s "
                f"(gate {gate}%)"
            )

    # --- host baseline on a subsample (serial loop; linear extrapolation)
    # Two baselines: the native C++ Montgomery path (intops.mod_pow routes
    # wide odd-modulus pow through csrc/fsdkr_native.cpp — this is the
    # denominator of vs_baseline) and pure CPython (FSDKR_NATIVE_POW=0,
    # reported as vs_cpython for comparability with earlier rounds).
    from fsdkr_tpu import native
    from fsdkr_tpu.backend.batch_verifier import HostBatchVerifier
    from fsdkr_tpu.backend.powm import rangeopt_enabled
    from fsdkr_tpu.core import intops
    from fsdkr_tpu.core.secp256k1 import GENERATOR
    from fsdkr_tpu.proofs.pdl_slack import PDLwSlackStatement

    log(f"native core available: {native.available()}")

    host = HostBatchVerifier()
    key = keys[2 % n]
    # >= 25% of the n^2 (sender, receiver) pair loop; BENCH_HOST_PAIRS
    # caps the subsample for the large full-width shapes (n=64/n=256),
    # where the serial CPython arm alone would otherwise dominate the
    # step's wall-clock — the extrapolation stays linear either way
    pair_target = max(8, (n * n) // 4)
    hp = os.environ.get("BENCH_HOST_PAIRS")
    if hp:
        pair_target = max(8, min(pair_target, int(hp)))
    pdl_items, range_items = [], []
    for msg in msgs:
        for i in range(n):
            if len(pdl_items) >= pair_target:
                break
            st = PDLwSlackStatement(
                ciphertext=msg.points_encrypted_vec[i],
                ek=key.paillier_key_vec[i],
                Q=msg.points_committed_vec[i],
                G=GENERATOR,
                h1=key.h1_h2_n_tilde_vec[i].g,
                h2=key.h1_h2_n_tilde_vec[i].ni,
                N_tilde=key.h1_h2_n_tilde_vec[i].N,
            )
            pdl_items.append((msg.pdl_proof_vec[i], st))
            range_items.append(
                (
                    msg.range_proofs[i],
                    msg.points_encrypted_vec[i],
                    key.paillier_key_vec[i],
                    key.h1_h2_n_tilde_vec[i],
                )
            )
        if len(pdl_items) >= pair_target:
            break

    rp_sample = msgs[: max(2, n // 4)]
    rp_items = [(m.ring_pedersen_proof, m.ring_pedersen_statement) for m in rp_sample]
    ck_items = [(m.dk_correctness_proof, m.ek) for m in rp_sample]

    def measure_host(tag):
        t0 = time.time()
        ok_pdl = all(v is None for v in host.verify_pdl(pdl_items))
        ok_range = all(host.verify_range(range_items))
        per_pair = (time.time() - t0) / len(pdl_items)

        t0 = time.time()
        ok_rp = all(host.verify_ring_pedersen(rp_items, m_sec))
        per_rp = (time.time() - t0) / len(rp_items)

        t0 = time.time()
        ok_ck = all(host.verify_correct_key(ck_items, cfg.correct_key_rounds))
        per_ck = (time.time() - t0) / len(ck_items)
        if not (ok_pdl and ok_range and ok_rp and ok_ck):
            raise RuntimeError(f"host[{tag}] baseline rejected a valid proof")

        total = n * n * per_pair + n * per_rp + n * per_ck
        log(
            f"host[{tag}] baseline (extrapolated from {len(pdl_items)} of "
            f"{n * n} pairs, {len(rp_items)} of {n} rp/ck): "
            f"{total:.2f}s -> {proofs / total:.1f} proofs/s"
        )
        return total

    t_host_native = measure_host("native-c++")

    # force CPython pow for the cpython arm: the env switch covers the
    # per-call GMP route, the module flag the cached own-core route
    saved_np = os.environ.get("FSDKR_NATIVE_POW")
    os.environ["FSDKR_NATIVE_POW"] = "0"
    intops._native_modexp = False
    try:
        t_host_py = measure_host("cpython")
    finally:
        if saved_np is None:
            os.environ.pop("FSDKR_NATIVE_POW", None)
        else:
            os.environ["FSDKR_NATIVE_POW"] = saved_np
        intops._native_modexp = None  # restore autodetect

    result = {
        "metric": _metric(n, t, bits),
        "value": round(proofs / t_tpu, 2),
        "unit": "proofs/s",
        "vs_baseline": round(t_host_native / t_tpu, 2),
        "vs_cpython": round(t_host_py / t_tpu, 2),
        # vs_baseline is only "vs native C++" when the core actually loaded;
        # otherwise both baselines are CPython and this flags it
        "host_native_available": native.available(),
        # which routes the hot paths took (auto-routed by platform,
        # forceable via FSDKR_DEVICE_EC / FSDKR_DEVICE_POWM), and which
        # modexp pipeline (a preflight-degraded battery sets
        # BENCH_DEGRADED so XLA-chain numbers can never read as the
        # nominal Pallas configuration)
        "device_ec": tpu_cfg.device_ec,
        "device_powm": tpu_cfg.device_powm,
        "pallas": os.environ.get("FSDKR_PALLAS", "auto"),
        **({"degraded": os.environ["BENCH_DEGRADED"]}
           if os.environ.get("BENCH_DEGRADED") else {}),
        "collect_warm_s": round(t_tpu, 2),
        "collect_cold_s": round(t_tpu_cold, 2),
        "compile_overhead_s": round(t_tpu_cold - t_tpu, 2),
        "fresh_compiles": cache_after - cache_before,
        "distribute_batch_s": round(t_distribute, 2),
        "distribute_warm_s": round(t_distribute_warm, 2),
        # the offline/online split (FSDKR_PRECOMPUTE): the warm run
        # consumes the prefilled pools, so it IS the online critical
        # path; the offline fill is what a serving system pays between
        # refresh rounds (producer overlapped with collect in prod)
        "distribute_online_s": round(t_distribute_warm, 2),
        "precompute_offline_s": round(t_offline, 2),
        "keygen_work": keygen_work,
        **pre_out,
        "powm_cache_distribute": powm_cache_distribute,
        **crt_out,
        # persistent precompute cache (comb tables / power ladders /
        # Montgomery contexts): warm-collect deltas — misses_warm == 0
        # means every table build was served from the cache
        "powm_cache": {
            **cache_warm,
            "hits_warm": cache_warm["hits"] - cache_cold["hits"],
            "misses_warm": cache_warm["misses"] - cache_cold["misses"],
        },
        "fsdkr_threads": native.thread_count(),
        # range-opt provenance (ISSUE 8): which Montgomery inner loop the
        # native core resolved (mpn = GMP asm via dlopen, portable = own
        # u128 CIOS) and whether the shared-exponent/joint-comb/scheduler
        # path was active — the A/B pair rangeopt_ab_n16_{on,off}.json
        # differs in exactly this flag
        "native_engine": native.engine_kind(),
        "rangeopt_enabled": rangeopt_enabled(),
        # warm-collect fold statistics of the randomized batch verifier
        # (FSDKR_RLC): fullwidth_ladders must read O(rlc_groups), not
        # O(rows_folded), and bisect_fallbacks 0 on honest transcripts
        **rlc_out,
        # the memory-plan block (ISSUE 10): budget, staged/peak bytes,
        # VmHWM, tiles executed — `tiles` > 0 means the streaming
        # verification plan actually cut this workload
        **mem_fields(),
        **trace_ab,
        # the unified registry snapshot (schema-versioned): per-phase
        # latency percentiles, pool/producer gauges, subsystem counters
        **telemetry_out,
    }
    if trace_out:
        result["trace"] = trace_out  # warm-collect per-phase seconds
    if trace_distribute:
        result["trace_distribute"] = trace_distribute
    if trace_distribute_warm:
        result["trace_distribute_warm"] = trace_distribute_warm
    result.update(rf)  # per-phase {gmacs, mfu} + mfu_collect + peak_macs
    if mfu_distribute:
        result["mfu_distribute"] = mfu_distribute
    emit(result)
    telemetry_artifacts()


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # always leave a JSON line for the driver
        import traceback

        traceback.print_exc(file=sys.stderr)
        try:
            n = int(os.environ.get("BENCH_N", "16"))
            t = int(os.environ.get("BENCH_T", "8"))
            bits = int(os.environ.get("BENCH_BITS", "2048"))
        except ValueError:
            n, t, bits = 16, 8, 2048
        emit(
            {
                "metric": _metric(n, t, bits),
                "value": 0,
                "unit": "proofs/s",
                "vs_baseline": 0,
                "error": f"{type(e).__name__}: {e}",
            }
        )
        sys.exit(0)
