#!/usr/bin/env python
"""North-star benchmark (BASELINE.json): RefreshMessage.collect wall-clock,
reported as proofs verified per second, TPU batch backend vs the host
(pure-Python) baseline on the identical workload.

Prints exactly ONE JSON line to stdout:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
All progress goes to stderr.

Default workload: a real full-size refresh (2048-bit Paillier, M=256
ring-Pedersen, 11 correct-key rounds) at committee n=16, t=8 — one
collecting party verifies 2*n^2 PDL+range proofs, n ring-Pedersen and n
correct-key proofs (plus n^2 Feldman EC checks). `vs_baseline` is the
speedup of the TPU backend over the host backend (host measured on a
subsample, extrapolated linearly — it is a serial per-proof loop).

Environment knobs: BENCH_N / BENCH_T / BENCH_BITS / BENCH_M override the
workload for experiments; defaults match BASELINE.md.
"""

import json
import os
import sys
import time


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    n = int(os.environ.get("BENCH_N", "16"))
    t = int(os.environ.get("BENCH_T", "8"))
    bits = int(os.environ.get("BENCH_BITS", "2048"))
    m_sec = int(os.environ.get("BENCH_M", "256"))

    # persistent compilation cache: repeat bench runs skip XLA compiles
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")
    except Exception:
        pass

    from fsdkr_tpu.config import ProtocolConfig
    from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen

    cfg = ProtocolConfig(paillier_bits=bits, m_security=m_sec)
    tpu_cfg = cfg.with_backend("tpu")

    log(f"devices: {jax.devices()}")
    log(f"setup: keygen + distribute, n={n} t={t} bits={bits} M={m_sec} ...")
    t0 = time.time()
    keys = simulate_keygen(t, n, cfg)
    t_keygen = time.time() - t0

    t0 = time.time()
    results = RefreshMessage.distribute_batch(
        [(key.i, key) for key in keys], n, tpu_cfg
    )
    msgs = [m for m, _ in results]
    dks = [dk for _, dk in results]
    t_distribute = time.time() - t0
    log(f"setup done: keygen {t_keygen:.1f}s, distribute {t_distribute:.1f}s")

    # proof instances verified by one collect (excluding n^2 Feldman EC
    # checks and 2 joins' dlog proofs, which are zero here)
    proofs = 2 * n * n + 2 * n

    # --- TPU backend: warm-up (compiles), then timed run ----------------
    log("tpu collect: warm-up (compiles cached to .jax_cache) ...")
    t0 = time.time()
    RefreshMessage.collect(msgs, keys[0].clone(), dks[0], (), tpu_cfg)
    t_tpu_cold = time.time() - t0
    log(f"tpu collect cold: {t_tpu_cold:.2f}s")

    t0 = time.time()
    RefreshMessage.collect(msgs, keys[1].clone(), dks[1], (), tpu_cfg)
    t_tpu = time.time() - t0
    log(f"tpu collect warm: {t_tpu:.2f}s -> {proofs / t_tpu:.1f} proofs/s")

    # --- host baseline on a subsample (serial loop; linear extrapolation)
    from fsdkr_tpu.backend.batch_verifier import HostBatchVerifier
    from fsdkr_tpu.core.secp256k1 import GENERATOR
    from fsdkr_tpu.proofs.pdl_slack import PDLwSlackStatement

    host = HostBatchVerifier()
    key = keys[2]
    sample = max(4, n // 2)
    pdl_items, range_items = [], []
    for msg in msgs[:2]:
        for i in range(sample // 2):
            st = PDLwSlackStatement(
                ciphertext=msg.points_encrypted_vec[i],
                ek=key.paillier_key_vec[i],
                Q=msg.points_committed_vec[i],
                G=GENERATOR,
                h1=key.h1_h2_n_tilde_vec[i].g,
                h2=key.h1_h2_n_tilde_vec[i].ni,
                N_tilde=key.h1_h2_n_tilde_vec[i].N,
            )
            pdl_items.append((msg.pdl_proof_vec[i], st))
            range_items.append(
                (
                    msg.range_proofs[i],
                    msg.points_encrypted_vec[i],
                    key.paillier_key_vec[i],
                    key.h1_h2_n_tilde_vec[i],
                )
            )

    t0 = time.time()
    assert all(v is None for v in host.verify_pdl(pdl_items))
    assert all(host.verify_range(range_items))
    per_pair = (time.time() - t0) / len(pdl_items)

    rp_items = [(m.ring_pedersen_proof, m.ring_pedersen_statement) for m in msgs[:2]]
    t0 = time.time()
    assert all(host.verify_ring_pedersen(rp_items, m_sec))
    per_rp = (time.time() - t0) / len(rp_items)

    ck_items = [(m.dk_correctness_proof, m.ek) for m in msgs[:2]]
    t0 = time.time()
    assert all(host.verify_correct_key(ck_items, cfg.correct_key_rounds))
    per_ck = (time.time() - t0) / len(ck_items)

    t_host = n * n * per_pair + n * per_rp + n * per_ck
    log(
        f"host baseline (extrapolated from {len(pdl_items)} pairs): "
        f"{t_host:.2f}s -> {proofs / t_host:.1f} proofs/s"
    )

    result = {
        "metric": f"collect() proof verification throughput @ n={n},t={t},{bits}-bit",
        "value": round(proofs / t_tpu, 2),
        "unit": "proofs/s",
        "vs_baseline": round(t_host / t_tpu, 2),
    }
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
