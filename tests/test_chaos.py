"""Chaos-hardened serving (ISSUE 11): the fault-injection plan, its
hook sites, and the service's failure semantics under injection —
deterministic seed-driven decisions, labeled pool-dry storms, memory
squeezes, worker crash isolation + respawn, retry-with-backoff for
transient finalize/worker failures (with the repeated-finalize purity
pin), the deadline reaper's `timed_out` state naming missing senders,
idempotent submission, wait() timeout semantics, admission shedding,
and the bisection-storm guard.

Protocol-level streaming equivalence stays in tests/test_streaming.py;
here the FAILURE paths are under test.
"""

import pytest

from fsdkr_tpu import precompute
from fsdkr_tpu.protocol import RefreshMessage, finalize_streams, simulate_keygen
from fsdkr_tpu.serving import (
    SLO,
    BatchPolicy,
    BisectGuard,
    OverloadPolicy,
    RefreshService,
    ServeRejected,
    faults,
)
from fsdkr_tpu.serving import metrics as smetrics
from fsdkr_tpu.telemetry import registry


@pytest.fixture(autouse=True)
def _clean():
    faults.reset()
    precompute.clear_targets()
    precompute.clear_pools()
    yield
    faults.reset()
    precompute.clear_targets()
    precompute.clear_pools()


# ---------------------------------------------------------------------------
# the fault plan


def test_fault_plan_parse_and_determinism():
    plan = faults.FaultPlan.parse(
        "seed=7, msg_tamper=0.5, worker_crash=1.0, delay_s=0.1, "
        "pool_dry=0.0, finalize_exc_max=2"
    )
    assert plan.seed == 7 and plan.delay_s == 0.1
    assert plan.caps == {"finalize_exc": 2}
    # decisions are pure functions of (seed, site, key)
    a = [plan._roll("msg_tamper", (s, 1)) for s in range(64)]
    b = [plan._roll("msg_tamper", (s, 1)) for s in range(64)]
    assert a == b and any(a) and not all(a)  # ~half fire at rate 0.5
    plan2 = faults.FaultPlan.parse("seed=8,msg_tamper=0.5")
    assert a != [plan2._roll("msg_tamper", (s, 1)) for s in range(64)]
    # rate 0 / unlisted sites never fire
    assert not any(plan._roll("pool_dry", (s,)) for s in range(64))
    assert not any(plan._roll("msg_drop", (s,)) for s in range(64))
    # rate 1 always fires
    assert all(plan.fire("worker_crash", (s,)) for s in range(8))


def test_fault_plan_caps_and_accounting():
    plan = faults.configure("seed=1,finalize_exc=1.0,finalize_exc_max=2")
    assert faults.active() is plan
    fired = [plan.fire("finalize_exc", (i,)) for i in range(5)]
    assert fired == [True, True, False, False, False]  # capped at 2
    assert plan.injected() == {"finalize_exc": 2}
    assert registry.counter(
        "fsdkr_fault_injected", labelnames=("site",)
    ).value(site="finalize_exc") >= 2
    faults.reset()
    assert faults.active() is None


def test_fault_plan_unknown_key_rejected():
    with pytest.raises(ValueError, match="unknown key"):
        faults.FaultPlan.parse("seed=1,msg_tmaper=0.5")
    with pytest.raises(ValueError, match="bad entry"):
        faults.FaultPlan.parse("msg_tamper")


def test_fault_plan_env_activation(monkeypatch):
    monkeypatch.delenv("FSDKR_FAULTS", raising=False)
    assert faults.active() is None
    monkeypatch.setenv("FSDKR_FAULTS", "seed=5,pool_dry=1.0")
    plan = faults.active()
    assert plan is not None and plan.rates["pool_dry"] == 1.0
    assert faults.active() is plan  # cached per spec string
    monkeypatch.setenv("FSDKR_FAULTS", "seed=6,pool_dry=1.0")
    assert faults.active().seed == 6  # spec change reparsed


# ---------------------------------------------------------------------------
# hook sites outside the service


def test_pool_dry_injection_labeled():
    """ISSUE 11 satellite: injected dry fallbacks are labeled
    cause=injected (and starve the take WITHOUT consuming the pooled
    entry); real dries are labeled cause=real — a chaos storm cannot
    hide a producer regression."""
    from fsdkr_tpu.precompute import pools

    dry = registry.counter("fsdkr_pool_dry", labelnames=("kind", "cause"))
    inj0 = dry.value(kind="enc", cause="injected")
    real0 = dry.value(kind="enc", cause="real")
    assert pools.put("enc", 31337, (5, 25))
    faults.configure("seed=2,pool_dry=1.0")
    assert pools.take("enc", 31337) is None  # starved, entry kept
    assert dry.value(kind="enc", cause="injected") == inj0 + 1
    assert dry.value(kind="enc", cause="real") == real0
    faults.reset()
    assert pools.take("enc", 31337) == (5, 25)  # entry survived the storm
    assert pools.take("enc", 31337) is None  # genuinely dry now
    assert dry.value(kind="enc", cause="real") == real0 + 1


def test_mem_squeeze_budget(monkeypatch):
    from fsdkr_tpu.backend import memplan

    monkeypatch.delenv("FSDKR_MEM_BUDGET_MB", raising=False)
    full = 256 * (1 << 20)
    assert memplan.mem_budget_bytes() == full
    faults.configure("seed=3,mem_squeeze=1.0,squeeze_factor=0.25")
    assert memplan.mem_budget_bytes() == full // 4
    faults.reset()
    assert memplan.mem_budget_bytes() == full


# ---------------------------------------------------------------------------
# service failure semantics


def _service(test_config, keys, **kw):
    kw.setdefault("policy", BatchPolicy(max_sessions=6, linger_s=0.02))
    kw.setdefault("backoff_s", 0.01)
    svc = RefreshService(**kw)
    svc.admit(
        "com", [k.clone() for k in keys], test_config,
        SLO(arrival_rate_hz=0.5),
    )
    return svc


def test_worker_crash_isolation_and_respawn(test_config):
    """A dying worker thread settles only its own session (no blame:
    an injected crash is infrastructure, not a verdict), is respawned,
    and the queue keeps draining: the very next healthy session on the
    SAME committee completes."""
    keys = simulate_keygen(1, 3, test_config)
    svc = _service(test_config, keys, retries=0)
    try:
        svc.start()
        faults.configure("seed=4,worker_crash=1.0")
        sid = svc.submit("com")
        assert svc.drain(timeout=30)
        s = svc.wait(sid, timeout=1)
        assert s.state == "aborted" and not s.blame
        assert "InjectedWorkerCrash" in s.error
        assert "worker_crash" in s.faults
        assert svc.stats()["workers_respawned"] >= 1
        faults.reset()
        sid2 = svc.submit("com")
        assert svc.drain(timeout=60)
        assert svc.wait(sid2, timeout=1).state == "done"
    finally:
        faults.reset()
        svc.stop()


def test_worker_crash_retry_recovers(test_config):
    """One injected crash + FSDKR_SERVE_RETRIES>0: the session requeues
    with backoff and completes — outcome `recovered`, not aborted."""
    keys = simulate_keygen(1, 3, test_config)
    svc = _service(test_config, keys, retries=2)
    try:
        svc.start()
        faults.configure("seed=5,worker_crash=1.0,worker_crash_max=1")
        sid = svc.submit("com")
        assert svc.drain(timeout=60)
        s = svc.wait(sid, timeout=1)
        assert s.state == "done", s.error
        assert s.retries == 1 and "worker_crash" in s.faults
    finally:
        faults.reset()
        svc.stop()


def test_finalize_exc_retry_recovers(test_config):
    """A failed finalize LAUNCH retries with backoff and completes; the
    retried finalize is a pure function of the staged public messages,
    so the committee rotates exactly once, coherently."""
    keys = simulate_keygen(1, 3, test_config)
    svc = _service(test_config, keys, retries=2)
    try:
        svc.start()
        faults.configure("seed=6,finalize_exc=1.0,finalize_exc_max=1")
        r0 = smetrics.retries_counter().value(stage="finalize")
        sid = svc.submit("com")
        assert svc.drain(timeout=60)
        s = svc.wait(sid, timeout=1)
        assert s.state == "done", s.error
        assert "finalize_exc" in s.faults
        assert smetrics.retries_counter().value(stage="finalize") == r0 + 1
        # post-adopt coherence: one epoch advanced, all parties agree on
        # the rotated public state (a double or partial adoption would
        # diverge pk_vec across parties)
        com = svc._committees["com"]
        assert com.epochs == 1
        assert all(k.pk_vec == com.keys[0].pk_vec for k in com.keys)
    finally:
        faults.reset()
        svc.stop()


def test_finalize_exhausted_retries_abort_without_blame(test_config):
    keys = simulate_keygen(1, 3, test_config)
    svc = _service(test_config, keys, retries=1)
    try:
        svc.start()
        faults.configure("seed=7,finalize_exc=1.0")  # every attempt fails
        sid = svc.submit("com")
        assert svc.drain(timeout=60)
        s = svc.wait(sid, timeout=1)
        assert s.state == "aborted" and not s.blame
        assert "InjectedFinalizeError" in s.error
    finally:
        faults.reset()
        svc.stop()


def test_repeated_finalize_bit_identity(one_refresh_round, test_config):
    """The retry-safety pin: a finalize attempt that dies BEFORE the
    launch (the service's injection point) leaves the streams
    re-finalizable, the retried finalize mutates the key bit-identically
    to barrier collect, and any FURTHER finalize only replays the stored
    verdict — no re-verification, no second adoption."""
    keys, msgs, dks = one_refresh_round
    kb, ks = keys[0].clone(), keys[0].clone()
    RefreshMessage.collect(msgs, kb, dks[0], (), test_config)
    st = RefreshMessage.collect_stream(
        ks, dks[0], [m.party_index for m in msgs], (), test_config
    )
    for m in msgs:
        assert st.offer(m) == "accepted"
    # "attempt 0" failed at launch: nothing touched the streams; the
    # retry runs the same pure function over the same staged messages
    assert finalize_streams([st], test_config) == [None]
    assert ks.keys_linear.x_i.to_int() == kb.keys_linear.x_i.to_int()
    assert ks.pk_vec == kb.pk_vec
    assert ks.paillier_dk.p == kb.paillier_dk.p
    x_once = ks.keys_linear.x_i.to_int()
    # a third finalize replays the verdict without re-adopting
    assert finalize_streams([st], test_config) == [None]
    assert ks.keys_linear.x_i.to_int() == x_once


def test_stream_close_semantics(one_refresh_round, test_config):
    keys, msgs, dks = one_refresh_round
    st = RefreshMessage.collect_stream(
        keys[0].clone(), dks[0], [m.party_index for m in msgs], (),
        test_config,
    )
    st.offer(msgs[0])
    err = RuntimeError("reaped")
    assert st.close(err) is True
    assert st.done and st.error is err
    assert st.offer(msgs[1]) == "late"
    assert st._pairs == {}  # staged refs released
    # a fused launch already holding this session replays, never adopts
    assert finalize_streams([st], test_config) == [err]
    assert st.close(RuntimeError("again")) is False  # verdict immutable
    assert st.error is err


def test_deadline_reaper_names_missing_senders(test_config):
    """Dropped broadcasts: the session ends `timed_out` (never wedged),
    the error NAMES the missing senders (quorum gap is identifiable,
    like abort blame), and the committee is freed for the next
    session."""
    keys = simulate_keygen(1, 3, test_config)
    # deadline must be comfortably above one healthy session (~1s warm
    # on this box, more under CPU contention): 4s keeps the follow-up
    # healthy session from flaking into timed_out on a loaded machine
    svc = _service(test_config, keys, retries=0, deadline_s=4.0)
    try:
        svc.start()
        faults.configure("seed=8,msg_drop=1.0")  # every broadcast lost
        sid = svc.submit("com")
        assert svc.drain(timeout=30)
        s = svc.wait(sid, timeout=1)
        assert s.state == "timed_out"
        assert "missing senders [1, 2, 3]" in s.error, s.error
        assert any(f.startswith("msg_drop") for f in s.faults)
        assert svc.stats()["sessions_timed_out"] == 1
        assert smetrics.sessions_counter().value(outcome="timed_out") >= 1
        faults.reset()
        sid2 = svc.submit("com")  # committee not wedged
        assert svc.drain(timeout=60)
        assert svc.wait(sid2, timeout=1).state == "done"
    finally:
        faults.reset()
        svc.stop()


def test_delayed_broadcast_delivered_by_reaper(test_config):
    """A delayed message (delay < deadline) is delivered by the reaper
    and the session completes — out-of-order late arrival is a latency
    event, not a failure."""
    keys = simulate_keygen(1, 3, test_config)
    svc = _service(test_config, keys, retries=0, deadline_s=30.0)
    try:
        svc.start()
        faults.configure(
            "seed=9,msg_delay=1.0,msg_delay_max=1,delay_s=0.3"
        )
        sid = svc.submit("com")
        assert svc.drain(timeout=60)
        s = svc.wait(sid, timeout=1)
        assert s.state == "done", s.error
        assert any(f.startswith("msg_delay") for f in s.faults)
    finally:
        faults.reset()
        svc.stop()


def test_tampered_broadcast_aborts_with_blame(test_config):
    """Tampered-then-corrected broadcast: first arrival wins, the
    session aborts with an identifiable FsDkrError — a tampered session
    can never finish clean, and the blame flag separates it from
    transient aborts."""
    keys = simulate_keygen(1, 3, test_config)
    svc = _service(test_config, keys, retries=2)
    try:
        svc.start()
        faults.configure("seed=10,msg_tamper=1.0,msg_tamper_max=1")
        sid = svc.submit("com")
        assert svc.drain(timeout=60)
        s = svc.wait(sid, timeout=1)
        assert s.state == "aborted" and s.blame, (s.state, s.error)
        assert "PDLwSlackProofError" in s.error
        assert any(f.startswith("msg_tamper") for f in s.faults)
        assert s.retries == 0  # a verdict is never retried
    finally:
        faults.reset()
        svc.stop()


def test_submit_idempotent_on_epoch(test_config):
    """ISSUE 11 satellite: duplicate submissions keyed by (committee
    fingerprint, epoch) return the EXISTING session — in flight or
    finished — instead of double-spending pooled key bundles."""
    keys = simulate_keygen(1, 3, test_config)
    svc = _service(test_config, keys)
    try:
        svc.start()
        sid = svc.submit("com", epoch=0)
        assert svc.submit("com", epoch=0) == sid  # in flight: deduped
        assert svc.drain(timeout=60)
        assert svc.wait(sid, timeout=1).state == "done"
        # finished sessions keep deduping (client retry after success)
        assert svc.submit("com", epoch=0) == sid
        sid1 = svc.submit("com", epoch=1)
        assert sid1 != sid
        assert svc.drain(timeout=60)
        assert svc.stats()["sessions_done"] == 2  # exactly two epochs ran
        # epoch-less submissions keep the legacy always-new behavior
        assert svc.submit("com") not in (sid, sid1)
        assert svc.drain(timeout=60)
    finally:
        svc.stop()


def test_submit_epoch_retryable_after_failure(test_config):
    """A FAILED epoch must not dedupe forever: the retry contract says
    timed_out is retryable, so a resubmission of the same (committee,
    epoch) after a failure creates a FRESH session instead of handing
    back the dead one."""
    keys = simulate_keygen(1, 3, test_config)
    # 4s deadline: see test_deadline_reaper_names_missing_senders
    svc = _service(test_config, keys, retries=0, deadline_s=4.0)
    try:
        svc.start()
        faults.configure("seed=12,msg_drop=1.0")
        sid = svc.submit("com", epoch=0)
        assert svc.drain(timeout=30)
        assert svc.wait(sid, timeout=1).state == "timed_out"
        faults.reset()
        sid2 = svc.submit("com", epoch=0)  # retry: NEW session
        assert sid2 != sid
        assert svc.drain(timeout=60)
        assert svc.wait(sid2, timeout=1).state == "done"
        assert svc.submit("com", epoch=0) == sid2  # done: dedupes again
    finally:
        faults.reset()
        svc.stop()


def test_delayed_plus_dropped_without_deadline_terminates(test_config):
    """Wedge regression: one message delayed AND one dropped with the
    deadline OFF — after the reaper delivers the delayed message the
    session can never reach quorum and must settle as timed_out (naming
    the dropped sender) instead of hanging forever."""
    keys = simulate_keygen(1, 3, test_config)
    svc = _service(test_config, keys, retries=0, deadline_s=0.0)
    try:
        svc.start()
        # precedence per message is drop > tamper > delay > dup, so with
        # _max=1 caps the first message drops and the second delays
        faults.configure(
            "seed=13,msg_drop=1.0,msg_drop_max=1,"
            "msg_delay=1.0,msg_delay_max=1,delay_s=0.2"
        )
        sid = svc.submit("com")
        assert svc.drain(timeout=30), "delayed+dropped session wedged"
        s = svc.wait(sid, timeout=1)
        assert s.state == "timed_out"
        assert "missing senders" in s.error, s.error
    finally:
        faults.reset()
        svc.stop()


def test_wait_timeout_raises(test_config):
    """ISSUE 11 satellite: wait() never hands back an unfinished
    session — a timeout raises, distinguishable from completion."""
    keys = simulate_keygen(1, 3, test_config)
    svc = _service(test_config, keys)  # never started: nothing runs
    sid = svc.submit("com")
    with pytest.raises(TimeoutError, match="pooled"):
        svc.wait(sid, timeout=0.05)
    with pytest.raises(KeyError):
        svc.wait(999999, timeout=0)


def test_overload_shed_rejects_with_retry_after(test_config):
    keys = simulate_keygen(1, 3, test_config)
    svc = _service(
        test_config, keys, overload=OverloadPolicy(max_queue=1)
    )
    r0 = smetrics.sessions_counter().value(outcome="rejected")
    svc.submit("com")  # queue depth 0 -> admitted
    with pytest.raises(ServeRejected) as ei:
        svc.submit("com")  # queue depth 1 >= max_queue -> shed
    assert ei.value.retry_after_s > 0
    assert ei.value.reason == "overload"
    assert svc.sessions_rejected == 1
    assert svc.stats()["sessions_rejected"] == 1
    assert smetrics.sessions_counter().value(outcome="rejected") == r0 + 1


def test_bisect_guard_window():
    g = BisectGuard(budget=2, window_s=1.0)
    assert g.enabled()
    assert g.blocked("c", now=100.0) is None
    g.charge("c", 3, now=100.0)
    b = g.blocked("c", now=100.1)
    assert b is not None and 0.8 <= b <= 1.0  # retry when window rolls
    assert g.blocked("other", now=100.1) is None  # per-committee
    assert g.blocked("c", now=101.2) is None  # window rolled
    g.charge("d", 2, now=200.0)  # at budget, not over
    assert g.blocked("d", now=200.1) is None
    off = BisectGuard(budget=0)
    off.charge("c", 99)
    assert not off.enabled() and off.blocked("c") is None


def test_bisect_guard_sheds_submission(test_config):
    keys = simulate_keygen(1, 3, test_config)
    svc = _service(
        test_config, keys, guard=BisectGuard(budget=1, window_s=60.0)
    )
    svc.guard.charge("com", 5)  # a tamper storm just cost 5 bisections
    with pytest.raises(ServeRejected) as ei:
        svc.submit("com")
    assert ei.value.reason == "bisection budget exhausted"
    assert ei.value.retry_after_s > 0
