"""Prove/verify roundtrip + soundness-negative tests for each proof system
(reference test strategy: SURVEY.md §4 item 1; soundness negative modeled on
`/root/reference/src/zk_pdl_with_slack.rs:268-331` and generalized to every
system)."""

import secrets

import pytest

from fsdkr_tpu.config import TEST_CONFIG
from fsdkr_tpu.core import intops, paillier
from fsdkr_tpu.core.secp256k1 import GENERATOR, Scalar
from fsdkr_tpu.errors import PDLwSlackProofError, RingPedersenProofError
from fsdkr_tpu.proofs import (
    AliceProof,
    BobProof,
    BobProofExt,
    CompositeDLogProof,
    DLogStatement,
    NiCorrectKeyProof,
    PDLwSlackProof,
    PDLwSlackStatement,
    PDLwSlackWitness,
    RingPedersenProof,
    RingPedersenStatement,
)

BITS = TEST_CONFIG.paillier_bits


@pytest.fixture(scope="module")
def setup():
    """Shared ZKP setup: (dlog_statement, ek, dk), like the reference's
    generate_init (/root/reference/src/range_proofs.rs:626-648), built with
    the production setup helper."""
    from fsdkr_tpu.protocol.keygen import generate_h1_h2_n_tilde

    n_tilde, h1, h2, _, _ = generate_h1_h2_n_tilde(TEST_CONFIG)
    dlog = DLogStatement(N=n_tilde, g=h1, ni=h2)
    ek, dk = paillier.keygen(BITS)
    return dlog, ek, dk


class TestAliceRange:
    def test_roundtrip(self, setup):
        dlog, ek, _ = setup
        a = Scalar.random().to_int()
        r = intops.sample_unit(ek.n)
        cipher = paillier.encrypt_with_randomness(ek, a, r)
        proof = AliceProof.generate(a, cipher, ek, dlog, r)
        assert proof.verify(cipher, ek, dlog)

    def test_soundness_wrong_plaintext(self, setup):
        # encrypt a+1 but prove knowledge of a (mirrors the reference's
        # PDL soundness-negative pattern)
        dlog, ek, _ = setup
        a = Scalar.random().to_int()
        r = intops.sample_unit(ek.n)
        cipher = paillier.encrypt_with_randomness(ek, a + 1, r)
        proof = AliceProof.generate(a, cipher, ek, dlog, r)
        assert not proof.verify(cipher, ek, dlog)

    def test_range_gate(self, setup):
        # forged s1 beyond q^3 must be rejected regardless of the algebra
        dlog, ek, _ = setup
        a = Scalar.random().to_int()
        r = intops.sample_unit(ek.n)
        cipher = paillier.encrypt_with_randomness(ek, a, r)
        proof = AliceProof.generate(a, cipher, ek, dlog, r)
        from fsdkr_tpu.core.secp256k1 import N as Q

        forged = AliceProof(z=proof.z, e=proof.e, s=proof.s, s1=Q**3 + 1, s2=proof.s2)
        assert not forged.verify(cipher, ek, dlog)


class TestBobRange:
    def test_mta_and_mtawc_roundtrip(self, setup):
        # full MtA flow as in the reference's bob_zkp test
        # (/root/reference/src/range_proofs.rs:672-745)
        dlog, ek, dk = setup
        a = Scalar.random().to_int()
        enc_a = paillier.encrypt(ek, a)
        b = Scalar.random()
        b_times_enc_a = paillier.mul(ek, enc_a, b.to_int())
        beta_prim = secrets.randbelow(ek.n)
        r = paillier.sample_randomness(ek)
        enc_beta = paillier.encrypt_with_randomness(ek, beta_prim, r)
        mta_out = paillier.add(ek, b_times_enc_a, enc_beta)

        proof, _ = BobProof.generate(enc_a, mta_out, b, beta_prim, ek, dlog, r)
        assert proof.verify(enc_a, mta_out, ek, dlog)

        # MtA output decrypts to a*b + beta_prim (homomorphism sanity)
        assert paillier.decrypt(dk, ek, mta_out) == (a * b.to_int() + beta_prim) % ek.n

        ext = BobProofExt.generate(enc_a, mta_out, b, beta_prim, ek, dlog, r)
        X = GENERATOR * b
        assert ext.verify(enc_a, mta_out, ek, dlog, X)

    def test_soundness_wrong_b(self, setup):
        dlog, ek, _ = setup
        a = Scalar.random().to_int()
        enc_a = paillier.encrypt(ek, a)
        b = Scalar.random()
        beta_prim = secrets.randbelow(ek.n)
        r = paillier.sample_randomness(ek)
        mta_out = paillier.add(
            ek,
            paillier.mul(ek, enc_a, (b + Scalar.from_int(1)).to_int()),  # b+1 used
            paillier.encrypt_with_randomness(ek, beta_prim, r),
        )
        proof, _ = BobProof.generate(enc_a, mta_out, b, beta_prim, ek, dlog, r)
        assert not proof.verify(enc_a, mta_out, ek, dlog)

    def test_ext_soundness_wrong_X(self, setup):
        dlog, ek, _ = setup
        a = Scalar.random().to_int()
        enc_a = paillier.encrypt(ek, a)
        b = Scalar.random()
        beta_prim = secrets.randbelow(ek.n)
        r = paillier.sample_randomness(ek)
        mta_out = paillier.add(
            ek,
            paillier.mul(ek, enc_a, b.to_int()),
            paillier.encrypt_with_randomness(ek, beta_prim, r),
        )
        ext = BobProofExt.generate(enc_a, mta_out, b, beta_prim, ek, dlog, r)
        wrong_X = GENERATOR * (b + Scalar.from_int(1))
        assert not ext.verify(enc_a, mta_out, ek, dlog, wrong_X)


class TestPDLwSlack:
    def _statement(self, setup, shift=0):
        dlog, ek, _ = setup
        x = Scalar.random()
        r = paillier.sample_randomness(ek)
        c = paillier.encrypt_with_randomness(ek, x.to_int() + shift, r)
        st = PDLwSlackStatement(
            ciphertext=c,
            ek=ek,
            Q=GENERATOR * x,
            G=GENERATOR,
            h1=dlog.g,
            h2=dlog.ni,
            N_tilde=dlog.N,
        )
        return st, PDLwSlackWitness(x=x, r=r)

    def test_roundtrip(self, setup):
        # mirrors /root/reference/src/zk_pdl_with_slack.rs:205-266
        st, w = self._statement(setup)
        PDLwSlackProof.prove(w, st).verify(st)

    def test_soundness_encrypt_x_plus_one(self, setup):
        # the reference's only adversarial test
        # (/root/reference/src/zk_pdl_with_slack.rs:268-331)
        st, w = self._statement(setup, shift=1)
        proof = PDLwSlackProof.prove(w, st)
        with pytest.raises(PDLwSlackProofError) as exc:
            proof.verify(st)
        # u1 (EC equation) holds; the ciphertext equation u2 must fail
        assert exc.value.is_u1_eq and not exc.value.is_u2_eq


class TestRingPedersen:
    M = TEST_CONFIG.m_security

    def test_roundtrip(self):
        st, w = RingPedersenStatement.generate(TEST_CONFIG)
        proof = RingPedersenProof.prove(w, st, self.M)
        proof.verify(st, self.M)  # raises on failure

    def test_soundness_wrong_lambda(self):
        st, w = RingPedersenStatement.generate(TEST_CONFIG)
        bad_w = type(w)(p=w.p, q=w.q, lam=w.lam + 1, phi=w.phi)
        proof = RingPedersenProof.prove(bad_w, st, self.M)
        with pytest.raises(RingPedersenProofError):
            proof.verify(st, self.M)

    def test_wrong_length_rejected(self):
        st, w = RingPedersenStatement.generate(TEST_CONFIG)
        proof = RingPedersenProof.prove(w, st, self.M)
        truncated = type(proof)(A=proof.A[:-1], Z=proof.Z[:-1])
        with pytest.raises(RingPedersenProofError):
            truncated.verify(st, self.M)


class TestCompositeDLog:
    def test_roundtrip_both_bases(self):
        # both-direction usage as in the join path, via the production
        # helper (/root/reference/src/add_party_message.rs:69-92)
        from fsdkr_tpu.protocol.keygen import generate_dlog_statement_proofs

        st_h1, p1, p2 = generate_dlog_statement_proofs(TEST_CONFIG)
        st_h2 = DLogStatement(N=st_h1.N, g=st_h1.ni, ni=st_h1.g)
        assert p1.verify(st_h1)
        assert p2.verify(st_h2)

    def test_soundness_wrong_secret(self, setup):
        dlog, _, _ = setup
        proof = CompositeDLogProof.prove(dlog, 12345)  # not the dlog
        assert not proof.verify(dlog)


class TestCorrectKey:
    ROUNDS = TEST_CONFIG.correct_key_rounds

    def test_roundtrip(self, setup):
        _, ek, dk = setup
        proof = NiCorrectKeyProof.proof(dk, rounds=self.ROUNDS)
        assert proof.verify(ek, rounds=self.ROUNDS)

    def test_rejects_wrong_modulus(self, setup):
        _, ek, dk = setup
        other_ek, _ = paillier.keygen(BITS)
        proof = NiCorrectKeyProof.proof(dk, rounds=self.ROUNDS)
        assert not proof.verify(other_ek, rounds=self.ROUNDS)

    def test_rejects_smooth_modulus(self):
        # modulus with a small factor must fail the primorial gate
        from fsdkr_tpu.core.paillier import EncryptionKey

        n = 3 * (2**255 - 19)
        fake = NiCorrectKeyProof(sigma_vec=[1] * self.ROUNDS)
        assert not fake.verify(EncryptionKey.from_n(n), rounds=self.ROUNDS)
