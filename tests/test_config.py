"""ProtocolConfig routing knobs: EC device/host dispatch and the
accelerator probe's failure-caching semantics."""

from fsdkr_tpu import config as cfgmod
from fsdkr_tpu.config import ProtocolConfig


class TestDeviceEcRouting:
    def test_host_backend_never_device_ec(self, monkeypatch):
        monkeypatch.setenv("FSDKR_DEVICE_EC", "1")
        assert ProtocolConfig(paillier_bits=768).device_ec is False

    def test_env_forces_route(self, monkeypatch):
        cfg = ProtocolConfig(paillier_bits=768).with_backend("tpu")
        monkeypatch.setenv("FSDKR_DEVICE_EC", "0")
        assert cfg.device_ec is False
        monkeypatch.setenv("FSDKR_DEVICE_EC", "1")
        assert cfg.device_ec is True

    def test_auto_routes_host_on_cpu_platform(self, monkeypatch):
        """The suite runs on the CPU platform, where the measured EC
        crossover (bench_results/ec_ab_cpu.json) says host wins — auto
        must pick the host route."""
        cfg = ProtocolConfig(paillier_bits=768).with_backend("tpu")
        monkeypatch.setenv("FSDKR_DEVICE_EC", "auto")
        assert cfg.device_ec is False

    def test_probe_failure_not_cached(self, monkeypatch):
        """A transient jax.devices() failure must not pin the routing:
        only successful probes are cached (TPU init is flaky here)."""
        monkeypatch.setattr(cfgmod, "_accel_probe", None)
        import builtins

        real_import = builtins.__import__

        def failing_import(name, *a, **k):
            if name == "jax":
                raise RuntimeError("backend init failed")
            return real_import(name, *a, **k)

        monkeypatch.setattr(builtins, "__import__", failing_import)
        assert cfgmod._accelerator_present() is False
        monkeypatch.setattr(builtins, "__import__", real_import)
        assert cfgmod._accel_probe is None  # failure was not cached
        assert cfgmod._accelerator_present() is False  # cpu platform
        assert cfgmod._accel_probe is False  # success cached


class TestWipeHelpers:
    def test_wipe_array_zeroes_in_place(self):
        from fsdkr_tpu.ops.limbs import ints_to_limbs, limbs_to_ints, wipe_array

        vals = [(1 << 255) - 19, 12345, 0]
        arr = ints_to_limbs(vals, 16)
        assert limbs_to_ints(arr) == vals
        view = arr.reshape(3, 16)  # wiping a view wipes the base
        wipe_array(view)
        assert not arr.any()
        wipe_array(None)  # no-op, no raise

    def test_native_bufs_wiped(self):
        from fsdkr_tpu import native

        if not native.available():
            import pytest

            pytest.skip("native core unavailable")
        buf = native._to_buf([0xDEADBEEF], 2)
        assert any(buf)
        native._wipe_buf(buf)
        assert not any(buf)


class TestDevicePowmRouting:
    """backend.powm._device_powm mirrors the device_ec contract and the
    host fallbacks must agree with the CPython oracle."""

    def test_env_forces_route(self, monkeypatch):
        from fsdkr_tpu.backend import powm

        monkeypatch.setenv("FSDKR_DEVICE_POWM", "0")
        assert powm._device_powm() is False
        monkeypatch.setenv("FSDKR_DEVICE_POWM", "1")
        assert powm._device_powm() is True

    def test_auto_routes_host_on_cpu_platform(self, monkeypatch):
        from fsdkr_tpu.backend import powm

        monkeypatch.setenv("FSDKR_DEVICE_POWM", "auto")
        assert powm._device_powm() is False

    def test_host_route_matches_oracle(self, monkeypatch):
        """Forced-host tpu_powm / tpu_powm_shared / tpu_modmul must equal
        pow; and the device path must never be entered (the launch would
        be the bug — this is the route under test, not the kernels)."""
        from fsdkr_tpu.backend import powm

        monkeypatch.setenv("FSDKR_DEVICE_POWM", "0")

        def boom(*a, **k):  # device entry = routing failure
            raise AssertionError("device launch on forced-host route")

        monkeypatch.setattr(powm, "_cached_ctx", boom)
        mods = [(1 << 255) | 199, (1 << 255) | 321]
        bases = [123456789, 987654321]
        exps = [(1 << 64) | 7, (1 << 64) | 9]
        assert powm.tpu_powm(bases, exps, mods) == [
            pow(b, e, m) for b, e, m in zip(bases, exps, mods)
        ]
        assert powm.tpu_modmul(bases, exps, mods) == [
            (b * e) % m for b, e, m in zip(bases, exps, mods)
        ]
        grouped = powm.tpu_powm_shared(bases, [exps, exps[:1]], mods)
        assert grouped == [
            [pow(bases[0], e, mods[0]) for e in exps],
            [pow(bases[1], exps[0], mods[1])],
        ]
