"""Persistent precompute cache: budget/eviction semantics and — the
security-relevant tier-1 pin — isolation across interleaved committees.

The cache (utils.lru) holds comb window tables, comb power ladders, and
Montgomery contexts keyed by full public values (base, modulus,
geometry). Interleaving collects of two DIFFERENT committees must
produce results identical to cold-cache runs: a hit under one
committee's key can never serve another's math. The unit layer checks
the engines value-for-value; the collect layer checks accept/reject
verdicts (honest accept + tampered reject) warm vs cold.
"""

import copy
import dataclasses
import random

import pytest

from fsdkr_tpu import native
from fsdkr_tpu.utils.lru import (
    BudgetLRU,
    cache_stats,
    clear_caches,
    global_cache,
)

RNG = random.Random(0xCACE)


def _odd_mod(bits):
    return RNG.getrandbits(bits) | (1 << (bits - 1)) | 1


# ---------------------------------------------------------------------------
# LRU semantics (the _CTX_CACHE clear()-on-overflow fix)


def test_lru_evicts_oldest_not_all():
    lru = BudgetLRU(100)
    lru.put("a", 1, 40)
    lru.put("b", 2, 40)
    assert lru.get("a") == 1  # refresh a: b is now oldest
    lru.put("c", 3, 40)  # overflow: evict b ONLY
    assert lru.get("b") is None
    assert lru.get("a") == 1 and lru.get("c") == 3
    assert lru.stats()["evictions"] == 1


def test_lru_budget_and_oversize():
    lru = BudgetLRU(100)
    lru.put("big", 1, 101)  # larger than the whole budget: not cached
    assert lru.get("big") is None
    lru.put("a", 1, 60)
    lru.put("b", 2, 60)  # evicts a
    assert lru.get("a") is None and lru.get("b") == 2
    assert lru.stats()["bytes"] <= 100


def test_lru_update_replaces_bytes():
    lru = BudgetLRU(100)
    lru.put("a", 1, 80)
    lru.put("a", 2, 30)  # replace, not accumulate
    assert lru.get("a") == 2
    assert lru.stats()["bytes"] == 30
    assert lru.stats()["entries"] == 1


# ---------------------------------------------------------------------------
# engine-level isolation: interleaved (base, modulus) groups, warm vs cold


@pytest.mark.skipif(not native.available(), reason="no native core")
def test_native_comb_cache_isolation():
    m_a, m_b = _odd_mod(768), _odd_mod(768)
    base_a, base_b = RNG.randrange(2, m_a), RNG.randrange(2, m_b)
    exps_a = [RNG.getrandbits(768) for _ in range(6)]
    exps_b = [RNG.getrandbits(768) for _ in range(6)]

    clear_caches()
    cold_a = native.modexp_shared(base_a, exps_a, m_a)
    clear_caches()
    cold_b = native.modexp_shared(base_b, exps_b, m_b)

    clear_caches()
    warm = [
        native.modexp_shared(base_a, exps_a, m_a),
        native.modexp_shared(base_b, exps_b, m_b),
        native.modexp_shared(base_a, exps_a, m_a),  # hit for A
        native.modexp_shared(base_b, exps_b, m_b),  # hit for B
    ]
    assert warm[0] == warm[2] == cold_a
    assert warm[1] == warm[3] == cold_b
    stats = cache_stats()
    assert stats["hits"] >= 2  # second round served from the cache
    assert cold_a == [pow(base_a, e, m_a) for e in exps_a]
    assert cold_b == [pow(base_b, e, m_b) for e in exps_b]


def test_device_comb_powers_cache_isolation():
    from fsdkr_tpu.ops.montgomery import shared_base_modexp

    m_a, m_b = _odd_mod(768), _odd_mod(768)
    bases_a = [RNG.randrange(2, m_a) for _ in range(2)]
    bases_b = [RNG.randrange(2, m_b) for _ in range(2)]
    exps = [[RNG.getrandbits(256) for _ in range(4)] for _ in range(2)]

    clear_caches()
    cold_a = shared_base_modexp(bases_a, exps, [m_a] * 2, 48)
    clear_caches()
    cold_b = shared_base_modexp(bases_b, exps, [m_b] * 2, 48)

    clear_caches()
    assert shared_base_modexp(bases_a, exps, [m_a] * 2, 48) == cold_a
    assert shared_base_modexp(bases_b, exps, [m_b] * 2, 48) == cold_b
    s0 = cache_stats()["hits"]
    assert shared_base_modexp(bases_a, exps, [m_a] * 2, 48) == cold_a
    assert shared_base_modexp(bases_b, exps, [m_b] * 2, 48) == cold_b
    assert cache_stats()["hits"] > s0
    for bs, m, out in ((bases_a, m_a, cold_a), (bases_b, m_b, cold_b)):
        for b, es, o in zip(bs, exps, out):
            assert o == [pow(b, e, m) for e in es]


def test_cache_budget_zero_disables(monkeypatch):
    import fsdkr_tpu.utils.lru as lru_mod

    monkeypatch.setattr(lru_mod, "_GLOBAL", BudgetLRU(0))
    m = _odd_mod(768)
    base = RNG.randrange(2, m)
    exps = [RNG.getrandbits(512) for _ in range(4)]
    if native.available():
        assert native.modexp_shared(base, exps, m) == [
            pow(base, e, m) for e in exps
        ]
    assert global_cache().stats()["entries"] == 0


# ---------------------------------------------------------------------------
# collect-level isolation: two committees, interleaved warm collects vs
# cold-cache collects — verdict-identical, honest and tampered


def _run_collect(refreshed, config, mutate=None, collector=0):
    from fsdkr_tpu.protocol import RefreshMessage

    keys, msgs, dks = refreshed
    msgs = copy.deepcopy(msgs)
    if mutate is not None:
        mutate(msgs)
    key = keys[collector].clone()
    try:
        RefreshMessage.collect(msgs, key, dks[collector], (), config)
        return None
    except Exception as e:  # noqa: BLE001 - verdict identity compares classes
        return type(e).__name__


def _tamper(msgs):
    msgs[1].pdl_proof_vec[0] = dataclasses.replace(
        msgs[1].pdl_proof_vec[0], s1=msgs[1].pdl_proof_vec[0].s1 + 1
    )


@pytest.mark.heavy
def test_collect_interleaved_committees(one_refresh_round, test_config):
    """Interleaved collects of two different committees, warm cache, must
    match each committee's cold-cache verdicts exactly (honest accept,
    tampered reject) — no cross-key contamination through the persistent
    tables."""
    from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen

    config = test_config.with_backend("tpu")
    # committee B is independent of the (session-cached) committee A
    keygen = getattr(simulate_keygen, "uncached", simulate_keygen)
    keys_b = keygen(1, 3, test_config)
    out_b = [RefreshMessage.distribute(k.i, k, 3, config) for k in keys_b]
    round_b = (keys_b, [m for m, _ in out_b], [dk for _, dk in out_b])
    round_a = one_refresh_round

    # cold-cache reference verdicts, one committee at a time
    clear_caches()
    cold = [
        _run_collect(round_a, config),
        _run_collect(round_a, config, mutate=_tamper),
    ]
    clear_caches()
    cold += [
        _run_collect(round_b, config),
        _run_collect(round_b, config, mutate=_tamper),
    ]

    # warm interleaved: A, B, A(tampered), B(tampered), A, B
    clear_caches()
    warm = [
        _run_collect(round_a, config),
        _run_collect(round_b, config),
        _run_collect(round_a, config, mutate=_tamper),
        _run_collect(round_b, config, mutate=_tamper),
        _run_collect(round_a, config),
        _run_collect(round_b, config),
    ]
    assert warm[0] is None and warm[4] is None  # honest A accepts warm
    assert warm[1] is None and warm[5] is None  # honest B accepts warm
    assert warm[0] == warm[4] == cold[0]
    assert warm[1] == warm[5] == cold[2]
    assert warm[2] == cold[1]  # tampered A rejects identically
    assert warm[3] == cold[3]  # tampered B rejects identically
    assert cold[1] is not None and cold[3] is not None
