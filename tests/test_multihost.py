"""Multi-host (DCN) path exercised for real: a 2-process jax.distributed
CPU cluster (Gloo transport standing in for DCN) runs the sharded
Montgomery kernel over the host-aligned global mesh, with per-host row
contribution and cross-host verdict gather — the layout SURVEY.md §5
specifies for multi-slice scale-out. Round-3 coverage only tested the
single-host degeneracy; this spawns actual processes."""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.heavy
def test_two_process_cluster_sharded_kernel():
    worker = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
    port = _free_port()
    env = {
        k: v
        for k, v in os.environ.items()
        # workers configure their own platform/devices; strip the suite's
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"multihost workers hung; partial output: {outs}")
    if any(
        b"Multiprocess computations aren't implemented" in out.encode()
        if isinstance(out, str)
        else b"Multiprocess computations aren't implemented" in out
        for out in outs
    ):
        pytest.skip(
            "this jaxlib cannot run multiprocess computations on the CPU "
            "backend (capability gap, not a repo regression)"
        )
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-2000:]}"
        assert f"proc {i}: MULTIHOST-OK" in out
