"""Adversarial tamper matrix for the join path: a malicious joining
party's broadcast (JoinMessage, `/root/reference/src/add_party_message.rs:36-45`)
is perturbed field by field; the existing committee's collect must reject
it with the matching identifiable-abort error.

Complements tests/test_tamper.py (RefreshMessage surface). The joining
party's own collect deliberately verifies less (reference behavior,
SURVEY.md §3.4) — these cases exercise the EXISTING members' acceptance
gates for a new party (`protocol/refresh.py` collect_sessions join
adoption: correct-key, both-direction composite-dlog, moduli size,
ring-Pedersen)."""

import copy
import dataclasses

import pytest

from fsdkr_tpu.errors import (
    DLogProofValidation,
    ModuliTooSmall,
    PaillierVerificationError,
    RingPedersenProofError,
)
from fsdkr_tpu.protocol import JoinMessage, RefreshMessage
from fsdkr_tpu.protocol.join import JoinMessage as _JM


@pytest.fixture(scope="module")
def join_round(test_config):
    """(t=1, n=3) committee admits one new party at index 4: existing
    members run replace+distribute, the join broadcasts its message."""
    from fsdkr_tpu.protocol import simulate_keygen

    keys = simulate_keygen(1, 3, test_config)
    join_msg, pair = JoinMessage.distribute(test_config)
    join_msg.set_party_index(4)
    new_n = 4
    out = [
        RefreshMessage.replace(
            [join_msg], k, {i + 1: i + 1 for i in range(3)}, new_n, test_config
        )
        for k in keys
    ]
    return keys, [m for m, _ in out], [dk for _, dk in out], join_msg, pair


def _collect_with_join(join_round, config, mutate):
    keys, msgs, dks, join_msg, _pair = join_round
    evil = copy.deepcopy(join_msg)
    mutate(evil)
    RefreshMessage.collect(
        copy.deepcopy(msgs), keys[0].clone(), dks[0], (evil,), config
    )


CASES = [
    (
        "correct_key_sigma",
        PaillierVerificationError,
        lambda j: j.dk_correctness_proof.sigma_vec.__setitem__(
            0, j.dk_correctness_proof.sigma_vec[0] + 1
        ),
    ),
    (
        "composite_dlog_y",
        DLogProofValidation,
        lambda j: setattr(
            j,
            "composite_dlog_proof_base_h1",
            dataclasses.replace(
                j.composite_dlog_proof_base_h1,
                y=j.composite_dlog_proof_base_h1.y + 1,
            ),
        ),
    ),
    (
        "composite_dlog_swapped",
        DLogProofValidation,
        lambda j: (
            lambda h1, h2: (
                setattr(j, "composite_dlog_proof_base_h1", h2),
                setattr(j, "composite_dlog_proof_base_h2", h1),
            )
        )(j.composite_dlog_proof_base_h1, j.composite_dlog_proof_base_h2),
    ),
    (
        "ek_too_small",
        (PaillierVerificationError, ModuliTooSmall),
        lambda j: setattr(j, "ek", type(j.ek).from_n((1 << 520) + 21)),
    ),
    (
        "ring_pedersen_Z",
        RingPedersenProofError,
        lambda j: j.ring_pedersen_proof.Z.__setitem__(
            0, j.ring_pedersen_proof.Z[0] + 1
        ),
    ),
]


@pytest.mark.parametrize("name,err,mutate", CASES, ids=[c[0] for c in CASES])
def test_tampered_join_rejected(join_round, test_config, name, err, mutate):
    with pytest.raises(err):
        _collect_with_join(join_round, test_config, mutate)


@pytest.mark.parametrize("name,err,mutate", CASES, ids=[c[0] for c in CASES])
def test_rlc_join_verdicts_identical(
    join_round, test_config, monkeypatch, name, err, mutate
):
    """FSDKR_RLC A/B over the join tamper matrix on the batched backend:
    the RLC-folded families a join exercises (correct-key,
    ring-Pedersen) and the unfolded composite-dlog path must raise the
    same identifiable-abort error (type + party attribution) in both
    legs — the bisection fallback preserves exact blame."""
    monkeypatch.setenv("FSDKR_DEVICE_POWM", "0")
    monkeypatch.setenv("FSDKR_DEVICE_EC", "0")
    seen = {}
    for leg in ("0", "1"):
        monkeypatch.setenv("FSDKR_RLC", leg)
        with pytest.raises(err) as ei:
            _collect_with_join(
                join_round, test_config.with_backend("tpu"), mutate
            )
        seen[leg] = (
            type(ei.value).__name__,
            getattr(ei.value, "party_index", None),
        )
    assert seen["0"] == seen["1"]


def test_honest_join_accepted(join_round, test_config):
    """Baseline: the fixture's join is genuinely valid, and the new
    party derives a working LocalKey whose share matches the committee."""
    keys, msgs, dks, join_msg, pair = join_round
    _collect_with_join(join_round, test_config, lambda j: None)
    new_key = join_msg.collect(
        copy.deepcopy(msgs), pair, (join_msg,), 1, 4, test_config
    )
    assert new_key.i == 4
    from fsdkr_tpu.core.secp256k1 import GENERATOR

    assert GENERATOR * new_key.keys_linear.x_i == new_key.pk_vec[3]
    assert new_key.y_sum_s == keys[0].y_sum_s


assert _JM is JoinMessage  # module wiring sanity
