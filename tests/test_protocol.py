"""Integration tests of the refresh protocol, mirroring the reference suite
(`/root/reference/src/test.rs`): reconstruct-equality (test1),
sign→rotate→sign, removal, and add-party-with-permute (SURVEY.md §4 item 2).

All scenarios run at TEST_CONFIG sizes (768-bit Paillier, M=32) on the host
backend; kernel-vs-oracle and full-size runs live elsewhere.
"""

import pytest

from fsdkr_tpu.config import TEST_CONFIG
from fsdkr_tpu.core import vss
from fsdkr_tpu.errors import FsDkrError, PartiesThresholdViolation
from fsdkr_tpu.protocol import (
    JoinMessage,
    RefreshMessage,
    simulate_dkr,
    simulate_dkr_removal,
    simulate_keygen,
    simulate_offline_stage,
    simulate_signing,
)

CFG = TEST_CONFIG


def reconstruct_from(keys, t, n, count):
    params = vss.ShamirSecretSharing(t, n)
    shares = [k.keys_linear.x_i for k in keys[:count]]
    return vss.reconstruct(params, list(range(count)), shares)


class TestRefresh:
    def test1_reconstruct_equality(self):
        """Same secret, new shares (reference src/test.rs:34-67)."""
        t, n = 2, 5
        keys = simulate_keygen(t, n, CFG)
        old_x = [k.keys_linear.x_i for k in keys]
        old_secret = reconstruct_from(keys, t, n, t + 1)

        simulate_dkr(keys, CFG)

        new_x = [k.keys_linear.x_i for k in keys]
        new_secret = reconstruct_from(keys, t, n, t + 1)
        assert old_secret.v == new_secret.v
        assert [s.v for s in old_x] != [s.v for s in new_x]

    def test_pk_vec_length_pinned(self):
        """Regression pin for reference quirk 1 (Vec::insert): pk_vec stays
        exactly n after refresh, and matches x_i*G per party."""
        from fsdkr_tpu.core.secp256k1 import GENERATOR

        t, n = 1, 3
        keys = simulate_keygen(t, n, CFG)
        simulate_dkr(keys, CFG)
        for k in keys:
            assert len(k.pk_vec) == n
            # the rebuilt X_j must be consistent across parties and match
            # each party's own refreshed share
            assert k.pk_vec[k.i - 1] == GENERATOR * k.keys_linear.x_i

    def test_distribute_threshold_guards(self):
        t, n = 2, 5
        keys = simulate_keygen(t, n, CFG)
        # t > new_n/2 must error (conscious fix of reference panic, quirk 2)
        with pytest.raises(PartiesThresholdViolation):
            RefreshMessage.distribute(keys[0].i, keys[0], 3, CFG)

    def test_collect_requires_threshold_plus_one(self):
        t, n = 2, 5
        keys = simulate_keygen(t, n, CFG)
        msgs, dks = [], []
        for key in keys:
            m, dk = RefreshMessage.distribute(key.i, key, n, CFG)
            msgs.append(m)
            dks.append(dk)
        with pytest.raises(PartiesThresholdViolation):
            RefreshMessage.collect(msgs[:t], keys[0], dks[0], (), CFG)


class TestSignRotateSign:
    def test_sign_rotate_sign(self):
        """(reference src/test.rs:69-80)"""
        keys = simulate_keygen(2, 5, CFG)
        simulate_signing(simulate_offline_stage(keys, [1, 2, 3]), b"ZenGo")
        simulate_dkr(keys, CFG)
        simulate_signing(simulate_offline_stage(keys, [2, 3, 4]), b"ZenGo")
        simulate_dkr(keys, CFG)
        simulate_signing(simulate_offline_stage(keys, [1, 3, 5]), b"ZenGo")

    def test_remove_sign_rotate_sign(self):
        """(reference src/test.rs:82-93)"""
        keys = simulate_keygen(2, 5, CFG)
        simulate_signing(simulate_offline_stage(keys, [1, 2, 3]), b"ZenGo")
        simulate_dkr_removal(keys, [1], CFG)
        simulate_signing(simulate_offline_stage(keys, [2, 3, 4]), b"ZenGo")
        simulate_dkr_removal(keys, [1, 2], CFG)
        simulate_signing(simulate_offline_stage(keys, [3, 4, 5]), b"ZenGo")


class TestAddPartyWithPermute:
    def test_add_party_with_permute(self):
        """Remove parties 2 and 7 of a (2,7) committee, permute survivors,
        add two fresh parties at indices 2 and 7, rotate, then sign with a
        quorum containing both fresh parties (reference src/test.rs:95-224)."""
        t, n = 2, 7
        all_keys = simulate_keygen(t, n, CFG)
        old_secret = reconstruct_from(all_keys, t, n, t + 1)

        keys = [k for k in all_keys if k.i not in (2, 7)]
        old_to_new_map = {1: 4, 3: 1, 4: 3, 5: 6, 6: 5}

        # two new parties generate join messages, assigned indices 2 and 7
        join_messages = []
        new_pairs = []
        for idx in (2, 7):
            jm, pair = JoinMessage.distribute(CFG)
            jm.set_party_index(idx)
            join_messages.append(jm)
            new_pairs.append(pair)

        # all existing parties run replace (state surgery + distribute)
        refresh_messages, dks = [], []
        for key in keys:
            m, dk = RefreshMessage.replace(join_messages, key, old_to_new_map, n, CFG)
            refresh_messages.append(m)
            dks.append(dk)

        # existing parties collect
        new_keys = []
        for key, dk in zip(keys, dks):
            RefreshMessage.collect(refresh_messages, key, dk, join_messages, CFG)
            new_keys.append((key.i, key))

        # new parties derive their LocalKeys
        for jm, pair in zip(join_messages, new_pairs):
            lk = jm.collect(refresh_messages, pair, join_messages, t, n, CFG)
            new_keys.append((lk.i, lk))

        new_keys.sort(key=lambda e: e[0])
        keys = [k for _, k in new_keys]
        assert [k.i for k in keys] == list(range(1, n + 1))

        new_secret = reconstruct_from(keys, t, n, t + 1)
        assert old_secret.v == new_secret.v

        # quorum includes both fresh parties (indices 2 and 7)
        simulate_signing(simulate_offline_stage(keys, [1, 2, 7]), b"ZenGo")


class TestWireTamper:
    def test_inconsistent_public_key_rejected(self):
        """A sender broadcasting a wrong group public_key must be rejected
        by existing-party collect, not just by joiners (hardening beyond
        reference quirk 5: add_party_message.rs:268-274 gates only the
        join path)."""
        from fsdkr_tpu.core.secp256k1 import GENERATOR
        from fsdkr_tpu.errors import BroadcastedPublicKeyError

        t, n = 1, 3
        keys = simulate_keygen(t, n, CFG)
        msgs, dks = [], []
        for key in keys:
            m, dk = RefreshMessage.distribute(key.i, key, n, CFG)
            msgs.append(m)
            dks.append(dk)
        msgs[1].public_key = msgs[1].public_key + GENERATOR  # lie
        with pytest.raises(BroadcastedPublicKeyError) as ei:
            RefreshMessage.collect(msgs, keys[0], dks[0], (), CFG)
        assert ei.value.party_index == msgs[1].party_index  # culprit named

    def test_lying_old_party_index_rejected(self):
        """Regression pin for reference quirk 4: the TODO at
        src/refresh_message.rs:199 leaves the broadcast old_party_index
        untrusted-but-unchecked, so a sender lying about its old index
        reweights the Lagrange combination and would silently rotate the
        committee onto a DIFFERENT secret. This rebuild's hardening gate
        (interpolate_constant_term in protocol/refresh.py: the weighted
        Feldman constant terms must re-derive the unchanged group key)
        must abort with PublicShareValidationError instead."""
        from fsdkr_tpu.errors import PublicShareValidationError

        t, n = 1, 3
        keys = simulate_keygen(t, n, CFG)
        msgs, dks = [], []
        for key in keys:
            m, dk = RefreshMessage.distribute(key.i, key, n, CFG)
            msgs.append(m)
            dks.append(dk)
        # swap the first two senders' old indices: both values stay
        # individually plausible (distinct, in range), only the
        # attribution lies — exactly the case the reference TODO admits
        msgs[0].old_party_index, msgs[1].old_party_index = (
            msgs[1].old_party_index,
            msgs[0].old_party_index,
        )
        with pytest.raises(PublicShareValidationError):
            RefreshMessage.collect(msgs, keys[2].clone(), dks[2], (), CFG)

    def test_tampered_ciphertext_detected(self):
        """A malicious sender mutating an encrypted share must be caught by
        the proof batch (identifiable abort)."""
        t, n = 1, 3
        keys = simulate_keygen(t, n, CFG)
        msgs, dks = [], []
        for key in keys:
            m, dk = RefreshMessage.distribute(key.i, key, n, CFG)
            msgs.append(m)
            dks.append(dk)
        msgs[1].points_encrypted_vec[0] += 1  # tamper
        with pytest.raises(FsDkrError):
            RefreshMessage.collect(msgs, keys[0], dks[0], (), CFG)


@pytest.mark.slow
def test_full_size_refresh_end_to_end():
    """One complete refresh at the reference's production parameters
    (2048-bit Paillier, M=256 ring-Pedersen, 11 correct-key rounds,
    `/root/reference/src/lib.rs:26-27`) through the batched TPU backend:
    secret preserved, shares rotated. Minutes on the single-core CPU
    platform — excluded from quick runs, the bench path exercises the
    same parameters on the real chip."""
    from fsdkr_tpu.config import ProtocolConfig

    cfg = ProtocolConfig()  # full-size defaults
    tpu = cfg.with_backend("tpu")
    t, n = 1, 3
    keys = simulate_keygen(t, n, cfg)
    old = [k.keys_linear.x_i for k in keys]

    simulate_dkr(keys, tpu)

    params = vss.ShamirSecretSharing(t, n)
    new = [k.keys_linear.x_i for k in keys]
    assert (
        vss.reconstruct(params, [0, 1], old[:2]).v
        == vss.reconstruct(params, [1, 2], new[1:]).v
    )
    assert all(o != w for o, w in zip(old, new))
