"""Differential tests: native secp256k1 core (csrc/fsdkr_ec.cpp via
fsdkr_tpu.native.ec) against the pure-Python Jacobian oracle
(fsdkr_tpu.core.secp256k1). The oracle stays native-free by design —
these tests are the bridge's correctness anchor."""

import secrets

import pytest

from fsdkr_tpu.core import secp256k1 as E
from fsdkr_tpu.core import vss
from fsdkr_tpu.native import ec as native_ec

pytestmark = pytest.mark.skipif(
    not native_ec.available(), reason="native EC core unavailable"
)

Q = E.CURVE_ORDER
G = E.GENERATOR


def rand_point():
    return G * E.Scalar.from_int(secrets.randbelow(Q - 1) + 1)


def as_xy(p):
    return None if p.infinity else (p.x, p.y)


class TestScalarMul:
    def test_differential_including_edges(self):
        pts, scs, want = [], [], []
        for s in [0, 1, 2, Q - 1, Q // 2, secrets.randbelow(Q)]:
            P = rand_point()
            pts.append(as_xy(P))
            scs.append(s)
            want.append(as_xy(P * E.Scalar.from_int(s)))
        pts.append(None)  # identity input
        scs.append(12345)
        want.append(None)
        assert native_ec.scalar_mul_batch(pts, scs) == want


class TestHorner:
    def test_matches_python_horner(self):
        commits = [rand_point() for _ in range(9)]
        idxs = [1, 2, 7, 255, 65535]
        want = []
        for u in idxs:
            acc = E.Point.identity()
            for a_k in reversed(commits):
                acc = acc * u + a_k
            want.append(as_xy(acc))
        got = native_ec.horner_batch([as_xy(c) for c in commits], idxs)
        assert got == want

    def test_index_overflow_returns_none(self):
        commits = [as_xy(rand_point())]
        assert native_ec.horner_batch(commits, [1 << 32]) is None


class TestLincomb2:
    def test_matches_python(self):
        P, Qp = rand_point(), rand_point()
        a = [0, 1, secrets.randbelow(Q), Q - 1]
        b = [secrets.randbelow(Q), 0, secrets.randbelow(Q), 1]
        want = [
            as_xy(P * E.Scalar.from_int(ai) + Qp * E.Scalar.from_int(bi))
            for ai, bi in zip(a, b)
        ]
        got = native_ec.lincomb2_batch(
            [as_xy(P)] * 4, a, [as_xy(Qp)] * 4, b
        )
        assert got == want


class TestFeldmanRouting:
    def test_host_backend_matches_oracle_and_rejects_tamper(self):
        """HostBatchVerifier.validate_feldman (native-routed) must agree
        with vss.validate_share_public (pure Python) on valid shares and
        on a tampered one."""
        from fsdkr_tpu.backend.batch_verifier import HostBatchVerifier

        t, n = 3, 8
        secret = E.Scalar.from_int(secrets.randbelow(Q - 1) + 1)
        scheme, shares = vss.share(t, n, secret)
        pub = [G * s for s in shares]
        items = [(scheme, pub[i], i + 1) for i in range(n)]
        # tamper one public share
        items.append((scheme, pub[0] + G, 2))
        got = HostBatchVerifier().validate_feldman(items)
        want = [
            scheme.validate_share_public(point, idx)
            for scheme, point, idx in items
        ]
        assert got == want
        assert got[:n] == [True] * n and got[n] is False
