"""Secret-CRT prover engine (FSDKR_CRT, backend/crt.py) + GMP bridge.

Pins the four contracts of the CRT tentpole:
- PARITY: the decomposition is an arithmetic identity — proofs and
  transcripts are bit-identical between FSDKR_CRT=0 and =1 for every
  prover entry point (ring-Pedersen gen+prove, correct-key, Paillier
  decrypt), including Garner edge cases (adjacent primes, unbalanced
  leg widths).
- FAULT CHECK: a corrupted CRT leg aborts with CrtFaultError before any
  recombined value is emitted (Bellcore/BDL: a faulted output would
  leak a factor through one gcd).
- SECRET-STORE ISOLATION: factorization-derived integers (p, q, leg
  orders, the Garner coefficient) never appear in the public precompute
  LRU — they live only in the per-session secret store.
- ENGINE EQUIVALENCE: the GMP bridge (native/gmp.py) agrees with
  CPython pow on every edge shape, and all engines agree at any
  FSDKR_THREADS setting (thread additions live in test_thread_parity).

This file must stay green with FSDKR_CRT=0 and/or FSDKR_GMP=0 forced
from the environment (scripts/ci.sh runs that leg): tests pin their own
gate values via monkeypatch.
"""

import math
import random
import secrets as _secrets

import pytest

from fsdkr_tpu import native
from fsdkr_tpu.backend import crt
from fsdkr_tpu.backend.powm import crt_powm
from fsdkr_tpu.core import paillier, primes
from fsdkr_tpu.errors import CrtFaultError
from fsdkr_tpu.native import gmp

RNG = random.Random(0xC127)


class _SeededSecrets:
    """Deterministic stand-in for a proof module's `secrets` import, so
    the FSDKR_CRT=0/1 arms sample identical nonces and the proof bytes
    can be compared bit-for-bit."""

    def __init__(self, seed):
        self._rng = random.Random(seed)

    def randbelow(self, bound):
        return self._rng.randrange(bound)

    def randbits(self, k):
        return self._rng.getrandbits(k)


def _modulus(bits=512):
    return primes.gen_modulus(bits)


def _next_prime(x):
    c = x + 2
    while not primes.is_probable_prime(c, 16):
        c += 2
    return c


# ---------------------------------------------------------------------------
# engine parity


def test_crt_modexp_parity_plain_and_square():
    n, p, q = _modulus(512)
    ctx = crt.get_context(n, p, q)
    ctx2 = crt.get_context(n * n, p, q)
    bs = [RNG.randrange(1, n) | 1 for _ in range(5)]
    es = [RNG.getrandbits(w) for w in (1, 64, 511, 700, 0)]
    assert crt.crt_modexp_batch(bs, es, [ctx] * 5) == [
        pow(b, e, n) for b, e in zip(bs, es)
    ]
    bs2 = [RNG.randrange(1, n * n) | 1 for _ in range(3)]
    es2 = [RNG.getrandbits(1024) for _ in range(3)]
    assert crt.crt_modexp_batch(bs2, es2, [ctx2] * 3) == [
        pow(b, e, n * n) for b, e in zip(bs2, es2)
    ]


def test_crt_garner_adjacent_primes():
    # p ~ q (consecutive primes): Garner's (xp - xq) * qinv mod p leg is
    # maximally collision-prone here; must stay exact
    p = primes.gen_prime(256)
    q = _next_prime(p)
    n = p * q
    ctx = crt.get_context(n, p, q)
    bs = [RNG.randrange(2, n) for _ in range(4)]
    es = [RNG.getrandbits(512) for _ in range(4)]
    got = crt.crt_modexp_batch(bs, es, [ctx] * 4)
    assert got == [pow(b, e, n) for b, e in zip(bs, es)]


def test_crt_garner_unbalanced_widths():
    # one 192-bit and one 640-bit factor: leg limb widths differ 3x
    p = primes.gen_prime(192)
    q = primes.gen_prime(640)
    n = p * q
    ctx = crt.get_context(n, p, q)
    b = RNG.randrange(2, n)
    e = RNG.getrandbits(832)
    assert crt.crt_modexp_batch([b], [e], [ctx]) == [pow(b, e, n)]
    ctx2 = crt.get_context(n * n, p, q)
    assert crt.crt_modexp_batch([b], [e], [ctx2]) == [pow(b, e, n * n)]


def test_crt_context_rejects_bad_factorizations():
    p = primes.gen_prime(128)
    q = primes.gen_prime(128)
    with pytest.raises(ValueError):
        crt.CrtContext(p * p, p, p)  # p == q: no CRT split exists
    with pytest.raises(ValueError):
        crt.CrtContext(p * q + 2, p, q)  # not the product
    with pytest.raises(ValueError):
        crt.CrtContext((p * q) ** 2 + 1, p, q)


def test_crt_non_unit_base_falls_back_exactly():
    n, p, q = _modulus(384)
    ctx = crt.get_context(n, p, q)
    rows = [(p, 17), (2 * q, 33), (RNG.randrange(2, n) | 1, 129)]
    got = crt.crt_modexp_batch(
        [b for b, _ in rows], [e for _, e in rows], [ctx] * 3
    )
    assert got == [pow(b, e, n) for b, e in rows]


def test_crt_powm_planner_route(monkeypatch):
    n, p, q = _modulus(384)
    bs = [RNG.randrange(2, n) for _ in range(4)]
    es = [RNG.getrandbits(384) for _ in range(4)]
    want = [pow(b, e, n) for b, e in zip(bs, es)]
    monkeypatch.setenv("FSDKR_CRT", "1")
    assert crt_powm(bs, es, [n] * 4, [(p, q), None, (p, q), None]) == want
    monkeypatch.setenv("FSDKR_CRT", "0")
    assert crt_powm(bs, es, [n] * 4, [(p, q)] * 4) == want


def test_crt_powm_shared_parity():
    n, p, q = _modulus(512)
    ctx = crt.get_context(n, p, q)
    base = pow(RNG.randrange(2, n), 2, n)
    exps = [0, 1, (p - 1) * (q - 1) - 1] + [
        RNG.getrandbits(512) for _ in range(8)
    ]
    assert crt.crt_powm_shared(base, exps, ctx) == [
        pow(base, e, n) for e in exps
    ]


# ---------------------------------------------------------------------------
# prover entry points: bit-identical FSDKR_CRT=0 vs =1


def test_ring_pedersen_prove_bit_identical(monkeypatch):
    from fsdkr_tpu.proofs import ring_pedersen as rp_mod
    from fsdkr_tpu.proofs.ring_pedersen import (
        RingPedersenProof,
        RingPedersenStatement,
        RingPedersenWitness,
    )
    from fsdkr_tpu.core.paillier import EncryptionKey

    stmts, wits = [], []
    for n, p, q in primes.gen_moduli_batch(512, 2):
        phi = (p - 1) * (q - 1)
        lam = RNG.randrange(phi)
        t = pow(RNG.randrange(2, n), 2, n)
        stmts.append(
            RingPedersenStatement(
                S=pow(t, lam, n), T=t, N=n, ek=EncryptionKey.from_n(n)
            )
        )
        wits.append(RingPedersenWitness(p=p, q=q, lam=lam, phi=phi))

    arms = {}
    for gate in ("1", "0"):
        monkeypatch.setenv("FSDKR_CRT", gate)
        monkeypatch.setattr(rp_mod, "secrets", _SeededSecrets(0xABCD))
        arms[gate] = RingPedersenProof.prove_batch(wits, stmts, 16)
    monkeypatch.setattr(rp_mod, "secrets", _secrets)
    assert [(pf.A, pf.Z) for pf in arms["1"]] == [
        (pf.A, pf.Z) for pf in arms["0"]
    ]
    for pf, st in zip(arms["1"], stmts):
        pf.verify(st, 16)


def test_ring_pedersen_generate_crt_verifies(monkeypatch):
    from fsdkr_tpu.config import TEST_CONFIG
    from fsdkr_tpu.proofs.ring_pedersen import (
        RingPedersenProof,
        RingPedersenStatement,
    )

    monkeypatch.setenv("FSDKR_CRT", "1")
    st, w = RingPedersenStatement.generate_batch(1, TEST_CONFIG)[0]
    assert st.S == pow(st.T, w.lam, st.N)  # CRT-computed S is exact
    proof = RingPedersenProof.prove(w, st, 16)
    proof.verify(st, 16)


def test_correct_key_bit_identical(monkeypatch):
    # the correct-key prover is deterministic given dk (Fiat-Shamir
    # bases, fixed exponent d): the two arms must agree byte-for-byte
    from fsdkr_tpu.proofs.correct_key import NiCorrectKeyProof

    n, p, q = _modulus(768)
    dk = paillier.DecryptionKey(p=p, q=q)
    ek = paillier.EncryptionKey.from_n(n)
    arms = {}
    for gate in ("1", "0"):
        monkeypatch.setenv("FSDKR_CRT", gate)
        arms[gate] = NiCorrectKeyProof.proof_batch([dk], rounds=3)[0]
    assert arms["1"].sigma_vec == arms["0"].sigma_vec
    assert arms["1"].verify(ek, rounds=3)


def test_paillier_decrypt_bit_identical(monkeypatch):
    ek, dk = paillier.keygen(768)
    m = RNG.randrange(1 << 64)
    c = paillier.encrypt(ek, m)
    outs = {}
    for gate in ("1", "0"):
        monkeypatch.setenv("FSDKR_CRT", gate)
        outs[gate] = paillier.decrypt(dk, ek, c)
    assert outs["1"] == outs["0"] == m


# ---------------------------------------------------------------------------
# fault trip wire: a corrupted leg ABORTS, never emits a value


def _corrupting(fn, bump_row):
    def wrapped(bases, exps, mods):
        out = fn(bases, exps, mods)
        out[bump_row] = (out[bump_row] + 1) % mods[bump_row]
        return out

    return wrapped


def test_fault_check_trips_on_corrupted_leg(monkeypatch):
    n, p, q = _modulus(384)
    ctx = crt.get_context(n, p, q)
    bs = [RNG.randrange(2, n) | 1 for _ in range(3)]
    es = [RNG.getrandbits(384) for _ in range(3)]
    real = crt._leg_powm
    for bad_leg in (0, 4):  # a p-leg and a q-leg
        monkeypatch.setattr(crt, "_leg_powm", _corrupting(real, bad_leg))
        with pytest.raises(CrtFaultError):
            crt.crt_modexp_batch(bs, es, [ctx] * 3)
    monkeypatch.setattr(crt, "_leg_powm", real)


def test_fault_check_trips_in_shared_and_single(monkeypatch):
    n, p, q = _modulus(384)
    ctx = crt.get_context(n, p, q)

    real = native.modexp_shared

    def corrupted_shared(base, exps, mod, cache=True):
        out = real(base, exps, mod, cache=cache)
        out[1] = (out[1] + 1) % mod
        return out

    monkeypatch.setattr(native, "modexp_shared", corrupted_shared)
    with pytest.raises(CrtFaultError):
        crt.crt_powm_shared(
            pow(RNG.randrange(2, n), 2, n),
            [RNG.getrandbits(256) for _ in range(4)],
            ctx,
        )
    monkeypatch.undo()

    monkeypatch.setattr(crt, "_leg_powm", _corrupting(crt._leg_powm, 0))
    with pytest.raises(CrtFaultError):
        crt.fault_checked_powm(RNG.randrange(2, p) | 1, p - 1, p * p)


def test_faulted_decrypt_aborts_not_wrong(monkeypatch):
    ek, dk = paillier.keygen(768)
    c = paillier.encrypt(ek, 42)
    monkeypatch.setenv("FSDKR_CRT", "1")
    monkeypatch.setattr(crt, "_leg_powm", _corrupting(crt._leg_powm, 0))
    with pytest.raises(CrtFaultError):
        paillier.decrypt(dk, ek, c)


# ---------------------------------------------------------------------------
# secret store: bounded, wiped, and isolated from the public LRU


def test_secret_store_isolation_from_public_lru(monkeypatch):
    from fsdkr_tpu.utils import lru

    monkeypatch.setenv("FSDKR_CRT", "1")
    lru.clear_caches()
    crt.clear_store()

    n, p, q = _modulus(512)
    ctx = crt.get_context(n, p, q)
    secret_ints = {
        p, q, ctx.d_p, ctx.d_q, ctx.qinv, p * p, q * q,
        p - 1, q - 1, p * (p - 1), q * (q - 1),
    }
    # run every CRT path, plus a cacheable PUBLIC comb for contrast
    crt.crt_modexp_batch(
        [RNG.randrange(2, n) | 1], [RNG.getrandbits(512)], [ctx]
    )
    crt.crt_powm_shared(
        pow(RNG.randrange(2, n), 2, n),
        [RNG.getrandbits(512) for _ in range(4)],
        ctx,
    )
    crt.fault_checked_powm(RNG.randrange(2, p) | 1, p - 1, p * p)
    native.modexp_shared(3, [RNG.getrandbits(256) for _ in range(4)], n)

    cache = lru.global_cache()
    seen_public_comb = False
    for key in list(cache._d.keys()):
        for part in key:
            assert not (
                isinstance(part, int) and part in secret_ints
            ), f"secret-derived integer leaked into public LRU key {key!r}"
        if key[0] == "native-comb":
            seen_public_comb = True
    assert seen_public_comb  # the public path DID cache, isolation is real

    assert crt.store_stats()["entries"] >= 1
    crt.clear_store()
    assert crt.store_stats()["entries"] == 0
    assert ctx.p_leg == 0 and ctx.qinv == 0  # wiped, not just dropped


def test_check_prime_helper():
    assert crt._is_prime64((1 << 61) - 1)
    assert not crt._is_prime64((1 << 62) - 1)
    r = crt._fresh_check_prime([123456789])
    assert r.bit_length() == 64 and crt._is_prime64(r)


# ---------------------------------------------------------------------------
# GMP bridge: edge parity with CPython pow


@pytest.mark.skipif(not gmp.available(), reason="GMP bridge unavailable")
def test_gmp_powm_edges():
    m = RNG.getrandbits(512) | (1 << 511) | 1
    b, e = RNG.randrange(m), RNG.getrandbits(512)
    assert gmp.powm(b, e, m) == pow(b, e, m)
    assert gmp.powm(b, e, m, secret=True) == pow(b, e, m)
    assert gmp.powm(b, 0, m) == 1
    assert gmp.powm(0, 5, m) == 0
    assert gmp.powm(b, e, 2 * m) == pow(b, e, 2 * m)  # even modulus
    assert gmp.powm(5, 3, 1) == 0
    # negative exponent rides the pow fallback: EXACT behavior parity —
    # same inverse when it exists, same ValueError when it does not
    # (b is a random draw, so both outcomes occur across runs)
    try:
        want_inv = pow(b, -1, m)
    except ValueError:
        with pytest.raises(ValueError):
            gmp.powm(b, -1, m)
    else:
        assert gmp.powm(b, -1, m) == want_inv
    assert gmp.powm(3, -1, 65537) == pow(3, -1, 65537)
    assert gmp.powm_batch([b, 0, b], [e, 7, 1], [m, m, m]) == [
        pow(b, e, m), 0, b % m,
    ]


@pytest.mark.skipif(not gmp.available(), reason="GMP bridge unavailable")
def test_gmp_gcd_and_cached_operand():
    wide = primes._wide_primorial()
    op = gmp.PublicOperand(wide)
    for _ in range(20):
        c = RNG.getrandbits(256) | 1
        assert gmp.gcd(c, op) == math.gcd(c, wide)
        assert gmp.gcd(c, wide) == math.gcd(c, wide)


def test_gmp_gate_off(monkeypatch):
    monkeypatch.setenv("FSDKR_GMP", "0")
    assert not gmp.available()
    m = RNG.getrandbits(256) | 1
    assert gmp.powm(3, 5, m) == pow(3, 5, m)  # pure fallback still exact


# ---------------------------------------------------------------------------
# batched prime pipeline


def test_gen_primes_batch_shape_and_primality():
    ps = primes.gen_primes_batch(192, 3)
    assert len(ps) == 3
    for p in ps:
        assert p.bit_length() == 192
        assert (p >> 190) == 0b11  # top two bits forced
        assert primes.is_probable_prime(p, 20)


def test_gen_moduli_batch():
    for n, p, q in primes.gen_moduli_batch(384, 2):
        assert n == p * q and p != q and n.bit_length() == 384


def test_native_mr_batch_agrees_with_oracle():
    cases = [2**89 - 1, 561, (2**61 - 1) * (2**31 - 1), 2**107 - 1]
    got = native.is_probable_prime_batch(cases, 16)
    if got is None:
        pytest.skip("native core unavailable")
    assert got == [True, False, False, True]
