"""FSDKR_DELEGATE — Feldman-MSM delegation A/B discipline (ISSUE 17
tentpole (c), proofs/msm_delegate.py).

The arm is gated on bit-identical verdicts in both knob positions, on
honest AND tampered transcripts: a certificate can only ever
short-circuit a scheme whose rows all pass, and every failure mode
(forged certificate, missing certificate, tampered commitments or
share points) demotes its scheme to the honest per-row path. The
delegated verifier's measured group-op count must sit strictly below
the honest arm's op model — the whole point of outsourcing the MSM.
"""

import dataclasses

import pytest

from fsdkr_tpu.core.secp256k1 import GENERATOR
from fsdkr_tpu.proofs import msm_delegate
from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen
from fsdkr_tpu.protocol.serialization import (
    local_key_to_json,
    refresh_message_from_json,
    refresh_message_to_json,
)


def _distribute(cfg, monkeypatch, delegate="1", t=1, n=3):
    monkeypatch.setenv("FSDKR_DELEGATE", delegate)
    keys = simulate_keygen(t, n, cfg)
    res = RefreshMessage.distribute_batch([(k.i, k) for k in keys], n, cfg)
    return keys, [m for m, _ in res], [dk for _, dk in res]


def _collect(cfg, keys, msgs, dks):
    k = keys[0].clone()
    err = RefreshMessage.collect_sessions([(msgs, k, dks[0], ())], cfg)[0]
    return err, local_key_to_json(k)


# the tpu-backend variant cold-compiles the whole batched collect
# pipeline (~3.5 min on the fallback platform), so it rides the slow
# lane; scripts/ci.sh's fusion leg covers tpu-backend delegate A/B at
# the fast 640-bit shape on every CI run.
@pytest.mark.parametrize(
    "backend", ["host", pytest.param("tpu", marks=pytest.mark.slow)]
)
def test_verdict_parity_honest(test_config, monkeypatch, backend):
    """Certs emitted at distribute; collect agrees in both knob
    positions, rows actually ride the certificate when enabled."""
    cfg = test_config.with_backend(backend)
    keys, msgs, dks = _distribute(cfg, monkeypatch)
    assert all(
        m.coefficients_committed_vec.delegate_cert is not None for m in msgs
    )

    msm_delegate.stats_reset()
    err_on, state_on = _collect(cfg, keys, msgs, dks)
    st = msm_delegate.stats()
    assert err_on is None
    assert st["schemes_delegated"] == len(msgs)
    assert st["rows_delegated"] > 0 and st["certs_rejected"] == 0

    monkeypatch.setenv("FSDKR_DELEGATE", "0")
    msm_delegate.stats_reset()
    err_off, state_off = _collect(cfg, keys, msgs, dks)
    assert err_off is None
    assert msm_delegate.stats()["schemes_delegated"] == 0
    assert state_on == state_off


def test_verdict_parity_tampered(test_config, monkeypatch):
    """A tampered commitment vector fails identically in both arms —
    the broken certificate check demotes the scheme to the honest path,
    which raises exactly the honest arm's error."""
    cfg = test_config
    keys, msgs, dks = _distribute(cfg, monkeypatch)
    vss = msgs[1].coefficients_committed_vec
    bad_commits = list(vss.commitments)
    bad_commits[0] = bad_commits[0] + GENERATOR
    msgs_bad = list(msgs)
    msgs_bad[1] = dataclasses.replace(
        msgs[1],
        coefficients_committed_vec=dataclasses.replace(
            vss, commitments=bad_commits
        ),
    )

    msm_delegate.stats_reset()
    err_on, _ = _collect(cfg, keys, msgs_bad, dks)
    assert msm_delegate.stats()["certs_rejected"] >= 1

    monkeypatch.setenv("FSDKR_DELEGATE", "0")
    err_off, _ = _collect(cfg, keys, msgs_bad, dks)
    assert err_on is not None and err_off is not None
    assert type(err_on) is type(err_off)
    assert str(err_on) == str(err_off)


def test_forged_certificate_rejected(test_config, monkeypatch):
    """A forged certificate point never resolves rows: the scheme falls
    back to the honest path (counted), and because the underlying rows
    are honest the verdict stays clean — structural bit-identity."""
    cfg = test_config
    keys, msgs, dks = _distribute(cfg, monkeypatch)
    vss = msgs[1].coefficients_committed_vec
    msgs_forged = list(msgs)
    msgs_forged[1] = dataclasses.replace(
        msgs[1],
        coefficients_committed_vec=dataclasses.replace(
            vss, delegate_cert=GENERATOR * 0xDEADBEEF
        ),
    )

    msm_delegate.stats_reset()
    err, _ = _collect(cfg, keys, msgs_forged, dks)
    st = msm_delegate.stats()
    assert err is None
    assert st["certs_rejected"] == 1
    assert st["fallback_rows"] > 0
    assert st["schemes_delegated"] == len(msgs) - 1


def test_missing_certificate_falls_back(test_config, monkeypatch):
    """Distribute with the arm off, collect with it on: no certs on the
    wire, every scheme rides the honest path, verdict clean."""
    cfg = test_config
    keys, msgs, dks = _distribute(cfg, monkeypatch, delegate="0")
    assert all(
        m.coefficients_committed_vec.delegate_cert is None for m in msgs
    )
    monkeypatch.setenv("FSDKR_DELEGATE", "1")
    msm_delegate.stats_reset()
    err, _ = _collect(cfg, keys, msgs, dks)
    st = msm_delegate.stats()
    assert err is None
    assert st["schemes_delegated"] == 0 and st["fallback_rows"] > 0


def test_cert_survives_wire(test_config, monkeypatch):
    """The certificate rides the canonical VSS encoding; a cert-free
    message byte-matches the pre-delegation encoding."""
    cfg = test_config
    keys, msgs, dks = _distribute(cfg, monkeypatch)
    rt = [refresh_message_from_json(refresh_message_to_json(m)) for m in msgs]
    assert all(
        m.coefficients_committed_vec.delegate_cert
        == r.coefficients_committed_vec.delegate_cert
        for m, r in zip(msgs, rt)
    )
    msm_delegate.stats_reset()
    err, _ = _collect(cfg, keys, rt, dks)
    assert err is None
    assert msm_delegate.stats()["schemes_delegated"] == len(msgs)

    monkeypatch.setenv("FSDKR_DELEGATE", "0")
    _, msgs_plain, _ = _distribute(cfg, monkeypatch, delegate="0")
    enc = refresh_message_to_json(msgs_plain[0])
    assert "delegate_cert" not in enc


def _synthetic_scheme(t, n):
    """Full-parameter Feldman instance without the Paillier protocol
    around it: the delegation economics are pure EC, so the op-count
    inequality is pinned at the paper shape (n=16, t=8) directly."""
    from fsdkr_tpu.core import vss
    from fsdkr_tpu.core.secp256k1 import Scalar

    scheme, shares = vss.share(t, n, Scalar.from_int(0x1234567))
    points = [GENERATOR * s for s in shares]
    return scheme, shares, points


def test_delegated_ops_strictly_below_honest_model(monkeypatch):
    """The acceptance inequality at the fused full-parameter launch
    shape (n=16, t=8, S=4 sessions of one committee): measured group
    ops of the delegated checks < the honest arm's per-row Horner op
    model over the same rows. One certificate check resolves every
    session's duplicate rows of a scheme, while the honest arm
    evaluates all S x n Horner chains — the Feldman-side face of the
    cross-session amortization the pair families get from value dedup.
    (At S=1 the honest arm's tiny <=4-bit scalars make n=16 a near
    wash; the delegate bench JSON publishes both shapes.)"""
    monkeypatch.setenv("FSDKR_DELEGATE", "1")
    t, n, s_sessions = 8, 16, 4
    scheme, shares, points = _synthetic_scheme(t, n)
    msm_delegate.emit_cert(scheme, shares, points)
    items = [
        (scheme, points[u - 1], u)
        for _ in range(s_sessions)
        for u in range(1, n + 1)
    ]
    msm_delegate.stats_reset()
    pre = msm_delegate.try_delegate(items, None)
    assert pre is not None and all(pre)
    measured = msm_delegate.stats()["group_ops"]
    model = msm_delegate.honest_model_ops(items)
    assert 0 < measured < model, (measured, model)
    # the certificate ran once, not once per session
    assert msm_delegate.stats()["schemes_delegated"] == 1
    assert msm_delegate.stats()["rows_delegated"] == s_sessions * n


def test_tampered_share_point_rejected_by_cert(test_config, monkeypatch):
    """Rho binds the share points: editing one S_u re-randomizes every
    coefficient, so the certificate check fails and the honest path
    catches the bad row — never a delegated false accept."""
    cfg = test_config
    keys, msgs, dks = _distribute(cfg, monkeypatch)
    n = len(msgs)
    items = [
        (msg.coefficients_committed_vec, msg.points_committed_vec[i], i + 1)
        for msg in msgs
        for i in range(n)
    ]
    # tamper one claimed share point of scheme 0
    items[1] = (items[1][0], items[1][1] + GENERATOR, items[1][2])
    msm_delegate.stats_reset()
    pre = msm_delegate.try_delegate(items, cfg.hash_alg)
    st = msm_delegate.stats()
    assert pre is not None
    assert all(v is None for v in pre[:n])  # scheme 0 demoted entirely
    assert all(pre[n:])  # untouched schemes still delegate
    assert st["certs_rejected"] == 1
