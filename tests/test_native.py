"""Differential tests for the native host bignum core (csrc/ via ctypes):
the rebuild's equivalent of the reference's GMP layer. Skipped entirely if
the toolchain is unavailable (every caller has a pure-Python fallback)."""

import secrets

import pytest

from fsdkr_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++?)"
)


class TestModexp:
    @pytest.mark.parametrize("bits", [64, 512, 2048, 4096])
    def test_vs_pow(self, bits):
        for _ in range(3):
            n = secrets.randbits(bits) | (1 << (bits - 1)) | 1
            b, e = secrets.randbits(bits), secrets.randbits(bits)
            assert native.modexp(b, e, n) == pow(b, e, n)

    def test_edge_exponents(self):
        n = secrets.randbits(512) | (1 << 511) | 1
        for e in (0, 1, 2, 15, 16, 17, n - 1):
            assert native.modexp(3, e, n) == pow(3, e, n)

    def test_base_reduction(self):
        n = secrets.randbits(256) | (1 << 255) | 1
        assert native.modexp(n + 7, 13, n) == pow(n + 7, 13, n)

    def test_even_modulus_falls_back(self):
        # even moduli are outside Montgomery range: must still be correct
        assert native.modexp(7, 5, 100) == pow(7, 5, 100)

    def test_batch(self):
        mods = [secrets.randbits(1024) | (1 << 1023) | 1 for _ in range(6)]
        bs = [secrets.randbits(1024) for _ in mods]
        es = [secrets.randbits(700) for _ in mods]
        assert native.modexp_batch(bs, es, mods) == [
            pow(b, e, m) for b, e, m in zip(bs, es, mods)
        ]

    def test_batch_length_mismatch(self):
        with pytest.raises(ValueError):
            native.modexp_batch([1, 2], [3], [5, 7])


class TestMillerRabin:
    def test_known_primes(self):
        for p in (2**127 - 1, 2**521 - 1, 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141):
            assert native.is_probable_prime(p, 30) is True

    def test_known_composites(self):
        assert native.is_probable_prime((2**127 - 1) * (2**89 - 1), 30) is False
        # Carmichael number: classic Fermat-test trap
        assert native.is_probable_prime(561, 30) is False

    def test_vs_sympy(self):
        import sympy

        for bits in (64, 256):
            for _ in range(10):
                c = secrets.randbits(bits) | 1 | (1 << (bits - 1))
                assert native.is_probable_prime(c, 30) == sympy.isprime(c)

    def test_primes_module_dispatch(self):
        from fsdkr_tpu.core import primes

        p = primes.gen_prime(256)
        assert native.is_probable_prime(p, 30) is True


class TestModexpShared:
    def test_differential_vs_pow(self):
        """Fixed-base comb vs CPython pow: random, zero, one, full-width
        exponents over one shared (base, modulus)."""
        from fsdkr_tpu import native

        mod = (1 << 1023) * 2 + 12345 * 2 + 1  # odd 1024-bit
        base = 0xDEADBEEF << 512
        exps = [0, 1, 2, 15, 16, (1 << 512) - 3, (1 << 1024) - 1]
        import secrets as _s

        exps += [_s.randbits(1024) for _ in range(9)]
        got = native.modexp_shared(base, exps, mod)
        assert got == [pow(base, e, mod) for e in exps]

    def test_even_modulus_falls_back(self):
        from fsdkr_tpu import native

        assert native.modexp_shared(7, [5, 0], 100) == [
            pow(7, 5, 100), 1,
        ]
