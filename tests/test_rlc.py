"""Cross-proof randomized batch verification (FSDKR_RLC, backend.rlc):
planner/fold algebra, the variable-arity joint-ladder engines, and the
bisection driver.

Collect-level A/B identity and blame attribution live in
tests/test_tamper.py (refresh surface) and tests/test_join_tamper.py
(join surface); this file pins the building blocks at engine level.
"""

import random

import pytest

from fsdkr_tpu.backend import rlc
from fsdkr_tpu.backend.powm import multi_powm


def _oracle(bases_rows, exps_rows, moduli):
    out = []
    for bs, es, m in zip(bases_rows, exps_rows, moduli):
        acc = 1
        for b, e in zip(bs, es):
            acc = acc * pow(b % m, e, m) % m
        out.append(acc)
    return out


def _random_rows(rng, rows, k, mod_bits, exp_bits):
    mods, bases, exps = [], [], []
    for _ in range(rows):
        m = rng.getrandbits(mod_bits) | (1 << (mod_bits - 1)) | 1
        mods.append(m)
        bases.append(tuple(rng.randrange(1, m) for _ in range(k)))
        exps.append(tuple(rng.getrandbits(w) for w in exp_bits))
    return bases, exps, mods


@pytest.mark.parametrize("k", [2, 9, 33])
def test_host_joint_ladder_variable_arity(k):
    """The native engine (and its CPython fallback) handles n-term rows —
    k=9 and k=33 cross the old 8-term cap."""
    rng = random.Random(1000 + k)
    widths = [128 if t % 2 else 384 for t in range(k)]
    bases, exps, mods = _random_rows(rng, 5, k, 512, widths)
    assert multi_powm(bases, exps, mods, device=False) == _oracle(
        bases, exps, mods
    )


@pytest.mark.parametrize("k", [9, 17, 21])
def test_device_joint_ladder_variable_arity(k):
    """Device routing for n-term rows: rows wider than the
    FSDKR_DEVICE_MAX_TERMS cap split into sub-rows (partials recombined
    host-side), so the compiled kernel variants stay bounded while the
    result is exactly the oracle product."""
    rng = random.Random(2000 + k)
    widths = [128] * k
    bases, exps, mods = _random_rows(rng, 4, k, 256, widths)
    assert multi_powm(bases, exps, mods, device=True) == _oracle(
        bases, exps, mods
    )


def test_device_tree_fold_matches_sequential():
    """The CIOS kernel's log-depth tree fold (>= 4 active terms) is exact:
    compare a 5-term device launch against the host oracle."""
    rng = random.Random(42)
    bases, exps, mods = _random_rows(rng, 3, 5, 256, [128] * 5)
    assert multi_powm(bases, exps, mods, device=True) == _oracle(
        bases, exps, mods
    )


def test_rns_multi_modexp_many_terms():
    """The RNS kernel's n-term path (tree fold engages at >= 4 active
    terms), called directly — the row-count router would otherwise only
    reach it at >= FSDKR_RNS_MIN_ROWS rows."""
    from fsdkr_tpu.ops.rns import rns_multi_modexp

    rng = random.Random(7)
    k = 6
    bases, exps, mods = _random_rows(rng, 4, k, 256, [128] * k)
    got = rns_multi_modexp(
        [list(b) for b in bases], [list(e) for e in exps], mods, 256,
        [128] * k,
    )
    assert got == _oracle(bases, exps, mods)


# ---------------------------------------------------------------------------


def test_sample_rhos_domain():
    rhos = rlc.sample_rhos(256)
    assert len(rhos) == 256
    assert all(1 <= r < (1 << rlc.RLC_BITS) for r in rhos)
    assert len(set(rhos)) > 250  # 128-bit CSPRNG draws do not collide


def test_fold_algebra_ring_pedersen():
    """The folded equation is exactly the rho-weighted product of the
    per-row equations: valid rows satisfy it for every rho; an invalid
    row breaks it for (all but a 2^-128 fraction of) rho."""
    from fsdkr_tpu.proofs.ring_pedersen import (
        RingPedersenProof,
        RingPedersenStatement,
    )
    from fsdkr_tpu.core.paillier import EncryptionKey

    rng = random.Random(3)
    n = 2**255 - 19  # prime, so S is invertible when building honest A_i
    t = rng.randrange(2, n)
    lam = rng.randrange(2, n)
    s = pow(t, lam, n)
    m_sec = 8
    z_vec = [rng.randrange(1, n) for _ in range(m_sec)]
    bits = [rng.getrandbits(1) == 1 for _ in range(m_sec)]
    a_vec = [
        pow(t, z, n) * (pow(s, -1, n) if b else 1) % n
        for z, b in zip(z_vec, bits)
    ]
    st = RingPedersenStatement(S=s, T=t, N=n, ek=EncryptionKey.from_n(n))
    proof = RingPedersenProof(A=a_vec, Z=z_vec)
    rhos = rlc.sample_rhos(m_sec)
    lhs, rhs = RingPedersenProof.rlc_fold(st, proof, bits, rhos)
    (lv,), (rv,) = (
        multi_powm([lhs[0]], [lhs[1]], [lhs[2]], device=False),
        multi_powm([rhs[0]], [rhs[1]], [rhs[2]], device=False),
    )
    assert lv == rv
    # break one row: the fold must detect it
    bad = list(a_vec)
    bad[3] = bad[3] * 2 % n
    lhs, rhs = RingPedersenProof.rlc_fold(
        st, RingPedersenProof(A=bad, Z=z_vec), bits, rlc.sample_rhos(m_sec)
    )
    (lv,), (rv,) = (
        multi_powm([lhs[0]], [lhs[1]], [lhs[2]], device=False),
        multi_powm([rhs[0]], [rhs[1]], [rhs[2]], device=False),
    )
    assert lv != rv


def test_fold_algebra_pdl_nn_closed_form():
    """rlc_fold_nn's closed-form (1+n)-power: prod_j (1 + s1_j n)^{rho_j}
    == 1 + (sum rho_j s1_j) n (mod n^2), checked against pow()."""
    from fsdkr_tpu.proofs.pdl_slack import PDLwSlackProof

    rng = random.Random(4)
    n = (rng.getrandbits(128) | (1 << 127)) | 1
    nn = n * n
    rows = [
        (1, 1, 0, rng.getrandbits(160), 1)  # (u2, c, e, s1, s2)
        for _ in range(5)
    ]
    rhos = rlc.sample_rhos(5)
    _, _, gs1 = PDLwSlackProof.rlc_fold_nn(n, nn, rows, rhos)
    want = 1
    for r, (_, _, _, s1, _) in zip(rhos, rows):
        want = want * pow(1 + (s1 % n) * n, r, nn) % nn
    assert gs1 == want


def test_bisect_rows_finds_bad_subset():
    """Synthetic group: rows 5 and 11 are bad. The driver must return
    exact verdicts and touch only O(bad * log n) combined checks."""
    bad = {5, 11}
    calls = {"combined": 0, "row": 0}

    def combined(sub):
        calls["combined"] += 1
        return not (set(sub) & bad)

    def row(i):
        calls["row"] += 1
        return i not in bad

    verdicts = rlc.bisect_rows(list(range(16)), combined, row)
    assert verdicts == {i: i not in bad for i in range(16)}
    assert calls["combined"] <= 14
    assert calls["row"] <= 8


def test_stats_counters():
    rlc.stats_reset()
    rlc.count("rlc_groups", 3)
    rlc.count("bisect_fallbacks")
    s = rlc.stats()
    assert s["rlc_groups"] == 3 and s["bisect_fallbacks"] == 1
    rlc.stats_reset()
    assert rlc.stats()["rlc_groups"] == 0
