"""The public-broadcast journal (ISSUE 12): CRC framing, segment
rotation, fsync policy, torn-tail tolerance vs mid-segment corruption,
and the journal_torn_write chaos site. Recovery semantics (what the
records MEAN) live in tests/test_recovery.py; here the FILE FORMAT is
the contract — a peer shard must be able to replay a journal it did
not write."""

import os

import pytest

from fsdkr_tpu.serving import faults
from fsdkr_tpu.serving.journal import (
    Journal,
    JournalCorruption,
    read_records,
    SEGMENT_MAGIC,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _recs(n, start=0):
    return [{"t": "broadcast", "sid": 1, "sender": start + i,
             "wire": "ab" * 50} for i in range(n)]


def test_append_read_roundtrip_in_order(tmp_path):
    j = Journal(tmp_path / "j", sync="off")
    recs = _recs(10)
    for r in recs:
        j.append(r)
    j.close()
    assert read_records(tmp_path / "j") == recs
    st = j.stats()
    assert st["records"] == 10 and st["segments"] == 1
    assert st["bytes"] > 0


def test_segment_rotation_and_fresh_segment_on_reopen(tmp_path):
    # tiny segments force rotation; order must survive the segment cuts
    j = Journal(tmp_path / "j", sync="off", segment_bytes=4096)
    recs = _recs(40)
    for r in recs:
        j.append(r)
    j.close()
    segs = Journal.segment_paths(tmp_path / "j")
    assert len(segs) > 1
    assert all(s.read_bytes().startswith(SEGMENT_MAGIC) for s in segs)
    assert read_records(tmp_path / "j") == recs
    # a NEW journal over the same directory never appends to an old
    # segment (a predecessor's tail may be torn): fresh file, higher idx
    j2 = Journal(tmp_path / "j", sync="off")
    j2.append({"t": "x", "sid": 2})
    j2.close()
    segs2 = Journal.segment_paths(tmp_path / "j")
    assert len(segs2) == len(segs) + 1
    assert read_records(tmp_path / "j") == recs + [{"t": "x", "sid": 2}]


def test_sync_policies(tmp_path, monkeypatch):
    ja = Journal(tmp_path / "a", sync="always")
    for r in _recs(3):
        ja.append(r)
    assert ja.fsyncs == 3
    ja.close()
    jb = Journal(tmp_path / "b", sync="batch", batch_records=2)
    for r in _recs(3):
        jb.append(r)
    assert jb.fsyncs == 1  # one full batch; the tail syncs at close
    jb.close()
    assert jb.fsyncs == 2
    jo = Journal(tmp_path / "c", sync="off")
    for r in _recs(3):
        jo.append(r)
    jo.close()
    assert jo.fsyncs == 0
    # the env knob parses strictly: a typo must not silently mean "off"
    monkeypatch.setenv("FSDKR_JOURNAL_SYNC", "fsync-plz")
    with pytest.raises(ValueError, match="FSDKR_JOURNAL_SYNC"):
        Journal(tmp_path / "d")
    monkeypatch.setenv("FSDKR_JOURNAL_SYNC", "always")
    assert Journal(tmp_path / "e").sync_policy == "always"


def test_torn_tail_dropped_and_counted(tmp_path):
    from fsdkr_tpu.telemetry import registry

    j = Journal(tmp_path / "j", sync="off")
    recs = _recs(5)
    for r in recs:
        j.append(r)
    j.close()
    seg = Journal.segment_paths(tmp_path / "j")[0]
    data = seg.read_bytes()
    torn = registry.counter("fsdkr_journal_torn_tails")
    # truncate INSIDE the final record's payload: the crash-mid-write
    # shape — dropped, counted, everything before it survives
    t0 = torn.value()
    seg.write_bytes(data[:-20])
    assert read_records(tmp_path / "j") == recs[:-1]
    assert torn.value() == t0 + 1
    # truncate inside the final record's frame HEADER: same treatment
    import json as _json
    import struct

    payload = _json.dumps(recs[-1], sort_keys=True,
                          separators=(",", ":")).encode()
    frame_len = struct.calcsize("<II") + len(payload)
    seg.write_bytes(data[: len(data) - frame_len + 3])
    t1 = torn.value()
    assert read_records(tmp_path / "j") == recs[:-1]
    assert torn.value() == t1 + 1


def test_mid_segment_corruption_raises_naming_segment_and_offset(tmp_path):
    j = Journal(tmp_path / "j", sync="off")
    for r in _recs(5):
        j.append(r)
    j.close()
    seg = Journal.segment_paths(tmp_path / "j")[0]
    data = bytearray(seg.read_bytes())
    # flip one payload byte in the MIDDLE of the file: CRC mismatch is
    # real corruption, never silently skipped
    mid = len(data) // 2
    data[mid] ^= 0xFF
    seg.write_bytes(bytes(data))
    with pytest.raises(JournalCorruption) as ei:
        read_records(tmp_path / "j")
    assert seg.name in str(ei.value)
    assert "offset" in str(ei.value)
    assert ei.value.offset > 0


def test_bad_magic_raises(tmp_path):
    j = Journal(tmp_path / "j", sync="off")
    j.append({"t": "x"})
    j.close()
    seg = Journal.segment_paths(tmp_path / "j")[0]
    seg.write_bytes(b"NOTAWAL!" + seg.read_bytes()[8:])
    with pytest.raises(JournalCorruption, match="magic"):
        read_records(tmp_path / "j")


def test_missing_and_empty_directory_are_clean_noops(tmp_path):
    assert read_records(tmp_path / "nonexistent") == []
    (tmp_path / "empty").mkdir()
    assert read_records(tmp_path / "empty") == []


def test_torn_write_fault_site(tmp_path):
    """journal_torn_write truncates the active segment mid-record: the
    record is LOST (that is the simulated crash), replay drops the torn
    tail of that segment and keeps everything else, and later appends
    land in a fresh segment."""
    from fsdkr_tpu.telemetry import registry

    j = Journal(tmp_path / "j", sync="off")
    j.append({"t": "a"})
    faults.configure("seed=5,journal_torn_write=1.0,journal_torn_write_max=1")
    j.append({"t": "b"})  # torn: lost on disk
    faults.reset()
    j.append({"t": "c"})
    j.close()
    assert len(Journal.segment_paths(tmp_path / "j")) == 2
    t0 = registry.counter("fsdkr_journal_torn_tails").value()
    assert read_records(tmp_path / "j") == [{"t": "a"}, {"t": "c"}]
    assert registry.counter("fsdkr_journal_torn_tails").value() == t0 + 1
    assert registry.counter(
        "fsdkr_fault_injected", labelnames=("site",)
    ).value(site="journal_torn_write") >= 1


def test_registry_counters_track_appends(tmp_path):
    from fsdkr_tpu.telemetry import registry

    r0 = registry.counter("fsdkr_journal_records").value()
    b0 = registry.counter("fsdkr_journal_bytes").value()
    s0 = registry.counter("fsdkr_journal_segments").value()
    j = Journal(tmp_path / "j", sync="off")
    for r in _recs(4):
        j.append(r)
    j.close()
    assert registry.counter("fsdkr_journal_records").value() == r0 + 4
    assert registry.counter("fsdkr_journal_bytes").value() == b0 + j.bytes
    assert registry.counter("fsdkr_journal_segments").value() == s0 + 1


def test_closed_journal_refuses_appends(tmp_path):
    j = Journal(tmp_path / "j", sync="off")
    j.append({"t": "a"})
    j.close()
    j.close()  # idempotent
    with pytest.raises(RuntimeError, match="closed"):
        j.append({"t": "b"})
    assert os.path.isdir(tmp_path / "j")
