"""Thread-parity suite for the row-parallel native engines.

Every batch entry point of both native cores (csrc/fsdkr_native.cpp,
csrc/fsdkr_ec.cpp) must produce BIT-IDENTICAL results at any
FSDKR_THREADS setting: rows are independent and the thread pool only
partitions the row range, so `=1` (the historical serial loop) and `=8`
(forced row pool, exercised even on single-core CI hosts) are compared
value-for-value — modexp, joint ladder, comb, modmul, EC lincomb/Horner/
scalar-mul, and Miller-Rabin verdicts — including the error/fallback
paths (even moduli, oversized rows) and under concurrent Python callers.

scripts/ci.sh runs this file with FSDKR_THREADS=8 forced so the
concurrent row pool is exercised on every commit, not only on many-core
bench hosts.
"""

import random

import pytest

from fsdkr_tpu import native
from fsdkr_tpu.native import ec as native_ec

RNG = random.Random(0x7157)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++?)"
)


def _odd_mod(bits):
    return RNG.getrandbits(bits) | (1 << (bits - 1)) | 1


def _with_threads(monkeypatch, val):
    monkeypatch.setenv("FSDKR_THREADS", val)


def _both_thread_counts(monkeypatch, fn):
    """Run fn() under FSDKR_THREADS=1 and =8 and return both results."""
    _with_threads(monkeypatch, "1")
    assert native.thread_count() == 1
    serial = fn()
    _with_threads(monkeypatch, "8")
    assert native.thread_count() == 8
    pooled = fn()
    return serial, pooled


# ---------------------------------------------------------------------------
# bignum core


def test_modexp_batch_parity(monkeypatch):
    mods = [_odd_mod(768) for _ in range(13)]
    bs = [RNG.getrandbits(768) for _ in mods]
    es = [RNG.getrandbits(RNG.choice([1, 64, 256, 700])) for _ in mods]
    serial, pooled = _both_thread_counts(
        monkeypatch, lambda: native.modexp_batch(bs, es, mods)
    )
    assert serial == pooled == [pow(b, e, m) for b, e, m in zip(bs, es, mods)]


def test_modexp_batch_fallback_parity(monkeypatch):
    # an even modulus fails the whole native batch on any thread: both
    # settings must take the row-wise CPython fallback and agree
    mods = [_odd_mod(512) for _ in range(7)] + [1 << 512]
    bs = [RNG.getrandbits(512) for _ in mods]
    es = [RNG.getrandbits(512) for _ in mods]
    serial, pooled = _both_thread_counts(
        monkeypatch, lambda: native.modexp_batch(bs, es, mods)
    )
    assert serial == pooled == [pow(b, e, m) for b, e, m in zip(bs, es, mods)]


def test_modexp_batch_tiled_parity(monkeypatch):
    # tiles + row pool together: results must match the untiled serial
    # loop exactly (tiling only re-buckets L/EL per tile, never values)
    mods = [_odd_mod(512) for _ in range(21)]
    bs = [RNG.getrandbits(512) for _ in mods]
    es = [RNG.getrandbits(384) for _ in mods]
    monkeypatch.setenv("FSDKR_TILE_ROWS", "4")
    serial, pooled = _both_thread_counts(
        monkeypatch, lambda: native.modexp_batch(bs, es, mods)
    )
    monkeypatch.setenv("FSDKR_TILE_ROWS", "0")
    _with_threads(monkeypatch, "1")
    untiled = native.modexp_batch(bs, es, mods)
    assert serial == pooled == untiled


def test_modexp_shared_parity(monkeypatch):
    m = _odd_mod(768)
    base = RNG.randrange(2, m)
    exps = [0, 1, (1 << 768) - 1] + [RNG.getrandbits(768) for _ in range(10)]
    for cache in (False, True):
        serial, pooled = _both_thread_counts(
            monkeypatch, lambda: native.modexp_shared(base, exps, m, cache=cache)
        )
        assert serial == pooled == [pow(base, e, m) for e in exps]


def test_multi_modexp_batch_parity(monkeypatch):
    m_vec = [_odd_mod(768) for _ in range(9)]
    bases = [tuple(RNG.randrange(1, m) for _ in range(3)) for m in m_vec]
    exps = [
        (RNG.getrandbits(768), RNG.getrandbits(256), RNG.getrandbits(64))
        for _ in m_vec
    ]
    serial, pooled = _both_thread_counts(
        monkeypatch, lambda: native.multi_modexp_batch(bases, exps, m_vec)
    )
    want = []
    for b, e, m in zip(bases, exps, m_vec):
        acc = 1
        for b_t, e_t in zip(b, e):
            acc = acc * pow(b_t, e_t, m) % m
        want.append(acc)
    assert serial == pooled == want


def test_modmul_batch_parity(monkeypatch):
    # mixed moduli incl. repeats (constants amortize over runs) and one
    # even modulus batch exercising the fallback under both settings
    shared = _odd_mod(768)
    mods = [shared] * 5 + [_odd_mod(768) for _ in range(6)]
    a = [RNG.getrandbits(800) for _ in mods]
    b = [RNG.getrandbits(800) for _ in mods]
    serial, pooled = _both_thread_counts(
        monkeypatch, lambda: native.modmul_batch(a, b, mods)
    )
    assert serial == pooled == [x * y % m for x, y, m in zip(a, b, mods)]
    even = mods[:3] + [1 << 700]
    a2, b2 = a[:4], b[:4]
    serial, pooled = _both_thread_counts(
        monkeypatch, lambda: native.modmul_batch(a2, b2, even)
    )
    assert serial == pooled == [x * y % m for x, y, m in zip(a2, b2, even)]


def test_miller_rabin_parity(monkeypatch):
    cases = [
        2**521 - 1,  # prime
        (2**127 - 1) * (2**89 - 1),  # semiprime
        561,  # Carmichael
        _odd_mod(512),
    ]
    for n in cases:
        serial, pooled = _both_thread_counts(
            monkeypatch, lambda: native.is_probable_prime(n, 16)
        )
        # witnesses are CSPRNG-fresh per call, but 16 rounds make the
        # verdict deterministic in practice for these inputs
        assert serial == pooled


def test_limb_widen_narrow_parity(monkeypatch):
    import numpy as np

    a16 = np.array(
        [[RNG.getrandbits(16) for _ in range(64)] for _ in range(64)],
        dtype=np.uint16,
    )
    serial, pooled = _both_thread_counts(
        monkeypatch, lambda: native.widen_limbs(a16).tolist()
    )
    assert serial == pooled == a16.astype(np.uint32).tolist()
    a32 = a16.astype(np.uint32)
    serial, pooled = _both_thread_counts(
        monkeypatch, lambda: native.narrow_limbs(a32).tolist()
    )
    assert serial == pooled == a16.tolist()
    bad = a32.copy()
    bad[5, 7] |= 1 << 20
    for val in ("1", "8"):
        _with_threads(monkeypatch, val)
        with pytest.raises(ValueError):
            native.narrow_limbs(bad)


# ---------------------------------------------------------------------------
# EC core


@pytest.mark.skipif(not native_ec.available(), reason="no native EC core")
def test_ec_batch_parity(monkeypatch):
    from fsdkr_tpu.core.secp256k1 import GENERATOR, N as ORDER

    pts, p = [], GENERATOR
    for _ in range(11):
        pts.append((p.x, p.y))
        p = p + GENERATOR
    pts.append(None)  # identity row
    sc = [RNG.randrange(0, ORDER) for _ in pts]
    serial, pooled = _both_thread_counts(
        monkeypatch, lambda: native_ec.scalar_mul_batch(pts, sc)
    )
    assert serial == pooled
    serial, pooled = _both_thread_counts(
        monkeypatch, lambda: native_ec.lincomb2_batch(pts, sc, pts, sc[::-1])
    )
    assert serial == pooled
    commits = pts[:4]
    idxs = list(range(1, 10))
    serial, pooled = _both_thread_counts(
        monkeypatch, lambda: native_ec.horner_batch(commits, idxs)
    )
    assert serial == pooled


# ---------------------------------------------------------------------------
# concurrent callers: the row pool must be safe under simultaneous batch
# calls from multiple Python threads (the tile pipeline does exactly
# this), including rows that force the error/fallback path


def test_concurrent_callers(monkeypatch):
    from concurrent.futures import ThreadPoolExecutor

    _with_threads(monkeypatch, "8")
    jobs = []
    for j in range(6):
        mods = [_odd_mod(512) for _ in range(5)]
        if j % 3 == 2:
            mods[2] = 1 << 512  # even: whole-batch fallback for this job
        bs = [RNG.getrandbits(512) for _ in mods]
        es = [RNG.getrandbits(300) for _ in mods]
        jobs.append((bs, es, mods))
    with ThreadPoolExecutor(max_workers=4) as ex:
        futs = [
            ex.submit(native.modexp_batch, bs, es, mods)
            for bs, es, mods in jobs
        ]
        got = [f.result() for f in futs]
    for (bs, es, mods), res in zip(jobs, got):
        assert res == [pow(b, e, m) for b, e, m in zip(bs, es, mods)]


def test_crt_modexp_batch_parity(monkeypatch):
    # the secret-CRT leg batch (run-grouped Montgomery constants): the
    # thread split must not disturb run boundaries' math
    shared = _odd_mod(512)
    mods = [shared] * 6 + [_odd_mod(512) for _ in range(5)]
    bs = [RNG.getrandbits(512) for _ in mods]
    es = [RNG.getrandbits(500) for _ in mods]
    serial, pooled = _both_thread_counts(
        monkeypatch, lambda: native.crt_modexp_batch(bs, es, mods)
    )
    assert serial == pooled == [pow(b, e, m) for b, e, m in zip(bs, es, mods)]


def test_miller_rabin_batch_parity(monkeypatch):
    cases = [2**521 - 1, (2**127 - 1) * (2**89 - 1), 561, _odd_mod(512)]
    serial, pooled = _both_thread_counts(
        monkeypatch, lambda: native.is_probable_prime_batch(cases, 16)
    )
    # witnesses are CSPRNG-fresh per call; 16 rounds make the verdicts
    # deterministic in practice for these inputs
    assert serial == pooled


def test_gmp_powm_batch_parity(monkeypatch):
    from fsdkr_tpu.native import gmp

    if not gmp.available():
        pytest.skip("GMP bridge unavailable")
    mods = [_odd_mod(512) for _ in range(9)]
    bs = [RNG.getrandbits(512) for _ in mods]
    es = [RNG.getrandbits(384) for _ in mods]
    for secret in (False, True):
        serial, pooled = _both_thread_counts(
            monkeypatch, lambda: gmp.powm_batch(bs, es, mods, secret=secret)
        )
        assert serial == pooled == [
            pow(b, e, m) for b, e, m in zip(bs, es, mods)
        ]


def test_prover_phase_parity(monkeypatch):
    """The CRT-routed prover phases (PR 2 loose end: pin the prover side
    before a multicore host measures it): ring-Pedersen prove, correct-
    key, and the batched keygen MR pipeline must be bit-identical (or
    verdict-identical where witnesses are CSPRNG-fresh) at 1 vs 8
    threads."""
    import random as _random

    from fsdkr_tpu.core import paillier, primes
    from fsdkr_tpu.proofs import ring_pedersen as rp_mod
    from fsdkr_tpu.proofs.correct_key import NiCorrectKeyProof
    from fsdkr_tpu.proofs.ring_pedersen import (
        RingPedersenProof,
        RingPedersenStatement,
        RingPedersenWitness,
    )

    monkeypatch.setenv("FSDKR_CRT", "1")
    n, p, q = primes.gen_modulus(512)
    phi = (p - 1) * (q - 1)
    lam = RNG.randrange(phi)
    t = pow(RNG.randrange(2, n), 2, n)
    st = RingPedersenStatement(
        S=pow(t, lam, n), T=t, N=n,
        ek=paillier.EncryptionKey.from_n(n),
    )
    wit = RingPedersenWitness(p=p, q=q, lam=lam, phi=phi)
    dk = paillier.DecryptionKey(p=p, q=q)

    class _Seeded:
        def __init__(self):
            self._rng = _random.Random(0x5EED)

        def randbelow(self, bound):
            return self._rng.randrange(bound)

    def run():
        monkeypatch.setattr(rp_mod, "secrets", _Seeded())
        proofs = RingPedersenProof.prove_batch([wit], [st], 8)
        ck = NiCorrectKeyProof.proof_batch([dk], rounds=3)
        return [(pf.A, pf.Z) for pf in proofs], ck[0].sigma_vec

    serial, pooled = _both_thread_counts(monkeypatch, run)
    assert serial == pooled

    # keygen MR pipeline: verdict parity over a fixed candidate set
    cands = [primes.gen_prime(128) for _ in range(2)] + [
        _odd_mod(128) * _odd_mod(128) for _ in range(2)
    ]
    serial, pooled = _both_thread_counts(
        monkeypatch, lambda: primes._mr_batch(cands, 16)
    )
    assert serial == pooled == [True, True, False, False]


def test_planner_thread_parity(monkeypatch):
    """multi_powm (host engines) end-to-end at both thread settings:
    comb-routed terms, joint rows, generic loners, negative exponents."""
    import math

    from fsdkr_tpu.backend.powm import multi_powm

    m = _odd_mod(768)
    h1, h2 = RNG.randrange(2, m), RNG.randrange(2, m)
    bases, exps = [], []
    for _ in range(8):
        while True:
            loner = RNG.randrange(2, m)
            if math.gcd(loner, m) == 1:
                break
        bases.append((h1, h2, loner))
        exps.append(
            (RNG.getrandbits(256), RNG.getrandbits(512), -RNG.getrandbits(128))
        )
    mods = [m] * 8

    def run():
        return multi_powm(
            [list(b) for b in bases], [list(e) for e in exps], mods,
            device=False,
        )

    serial, pooled = _both_thread_counts(monkeypatch, run)
    want = []
    for b, e in zip(bases, exps):
        acc = 1
        for b_t, e_t in zip(b, e):
            acc = acc * pow(b_t, e_t, m) % m
        want.append(acc)
    assert serial == pooled == want
