"""Memory-planned streaming verification (ISSUE 10, tier-1).

The contract under test: the bytes-budgeted tile plan
(FSDKR_MEM_BUDGET_MB, backend.memplan) produces verdicts,
identifiable-abort blame, and LocalKey mutations bit-identical to the
monolithic all-rows-resident path at EVERY budget — including a
starvation budget forcing 1-row tiles — while the fsdkr_mem_* gauges
prove the staged bytes actually stayed under the plan, and the
streaming-collect path inherits the same bounded-memory tiling.
"""

import copy
import dataclasses
import random
import types

import numpy as np
import pytest

from fsdkr_tpu.backend import memplan
from fsdkr_tpu.backend import rlc
from fsdkr_tpu.core.secp256k1 import GENERATOR
from fsdkr_tpu.errors import PDLwSlackProofError, RangeProofError
from fsdkr_tpu.proofs.pdl_slack import PDLwSlackStatement
from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen

# 768-bit TEST_CONFIG pair row estimate (used to pick budgets below)
_ROW_B = memplan.pair_row_bytes(2 * 768, 768)


# ---------------------------------------------------------------------------
# planner units (pure host math, milliseconds)


def test_planner_budget_shapes(monkeypatch):
    monkeypatch.setenv("FSDKR_MEM_PLAN", "1")
    # fits: one tile, no cut
    monkeypatch.setenv("FSDKR_MEM_BUDGET_MB", "64")
    plan = memplan.plan_rows(100, 1000, label="t")
    assert plan is not None and not plan.multi_tile
    assert plan.tiles == ((0, 100),)
    # budget of 10 rows per tile at inflight=2
    monkeypatch.setenv(
        "FSDKR_MEM_BUDGET_MB", str(20 * 1000 / (1 << 20))
    )
    plan = memplan.plan_rows(100, 1000, label="t")
    assert plan.inflight == 2
    assert plan.tile_rows == 10 and len(plan.tiles) == 10
    assert plan.tiles[0] == (0, 10) and plan.tiles[-1] == (90, 100)
    # in-flight staged bytes respect the budget by construction
    assert plan.tile_bytes(plan.tile_rows) * plan.inflight <= plan.budget
    # starvation budget: 1-row floor, never a refusal
    monkeypatch.setenv("FSDKR_MEM_BUDGET_MB", "0.0001")
    plan = memplan.plan_rows(5, 1000, label="t")
    assert plan.tile_rows == 1 and len(plan.tiles) == 5
    # disabled: no plan
    monkeypatch.setenv("FSDKR_MEM_PLAN", "0")
    assert memplan.plan_rows(100, 1000) is None


def test_planner_mesh_aligned_cuts(monkeypatch):
    """With a device mesh active, tile cuts round DOWN to device-count
    multiples (shard_kernels.tile_rows_for_mesh) so no tile falls off
    the sharded path."""
    from fsdkr_tpu.backend import powm

    monkeypatch.setenv("FSDKR_MEM_PLAN", "1")
    monkeypatch.setenv("FSDKR_MEM_BUDGET_MB", str(22 * 1000 / (1 << 20)))
    fake_mesh = types.SimpleNamespace(devices=np.zeros(4))
    monkeypatch.setattr(powm, "_MESH", fake_mesh)
    plan = memplan.plan_rows(100, 1000, label="t")
    # 11 rows of budget round down to 8 (a multiple of 4 devices)
    assert plan.tile_rows == 8
    assert all((hi - lo) % 4 == 0 or hi == 100 for lo, hi in plan.tiles)


def test_pair_row_bytes_width_bucketed():
    """The estimate is a function of PUBLIC width buckets only, and
    wider rows cost more (the 2048-bit full shape ~8x the data of the
    768-bit proxy rows is what motivates the plan)."""
    small = memplan.pair_row_bytes(2 * 768, 768)
    full = memplan.pair_row_bytes(2 * 2048, 2048)
    assert full > 2 * small
    # bucket stability: +1 bit inside a limb does not move the estimate
    assert memplan.pair_row_bytes(4096, 2048) == memplan.pair_row_bytes(
        4095, 2041
    )


# ---------------------------------------------------------------------------
# verdict + blame bit-identity, n=16, three budgets incl. 1-row tiles


@pytest.fixture(scope="module")
def committee16(test_config):
    """(t=1, n=16) honest round (shares the session keygen cache with
    the other n=16 suites)."""
    keys = simulate_keygen(1, 16, test_config)
    results = RefreshMessage.distribute_batch(
        [(k.i, k) for k in keys], 16, test_config
    )
    return keys, [m for m, _ in results], [dk for _, dk in results]


def _pair_items(msgs, key, n):
    pdl_items, range_items = [], []
    for msg in msgs:
        for i in range(n):
            st = PDLwSlackStatement(
                ciphertext=msg.points_encrypted_vec[i],
                ek=key.paillier_key_vec[i],
                Q=msg.points_committed_vec[i],
                G=GENERATOR,
                h1=key.h1_h2_n_tilde_vec[i].g,
                h2=key.h1_h2_n_tilde_vec[i].ni,
                N_tilde=key.h1_h2_n_tilde_vec[i].N,
            )
            pdl_items.append((msg.pdl_proof_vec[i], st))
            range_items.append(
                (
                    msg.range_proofs[i],
                    msg.points_encrypted_vec[i],
                    key.paillier_key_vec[i],
                    key.h1_h2_n_tilde_vec[i],
                )
            )
    return pdl_items, range_items


@pytest.mark.heavy  # n=16 pair batch x 4 arms: tier-1, not the smoke gate
def test_tiled_vs_monolithic_verdict_blame_identity_n16(
    committee16, test_config, monkeypatch
):
    """The satellite gate: one tampered PDL row (eq2 only) and one
    tampered range row at n=16 — the full per-row verdict vectors of
    both families are bit-identical between the monolithic arm and the
    streamed arm at three budgets, including one forcing 1-row tiles
    (512 tiles, every RLC group's fold crossing ~16 tile boundaries as
    running partial products, blame resolved through the shared
    bisection helpers)."""
    from fsdkr_tpu.backend.batch_verifier import get_backend

    monkeypatch.setenv("FSDKR_DEVICE_POWM", "0")
    monkeypatch.setenv("FSDKR_DEVICE_EC", "0")
    keys, msgs, _dks = committee16
    msgs = copy.deepcopy(msgs)
    n = 16
    bad_s, bad_r = 7, 3
    p = msgs[bad_s].pdl_proof_vec[bad_r]
    msgs[bad_s].pdl_proof_vec[bad_r] = dataclasses.replace(p, s2=p.s2 + 1)
    rp = msgs[2].range_proofs[11]
    msgs[2].range_proofs[11] = dataclasses.replace(rp, s=rp.s + 1)
    pdl_items, range_items = _pair_items(msgs, keys[0], n)
    bad_pdl_row = bad_s * n + bad_r
    bad_rng_row = 2 * n + 11

    backend = get_backend(test_config.with_backend("tpu"))
    # budgets: ~1-row tiles, a mid cut, and a few-tile cut
    one_row_mb = 0.9 * _ROW_B * 2 / (1 << 20)
    budgets = [f"{one_row_mb:.6f}", "0.1", "0.8"]

    monkeypatch.setenv("FSDKR_MEM_PLAN", "0")
    base = backend.verify_pairs(pdl_items, range_items)
    monkeypatch.setenv("FSDKR_MEM_PLAN", "1")
    for budget in budgets:
        monkeypatch.setenv("FSDKR_MEM_BUDGET_MB", budget)
        rlc.stats_reset()
        got = backend.verify_pairs(pdl_items, range_items)
        assert got == base, f"budget {budget} diverged"
        s = rlc.stats()
        assert s["stream_tiles"] > 1, f"budget {budget} did not tile"
        # the O(1)-full-width-ladders-per-group property survives
        # tiling: ladders stay O(groups), never O(rows) or O(tiles)
        assert s["fullwidth_ladders"] <= s["rlc_groups"]
        assert s["rows_folded"] >= 2 * n * n - 2
        assert s["bisect_fallbacks"] >= 1  # the tampered group bisected
    # 1-row-tile arm really had one row per tile
    assert int(s["stream_tiles"]) >= 2  # (last arm; first arm had 512)
    pdl_v, range_v = base
    assert pdl_v[bad_pdl_row] == (True, False, True)
    assert [i for i, v in enumerate(pdl_v) if v is not None] == [bad_pdl_row]
    assert [i for i, v in enumerate(range_v) if not v] == [bad_rng_row]


def test_collect_blame_identity_tiny_budget(
    one_refresh_round, test_config, monkeypatch
):
    """End-to-end collect at n=3 under a 1-row-tile budget: the
    identifiable-abort error (type + equation booleans / party index)
    matches the monolithic arm for a PDL tamper and a range tamper, and
    the honest transcript still adopts."""
    keys, msgs, dks = one_refresh_round
    monkeypatch.setenv("FSDKR_DEVICE_POWM", "0")
    monkeypatch.setenv("FSDKR_DEVICE_EC", "0")

    def run(mutate, plan, budget="0.004"):
        monkeypatch.setenv("FSDKR_MEM_PLAN", plan)
        monkeypatch.setenv("FSDKR_MEM_BUDGET_MB", budget)
        m2 = copy.deepcopy(msgs)
        mutate(m2)
        try:
            RefreshMessage.collect(
                m2, keys[0].clone(), dks[0], (),
                test_config.with_backend("tpu"),
            )
            return None
        except Exception as e:
            return (
                type(e).__name__,
                getattr(e, "is_u1_eq", None),
                getattr(e, "is_u2_eq", None),
                getattr(e, "is_u3_eq", None),
                getattr(e, "party_index", None),
            )

    def mut_pdl(m):
        p = m[1].pdl_proof_vec[2]
        m[1].pdl_proof_vec[2] = dataclasses.replace(p, s2=p.s2 + 1)

    def mut_rng(m):
        p = m[2].range_proofs[0]
        m[2].range_proofs[0] = dataclasses.replace(p, s=p.s + 1)

    assert run(lambda m: None, "1") is None  # honest, tiled
    for mut, err in ((mut_pdl, PDLwSlackProofError), (mut_rng, RangeProofError)):
        mono = run(mut, "0")
        tiled = run(mut, "1")
        assert mono is not None and mono[0] == err.__name__
        assert tiled == mono

    # FSDKR_RLC=0 arm: the per-row column path tiles row-locally too
    monkeypatch.setenv("FSDKR_RLC", "0")
    assert run(lambda m: None, "1") is None
    mono0 = run(mut_pdl, "0")
    assert run(mut_pdl, "1") == mono0 and mono0[0] == "PDLwSlackProofError"


@pytest.mark.slow  # tile-sized device-kernel variants cost ~2.5 min of
# XLA:CPU compiles this test alone triggers; the tier-1 identity pins
# above run the host engines (planner/fold logic is engine-independent)
def test_tiled_device_route_honest(one_refresh_round, test_config, monkeypatch):
    """The streamed driver on the DEVICE kernel routes (conftest forces
    FSDKR_DEVICE_POWM/EC=1): per-tile fold evaluation through the device
    joint-ladder planner and the per-tile range engines through the
    device kernels — verdicts match the monolithic device arm. Direct
    verify_pairs on the 9-row pair batch (not a full collect) keeps the
    device compiles this test pays small."""
    from fsdkr_tpu.backend.batch_verifier import get_backend

    keys, msgs, _dks = one_refresh_round
    pdl_items, range_items = _pair_items(copy.deepcopy(msgs), keys[0], 3)
    backend = get_backend(test_config.with_backend("tpu"))
    monkeypatch.setenv("FSDKR_MEM_PLAN", "0")
    base = backend.verify_pairs(pdl_items, range_items)
    monkeypatch.setenv("FSDKR_MEM_PLAN", "1")
    monkeypatch.setenv("FSDKR_MEM_BUDGET_MB", "0.04")  # ~3 tiles
    rlc.stats_reset()
    got = backend.verify_pairs(pdl_items, range_items)
    assert got == base
    assert all(v is None for v in got[0]) and all(got[1])
    assert rlc.stats()["stream_tiles"] > 1
    assert rlc.stats()["bisect_fallbacks"] == 0


# ---------------------------------------------------------------------------
# budget enforcement via the new gauges


def test_budget_enforcement_gauges(one_refresh_round, test_config, monkeypatch):
    """The gauges prove the plan held: tiles were cut at the planned
    size, in-flight staged bytes never exceeded the budget (tracked by
    the stage/release accounting the drivers run), and the cumulative
    staged counter moved."""
    from fsdkr_tpu.telemetry import registry

    keys, msgs, dks = one_refresh_round
    monkeypatch.setenv("FSDKR_DEVICE_POWM", "0")
    monkeypatch.setenv("FSDKR_DEVICE_EC", "0")
    monkeypatch.setenv("FSDKR_MEM_PLAN", "1")
    budget_mb = 4.2 * _ROW_B / (1 << 20)  # 2 rows per tile at inflight=2
    # (4.2, not 4.0: the env round-trips through a 6-decimal float MB
    # string, and an exact 4x budget can round DOWN a byte)
    monkeypatch.setenv("FSDKR_MEM_BUDGET_MB", f"{budget_mb:.6f}")
    memplan.stats_reset()
    rlc.stats_reset()
    RefreshMessage.collect(
        copy.deepcopy(msgs), keys[1].clone(), dks[1], (),
        test_config.with_backend("tpu"),
    )
    mem = memplan.mem_stats()
    budget = mem["budget_bytes"]
    assert rlc.stats()["stream_tiles"] > 1
    snap = registry.get_registry().snapshot()["metrics"]
    tile_rows = {
        v["labels"]["family"]: v["value"]
        for v in snap["fsdkr_mem_tile_rows"]["values"]
    }
    assert tile_rows["pairs"] == 2  # the planned cut
    # enforcement: in-flight staged bytes (inflight * tile) <= budget,
    # and the tracked peak never exceeded it
    assert 2 * tile_rows["pairs"] * _ROW_B <= budget
    assert 0 < mem["peak_resident_bytes"] <= budget
    assert mem["rss_peak_bytes"] > 0  # VmHWM sampler wired
    # the limb encoder's cumulative staged counter is alive
    assert mem["bytes_staged"] >= 0
    # default budget at test shapes: single tile, monolithic path (the
    # plan must add NO tiling to workloads that fit)
    monkeypatch.setenv("FSDKR_MEM_BUDGET_MB", "256")
    rlc.stats_reset()
    RefreshMessage.collect(
        copy.deepcopy(msgs), keys[2].clone(), dks[2], (),
        test_config.with_backend("tpu"),
    )
    assert rlc.stats()["stream_tiles"] == 0


# ---------------------------------------------------------------------------
# streaming collect inherits the tile plan


def test_streaming_collect_on_tiles_parity(
    one_refresh_round, test_config, monkeypatch
):
    """StreamingCollect finalize under a multi-tile budget: key state
    identical to barrier collect under the monolithic plan (honest),
    blame identical on tamper, and the stream-rows gauge returns to
    zero when sessions retire."""
    from fsdkr_tpu.protocol.streaming import _stream_rows_total

    keys, msgs, dks = one_refresh_round
    monkeypatch.setenv("FSDKR_DEVICE_POWM", "0")
    monkeypatch.setenv("FSDKR_DEVICE_EC", "0")
    cfg = test_config.with_backend("tpu")

    def stream_run(msgs_in, key, dk, seed):
        st = RefreshMessage.collect_stream(
            key, dk, [m.party_index for m in msgs_in], (), cfg
        )
        order = list(msgs_in)
        random.Random(seed).shuffle(order)
        for m in order:
            assert st.offer(m) == "accepted"
        gauge_mid = _stream_rows_total()
        assert gauge_mid >= len(msgs_in) * st.new_n
        try:
            st.finalize()
            err = None
        except Exception as e:
            err = (type(e).__name__, tuple(map(str, e.args)))
        assert _stream_rows_total() < gauge_mid
        return err

    # honest: barrier-monolithic vs streaming-tiled state identity
    monkeypatch.setenv("FSDKR_MEM_PLAN", "0")
    kb = keys[0].clone()
    RefreshMessage.collect(copy.deepcopy(msgs), kb, dks[0], (), cfg)
    monkeypatch.setenv("FSDKR_MEM_PLAN", "1")
    monkeypatch.setenv("FSDKR_MEM_BUDGET_MB", "0.004")  # 1-row tiles
    rlc.stats_reset()
    ks = keys[0].clone()
    assert stream_run(copy.deepcopy(msgs), ks, dks[0], seed=7) is None
    assert rlc.stats()["stream_tiles"] > 1  # finalize really tiled
    assert kb.keys_linear.x_i.to_int() == ks.keys_linear.x_i.to_int()
    assert kb.pk_vec == ks.pk_vec
    assert [e.n for e in kb.paillier_key_vec] == [
        e.n for e in ks.paillier_key_vec
    ]

    # tampered: same blame through the tiled streaming finalize
    bad = copy.deepcopy(msgs)
    p = bad[1].pdl_proof_vec[0]
    bad[1].pdl_proof_vec[0] = dataclasses.replace(p, s2=p.s2 + 1)
    monkeypatch.setenv("FSDKR_MEM_PLAN", "0")
    try:
        RefreshMessage.collect(
            copy.deepcopy(bad), keys[1].clone(), dks[1], (), cfg
        )
        ref = None
    except Exception as e:
        ref = (type(e).__name__, tuple(map(str, e.args)))
    monkeypatch.setenv("FSDKR_MEM_PLAN", "1")
    got = stream_run(copy.deepcopy(bad), keys[1].clone(), dks[1], seed=3)
    assert ref is not None and got == ref


# ---------------------------------------------------------------------------
# Feldman/EC columns stream through the same plan


def test_feldman_streamed_verdicts(one_refresh_round, test_config, monkeypatch):
    from fsdkr_tpu.backend.batch_verifier import get_backend
    from fsdkr_tpu.protocol.refresh import _feldman_streamed

    keys, msgs, _dks = one_refresh_round
    monkeypatch.setenv("FSDKR_DEVICE_EC", "0")
    backend = get_backend(test_config.with_backend("tpu"))
    msgs = copy.deepcopy(msgs)
    # tamper one committed point so a False verdict crosses a tile cut
    msgs[1].points_committed_vec[2] = (
        msgs[1].points_committed_vec[2] + GENERATOR
    )
    items = [
        (m.coefficients_committed_vec, m.points_committed_vec[i], i + 1)
        for m in msgs
        for i in range(3)
    ]
    monkeypatch.setenv("FSDKR_MEM_PLAN", "0")
    base = backend.validate_feldman(items)
    monkeypatch.setenv("FSDKR_MEM_PLAN", "1")
    # ec_row_bytes=1024: 2-row tiles, the bad row mid-tile-stream
    monkeypatch.setenv("FSDKR_MEM_BUDGET_MB", f"{4096 / (1 << 20):.6f}")
    got = _feldman_streamed(backend, items)
    assert got == base
    assert got.count(False) == 1 and not got[5]
