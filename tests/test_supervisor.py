"""Multi-shard supervisor (ISSUE 12): real shard processes, a real
SIGKILL, journal replay on the peer. Marked `heavy` like the multihost
suite (two extra interpreter spawns); the per-commit smoke lives in
scripts/ci.sh's kill-recovery leg."""

import json
import time

import pytest

from fsdkr_tpu.config import TEST_CONFIG
from fsdkr_tpu.protocol import simulate_keygen
from fsdkr_tpu.serving.supervisor import ShardSupervisor, shard_for


def test_shard_for_is_stable_partition():
    assert shard_for("com0", 2) == shard_for("com0", 2)
    assert shard_for(7, 1) == 0
    buckets = {shard_for(f"c{i}", 4) for i in range(64)}
    assert buckets == {0, 1, 2, 3}  # every shard gets traffic


@pytest.mark.heavy
def test_kill_failover_replay_and_resume(tmp_path):
    """SIGKILL one of two shards mid-session: the supervisor detects
    the death, moves its committees to the peer, the peer replays the
    dead journal (terminal verdicts restored, in-flight secrets gone ->
    transient), the pending epoch resubmits and COMPLETES with the same
    verdict as the uninterrupted control (done/no-blame), the dead
    shard's flight dump sits beside its journal, and the journals
    account for every accepted broadcast."""
    from fsdkr_tpu.serving import recovery

    sup = ShardSupervisor(
        shards=2, root=tmp_path, deadline_s=10.0, hb_interval=0.4
    )
    sup.start()
    try:
        # two committees, one per shard (fingerprint partition)
        cids, want = [], {0, 1}
        i = 0
        while want:
            cid = f"com{i}"
            if shard_for(cid, 2) in want:
                want.discard(shard_for(cid, 2))
                cids.append(cid)
            i += 1
        keys = simulate_keygen(1, 3, TEST_CONFIG)
        for cid in cids:
            sup.admit(cid, [k.clone() for k in keys], TEST_CONFIG)

        # epoch 0 everywhere: the healthy baseline AND the terminal
        # records the failover replay must restore
        for cid in cids:
            sup.submit(cid, 0)
        assert sup.drain(180), f"epoch 0 wedged: {sup.pending}"
        assert all(o["state"] == "done" for o in sup.outcomes)

        victim_cid = cids[0]
        victim_shard = sup.assignment[victim_cid]
        bystander_cid = cids[1]
        # queue THREE epochs on the victim committee (they serialize
        # through the one-in-flight-per-committee slot), so the SIGKILL
        # lands with work guaranteed still pending however fast the box
        for e in (1, 2, 3):
            sup.submit(victim_cid, e)
        sup.submit(bystander_cid, 1)  # the uninterrupted control
        time.sleep(0.3)  # mid-session
        killed = sup.kill_shard(victim_shard)
        assert killed == victim_shard
        assert sup.drain(240), f"post-kill wedge: {sup.pending}"

        by_epoch = {(o["cid"], o["epoch"]): o for o in sup.outcomes}
        control = by_epoch[(bystander_cid, 1)]
        # verdict identical to the uninterrupted control run, for every
        # interrupted epoch — and at least one actually crossed the
        # failover (resubmit-after-replay) path
        assert control["state"] == "done" and not control["blame"]
        vias = set()
        for e in (1, 2, 3):
            recovered = by_epoch[(victim_cid, e)]
            assert recovered["state"] == "done" and not recovered["blame"], (
                recovered
            )
            vias.add(recovered["via"])
        assert vias & {"failover", "resubmit"}, vias

        agg = sup.aggregate()
        assert agg["kills"] == 1 and len(agg["failovers"]) == 1
        fo = agg["failovers"][0]
        assert fo["dead"] == victim_shard
        assert fo["mttr_s"] is not None and fo["mttr_s"] > 0
        rec = fo["recovery"]
        # epoch 0 replayed verbatim; the interrupted epoch-1 session is
        # either transient (secrets died with the shard) or was never
        # journaled past admission — both settle, neither fabricates
        assert rec["replayed_terminal"] >= 1
        assert rec["skipped"] == 0
        # the dead shard's postmortem sits beside its journal
        assert fo["flight_dump"] is not None
        flight = json.loads(open(fo["flight_dump"]).read())
        assert flight["events"], "dead shard's flight ring empty"
        # the peer's heartbeat journal counters aggregate across shards
        assert agg["journal"]["records"] > 0

        # zero lost accepted broadcasts: every session that accepted a
        # broadcast has a terminal record or was settled by the replay
        sessions, _coms = recovery.load_state(fo["journal_dir"])
        settled = rec["replayed_terminal"] + rec["resumed"] + rec[
            "aborted_transient"
        ]
        assert settled == len(sessions), (rec, len(sessions))
    finally:
        sup.stop()
