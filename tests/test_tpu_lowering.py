"""AOT TPU-lowerability audit for every device kernel family.

Interpret mode and the XLA:CPU backend accept programs that Mosaic (the
Pallas TPU compiler) and the TPU lowering rules reject — the round-5 n16
bench died on chip with `Unsupported cast: uint32 -> bfloat16` after the
entire CPU suite passed. JAX's AOT path compiles for a platform without
owning a device: `jax.jit(f).trace(*args).lower(lowering_platforms=
("tpu",))` runs the full StableHLO + Mosaic kernel lowering on the CPU
host and raises exactly where a real chip compile would (verified: the
reverted cast bug reproduces under this harness).

Mechanism: run each public entry point on CPU at tiny shapes while
recording the concrete (args, kwargs) of its inner jitted kernel, then
re-lower every recorded call for platform "tpu". This keeps the audit in
lockstep with production routing — whatever the entry point launches is
what gets lowered.

Limits: lowering stops short of the Mosaic *backend* (register
allocation, VMEM budgeting), so out-of-VMEM failures still need the real
chip; everything at the lowering layer (unsupported casts, primitives,
layouts) is caught here.
"""

import contextlib
import secrets

import jax
import jax.numpy as jnp
import numpy as np

from fsdkr_tpu.ops import ec_batch, montgomery, pallas_rns, rns
from fsdkr_tpu.ops.limbs import limbs_for_bits
from fsdkr_tpu.utils.aot_check import lower_for_tpu

BITS = 512


class _CaptureStop(Exception):
    """Raised by the recorder once the kernel call is captured — the
    drivers' results are discarded, so executing the kernel on CPU and
    the host post-processing after it would be pure waste."""


@contextlib.contextmanager
def capture_calls(module, name, into):
    """Swap module.<name> for a recorder that stores (fn, args, kwargs)
    of the first call and aborts the driver via _CaptureStop."""
    orig = getattr(module, name)

    def recorder(*args, **kwargs):
        into.append((orig, args, kwargs))
        raise _CaptureStop

    setattr(module, name, recorder)
    try:
        with contextlib.suppress(_CaptureStop):
            yield
    finally:
        setattr(module, name, orig)


def _modexp_workload(rows):
    moduli = [
        secrets.randbits(BITS) | (1 << (BITS - 1)) | 1 for _ in range(rows)
    ]
    bases = [secrets.randbelow(n) for n in moduli]
    exps = [secrets.randbits(64) for _ in range(rows)]
    return bases, exps, moduli


class TestKernelsLowerForTpu:
    def test_rns_xla_chain(self, monkeypatch):
        monkeypatch.setenv("FSDKR_PALLAS", "0")
        bases, exps, moduli = _modexp_workload(8)
        calls = []
        with capture_calls(rns, "_rns_modexp_kernel", calls):
            rns.rns_modexp(bases, exps, moduli, BITS)
        assert calls, "driver never reached the kernel"
        for fn, args, kwargs in calls:
            lower_for_tpu(fn, args, kwargs)

    def test_rns_pallas_fused(self, monkeypatch):
        monkeypatch.setenv("FSDKR_PALLAS", "1")
        bases, exps, moduli = _modexp_workload(8)
        calls = []
        # rns_modexp_pallas is reached from inside the jitted wrapper and
        # therefore only at trace time: if an earlier test already traced
        # this exact static signature (test_pallas.py does), the cached
        # executable never re-enters Python and the capture sees nothing
        rns._rns_modexp_full_pallas.clear_cache()
        with capture_calls(pallas_rns, "rns_modexp_pallas", calls):
            rns.rns_modexp(bases, exps, moduli, BITS)
        assert calls, "driver never reached the Pallas kernel"
        for fn, args, kwargs in calls:
            text = lower_for_tpu(fn, args, kwargs)
            assert "tpu_custom_call" in text  # Mosaic kernel actually ran

    def test_rns_mont_mul_pallas(self):
        rb = rns.rns_bases_for_bits(BITS, limbs_for_bits(BITS))
        rows, k = 8, rb.k
        x = jnp.asarray(
            np.array([[i % int(m) for m in rb.m_all] for i in range(2, rows + 2)],
                     np.uint32)
        )
        c1 = jnp.zeros((rows, k), jnp.uint32)
        nbmr = jnp.ones((rows, k + 1), jnp.uint32)
        shared = rns._pallas_shared(rns._prep_consts(rb))
        text = lower_for_tpu(
            pallas_rns.rns_mont_mul_pallas,
            (x, x, c1, nbmr, shared),
            dict(k=k, interpret=False),
        )
        assert "tpu_custom_call" in text

    def test_rns_shared_comb(self, monkeypatch):
        monkeypatch.setenv("FSDKR_PALLAS", "0")
        gmods = [
            secrets.randbits(BITS) | (1 << (BITS - 1)) | 1 for _ in range(2)
        ]
        gbases = [secrets.randbelow(n) for n in gmods]
        gexps = [[secrets.randbits(64) for _ in range(4)] for _ in gmods]
        calls = []
        with capture_calls(rns, "_rns_shared_modexp_kernel", calls):
            rns.rns_modexp_shared(gbases, gexps, gmods, BITS)
        assert calls, "driver never reached the comb kernel"
        for fn, args, kwargs in calls:
            lower_for_tpu(fn, args, kwargs)

    def test_cios_generic(self):
        bases, exps, moduli = _modexp_workload(8)
        ctx = montgomery.BatchModExp(moduli, limbs_for_bits(BITS))
        calls = []
        with capture_calls(montgomery, "_modexp_kernel", calls):
            ctx.modexp(bases, exps)
        assert calls, "driver never reached the CIOS kernel"
        for fn, args, kwargs in calls:
            lower_for_tpu(fn, args, kwargs)

    def test_cios_shared_comb(self):
        gmods = [
            secrets.randbits(BITS) | (1 << (BITS - 1)) | 1 for _ in range(2)
        ]
        gbases = [secrets.randbelow(n) for n in gmods]
        gexps = [[secrets.randbits(64) for _ in range(4)] for _ in gmods]
        calls = []
        with capture_calls(montgomery, "_shared_modexp_kernel", calls):
            montgomery.shared_base_modexp(
                gbases, gexps, gmods, limbs_for_bits(BITS)
            )
        assert calls, "driver never reached the shared CIOS kernel"
        for fn, args, kwargs in calls:
            lower_for_tpu(fn, args, kwargs)

    def test_cios_shared_exp(self):
        """Shared-exponent rows x limbs kernel (FSDKR_RANGEOPT): the
        Alice-range s^n column — ONE public exponent's 4-bit digit
        schedule as a dynamic i32 vector, per-row bases, digit-indexed
        table select instead of the generic kernel's per-row one-hot
        compare. Must lower for TPU like the generic CIOS kernel."""
        mod = secrets.randbits(BITS) | (1 << (BITS - 1)) | 1
        bases = [secrets.randbelow(mod) for _ in range(8)]
        exp = secrets.randbits(BITS)
        calls = []
        with capture_calls(montgomery, "_shared_exp_kernel", calls):
            montgomery.shared_exp_modexp(
                bases, exp, mod, limbs_for_bits(BITS)
            )
        assert calls, "driver never reached the shared-exponent kernel"
        for fn, args, kwargs in calls:
            lower_for_tpu(fn, args, kwargs)

    def test_cios_multi_exp(self):
        """Joint (Straus) multi-exponentiation kernel: the FSDKR_MULTIEXP
        pair-loop rows [s, c^{-1}] with exponents [n, e]."""
        moduli = [
            secrets.randbits(BITS) | (1 << (BITS - 1)) | 1 for _ in range(8)
        ]
        bases = [
            (secrets.randbelow(n - 1) + 1, secrets.randbelow(n - 1) + 1)
            for n in moduli
        ]
        exps = [
            (secrets.randbits(BITS), secrets.randbits(64)) for _ in moduli
        ]
        calls = []
        with capture_calls(montgomery, "_multi_modexp_kernel", calls):
            montgomery.multi_modexp(
                bases, exps, moduli, limbs_for_bits(BITS), (BITS, 64)
            )
        assert calls, "driver never reached the multi-exp kernel"
        for fn, args, kwargs in calls:
            lower_for_tpu(fn, args, kwargs)

    def test_cios_multi_exp_nterm_tree(self):
        """n-term RLC aggregate rows (FSDKR_RLC): >= 4 active terms
        engage the kernel's log-depth tree fold of the selected window
        entries — that shape must lower for TPU like the 2-term one."""
        k = 5
        moduli = [
            secrets.randbits(BITS) | (1 << (BITS - 1)) | 1 for _ in range(8)
        ]
        bases = [
            tuple(secrets.randbelow(n - 1) + 1 for _ in range(k))
            for n in moduli
        ]
        exps = [
            tuple(secrets.randbits(128) for _ in range(k)) for _ in moduli
        ]
        calls = []
        with capture_calls(montgomery, "_multi_modexp_kernel", calls):
            montgomery.multi_modexp(
                bases, exps, moduli, limbs_for_bits(BITS), (128,) * k
            )
        assert calls, "driver never reached the multi-exp kernel"
        for fn, args, kwargs in calls:
            lower_for_tpu(fn, args, kwargs)

    def test_rns_multi_exp(self, monkeypatch):
        monkeypatch.setenv("FSDKR_PALLAS", "0")
        moduli = [
            secrets.randbits(BITS) | (1 << (BITS - 1)) | 1 for _ in range(8)
        ]
        bases = [
            (secrets.randbelow(n - 1) + 1, secrets.randbelow(n - 1) + 1)
            for n in moduli
        ]
        exps = [
            (secrets.randbits(BITS), secrets.randbits(64)) for _ in moduli
        ]
        calls = []
        with capture_calls(rns, "_rns_multi_modexp_kernel", calls):
            rns.rns_multi_modexp(bases, exps, moduli, BITS, (BITS, 64))
        assert calls, "driver never reached the RNS multi-exp kernel"
        for fn, args, kwargs in calls:
            lower_for_tpu(fn, args, kwargs)

    def test_ec_batch(self):
        from fsdkr_tpu.core import secp256k1 as ec

        pts = [ec.GENERATOR * (i + 2) for i in range(4)]
        scalars = [secrets.randbelow(ec.CURVE_ORDER) for _ in range(4)]
        calls = []
        with capture_calls(ec_batch, "_scalar_mul_kernel", calls):
            ec_batch.batch_scalar_mul(pts, scalars)
        assert calls, "driver never reached the EC kernel"
        for fn, args, kwargs in calls:
            lower_for_tpu(fn, args, kwargs)


class TestProductionGeometryLowers:
    """The capture sweep runs at TEST_CONFIG size (768-bit); the bench
    compiles the same kernels at 2048-bit (mod N, k=131) and 4096-bit
    (mod N^2, k=260 — past the single-chunk matmul bound) with larger
    row tiles. Lower the fused kernel at bench geometry via abstract
    ShapeDtypeStruct rows — no data, just the real compile problem."""

    def _lower(self, bits, rows, exp_bits):
        rb = rns.rns_bases_for_bits(bits, limbs_for_bits(bits))
        k = rb.k
        C = 2 * k + 1
        shared = rns._pallas_shared(rns._prep_consts(rb))
        sds = jax.ShapeDtypeStruct
        res = sds((rows, C), jnp.uint32)
        exp = sds((rows, -(-exp_bits // 16)), jnp.uint32)
        c1 = sds((rows, k), jnp.uint32)
        nbmr = sds((rows, k + 1), jnp.uint32)
        text = lower_for_tpu(
            pallas_rns.rns_modexp_pallas,
            (res, exp, res, c1, nbmr, shared),
            dict(exp_bits=exp_bits, k=k, interpret=False),
        )
        assert "tpu_custom_call" in text

    def test_2048bit_full_exponent(self):
        self._lower(2048, 1024, 2048)

    def test_4096bit_full_exponent(self):
        self._lower(4096, 512, 4096)


class TestEntryLowersForTpu:
    def test_graft_entry(self):
        """The driver compile-checks entry() on the real chip; pre-flight
        the same compile here so a lowering break is caught on CPU."""
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "_graft_entry",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "__graft_entry__.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        fn, example_args = mod.entry()
        jax.jit(fn).trace(*example_args).lower(lowering_platforms=("tpu",))
