"""Streaming-vs-barrier collect equivalence (ISSUE 9, tier-1).

The contract under test: `StreamingCollect` (offer messages in ANY
arrival order, with duplicates and late deliveries) produces verdicts,
identifiable-abort blame, and LocalKey mutations bit-identical to
barrier `collect` on the canonical message list — honest and tampered,
at n=3 and n=16 — and fused `finalize_streams` batches behave like
fused barrier `collect_sessions`.
"""

import copy
import dataclasses
import random

import pytest

from fsdkr_tpu.errors import (
    PDLwSlackProofError,
    RangeProofError,
    RingPedersenProofError,
    SizeMismatchError,
)
from fsdkr_tpu.protocol import RefreshMessage, finalize_streams, simulate_keygen


def _err_key(e):
    return (type(e).__name__, tuple(map(str, getattr(e, "args", ()))))


def _barrier_err(msgs, key, dk, config):
    try:
        RefreshMessage.collect(msgs, key, dk, (), config)
        return None
    except Exception as e:
        return _err_key(e)


def _stream_err(msgs, key, dk, config, seed=0):
    st = RefreshMessage.collect_stream(
        key, dk, [m.party_index for m in msgs], (), config
    )
    order = list(msgs)
    random.Random(seed).shuffle(order)
    for m in order:
        assert st.offer(m) == "accepted"
    try:
        st.finalize()
        return None
    except Exception as e:
        return _err_key(e)


def _assert_keys_equal(a, b):
    assert a.keys_linear.x_i.to_int() == b.keys_linear.x_i.to_int()
    assert a.pk_vec == b.pk_vec
    assert [ek.n for ek in a.paillier_key_vec] == [
        ek.n for ek in b.paillier_key_vec
    ]
    assert a.paillier_dk.p == b.paillier_dk.p
    assert a.paillier_dk.q == b.paillier_dk.q


def test_streaming_honest_identical_state(one_refresh_round, test_config):
    """Honest round: shuffled streaming arrival rotates the key to the
    exact state barrier collect produces."""
    keys, msgs, dks = one_refresh_round
    kb, ks = keys[0].clone(), keys[0].clone()
    RefreshMessage.collect(msgs, kb, dks[0], (), test_config)
    assert _stream_err(msgs, ks, dks[0], test_config, seed=11) is None
    _assert_keys_equal(kb, ks)


def test_streaming_offer_statuses(one_refresh_round, test_config):
    """Duplicate, late, and unexpected arrivals are classified and
    ignored without changing the verdict."""
    keys, msgs, dks = one_refresh_round
    key = keys[1].clone()
    st = RefreshMessage.collect_stream(
        key, dks[1], [m.party_index for m in msgs], (), test_config
    )
    assert st.offer(msgs[2]) == "accepted"
    assert st.offer(msgs[2]) == "duplicate"
    bogus = copy.deepcopy(msgs[0])
    bogus.party_index = 99
    assert st.offer(bogus) == "unexpected"
    assert not st.ready and st.missing() == [1, 2]
    assert st.offer(msgs[0]) == "accepted"
    assert st.offer(msgs[1]) == "accepted"
    assert st.ready
    st.finalize()
    assert st.done and st.error is None
    assert st.offer(msgs[0]) == "late"
    # idempotent finalize: replays the stored verdict, no re-adoption
    st.finalize()
    kb = keys[1].clone()
    RefreshMessage.collect(msgs, kb, dks[1], (), test_config)
    _assert_keys_equal(kb, key)


def test_streaming_finalize_before_quorum(one_refresh_round, test_config):
    keys, msgs, dks = one_refresh_round
    st = RefreshMessage.collect_stream(
        keys[0].clone(), dks[0], [m.party_index for m in msgs], (), test_config
    )
    st.offer(msgs[0])
    with pytest.raises(ValueError, match="quorum"):
        st.finalize()
    # the session stays open: completing it afterwards works
    st.offer(msgs[1])
    st.offer(msgs[2])
    st.finalize()
    assert st.error is None


# every tamper lands on a different verification family / phase, so the
# replayed barrier error order is exercised end to end
TAMPERS = [
    (
        "pdl_s1",
        lambda msgs: msgs[1].pdl_proof_vec.__setitem__(
            0,
            dataclasses.replace(
                msgs[1].pdl_proof_vec[0], s1=msgs[1].pdl_proof_vec[0].s1 + 1
            ),
        ),
        PDLwSlackProofError,
    ),
    (
        "range_s",
        lambda msgs: msgs[1].range_proofs.__setitem__(
            0,
            dataclasses.replace(
                msgs[1].range_proofs[0], s=msgs[1].range_proofs[0].s + 1
            ),
        ),
        RangeProofError,
    ),
    (
        "ring_pedersen_Z",
        lambda msgs: msgs[2].ring_pedersen_proof.Z.__setitem__(
            0, msgs[2].ring_pedersen_proof.Z[0] + 1
        ),
        RingPedersenProofError,
    ),
    (
        "short_vector",
        lambda msgs: msgs[2].points_encrypted_vec.pop(),
        SizeMismatchError,
    ),
]


@pytest.mark.parametrize("name,mutate,expected", TAMPERS, ids=[t[0] for t in TAMPERS])
def test_streaming_tamper_blame_identical(
    one_refresh_round, test_config, name, mutate, expected
):
    """Single-field tampers: streaming (out-of-order arrival) raises the
    exact error instance barrier collect raises — same type, same
    identifiable-abort attribution."""
    keys, msgs, dks = one_refresh_round
    bad = copy.deepcopy(msgs)
    mutate(bad)
    e_b = _barrier_err(copy.deepcopy(bad), keys[0].clone(), dks[0], test_config)
    e_s = _stream_err(copy.deepcopy(bad), keys[0].clone(), dks[0], test_config, seed=5)
    assert e_b is not None and e_b[0] == expected.__name__
    assert e_s == e_b


def test_finalize_streams_fused_batch(one_refresh_round, test_config):
    """Fused finalize across sessions == fused barrier collect_sessions:
    one healthy session and one tampered session finalized in ONE
    launch; the tampered one gets its exact blame, the healthy one
    adopts — failing sessions never block the others."""
    keys, msgs, dks = one_refresh_round
    bad = copy.deepcopy(msgs)
    bad[0].range_proofs[1] = dataclasses.replace(
        bad[0].range_proofs[1], s=bad[0].range_proofs[1].s + 1
    )
    k_good, k_bad = keys[0].clone(), keys[1].clone()
    streams = []
    for key, dk, mlist, seed in (
        (k_good, dks[0], msgs, 3),
        (k_bad, dks[1], bad, 4),
    ):
        st = RefreshMessage.collect_stream(
            key, dk, [m.party_index for m in mlist], (), test_config
        )
        order = list(mlist)
        random.Random(seed).shuffle(order)
        for m in order:
            st.offer(m)
        streams.append(st)
    errs = finalize_streams(streams, test_config)
    ref = RefreshMessage.collect_sessions(
        [
            (msgs, keys[0].clone(), dks[0], ()),
            (copy.deepcopy(bad), keys[1].clone(), dks[1], ()),
        ],
        test_config,
    )
    assert errs[0] is None and ref[0] is None
    assert _err_key(errs[1]) == _err_key(ref[1])
    assert streams[0].error is None and streams[1].error is errs[1]


@pytest.fixture(scope="module")
def committee16(test_config):
    """One honest n=16 round (cached keygen; single distribute_batch
    shared by the honest and tamper arms below)."""
    keys = simulate_keygen(7, 16, test_config)
    results = RefreshMessage.distribute_batch(
        [(k.i, k) for k in keys], 16, test_config
    )
    return keys, [m for m, _ in results], [dk for _, dk in results]


def test_streaming_n16_honest_identical(committee16, test_config):
    """ISSUE 9 acceptance: honest n=16 session — streaming under
    shuffled arrival is state-identical to barrier collect."""
    keys, msgs, dks = committee16
    kb, ks = keys[0].clone(), keys[0].clone()
    RefreshMessage.collect(msgs, kb, dks[0], (), test_config)
    assert _stream_err(msgs, ks, dks[0], test_config, seed=16) is None
    _assert_keys_equal(kb, ks)


def test_streaming_n16_tamper_blame_identical(committee16, test_config):
    """ISSUE 9 acceptance: single tamper in an n=16 session — streaming
    blame (through the RLC fold + bisection) is bit-identical to
    barrier."""
    keys, msgs, dks = committee16
    bad = copy.deepcopy(msgs)
    bad[5].range_proofs[3] = dataclasses.replace(
        bad[5].range_proofs[3], s=bad[5].range_proofs[3].s + 1
    )
    e_b = _barrier_err(copy.deepcopy(bad), keys[0].clone(), dks[0], test_config)
    e_s = _stream_err(copy.deepcopy(bad), keys[0].clone(), dks[0], test_config, seed=61)
    assert e_b is not None and e_b[0] == "RangeProofError"
    assert e_s == e_b


def test_streaming_n16_adversarial_arrival(committee16, test_config):
    """ISSUE 11 satellite: adversarial arrival at n=16 — EVERY sender's
    message arrives twice (duplicate), sender 5's arrives tampered
    FIRST with the honest copy as the corrected duplicate (first
    arrival wins, so the tampered transcript is the canonical one), and
    after finalize every message arrives again (late). Verdict + blame
    are bit-identical to barrier collect on the accepted message list,
    and none of the duplicate/late deliveries perturb anything."""
    keys, msgs, dks = committee16
    tampered = copy.deepcopy(msgs[4])
    tampered.range_proofs[2] = dataclasses.replace(
        tampered.range_proofs[2], s=tampered.range_proofs[2].s + 1
    )
    tamper_pid = msgs[4].party_index

    st = RefreshMessage.collect_stream(
        keys[0].clone(), dks[0], [m.party_index for m in msgs], (),
        test_config,
    )
    order = list(msgs)
    random.Random(29).shuffle(order)
    for m in order:
        if m.party_index == tamper_pid:
            assert st.offer(tampered) == "accepted"
            assert st.offer(m) == "duplicate"  # corrected copy: too late
        else:
            assert st.offer(m) == "accepted"
            assert st.offer(m) == "duplicate"
    assert st.ready
    try:
        st.finalize()
        e_s = None
    except Exception as e:
        e_s = _err_key(e)
    # barrier on the ACCEPTED (canonical) list: honest except sender 5
    canon = [copy.deepcopy(m) for m in msgs]
    canon[4] = copy.deepcopy(tampered)
    e_b = _barrier_err(canon, keys[0].clone(), dks[0], test_config)
    assert e_b is not None and e_b[0] == "RangeProofError"
    assert e_s == e_b
    # late-after-finalize: every sender again, honest and tampered
    for m in msgs:
        assert st.offer(m) == "late"
    assert st.offer(tampered) == "late"
    assert st.error is not None and _err_key(st.error) == e_b


def test_streaming_n16_corrected_first_wins(committee16, test_config):
    """The mirror case: the HONEST copy arrives first and the tampered
    copy second (a rejected duplicate) for every sender — the session
    finishes clean, state-identical to barrier collect on the honest
    list. An adversary who loses the broadcast race changes nothing."""
    keys, msgs, dks = committee16
    kb, ks = keys[1].clone(), keys[1].clone()
    st = RefreshMessage.collect_stream(
        ks, dks[1], [m.party_index for m in msgs], (), test_config
    )
    order = list(msgs)
    random.Random(31).shuffle(order)
    for m in order:
        assert st.offer(m) == "accepted"
        bad = copy.deepcopy(m)
        bad.pdl_proof_vec[0] = dataclasses.replace(
            bad.pdl_proof_vec[0], s1=bad.pdl_proof_vec[0].s1 + 1
        )
        assert st.offer(bad) == "duplicate"  # tampered dup: ignored
    st.finalize()
    assert st.error is None
    RefreshMessage.collect(msgs, kb, dks[1], (), test_config)
    _assert_keys_equal(kb, ks)
