"""Crash recovery (ISSUE 12): journal replay through the shared
offer()/finalize path.

The contract under test: a session interrupted by process death
replays from the public-broadcast journal to the SAME verdict,
identifiable-abort blame, and adopted LocalKey state as the
uninterrupted run (shared-helper equivalence, like every prior
streaming/barrier pin) — honest and tampered, at n=3 (full service
path) and n=16 (a single-receiver shard replaying a foreign journal).
Terminal records replay their stored verdict with no recompute; a
session whose secret state cannot be re-derived aborts WITHOUT blame
(transient, retryable); an empty journal is a no-op; `submit(cid,
epoch=N)` keeps deduping across the restart."""

import time

import pytest

from fsdkr_tpu import precompute
from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen
from fsdkr_tpu.protocol.serialization import (
    refresh_message_from_json,
    refresh_message_to_json,
)
from fsdkr_tpu.serving import (
    BatchPolicy,
    Journal,
    MemoryKeystore,
    RefreshService,
    faults,
    recovery,
)


@pytest.fixture(autouse=True)
def _clean():
    faults.reset()
    precompute.clear_targets()
    precompute.clear_pools()
    yield
    faults.reset()
    precompute.clear_targets()
    precompute.clear_pools()


def _err_key(e):
    return (type(e).__name__, tuple(map(str, getattr(e, "args", ()))))


def _assert_keys_equal(a, b):
    assert a.keys_linear.x_i.to_int() == b.keys_linear.x_i.to_int()
    assert a.pk_vec == b.pk_vec
    assert [ek.n for ek in a.paillier_key_vec] == [
        ek.n for ek in b.paillier_key_vec
    ]
    assert a.paillier_dk.p == b.paillier_dk.p


def _crash_mid_flight(jdir, keys, config, spec=None):
    """Run one journaled service session to quorum with the launcher
    lingering 'forever', then crash: abandon the service object. What
    survives is exactly what survives real process death — the journal
    on disk — plus the keystore, which stands in for the re-derivable
    secret state (in-process restart semantics)."""
    ks = MemoryKeystore()
    svc = RefreshService(
        journal=str(jdir),
        keystore=ks,
        policy=BatchPolicy(max_sessions=10 ** 6, linger_s=3600.0),
    )
    svc.admit("com", [k.clone() for k in keys], config)
    svc.start()
    if spec:
        faults.configure(spec)
    sid = svc.submit("com", epoch=0)
    deadline = time.monotonic() + 120
    ready = False
    while time.monotonic() < deadline:
        with svc._lock:
            if svc._ready:
                ready = True
                break
        time.sleep(0.02)
    faults.reset()
    svc.stop(timeout=10)
    assert ready, "session never reached quorum before the crash"
    return ks, sid


def _control_barrier(jdir, ks, sid, config, cid="com"):
    """The uninterrupted run: barrier collect over the journaled wire
    messages (canonical order) on CLONES of the keystore's key state.
    Returns (per-party error keys, control key clones)."""
    js = recovery.load_state(jdir)[0][sid]
    msgs = sorted(
        (refresh_message_from_json(w) for _s, w in js.broadcasts),
        key=lambda m: m.party_index,
    )
    dks = ks.session_dks(cid, sid)
    control = [k.clone() for k in ks.committee_keys(cid)]
    errs = []
    for i, k in enumerate(control):
        try:
            RefreshMessage.collect(msgs, k, dks[i], (), config)
            errs.append(None)
        except Exception as e:
            errs.append(_err_key(e))
    return errs, control


def test_resume_bit_identity_honest_n3(tmp_path, test_config):
    """A session killed between quorum and finalize resumes from the
    journal and adopts the EXACT key state the uninterrupted barrier
    run produces."""
    keys = simulate_keygen(1, 3, test_config)
    jdir = tmp_path / "j"
    ks, sid = _crash_mid_flight(jdir, keys, test_config)
    control_errs, control = _control_barrier(jdir, ks, sid, test_config)
    assert control_errs == [None, None, None]

    svc2 = RefreshService(journal=str(jdir), keystore=ks)
    svc2.start()
    try:
        rep = recovery.recover(svc2, jdir, ks)
        assert rep["resumed"] == 1 and rep["replayed_terminal"] == 0
        assert rep["committees_admitted"] == 1
        assert rep["broadcasts_replayed"] == 3
        new_sid = rep["sessions"][sid]["sid"]
        assert svc2.drain(timeout=60)
        s2 = svc2.wait(new_sid, timeout=1)
        assert s2.state == "done" and s2.error is None and not s2.blame
        for a, b in zip(control, ks.committee_keys("com")):
            _assert_keys_equal(a, b)
    finally:
        svc2.stop()

    # double-recovery chain regression: a THIRD incarnation of the same
    # directory must NOT re-resume the original session (it was
    # superseded) — re-running the old broadcasts against the rotated
    # keys would re-adopt or blame honest senders. The origin's dks
    # are gone from the keystore, nothing resumes, and the committee
    # key state is untouched.
    assert ks.session_dks("com", sid) is None
    x_after = [k.keys_linear.x_i.to_int() for k in ks.committee_keys("com")]
    svc3 = RefreshService(journal=str(jdir), keystore=ks)
    svc3.start()
    try:
        rep3 = recovery.recover(svc3, jdir, ks)
        assert rep3["resumed"] == 0 and rep3["aborted_transient"] == 0
        # origin sid replays as a superseded terminal; the resumed
        # session replays its done verdict — nothing recomputes
        assert rep3["sessions"][sid]["disposition"] == "replayed_terminal"
        assert rep3["sessions"][new_sid]["state"] == "done"
        assert [
            k.keys_linear.x_i.to_int() for k in ks.committee_keys("com")
        ] == x_after
    finally:
        svc3.stop()


def test_resume_bit_identity_tampered_n3(tmp_path, test_config):
    """The journaled copy of a tampered broadcast (first arrival wins)
    replays to the SAME identifiable-abort blame the uninterrupted run
    produces — and no adoption happens on either side."""
    keys = simulate_keygen(1, 3, test_config)
    jdir = tmp_path / "j"
    ks, sid = _crash_mid_flight(
        jdir, keys, test_config, spec="seed=21,msg_tamper=1.0,msg_tamper_max=1"
    )
    control_errs, control = _control_barrier(jdir, ks, sid, test_config)
    assert any(e is not None for e in control_errs)
    blame_type = next(e for e in control_errs if e is not None)[0]

    svc2 = RefreshService(journal=str(jdir), keystore=ks)
    svc2.start()
    try:
        rep = recovery.recover(svc2, jdir, ks)
        new_sid = rep["sessions"][sid]["sid"]
        assert svc2.drain(timeout=60)
        s2 = svc2.wait(new_sid, timeout=1)
        assert s2.state == "aborted" and s2.blame, (s2.state, s2.error)
        assert blame_type in s2.error
        # a blamed session never adopted: key state matches the control
        # (whose collect also raised before adoption)
        for a, b in zip(control, ks.committee_keys("com")):
            _assert_keys_equal(a, b)
    finally:
        svc2.stop()


def test_terminal_replay_and_restart_idempotency(tmp_path, test_config):
    """ISSUE 12 satellite: a done epoch's terminal record replays its
    verdict with NO recompute, and `submit(cid, epoch=N)` keeps
    deduping from the journaled history after the restart (pinned
    restart-then-resubmit)."""
    keys = simulate_keygen(1, 3, test_config)
    jdir = tmp_path / "j"
    ks = MemoryKeystore()
    svc = RefreshService(journal=str(jdir), keystore=ks)
    svc.admit("com", [k.clone() for k in keys], test_config)
    svc.start()
    sid = svc.submit("com", epoch=0)
    assert svc.drain(timeout=60)
    assert svc.wait(sid, timeout=1).state == "done"
    svc.stop()

    svc2 = RefreshService(journal=str(jdir), keystore=ks)
    svc2.start()
    try:
        rep = recovery.recover(svc2, jdir, ks)
        assert rep["replayed_terminal"] == 1 and rep["resumed"] == 0
        new_sid = rep["sessions"][sid]["sid"]
        s2 = svc2.wait(new_sid, timeout=1)
        assert s2.state == "done"
        assert svc2.stats()["sessions_replayed"] == 1
        assert svc2.stats()["sessions_done"] == 0  # verdict, not work
        # the restart-then-resubmit pin: epoch 0 dedupes to the
        # replayed verdict; epoch 1 actually runs
        assert svc2.submit("com", epoch=0) == new_sid
        assert svc2.stats()["sessions_done"] == 0
        sid1 = svc2.submit("com", epoch=1)
        assert sid1 != new_sid
        assert svc2.drain(timeout=60)
        assert svc2.wait(sid1, timeout=1).state == "done"
        assert svc2.stats()["sessions_done"] == 1
    finally:
        svc2.stop()
    # same-directory restarts must not double the terminal set: the
    # replayed verdict is NOT re-journaled into the log it came from
    # (a peer adopting a foreign journal does re-journal). Epoch 0 has
    # exactly one terminal record however many times we restart.
    from fsdkr_tpu.serving.journal import read_records

    terminals_e0 = [
        r for r in read_records(jdir)
        if r.get("t") == "terminal" and r.get("epoch") == 0
    ]
    assert len(terminals_e0) == 1, terminals_e0


def test_unrecoverable_secrets_abort_transient_retryable(
    tmp_path, test_config
):
    """Cross-process death: the session's new dks died with the shard.
    Recovery must terminate the session `aborted` WITHOUT blame
    (RecoverySecretsUnavailable is not a verdict) and leave the epoch
    resubmittable — never fabricate a verdict."""
    keys = simulate_keygen(1, 3, test_config)
    jdir = tmp_path / "j"
    ks, sid = _crash_mid_flight(jdir, keys, test_config)
    # a peer shard's keystore: committee keys re-derivable, session
    # secrets NOT (they lived only in the dead process)
    ks2 = MemoryKeystore()
    ks2.put_committee("com", ks.committee_keys("com"))
    svc2 = RefreshService(journal=str(jdir), keystore=ks2)
    svc2.start()
    try:
        rep = recovery.recover(svc2, jdir, ks2)
        assert rep["aborted_transient"] == 1 and rep["resumed"] == 0
        new_sid = rep["sessions"][sid]["sid"]
        s2 = svc2.wait(new_sid, timeout=1)
        assert s2.state == "aborted" and not s2.blame
        assert "RecoverySecretsUnavailable" in s2.error
        # retryable: the same epoch resubmits as a FRESH session and
        # completes (the supervisor's failover path)
        sid2 = svc2.submit("com", epoch=0)
        assert sid2 != new_sid
        assert svc2.drain(timeout=60)
        assert svc2.wait(sid2, timeout=1).state == "done"
    finally:
        svc2.stop()


def test_recover_missing_or_empty_journal_is_noop(tmp_path, test_config):
    keys = simulate_keygen(1, 3, test_config)
    svc = RefreshService(journal=str(tmp_path / "live"))
    svc.admit("com", [k.clone() for k in keys], test_config)
    rep = recovery.recover(svc, tmp_path / "nonexistent")
    assert rep["resumed"] == rep["replayed_terminal"] == 0
    assert rep["aborted_transient"] == rep["skipped"] == 0
    (tmp_path / "empty").mkdir()
    rep = recovery.recover(svc, tmp_path / "empty")
    assert rep["resumed"] == rep["replayed_terminal"] == 0
    assert svc.stats()["inflight"] == 0


def _n16_journal(j, sid, cid, wires, order, config):
    """Hand-write one single-receiver session into a journal: the
    deployment shape where a shard hosts ONE party of a large
    committee, and recovery replays a journal its writer never shared
    a process with (the file format is the contract)."""
    j.append(
        {
            "t": "committee",
            "cid": cid,
            "n": 1,
            "tt": 7,
            "config": recovery.config_record(config),
        }
    )
    j.append({"t": "admitted", "sid": sid, "cid": cid, "epoch": 0})
    j.append(
        {"t": "collecting", "sid": sid, "expected": list(range(1, 17))}
    )
    for i in order:
        j.append(
            {"t": "broadcast", "sid": sid, "sender": i + 1,
             "wire": wires[i]}
        )


def test_n16_replay_bit_identity_honest_and_tampered(tmp_path, test_config):
    """The n=16 pin (acceptance): replayed verdict + blame bit-identical
    to the uninterrupted run, honest AND tampered, through a journal
    the recovering shard did not write. One distribute feeds both arms;
    the controls run as one fused barrier launch and the two resumed
    sessions COALESCE into one fused finalize (the recovery launch
    shape a real shard uses), so the pin also covers fused-launch
    isolation after replay."""
    keys = simulate_keygen(7, 16, test_config)
    results = RefreshMessage.distribute_batch(
        [(k.i, k) for k in keys], 16, test_config
    )
    dk0 = results[0][1]
    msgs_h = [m for m, _ in results]
    msgs_t = list(msgs_h)
    msgs_t[4] = faults.tamper_message(msgs_t[4])
    base = keys[0].clone()  # post-distribute, pre-collect receiver state
    import random as _random

    order = list(range(16))
    _random.Random(16).shuffle(order)  # journal = arrival order
    jdir = tmp_path / "j16"
    j = Journal(jdir, sync="off")
    _n16_journal(
        j, 1, "c16h", [refresh_message_to_json(m) for m in msgs_h],
        order, test_config,
    )
    _n16_journal(
        j, 2, "c16t", [refresh_message_to_json(m) for m in msgs_t],
        order, test_config,
    )
    j.close()

    # the uninterrupted run: both sessions in ONE fused barrier launch
    control_h, control_t = base.clone(), base.clone()
    errs = RefreshMessage.collect_sessions(
        [(msgs_h, control_h, dk0, ()), (msgs_t, control_t, dk0, ())],
        test_config,
    )
    assert errs[0] is None and errs[1] is not None
    blame_type = _err_key(errs[1])[0]

    ks = MemoryKeystore()
    live_h, live_t = base.clone(), base.clone()
    ks.put_committee("c16h", [live_h])
    ks.put_committee("c16t", [live_t])
    ks.put_session_dks("c16h", 1, [dk0])
    ks.put_session_dks("c16t", 2, [dk0])
    svc = RefreshService(journal=str(tmp_path / "peer"), keystore=ks)
    svc.start()
    try:
        rep = recovery.recover(svc, jdir, ks)
        assert rep["resumed"] == 2 and rep["broadcasts_replayed"] == 32
        sid_h = rep["sessions"][1]["sid"]
        sid_t = rep["sessions"][2]["sid"]
        assert svc.drain(timeout=300)
        s_h = svc.wait(sid_h, timeout=1)
        assert s_h.state == "done" and s_h.error is None, (
            s_h.state, s_h.error,
        )
        s_t = svc.wait(sid_t, timeout=1)
        assert s_t.state == "aborted" and s_t.blame, (s_t.state, s_t.error)
        assert blame_type in s_t.error
        _assert_keys_equal(control_h, live_h)  # adopted identically
        _assert_keys_equal(control_t, live_t)  # neither side adopted
    finally:
        svc.stop()
