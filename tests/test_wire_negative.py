"""Wire-format negatives: malformed or tampered broadcast bytes must fail
closed at decode time or be rejected by collect — never decode into a
message that verifies. Complements tests/test_tamper.py (object-level)
with byte/JSON-level adversarial inputs, per the reference's
serde-everything wire surface (`src/refresh_message.rs:29-30`)."""

import json

import pytest

from fsdkr_tpu.core.secp256k1 import P, Point
from fsdkr_tpu.errors import FsDkrError
from fsdkr_tpu.protocol import RefreshMessage
from fsdkr_tpu.protocol.serialization import (
    refresh_message_from_json,
    refresh_message_to_json,
)


class TestPointDecoding:
    def test_off_curve_point_rejected(self):
        # x = 5 with forced even-y prefix: 5^3+7 = 132 is a QR? decode
        # validates y^2 == x^3+7; craft an x whose rhs is a non-residue
        for x in range(2, 40):
            blob = bytes([2]) + x.to_bytes(32, "big")
            try:
                p = Point.from_bytes(blob)
            except ValueError:
                break  # found a non-residue x: rejection path exercised
            assert (p.y * p.y - (p.x**3 + 7)) % P == 0
        else:
            pytest.fail("no non-residue x found in range (unexpected)")

    def test_non_canonical_x_rejected(self):
        with pytest.raises(ValueError):
            Point.from_bytes(bytes([2]) + (P + 1).to_bytes(32, "big"))

    def test_bad_prefix_rejected(self):
        with pytest.raises(ValueError):
            Point.from_bytes(bytes([7]) + (5).to_bytes(32, "big"))


@pytest.fixture(scope="module")
def one_round(one_refresh_round):
    """Shared honest round (see conftest.one_refresh_round)."""
    return one_refresh_round


class TestWireTamper:
    def test_truncated_json_rejected(self, one_round):
        _, msgs, _ = one_round
        wire = refresh_message_to_json(msgs[0])
        with pytest.raises((json.JSONDecodeError, KeyError, ValueError)):
            refresh_message_from_json(wire[: len(wire) // 2])

    def test_missing_field_rejected(self, one_round):
        _, msgs, _ = one_round
        d = json.loads(refresh_message_to_json(msgs[0]))
        del d["ek"]
        with pytest.raises((KeyError, ValueError, TypeError)):
            refresh_message_from_json(json.dumps(d))

    def test_bitflipped_ciphertext_rejected_by_collect(
        self, one_round, test_config
    ):
        """A single hex-digit flip in a broadcast ciphertext decodes fine
        (it is just an integer) but must be caught by the PDL proof that
        binds it."""
        keys, msgs, dks = one_round
        d = json.loads(refresh_message_to_json(msgs[1]))
        c = d["points_encrypted_vec"][0]
        d["points_encrypted_vec"][0] = ("0" if c[0] != "0" else "1") + c[1:]
        evil = refresh_message_from_json(json.dumps(d))
        wire_msgs = [msgs[0], evil, msgs[2]]
        with pytest.raises(FsDkrError):
            RefreshMessage.collect(
                wire_msgs, keys[0].clone(), dks[0], (), test_config
            )

    # batched-backend collects cost ~11 s each on the CPU platform: keep
    # the smoke gate under 3 minutes (scripts/ci.sh), as in test_tamper
    @pytest.mark.parametrize(
        "backend", ["host", pytest.param("tpu", marks=pytest.mark.heavy)]
    )
    @pytest.mark.parametrize(
        "field,proof_key",
        [
            ("range_proofs", "s1"),
            ("range_proofs", "s2"),
            ("pdl_proof_vec", "s1"),
            ("pdl_proof_vec", "s3"),
        ],
    )
    def test_negative_int_through_wire_rejected(
        self, one_round, test_config, backend, field, proof_key
    ):
        """Hex int decoding admits a leading minus sign; a negative
        exponent-position field smuggled through the wire must yield an
        identifiable-abort FsDkrError on BOTH backends — on the batched
        backend it must fail its row, not crash the limb encoder."""
        keys, msgs, dks = one_round
        d = json.loads(refresh_message_to_json(msgs[1]))
        d[field][0][proof_key] = "-" + d[field][0][proof_key]
        evil = refresh_message_from_json(json.dumps(d))
        with pytest.raises(FsDkrError):
            RefreshMessage.collect(
                [msgs[0], evil, msgs[2]],
                keys[0].clone(),
                dks[0],
                (),
                test_config.with_backend(backend),
            )

    @pytest.mark.parametrize(
        "backend", ["host", pytest.param("tpu", marks=pytest.mark.heavy)]
    )
    def test_negative_ringpedersen_z_through_wire_rejected(
        self, one_round, test_config, backend
    ):
        keys, msgs, dks = one_round
        d = json.loads(refresh_message_to_json(msgs[1]))
        d["ring_pedersen_proof"]["Z"][0] = "-" + d["ring_pedersen_proof"]["Z"][0]
        evil = refresh_message_from_json(json.dumps(d))
        with pytest.raises(FsDkrError):
            RefreshMessage.collect(
                [msgs[0], evil, msgs[2]],
                keys[0].clone(),
                dks[0],
                (),
                test_config.with_backend(backend),
            )
