"""Wire-format negatives: malformed or tampered broadcast bytes must fail
closed at decode time or be rejected by collect — never decode into a
message that verifies. Complements tests/test_tamper.py (object-level)
with byte/JSON-level adversarial inputs, per the reference's
serde-everything wire surface (`src/refresh_message.rs:29-30`)."""

import json

import pytest

from fsdkr_tpu.core.secp256k1 import P, Point
from fsdkr_tpu.errors import FsDkrError
from fsdkr_tpu.protocol import RefreshMessage
from fsdkr_tpu.protocol.serialization import (
    refresh_message_from_json,
    refresh_message_to_json,
)


class TestPointDecoding:
    def test_off_curve_point_rejected(self):
        # x = 5 with forced even-y prefix: 5^3+7 = 132 is a QR? decode
        # validates y^2 == x^3+7; craft an x whose rhs is a non-residue
        for x in range(2, 40):
            blob = bytes([2]) + x.to_bytes(32, "big")
            try:
                p = Point.from_bytes(blob)
            except ValueError:
                break  # found a non-residue x: rejection path exercised
            assert (p.y * p.y - (p.x**3 + 7)) % P == 0
        else:
            pytest.fail("no non-residue x found in range (unexpected)")

    def test_non_canonical_x_rejected(self):
        with pytest.raises(ValueError):
            Point.from_bytes(bytes([2]) + (P + 1).to_bytes(32, "big"))

    def test_bad_prefix_rejected(self):
        with pytest.raises(ValueError):
            Point.from_bytes(bytes([7]) + (5).to_bytes(32, "big"))


@pytest.fixture(scope="module")
def one_round(one_refresh_round):
    """Shared honest round (see conftest.one_refresh_round)."""
    return one_refresh_round


class TestWireTamper:
    def test_truncated_json_rejected(self, one_round):
        _, msgs, _ = one_round
        wire = refresh_message_to_json(msgs[0])
        with pytest.raises((json.JSONDecodeError, KeyError, ValueError)):
            refresh_message_from_json(wire[: len(wire) // 2])

    def test_missing_field_rejected(self, one_round):
        _, msgs, _ = one_round
        d = json.loads(refresh_message_to_json(msgs[0]))
        del d["ek"]
        with pytest.raises((KeyError, ValueError, TypeError)):
            refresh_message_from_json(json.dumps(d))

    def test_bitflipped_ciphertext_rejected_by_collect(
        self, one_round, test_config
    ):
        """A single hex-digit flip in a broadcast ciphertext decodes fine
        (it is just an integer) but must be caught by the PDL proof that
        binds it."""
        keys, msgs, dks = one_round
        d = json.loads(refresh_message_to_json(msgs[1]))
        c = d["points_encrypted_vec"][0]
        d["points_encrypted_vec"][0] = ("0" if c[0] != "0" else "1") + c[1:]
        evil = refresh_message_from_json(json.dumps(d))
        wire_msgs = [msgs[0], evil, msgs[2]]
        with pytest.raises(FsDkrError):
            RefreshMessage.collect(
                wire_msgs, keys[0].clone(), dks[0], (), test_config
            )

    def test_multimegabit_s1_rejected_without_dead_row_blowup(
        self, one_round, test_config
    ):
        """A multi-megabit range-proof s1 decodes fine (it is a bare
        positive hex magnitude) but violates the q^3 slack gate: collect
        must reject it through the domain gate WITHOUT ever staging the
        row — in particular without building its (1 + s1*n) % n^2, the
        round-8 dead-row blowup (backend.tpu_verifier._range_finish /
        _range_opt_prepare skip gated rows before gs1). The staging-side
        guarantee is pinned white-box in tests/test_range_engines.py;
        this is the wire-level end-to-end negative."""
        keys, msgs, dks = one_round
        d = json.loads(refresh_message_to_json(msgs[1]))
        huge = (1 << 2_000_001) + 5  # ~2 Mbit, far past q^3
        d["range_proofs"][0]["s1"] = format(huge, "x")
        evil = refresh_message_from_json(json.dumps(d))
        assert evil.range_proofs[0].s1 == huge
        wire_msgs = [msgs[0], evil, msgs[2]]
        from fsdkr_tpu.errors import RangeProofError

        # the batched backend is where dead-row staging lives; the host
        # oracle short-circuits on the range gate before any arithmetic
        with pytest.raises(RangeProofError) as ei:
            RefreshMessage.collect(
                wire_msgs, keys[0].clone(), dks[0], (),
                test_config.with_backend("tpu"),
            )
        # reference loop attribution: the 0-based receiver slot of the
        # failing row (src/refresh_message.rs:330-350 loop order)
        assert ei.value.party_index == 0

    @pytest.mark.parametrize(
        "mutate_json",
        [
            lambda d: d["range_proofs"][0].__setitem__(
                "s1", "-" + d["range_proofs"][0]["s1"]
            ),
            lambda d: d["pdl_proof_vec"][0].__setitem__(
                "s3", "-" + d["pdl_proof_vec"][0]["s3"]
            ),
            lambda d: d["ring_pedersen_proof"]["Z"].__setitem__(
                0, "-" + d["ring_pedersen_proof"]["Z"][0]
            ),
            lambda d: d["points_encrypted_vec"].__setitem__(
                0, "-" + d["points_encrypted_vec"][0]
            ),
            lambda d: d["ring_pedersen_statement"].__setitem__(
                "N", "-" + d["ring_pedersen_statement"]["N"]
            ),
            lambda d: d["pdl_proof_vec"][0].__setitem__("z", "0xAB"),
            lambda d: d["ek"].__setitem__("n", "12_34"),
            lambda d: d["range_proofs"][0].__setitem__("e", ""),
        ],
        ids=[
            "neg_range_s1",
            "neg_pdl_s3",
            "neg_ringped_Z",
            "neg_ciphertext",
            "neg_statement_N",
            "prefixed_hex",
            "underscore_hex",
            "empty_hex",
        ],
    )
    def test_non_canonical_wire_int_rejected_at_decode(
        self, one_round, mutate_json
    ):
        """The canonical wire integer is a bare lowercase-hex magnitude:
        minus signs (negative smuggling into exponent/transcript
        positions), 0x prefixes, underscores, and empty strings all fail
        closed at message decode — where the receiver knows exactly which
        party sent the bytes."""
        _, msgs, _ = one_round
        d = json.loads(refresh_message_to_json(msgs[1]))
        mutate_json(d)
        with pytest.raises(ValueError):
            refresh_message_from_json(json.dumps(d))
