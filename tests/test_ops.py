"""Differential tests: TPU limb kernels vs the CPython host oracle
(SURVEY.md §4 rebuild implication v — every kernel checked against the
Python-int oracle). Runs on the virtual CPU platform (see conftest)."""

import secrets

import pytest

from fsdkr_tpu.core import primes
from fsdkr_tpu.ops import limbs
from fsdkr_tpu.ops.montgomery import (
    BatchModExp,
    batch_modexp,
    batch_modmul,
    shared_base_modexp,
)


class TestLimbs:
    def test_roundtrip(self):
        xs = [0, 1, (1 << 512) - 1, secrets.randbits(500)]
        arr = limbs.ints_to_limbs(xs, limbs.limbs_for_bits(512))
        assert limbs.limbs_to_ints(arr) == xs

    def test_rejects_overflow(self):
        with pytest.raises(ValueError):
            limbs.ints_to_limbs([1 << 64], 4)

    def test_rejects_negative(self):
        with pytest.raises(ValueError):
            limbs.ints_to_limbs([-1], 4)

    def test_montgomery_context_rejects_even(self):
        with pytest.raises(ValueError):
            limbs.MontgomeryContext([6], 4)


def _random_moduli(bits, count):
    """Odd moduli of roughly `bits` bits, mixed shapes (prime products and
    arbitrary odd numbers — Montgomery needs only oddness)."""
    out = []
    for i in range(count):
        if i % 2:
            out.append(secrets.randbits(bits) | (1 << (bits - 1)) | 1)
        else:
            half = bits // 2
            out.append(primes.gen_prime(half) * primes.gen_prime(half))
    return out


class TestBatchModExp:
    @pytest.mark.parametrize("bits", [256, 768, 1536])
    def test_vs_host_oracle(self, bits):
        B = 8
        moduli = _random_moduli(bits, B)
        bases = [secrets.randbelow(n) for n in moduli]
        exps = [secrets.randbits(bits) for _ in range(B)]
        k = limbs.limbs_for_bits(bits)
        got = batch_modexp(bases, exps, moduli, k)
        want = [pow(b, e, n) for b, e, n in zip(bases, exps, moduli)]
        assert got == want

    def test_mixed_exponent_sizes(self):
        bits = 512
        B = 6
        moduli = _random_moduli(bits, B)
        bases = [secrets.randbelow(n) for n in moduli]
        exps = [0, 1, 2, secrets.randbits(17), secrets.randbits(256), secrets.randbits(512)]
        got = batch_modexp(bases, exps, moduli, limbs.limbs_for_bits(bits))
        assert got == [pow(b, e, n) for b, e, n in zip(bases, exps, moduli)]

    def test_base_reduction(self):
        # bases >= modulus are reduced on the host side before the kernel
        bits = 256
        moduli = _random_moduli(bits, 2)
        bases = [moduli[0] + 5, moduli[1] * 2 + 7]
        exps = [3, 5]
        got = batch_modexp(bases, exps, moduli, limbs.limbs_for_bits(bits))
        assert got == [pow(b, e, n) for b, e, n in zip(bases, exps, moduli)]

    def test_modmul(self):
        bits = 768
        B = 8
        moduli = _random_moduli(bits, B)
        a = [secrets.randbelow(n) for n in moduli]
        b = [secrets.randbelow(n) for n in moduli]
        got = batch_modmul(a, b, moduli, limbs.limbs_for_bits(bits))
        assert got == [(x * y) % n for x, y, n in zip(a, b, moduli)]

    def test_reusable_context(self):
        bits = 512
        moduli = _random_moduli(bits, 4)
        ctx = BatchModExp(moduli, limbs.limbs_for_bits(bits))
        for _ in range(3):
            bases = [secrets.randbelow(n) for n in moduli]
            exps = [secrets.randbits(200) for _ in moduli]
            assert ctx.modexp(bases, exps) == [
                pow(b, e, n) for b, e, n in zip(bases, exps, moduli)
            ]

@pytest.mark.heavy
class TestSharedBaseModExp:
    """The fixed-base comb kernel: groups share (base, modulus), exactly
    the shape of the ring-Pedersen and PDL/range verification columns."""

    @pytest.mark.parametrize("bits", [256, 768])
    @pytest.mark.parametrize("host_ladder", [True, False])
    def test_vs_host_oracle(self, bits, host_ladder):
        G, M = 3, 6
        moduli = _random_moduli(bits, G)
        bases = [secrets.randbelow(n) for n in moduli]
        exps = [[secrets.randbits(bits) for _ in range(M)] for _ in range(G)]
        got = shared_base_modexp(
            bases, exps, moduli, limbs.limbs_for_bits(bits), host_ladder=host_ladder
        )
        assert got == [
            [pow(b, e, n) for e in grp]
            for b, grp, n in zip(bases, exps, moduli)
        ]

    def test_ragged_groups_and_edge_exponents(self):
        bits = 512
        moduli = _random_moduli(bits, 3)
        bases = [secrets.randbelow(n) for n in moduli]
        exps = [
            [0, 1, 2],
            [secrets.randbits(512)],
            [15, 16, 17, (1 << 512) - 1, secrets.randbits(40)],
        ]
        got = shared_base_modexp(bases, exps, moduli, limbs.limbs_for_bits(bits))
        assert got == [
            [pow(b, e, n) for e in grp]
            for b, grp, n in zip(bases, exps, moduli)
        ]

    def test_grouped_router_matches_host(self):
        from fsdkr_tpu.backend.powm import tpu_powm_grouped

        bits = 512
        n1, n2 = _random_moduli(bits, 2)
        b1, b2 = secrets.randbelow(n1), secrets.randbelow(n2)
        # 5 rows sharing (b1, n1) -> comb; 2 loner rows -> generic kernel
        bases = [b1] * 5 + [b2, secrets.randbelow(n2)]
        moduli = [n1] * 5 + [n2, n2]
        exps = [secrets.randbits(bits) for _ in bases]
        got = tpu_powm_grouped(bases, exps, moduli)
        assert got == [pow(b, e, n) for b, e, n in zip(bases, exps, moduli)]


class TestBatchModExpCarry:
    def test_worst_case_carry_chains(self):
        # moduli / operands built from long 0xffff runs stress the lazy
        # carry normalization and the borrow scan
        bits = 512
        k = limbs.limbs_for_bits(bits)
        n1 = (1 << bits) - 1  # all-ones odd modulus
        n2 = (1 << bits) - (1 << 17) + 1
        moduli = [n1, n2]
        bases = [n1 - 1, n2 - 2]
        exps = [n1 - 1, (1 << 256) + 1]
        got = batch_modexp(bases, exps, moduli, k)
        assert got == [pow(b, e, n) for b, e, n in zip(bases, exps, moduli)]


class TestBatchModInv:
    def test_tree_inversion_matches_pow(self):
        import random

        from fsdkr_tpu.ops.limbs import limbs_for_bits
        from fsdkr_tpu.ops.montgomery import batch_mod_inv_grouped

        rng = random.Random(11)
        groups = []
        for bits in (512, 768):
            for _ in range(3):
                m = rng.getrandbits(bits) | (1 << (bits - 1)) | 1
                vs = [rng.getrandbits(bits - 1) | 1 for _ in range(rng.choice([1, 5, 8]))]
                groups.append((m, vs))
        k = limbs_for_bits(768)
        res = batch_mod_inv_grouped(groups, k)
        import math

        for (m, vs), invs in zip(groups, res):
            for v, got in zip(vs, invs):
                if math.gcd(v, m) == 1:
                    assert got == pow(v, -1, m)
                else:  # group falls back to host; bad row reports None
                    assert got is None

    def test_non_invertible_group_falls_back(self):
        import random

        from fsdkr_tpu.ops.limbs import limbs_for_bits
        from fsdkr_tpu.ops.montgomery import batch_mod_inv_grouped

        rng = random.Random(12)
        # modulus divisible by 3; one value shares the factor
        p = 3
        m = 0
        while m % 2 == 0 or m.bit_length() != 512:
            m = p * (rng.getrandbits(510) | (1 << 509) | 1)
        good = [rng.getrandbits(500) | 1 for _ in range(3)]
        good = [g for g in good if __import__("math").gcd(g, m) == 1]
        vals = good + [p]  # p not invertible mod m
        m2 = rng.getrandbits(512) | (1 << 511) | 1
        other = [rng.getrandbits(500) | 1 for _ in range(4)]
        res = batch_mod_inv_grouped([(m, vals), (m2, other)], limbs_for_bits(512))
        # poisoned group: per-row fallback, None for the bad row
        for v, got in zip(vals[:-1], res[0][:-1]):
            assert got == pow(v, -1, m)
        assert res[0][-1] is None
        # healthy group unaffected
        for v, got in zip(other, res[1]):
            assert got == pow(v, -1, m2)


@pytest.mark.heavy
def test_comb_tree_matches_ladder():
    """Chunked tree accumulation (tree_chunk > 1) must agree with the
    sequential ladder (tree_chunk=1) and the host oracle, including a
    non-power-of-two window count (768-bit bucket -> 192 windows)."""
    import random

    import jax.numpy as jnp

    from fsdkr_tpu.ops.limbs import MontgomeryContext, ints_to_limbs, limbs_to_ints
    from fsdkr_tpu.ops.montgomery import _shared_modexp_kernel

    rng = random.Random(3)
    bits, e_bits, g, m = 256, 768, 2, 3
    k = bits // 16
    mods = [rng.getrandbits(bits) | (1 << (bits - 1)) | 1 for _ in range(g)]
    bases = [rng.getrandbits(bits - 1) % n for n in mods]
    exps = [[rng.getrandbits(e_bits) for _ in range(m)] for _ in range(g)]
    ctx = MontgomeryContext(mods, k)
    el = e_bits // 16
    args = (
        jnp.asarray(ints_to_limbs(bases, k)),
        jnp.asarray(
            [ints_to_limbs(grp, el) for grp in exps]
        ),
        jnp.asarray(ctx.n),
        jnp.asarray(ctx.n_prime),
        jnp.asarray(ctx.r2),
        jnp.asarray(ctx.one_mont),
    )
    want = [[pow(b, e, n) for e in grp] for b, grp, n in zip(bases, exps, mods)]
    for chunk in (1, 8, 64, 256):
        out = _shared_modexp_kernel(*args, exp_bits=e_bits, tree_chunk=chunk)
        got = limbs_to_ints(
            __import__("numpy").asarray(out).reshape(g * m, k)
        )
        got = [got[i * m : (i + 1) * m] for i in range(g)]
        assert got == want, f"tree_chunk={chunk} mismatch"
