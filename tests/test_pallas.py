"""Differential tests for the fused Pallas RNS MontMul kernel
(fsdkr_tpu.ops.pallas_rns) in interpret mode: bit-identical to the XLA
chain `ops.rns._rns_mont_mul`, and the full modexp pipeline with the
Pallas path forced must match CPython pow."""

import math
import secrets

import jax.numpy as jnp
import numpy as np
import pytest

from fsdkr_tpu.ops import rns
from fsdkr_tpu.ops.limbs import LIMB_BITS

BITS = 512
LIMBS = BITS // LIMB_BITS


@pytest.fixture(scope="module")
def bases_512():
    return rns.rns_bases_for_bits(BITS, LIMBS)


def _consts_arrays(rb):
    return rns._prep_consts(rb)


def _row_setup(rb, rows, bits=BITS):
    # coprime to every channel prime: colliding moduli take the
    # production per-row fallback, not the kernel under test
    channel_prod = rb.A * rb.B * rb.m_r
    moduli = []
    while len(moduli) < rows:
        n = secrets.randbits(bits) | (1 << (bits - 1)) | 1
        if math.gcd(n, channel_prod) == 1:
            moduli.append(n)
    c1 = np.zeros((rows, rb.k), np.uint32)
    n_bmr = np.zeros((rows, rb.k + 1), np.uint32)
    for r, n in enumerate(moduli):
        for i, a in enumerate(rb.A_primes):
            c1[r, i] = (-pow(n, -1, a)) % a * int(rb.Ai_inv[i]) % a
        for j, b in enumerate(rb.B_primes):
            n_bmr[r, j] = n % b
        n_bmr[r, rb.k] = n % rb.m_r
    return moduli, jnp.asarray(c1), jnp.asarray(n_bmr)


def _to_residues(xs, rb):
    return jnp.asarray(
        np.array(
            [[x % int(m) for m in rb.m_all] for x in xs], np.uint32
        )
    )


@pytest.mark.heavy
class TestPallasMontMul:
    def test_matches_xla_chain(self, bases_512):
        """Same inputs through the Pallas kernel (interpret) and the XLA
        `_rns_mont_mul` must agree channel-for-channel."""
        rb = bases_512
        rows = 8
        moduli, c1, n_bmr = _row_setup(rb, rows)
        xs = [secrets.randbelow(n) for n in moduli]
        ys = [secrets.randbelow(n) for n in moduli]
        x = _to_residues(xs, rb)
        y = _to_residues(ys, rb)

        consts_arrays = _consts_arrays(rb)
        (m_all, u_all, T1l, T1h, T2l, T2h, Ainv_B, c2_B, B_mod_A, Binv_r, Wl, Wh) = (
            consts_arrays
        )

        k = rb.k
        xla_consts = dict(
            k=k,
            m_all=m_all,
            u_all=u_all,
            T1s=rns._resplit(T1l, T1h),
            T2s=rns._resplit(T2l, T2h),
            mA_mr=jnp.concatenate([m_all[:k], m_all[2 * k :]]),
            uA_mr=jnp.concatenate([u_all[:k], u_all[2 * k :]]),
            Ainv_B=Ainv_B,
            c2_B=c2_B,
            B_mod_A=B_mod_A,
            Binv_r=Binv_r,
            c1_A=c1,
            N_Bmr=n_bmr,
        )
        want = np.asarray(rns._rns_mont_mul(x, y, xla_consts))

        from fsdkr_tpu.ops.pallas_rns import rns_mont_mul_pallas

        got = np.asarray(
            rns_mont_mul_pallas(
                x, y, c1, n_bmr, rns._pallas_shared(consts_arrays),
                k=k, interpret=True,
            )
        )
        assert (got == want).all()

    def test_matmul_chunking_4096_class(self):
        """The 4096-bit width class has k=260 channels — beyond the 2^24
        full-width f32 exactness bound. The chunked Pallas matmul must
        still match the XLA chain (regression for the unchunked-dot
        bug)."""
        from fsdkr_tpu.ops.limbs import limbs_for_bits
        from fsdkr_tpu.ops.pallas_rns import rns_mont_mul_pallas

        bits = 4096
        rb = rns.rns_bases_for_bits(bits, limbs_for_bits(bits))
        assert rb.k > 257  # the premise of this regression test
        rows = 8
        moduli, c1, n_bmr = _row_setup(rb, rows, bits=bits)
        # worst-case-ish inputs: residues near the channel maxima
        x = jnp.asarray(
            np.array(
                [[int(m) - 1 for m in rb.m_all] for _ in range(rows)], np.uint32
            )
        )
        y = jnp.asarray(
            np.array(
                [[int(m) - 2 for m in rb.m_all] for _ in range(rows)], np.uint32
            )
        )
        consts_arrays = _consts_arrays(rb)
        k = rb.k
        xla_consts = dict(
            k=k,
            m_all=consts_arrays[0],
            u_all=consts_arrays[1],
            T1s=rns._resplit(consts_arrays[2], consts_arrays[3]),
            T2s=rns._resplit(consts_arrays[4], consts_arrays[5]),
            mA_mr=jnp.concatenate(
                [consts_arrays[0][:k], consts_arrays[0][2 * k :]]
            ),
            uA_mr=jnp.concatenate(
                [consts_arrays[1][:k], consts_arrays[1][2 * k :]]
            ),
            Ainv_B=consts_arrays[6],
            c2_B=consts_arrays[7],
            B_mod_A=consts_arrays[8],
            Binv_r=consts_arrays[9],
            c1_A=c1,
            N_Bmr=n_bmr,
        )
        want = np.asarray(rns._rns_mont_mul(x, y, xla_consts))
        got = np.asarray(
            rns_mont_mul_pallas(
                x, y, c1, n_bmr, rns._pallas_shared(consts_arrays),
                k=k, interpret=True,
            )
        )
        assert (got == want).all()

    def test_full_modexp_pallas_forced(self, bases_512, monkeypatch):
        """rns_modexp with FSDKR_PALLAS=1 (interpret off-TPU) vs pow."""
        monkeypatch.setenv("FSDKR_PALLAS", "1")
        rows = 8
        moduli = [
            secrets.randbits(BITS) | (1 << (BITS - 1)) | 1 for _ in range(rows)
        ]
        bases = [secrets.randbelow(n) for n in moduli]
        exps = [secrets.randbits(64) for _ in range(rows)]
        got = rns.rns_modexp(bases, exps, moduli, BITS)
        want = [pow(b, e, n) for b, e, n in zip(bases, exps, moduli)]
        assert got == want
