"""Network ingress (ISSUE 13): wire framing, the asyncio TCP server's
hygiene policies (frame caps, CRC, backpressure, idle/slow-loris, peer
rate limiting, graceful drain), the end-to-end socket path (verdict
parity with the in-process control, tamper blame over the wire, typed
wait-timeout frames), the network fault sites, and the wire fuzz suite
— a hostile client must never crash the server or wedge a bystander's
connection.
"""

import socket
import struct
import threading
import time
import zlib

import pytest

from fsdkr_tpu.protocol import simulate_keygen
from fsdkr_tpu.serving import (
    SLO,
    IngressClient,
    IngressServer,
    OverloadPolicy,
    PeerRateLimiter,
    RefreshService,
    faults,
)
from fsdkr_tpu.serving import metrics as smetrics
from fsdkr_tpu.serving.ingress import (
    FRAME_HEADER,
    FrameError,
    _parse_frames,
    encode_frame,
)


@pytest.fixture(autouse=True)
def _clean():
    faults.reset()
    yield
    faults.reset()


def _serve(test_config, keys, cid, deadline_s=20.0, **svc_kw):
    """A started service with one admitted committee behind a started
    ingress. Caller stops both."""
    svc = RefreshService(deadline_s=deadline_s, **svc_kw)
    svc.admit(cid, [k.clone() for k in keys], test_config,
              SLO(arrival_rate_hz=0.5))
    svc.start()
    srv = IngressServer(svc).start()
    return svc, srv


def _run_epoch(cli, cid, epoch, wait_s=60.0):
    """Drive one full refresh epoch over the socket; returns the
    terminal response."""
    r = cli.submit(cid, epoch=epoch)
    assert r["type"] == "submitted", r
    bcasts = r.get("broadcasts")
    if bcasts is None:
        bcasts = cli.fetch(r["sid"])["broadcasts"]
    for _snd, wire in bcasts:
        ack = cli.broadcast(r["sid"], wire)
        assert ack["type"] == "broadcast_ack", ack
    term = cli.wait(r["sid"], wait_s)
    assert term["type"] == "terminal", term
    return term


# ---------------------------------------------------------------------------
# framing


def test_frame_roundtrip_and_partial_buffers():
    objs = [{"op": "ping", "rid": i, "pad": "x" * (i * 7)} for i in range(5)]
    blob = b"".join(encode_frame(o) for o in objs)
    # whole-buffer parse
    buf = bytearray(blob)
    out = _parse_frames(buf, 1 << 20)
    assert [o for o, _n in out] == objs and not buf
    # byte-at-a-time: every prefix parses only the complete frames
    buf = bytearray()
    seen = []
    for b in blob:
        buf.append(b)
        seen += [o for o, _n in _parse_frames(buf, 1 << 20)]
    assert seen == objs


def test_frame_defects_raise_with_cause():
    ok = encode_frame({"op": "ping"})
    # oversize length prefix
    giant = struct.pack("<II", 1 << 30, 0)
    with pytest.raises(FrameError, match="oversize"):
        _parse_frames(bytearray(giant), 1 << 20)
    # CRC mismatch
    bad = bytearray(ok)
    bad[-1] ^= 0xFF
    with pytest.raises(FrameError, match="crc"):
        _parse_frames(bad, 1 << 20)
    # valid CRC, garbage payload
    payload = b"\x00not-json"
    frame = FRAME_HEADER.pack(len(payload), zlib.crc32(payload)) + payload
    with pytest.raises(FrameError, match="malformed"):
        _parse_frames(bytearray(frame), 1 << 20)
    # valid JSON, not an object
    payload = b"[1,2,3]"
    frame = FRAME_HEADER.pack(len(payload), zlib.crc32(payload)) + payload
    with pytest.raises(FrameError, match="malformed"):
        _parse_frames(bytearray(frame), 1 << 20)
    # an incomplete tail is NOT an error — it waits for more bytes
    buf = bytearray(ok[:-2])
    assert _parse_frames(buf, 1 << 20) == [] and len(buf) == len(ok) - 2


def test_peer_rate_limiter_unit():
    lim = PeerRateLimiter(rps=2.0, burst=2.0)
    t = 100.0
    assert lim.charge("a", t) is None and lim.charge("a", t) is None
    hint = lim.charge("a", t)  # bucket dry
    assert hint is not None and hint > 0
    # hammering past a whole burst of sheds: close verdict
    verdicts = [lim.charge("a", t) for _ in range(4)]
    assert verdicts[-1] == -1.0
    # an independent peer is untouched
    assert lim.charge("b", t) is None
    # tokens refill with time (and a successful admit clears the debt)
    assert lim.charge("a", t + 10.0) is None
    assert lim.charge("a", t + 10.0) is None  # bucket now spent again
    # disconnect while spent: the bucket is RETAINED — an instant
    # reconnect must not buy a hammering peer a fresh burst
    lim.forget("a", t + 10.0)
    assert lim.charge("a", t + 10.0) is not None
    # disconnect after the bucket refilled to a full burst: dropped
    # (a fresh bucket would be no more permissive)
    lim.forget("a", t + 20.0)
    assert lim.charge("a", t + 20.0) is None
    assert PeerRateLimiter(rps=0).charge("x") is None  # disabled


# ---------------------------------------------------------------------------
# end-to-end over the socket


def test_socket_epoch_verdict_matches_in_process(test_config):
    """The same committee runs epoch 0 in-process and epoch 1 over the
    socket: identical verdicts (done, no blame). The wait-timeout comes
    back as a TYPED error frame mid-flight, and an idempotent resubmit
    over the wire returns the same session with its broadcast set."""
    keys = simulate_keygen(1, 3, test_config)
    svc, srv = _serve(test_config, keys, "e2e")
    cli = None
    try:
        sid0 = svc.submit("e2e", epoch=0)
        s0 = svc.wait(sid0, 60)
        assert s0.state == "done" and not s0.blame

        cli = IngressClient("127.0.0.1", srv.port)
        r = cli.submit("e2e", epoch=1)
        assert r["type"] == "submitted" and r["state"] == "collecting"
        assert sorted(r["senders"]) == [1, 2, 3]
        # typed timeout while short of quorum — not a closed connection
        t = cli.wait(r["sid"], 0.2)
        assert t == {"type": "error", "error": "timeout", "sid": r["sid"],
                     "timeout_s": 0.2, "rid": t["rid"]}
        # idempotent resubmit: same sid, broadcasts served again
        r2 = cli.submit("e2e", epoch=1)
        assert r2["sid"] == r["sid"] and len(r2["broadcasts"]) == 3
        for _snd, wire in r2["broadcasts"]:
            assert cli.broadcast(r["sid"], wire)["result"] == "accepted"
        term = cli.wait(r["sid"], 60)
        assert term["state"] == "done" and not term["blame"], term
        # the socket epoch rotated keys exactly like the in-process one
        assert svc.stats()["sessions_done"] == 2
        snap = smetrics.ingress_snapshot()
        assert snap["frames"]["in"] >= 6 and snap["frames"]["out"] >= 6
    finally:
        if cli is not None:
            cli.close()
        srv.stop()
        svc.stop()


def test_tampered_broadcast_over_wire_blamed(test_config):
    """A man-on-the-wire tampering one broadcast (tampered copy first,
    honest copy as the corrected duplicate) produces the identifiable-
    abort blame verdict — CRC is framing hygiene, the PROOFS are the
    authentication (SECURITY.md 'Ingress discipline')."""
    from fsdkr_tpu.protocol.serialization import (
        refresh_message_from_json,
        refresh_message_to_json,
    )

    keys = simulate_keygen(1, 3, test_config)
    svc, srv = _serve(test_config, keys, "tamper")
    cli = None
    try:
        cli = IngressClient("127.0.0.1", srv.port)
        r = cli.submit("tamper", epoch=0)
        sid = r["sid"]
        bcasts = dict(r["broadcasts"])
        bad = refresh_message_to_json(
            faults.tamper_message(refresh_message_from_json(bcasts[2]))
        )
        assert cli.broadcast(sid, bad)["result"] == "accepted"
        assert cli.broadcast(sid, bcasts[2])["result"] == "duplicate"
        for snd in (1, 3):
            assert cli.broadcast(sid, bcasts[snd])["result"] == "accepted"
        term = cli.wait(sid, 60)
        assert term["state"] == "aborted" and term["blame"], term
        assert "PDLwSlackProof" in (term["error"] or ""), term
    finally:
        if cli is not None:
            cli.close()
        srv.stop()
        svc.stop()


def test_deadline_names_missing_senders_over_wire(test_config):
    """Deliver 2 of 3 broadcasts and let the deadline fire: the
    timed_out verdict names the sender the network lost."""
    keys = simulate_keygen(1, 3, test_config)
    svc, srv = _serve(test_config, keys, "gap", deadline_s=2.0)
    cli = None
    try:
        cli = IngressClient("127.0.0.1", srv.port)
        r = cli.submit("gap", epoch=0)
        bcasts = dict(r["broadcasts"])
        for snd in (1, 3):
            cli.broadcast(r["sid"], bcasts[snd])
        term = cli.wait(r["sid"], 30)
        assert term["state"] == "timed_out", term
        assert "missing senders [2]" in (term["error"] or ""), term
        # a broadcast landing after the deadline is late, not accepted
        assert cli.broadcast(r["sid"], bcasts[2])["result"] == "late"
    finally:
        if cli is not None:
            cli.close()
        srv.stop()
        svc.stop()


# ---------------------------------------------------------------------------
# wire fuzz: hostile bytes never crash the server or wedge a bystander


def test_wire_fuzz_hostile_frames_isolated(test_config):
    """Random bytes, giant length prefixes, truncated frames, CRC-bad
    frames, valid-frame/garbage-payload mixes, and unknown ops each get
    exactly their own connection closed — and a bystander connection
    runs a full epoch to a clean verdict while the abuse is ongoing."""
    import random as _random

    keys = simulate_keygen(1, 3, test_config)
    svc, srv = _serve(test_config, keys, "fuzz")
    rng = _random.Random(1234)

    def hostile(blob):
        """Send `blob`, assert the server closes (EOF/RST) rather than
        hanging or answering garbage."""
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        try:
            s.sendall(blob)
            s.settimeout(5)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                try:
                    data = s.recv(4096)
                except socket.timeout:
                    pytest.fail("server neither closed nor answered")
                except OSError:
                    return  # RST: closed hard, good
                if not data:
                    return  # clean close
        finally:
            s.close()
        pytest.fail("hostile connection not closed in time")

    def crc_frame(payload: bytes) -> bytes:
        return FRAME_HEADER.pack(len(payload), zlib.crc32(payload)) + payload

    try:
        bad = bytearray(encode_frame({"op": "ping", "rid": 9}))
        bad[-1] ^= 0x5A
        blobs = [
            rng.randbytes(512),                       # noise
            struct.pack("<II", 1 << 31, 7),           # giant length prefix
            crc_frame(b"\xff\xfe garbage payload"),   # valid CRC, not JSON
            crc_frame(b"[1, 2, 3]"),                  # JSON, not an object
            crc_frame(b'{"op": "exec", "rid": 1}'),   # unknown op
            bytes(bad),                               # CRC mismatch
        ]
        # interleave abuse with bystander liveness on a healthy conn
        cli = IngressClient("127.0.0.1", srv.port)
        for i, blob in enumerate(blobs):
            hostile(blob)
            assert cli.ping()["type"] == "pong", f"bystander hurt by #{i}"
        # a truncated frame is LEGITIMATE partial data — the server must
        # wait (not crash), and our abandoning the connection must not
        # hurt anyone else
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        s.sendall(encode_frame({"op": "ping"})[:-3])
        s.close()
        assert cli.ping()["type"] == "pong"
        term = _run_epoch(cli, "fuzz", 0)
        assert term["state"] == "done" and not term["blame"], term
        cli.close()
        causes = smetrics.ingress_snapshot()["frames_rejected"]
        for cause in ("oversize", "malformed", "bad_op", "crc"):
            assert causes.get(cause, 0) >= 1, (cause, causes)
    finally:
        srv.stop()
        svc.stop()


def test_fuzz_random_mutations_of_valid_stream(test_config):
    """200 random mutations of a valid request stream: flip/truncate/
    splice bytes; the server survives them all and still serves."""
    import random as _random

    keys = simulate_keygen(1, 3, test_config)
    svc, srv = _serve(test_config, keys, "fuzz2")
    rng = _random.Random(99)
    base = encode_frame({"op": "ping", "rid": 1}) + encode_frame(
        {"op": "wait", "sid": 1, "timeout": 0, "rid": 2}
    )
    try:
        for _ in range(200):
            blob = bytearray(base)
            for _k in range(rng.randint(1, 6)):
                mode = rng.randrange(3)
                if mode == 0 and blob:
                    blob[rng.randrange(len(blob))] ^= 1 << rng.randrange(8)
                elif mode == 1 and blob:
                    del blob[rng.randrange(len(blob)):]
                else:
                    blob += rng.randbytes(rng.randint(1, 32))
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            try:
                s.sendall(bytes(blob))
            except OSError:
                pass  # server already closed us mid-send: fine
            finally:
                s.close()
        cli = IngressClient("127.0.0.1", srv.port)
        assert cli.ping()["type"] == "pong"
        cli.close()
    finally:
        srv.stop()
        svc.stop()


# ---------------------------------------------------------------------------
# admission control, rate limiting, backpressure, hygiene, drain


def test_overload_shed_is_a_rejected_frame(test_config):
    """With the service's workers not yet started, queued sessions pile
    up; the overload policy sheds the second submit as an explicit
    `rejected` frame carrying retry_after_s — then start() drains the
    first one to done."""
    keys = simulate_keygen(1, 3, test_config)
    svc = RefreshService(
        deadline_s=30.0, overload=OverloadPolicy(max_queue=1)
    )
    for cid in ("ovl-a", "ovl-b"):
        svc.admit(cid, [k.clone() for k in keys], test_config,
                  SLO(arrival_rate_hz=0.5))
    srv = IngressServer(svc).start()
    cli = None
    try:
        cli = IngressClient("127.0.0.1", srv.port)
        rid_a = cli.send({"op": "submit", "cid": "ovl-a", "epoch": 0})
        time.sleep(0.3)  # a queues (no workers yet)
        rej = cli.request({"op": "submit", "cid": "ovl-b", "epoch": 0})
        assert rej["type"] == "rejected" and rej["retry_after_s"] >= 0.1, rej
        assert rej["reason"] == "overload"
        svc.start()
        ra = cli.recv(rid_a, timeout=60)
        assert ra["type"] == "submitted", ra
        for _snd, wire in ra["broadcasts"]:
            cli.broadcast(ra["sid"], wire)
        assert cli.wait(ra["sid"], 60)["state"] == "done"
    finally:
        if cli is not None:
            cli.close()
        srv.stop()
        svc.stop()


def test_peer_rate_limit_sheds_then_closes(test_config):
    """An over-rps peer first gets `rejected` frames, then — still
    hammering — loses its connection; peer_rate_shed counts both."""
    keys = simulate_keygen(1, 3, test_config)
    svc = RefreshService(deadline_s=20.0)
    svc.admit("rate", [k.clone() for k in keys], test_config)
    svc.start()
    shed0 = smetrics.ingress_snapshot()["peer_rate_shed"]
    srv = IngressServer(
        svc, limiter=PeerRateLimiter(rps=1.0, burst=2.0)
    ).start()
    cli = None
    try:
        cli = IngressClient("127.0.0.1", srv.port)
        saw_rejected = False
        with pytest.raises(ConnectionError):
            for _ in range(32):
                r = cli.request({"op": "ping"}, timeout=5)
                if r.get("type") == "rejected":
                    saw_rejected = True
                    assert r["reason"] == "peer_rate"
        assert saw_rejected
        assert smetrics.ingress_snapshot()["peer_rate_shed"] > shed0
        # the peer's debt decays: a polite reconnect works again
        time.sleep(1.2)
        cli.close()
        cli = IngressClient("127.0.0.1", srv.port)
        assert cli.ping()["type"] == "pong"
    finally:
        if cli is not None:
            cli.close()
        srv.stop()
        svc.stop()


def test_backpressure_pauses_reads_under_inflight_budget(test_config):
    """Pipelined slow requests past the inflight byte budget force a
    real TCP read pause (counted), and every response still arrives
    once the budget drains — backpressure, not loss."""
    keys = simulate_keygen(1, 3, test_config)
    svc = RefreshService(deadline_s=30.0)
    svc.admit("bp", [k.clone() for k in keys], test_config)
    svc.start()
    srv = IngressServer(
        svc, conn_inflight_budget=160, inflight_budget=320
    ).start()
    cli = None
    try:
        cli = IngressClient("127.0.0.1", srv.port)
        sid = cli.submit("bp", epoch=0)["sid"]  # parks collecting
        # each wait frame is ~60 B and holds its budget for ~0.6 s
        rids = [
            cli.send({"op": "wait", "sid": sid, "timeout": 0.6})
            for _ in range(8)
        ]
        got = [cli.recv(rid, timeout=30) for rid in rids]
        assert all(g["error"] == "timeout" for g in got), got
        paused = smetrics.ingress_snapshot()["paused_reads"]
        assert sum(paused.values()) >= 1, paused
    finally:
        if cli is not None:
            cli.close()
        srv.stop()
        svc.stop()


def test_big_frame_release_resumes_reads(test_config):
    """REGRESSION: a single frame larger than half the per-connection
    budget pauses reads; its OWN release must resume them. (The bug:
    _release ran before the connection's charge was decremented, so
    the resume check saw the stale value and the connection wedged
    forever — the hygiene sweep deliberately spares paused conns.)"""
    keys = simulate_keygen(1, 3, test_config)
    svc = RefreshService(deadline_s=30.0)
    svc.admit("big", [k.clone() for k in keys], test_config)
    svc.start()
    srv = IngressServer(svc, conn_inflight_budget=256).start()
    cli = None
    try:
        cli = IngressClient("127.0.0.1", srv.port)
        # one ~600 B frame: charges past the 256 B budget alone, so its
        # release is the ONLY event that can ever resume this conn
        r = cli.request({"op": "ping", "pad": "x" * 600}, timeout=10)
        assert r["type"] == "pong", r
        # reads resumed: the next request on the same conn is answered
        assert cli.ping()["type"] == "pong"
        paused = smetrics.ingress_snapshot()["paused_reads"]
        assert paused.get("conn", 0) >= 1, paused
    finally:
        if cli is not None:
            cli.close()
        srv.stop()
        svc.stop()


def test_sweep_resumes_server_paused_idle_conn(test_config):
    """REGRESSION: a connection paused by the GLOBAL budget pass while
    holding no charge of its own has no release of its own to resume
    it, and while global inflight oscillates in (budget/2, budget] the
    release-side resume checks never fire — the hygiene sweep must be
    its resume backstop (it deliberately never closes paused conns)."""
    keys = simulate_keygen(1, 3, test_config)
    svc = RefreshService(deadline_s=20.0)
    svc.admit("sw", [k.clone() for k in keys], test_config)
    svc.start()
    srv = IngressServer(svc).start()
    cli = None
    try:
        cli = IngressClient("127.0.0.1", srv.port, timeout=10)
        assert cli.ping()["type"] == "pong"
        conn = next(iter(srv.conns))
        paused = threading.Event()

        def _pause():
            # what the global pass does to an idle bystander, with the
            # load band then held above budget/2 by OTHER connections
            srv.inflight = srv.inflight_budget // 2 + 1
            conn.paused = True
            conn.transport.pause_reading()
            paused.set()

        srv.loop.call_soon_threadsafe(_pause)
        assert paused.wait(5)
        deadline = time.monotonic() + 5.0
        while conn.paused and time.monotonic() < deadline:
            time.sleep(0.1)
        assert not conn.paused, "sweep never resumed the idle paused conn"
        srv.loop.call_soon_threadsafe(setattr, srv, "inflight", 0)
        assert cli.ping()["type"] == "pong"  # reads really did resume
    finally:
        if cli is not None:
            cli.close()
        srv.stop()
        svc.stop()


def test_slow_read_loris_closed_despite_drip(test_config):
    """A peer dribbling one byte of a never-completed frame keeps the
    idle clock fresh — but no single frame gets longer than idle_s to
    complete (read-side slow-loris)."""
    keys = simulate_keygen(1, 3, test_config)
    svc = RefreshService(deadline_s=20.0)
    svc.admit("loris", [k.clone() for k in keys], test_config)
    svc.start()
    srv = IngressServer(svc, idle_s=0.6).start()
    try:
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        frame = encode_frame({"op": "ping", "rid": 1})
        closed = False
        try:
            for b in frame[:-1]:  # drip, never completing the frame
                s.sendall(bytes([b]))
                time.sleep(0.1)
        except OSError:
            closed = True
        if not closed:
            s.settimeout(5)
            try:
                closed = s.recv(64) == b""
            except OSError:
                closed = True
        s.close()
        assert closed, "slow-read loris survived its frame budget"
        causes = smetrics.ingress_snapshot()["frames_rejected"]
        assert causes.get("slow_read", 0) >= 1, causes
    finally:
        srv.stop()
        svc.stop()


def test_idle_timeout_closes_connection(test_config):
    keys = simulate_keygen(1, 3, test_config)
    svc = RefreshService(deadline_s=20.0)
    svc.admit("idle", [k.clone() for k in keys], test_config)
    svc.start()
    srv = IngressServer(svc, idle_s=0.6).start()
    try:
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        s.settimeout(10)
        deadline = time.monotonic() + 8
        closed = False
        while time.monotonic() < deadline:
            try:
                if s.recv(1024) == b"":
                    closed = True
                    break
            except OSError:
                closed = True
                break
        s.close()
        assert closed, "idle connection never closed"
        conns = smetrics.ingress_snapshot()["connections"]
        assert conns.get("idle", 0) >= 1, conns
    finally:
        srv.stop()
        svc.stop()


def test_graceful_drain_answers_inflight_then_closes(test_config):
    """stop(): the listener closes first, an in-flight wait still gets
    its terminal answer, and only then does the connection close."""
    keys = simulate_keygen(1, 3, test_config)
    svc, srv = _serve(test_config, keys, "drain")
    cli = None
    try:
        cli = IngressClient("127.0.0.1", srv.port)
        r = cli.submit("drain", epoch=0)
        for _snd, wire in r["broadcasts"]:
            cli.broadcast(r["sid"], wire)
        rid = cli.send({"op": "wait", "sid": r["sid"], "timeout": 60})
        stopper = threading.Thread(target=srv.stop, args=(30.0,))
        stopper.start()
        term = cli.recv(rid, timeout=60)
        assert term["type"] == "terminal" and term["state"] == "done", term
        stopper.join(timeout=40)
        with pytest.raises(OSError):
            socket.create_connection(("127.0.0.1", srv.port), timeout=2)
    finally:
        if cli is not None:
            cli.close()
        srv.stop()
        svc.stop()


# ---------------------------------------------------------------------------
# network fault sites + redirect


def test_conn_drop_and_frame_truncate_fault_sites(test_config):
    """conn_drop kills the connection after a request; frame_truncate
    tears a response mid-frame. Both read as ConnectionError to the
    client, whose reconnect then succeeds (caps exhausted)."""
    keys = simulate_keygen(1, 3, test_config)
    svc = RefreshService(deadline_s=20.0)
    svc.admit("flt", [k.clone() for k in keys], test_config)
    svc.start()
    srv = IngressServer(svc).start()
    try:
        faults.configure("seed=3,conn_drop=1.0,conn_drop_max=1")
        cli = IngressClient("127.0.0.1", srv.port, timeout=5)
        with pytest.raises(ConnectionError):
            cli.ping()
        cli.close()
        cli = IngressClient("127.0.0.1", srv.port, timeout=5)
        assert cli.ping()["type"] == "pong"  # cap spent: healthy again
        cli.close()
        conns = smetrics.ingress_snapshot()["connections"]
        assert conns.get("faulted", 0) >= 1, conns

        faults.configure("seed=3,frame_truncate=1.0,frame_truncate_max=1")
        cli = IngressClient("127.0.0.1", srv.port, timeout=5)
        with pytest.raises(ConnectionError):
            cli.ping()
        cli.close()
        cli = IngressClient("127.0.0.1", srv.port, timeout=5)
        assert cli.ping()["type"] == "pong"
        cli.close()
    finally:
        faults.reset()
        srv.stop()
        svc.stop()


def test_net_dup_responses_deduped_by_rid(test_config):
    keys = simulate_keygen(1, 3, test_config)
    svc = RefreshService(deadline_s=20.0)
    svc.admit("dup", [k.clone() for k in keys], test_config)
    svc.start()
    srv = IngressServer(svc).start()
    try:
        faults.configure("seed=5,net_dup=1.0")
        cli = IngressClient("127.0.0.1", srv.port, timeout=10)
        for _ in range(4):  # every response arrives twice; rid dedupes
            assert cli.ping()["type"] == "pong"
        cli.close()
    finally:
        faults.reset()
        srv.stop()
        svc.stop()


def test_client_same_batch_dup_not_parked_and_state_bounded():
    """REGRESSION: a net_dup duplicate of the awaited rid landing in
    the SAME parse batch must be discarded, not parked forever in
    `_pending`; and `_done_rids` must stay bounded on a long-lived
    client."""
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)
    cli = IngressClient("127.0.0.1", lsock.getsockname()[1], timeout=1)
    try:
        # both copies of rid 1's response sit in the buffer before recv
        cli._rid = 1
        frame = encode_frame({"type": "pong", "rid": 1})
        cli._buf += frame + frame
        assert cli.recv(1, timeout=1)["type"] == "pong"
        assert not cli._pending, cli._pending  # dup discarded, not parked
        # dup-tracking state is pruned up to the oldest rid still
        # awaiting its recv (here: none outstanding)
        cli._done_rids.update(range(1, 5000))
        cli._pending.update({r: {} for r in range(2, 50)})
        cli._rid = 5000
        cli._buf += encode_frame({"type": "pong", "rid": 5000})
        assert cli.recv(5000, timeout=1)["type"] == "pong"
        assert len(cli._done_rids) == 1, cli._done_rids
        assert not cli._pending, cli._pending
        # a parked response whose rid is STILL outstanding survives the
        # prune and is handed back — pop runs before the prune, so this
        # neither KeyErrors nor discards a response the caller awaits
        cli._rid = 9000
        cli._outstanding.add(20)
        cli._pending[20] = {"type": "pong", "rid": 20}
        assert cli.recv(20, timeout=1)["type"] == "pong"
        assert 20 not in cli._pending and not cli._outstanding
    finally:
        cli.close()
        lsock.close()


def test_redirect_for_unowned_committee(test_config):
    keys = simulate_keygen(1, 3, test_config)
    svc = RefreshService(deadline_s=20.0)
    svc.admit("mine", [k.clone() for k in keys], test_config)
    svc.start()
    srv = IngressServer(
        svc,
        router=lambda cid: {"ports": {"0": 12345, "1": 23456},
                            "hint": 23456},
    ).start()
    cli = None
    try:
        cli = IngressClient("127.0.0.1", srv.port)
        r = cli.submit("not-mine")
        assert r["type"] == "redirect" and r["hint"] == 23456, r
        assert r["ports"] == {"0": 12345, "1": 23456}
        # owned committees are served, not redirected
        r = cli.submit("mine", epoch=0)
        assert r["type"] == "submitted", r
    finally:
        if cli is not None:
            cli.close()
        srv.stop()
        svc.stop()


def test_external_submit_requires_deadline_and_scheduler(
    test_config, monkeypatch
):
    keys = simulate_keygen(1, 3, test_config)
    svc = RefreshService()  # deadline off
    svc.admit("nodl", [k.clone() for k in keys], test_config)
    with pytest.raises(ValueError, match="deadline"):
        svc.submit("nodl", external=True)
    monkeypatch.setenv("FSDKR_SERVE", "0")
    svc2 = RefreshService(deadline_s=5.0)
    svc2.admit("nodl2", [k.clone() for k in keys], test_config)
    with pytest.raises(ValueError, match="scheduler"):
        svc2.submit("nodl2", external=True)
