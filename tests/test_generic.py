"""Curve/hash genericity (reference: generic `E` + `HashChoice<H>`,
src/refresh_message.rs:31): the transcript digest is a runtime config knob
threaded through every proof, and the curve core is a factory with
registered instances beyond secp256k1."""

import pytest

from fsdkr_tpu.config import ProtocolConfig
from fsdkr_tpu.core.transcript import (
    Transcript,
    challenge_bits,
    digest_bytes,
    get_hash_algorithm,
    set_hash_algorithm,
)


@pytest.fixture(autouse=True)
def _restore_hash():
    prev = get_hash_algorithm()
    yield
    set_hash_algorithm(prev)


class TestHashChoice:
    def test_digest_sizes_and_bit_capacity(self):
        assert digest_bytes("sha256") == 32
        assert digest_bytes("sha3_512") == 64
        with pytest.raises(ValueError):
            challenge_bits(1, 257, "sha256")
        assert len(challenge_bits(1, 300, "sha3_512")) == 300

    def test_transcripts_differ_by_algorithm(self):
        a = Transcript(b"d", algorithm="sha256").chain_int(7).result_int()
        b = Transcript(b"d", algorithm="sha3_256").chain_int(7).result_int()
        assert a != b

    def test_config_gates_m_security_by_digest(self):
        with pytest.raises(ValueError):
            ProtocolConfig(paillier_bits=768, m_security=300)  # sha256 cap
        cfg = ProtocolConfig(
            paillier_bits=768, m_security=300, hash_alg="sha512"
        )
        assert cfg.hash_alg == "sha512"
        with pytest.raises(ValueError):
            ProtocolConfig(paillier_bits=768, hash_alg="md5")

    def test_refresh_end_to_end_under_sha3_512(self):
        """Full refresh with every Fiat-Shamir transcript on sha3-512 —
        prover and verifier agree through the config knob alone, without
        touching the process-default digest."""
        from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen

        cfg = ProtocolConfig(
            paillier_bits=768,
            m_security=32,
            correct_key_rounds=3,
            hash_alg="sha3_512",
        )
        keys = simulate_keygen(1, 3, cfg)
        msgs, dks = [], []
        for k in keys:
            m, dk = RefreshMessage.distribute(k.i, k, 3, cfg)
            msgs.append(m)
            dks.append(dk)
        RefreshMessage.collect(msgs, keys[0], dks[0], (), cfg)
        # hash_alg flows by parameter, not by global installation
        # (reference: per-message HashChoice<H>, src/refresh_message.rs:31)
        assert get_hash_algorithm() == "sha256"

    def test_two_digests_interleaved_in_one_process(self):
        """Two committees with different transcript digests refresh with
        their protocol steps interleaved — per-instance digest binding
        (reference: H is a per-message type parameter,
        src/refresh_message.rs:31,46-47)."""
        from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen

        cfg_a = ProtocolConfig(
            paillier_bits=768, m_security=32, correct_key_rounds=3
        )  # sha256
        cfg_b = ProtocolConfig(
            paillier_bits=768,
            m_security=32,
            correct_key_rounds=3,
            hash_alg="sha3_512",
        )
        keys_a = simulate_keygen(1, 3, cfg_a)
        keys_b = simulate_keygen(1, 3, cfg_b)

        # interleave the distribute phases of the two sessions
        msgs_a, dks_a = [], []
        msgs_b, dks_b = [], []
        for ka, kb in zip(keys_a, keys_b):
            ma, da = RefreshMessage.distribute(ka.i, ka, 3, cfg_a)
            mb, db = RefreshMessage.distribute(kb.i, kb, 3, cfg_b)
            msgs_a.append(ma)
            dks_a.append(da)
            msgs_b.append(mb)
            dks_b.append(db)

        # interleave the collects; both must verify under their own digest
        RefreshMessage.collect(msgs_b, keys_b[0], dks_b[0], (), cfg_b)
        RefreshMessage.collect(msgs_a, keys_a[0], dks_a[0], (), cfg_a)
        RefreshMessage.collect(msgs_b, keys_b[1], dks_b[1], (), cfg_b)

        # cross-session verification fails: session A's proofs do not
        # verify under session B's digest
        from fsdkr_tpu.backend import get_backend

        backend_b = get_backend(cfg_b)
        rp_items = [
            (m.ring_pedersen_proof, m.ring_pedersen_statement) for m in msgs_a
        ]
        assert not any(backend_b.verify_ring_pedersen(rp_items, 32))

    def test_cross_hash_verification_fails(self):
        """A proof generated under one digest must not verify under
        another (domain separation of the knob)."""
        from fsdkr_tpu.proofs.composite_dlog import (
            CompositeDLogProof,
            DLogStatement,
        )
        from fsdkr_tpu.protocol.keygen import generate_h1_h2_n_tilde

        cfg = ProtocolConfig(paillier_bits=768, m_security=32)
        n_tilde, h1, h2, xhi, _ = generate_h1_h2_n_tilde(cfg)
        st = DLogStatement(N=n_tilde, g=h1, ni=h2)
        set_hash_algorithm("sha256")
        proof = CompositeDLogProof.prove(st, xhi)
        assert proof.verify(st)
        set_hash_algorithm("sha3_256")
        assert not proof.verify(st)


class TestGenericCurve:
    def test_secp256r1_group_law(self):
        from fsdkr_tpu.core.curves import get_curve

        c = get_curve("secp256r1")
        G = c.GENERATOR
        # generator satisfies the curve equation
        assert (G.y * G.y - (G.x**3 + c.params.a * G.x + c.params.b)) % c.P == 0
        # group order: n*G = identity, (n+1)*G = G
        assert (G * c.N).infinity
        assert G * (c.N + 1) == G
        # distributivity and add/double consistency
        k1, k2 = c.Scalar.from_int(123456789), c.Scalar.from_int(987654321)
        assert G * (k1 + k2) == G * k1 + G * k2
        assert G + G == G * 2

    def test_secp256r1_encoding_roundtrip(self):
        from fsdkr_tpu.core.curves import get_curve

        c = get_curve("secp256r1")
        p = c.GENERATOR * c.Scalar.from_int(0xDEADBEEF)
        assert c.Point.from_bytes(p.to_bytes(compressed=True)) == p
        assert c.Point.from_bytes(p.to_bytes(compressed=False)) == p
        with pytest.raises(ValueError):
            c.Point.from_bytes(b"\x02" + b"\xff" * 32)  # x >= P

    def test_secp256r1_jacobian_matches_additions(self):
        from fsdkr_tpu.core.curves import get_curve

        c = get_curve("secp256r1")
        G = c.GENERATOR
        acc = c.Point.identity()
        for k in range(1, 9):
            acc = acc + G
            assert G * k == acc

    def test_secp256k1_served_by_registry(self):
        from fsdkr_tpu.core import secp256k1
        from fsdkr_tpu.core.curves import get_curve

        c = get_curve("secp256k1")
        assert c.Point is secp256k1.Point  # one Point type in the process
        with pytest.raises(ValueError):
            get_curve("curve25519")

    def test_protocol_layer_pins_secp256k1(self):
        with pytest.raises(ValueError):
            ProtocolConfig(paillier_bits=768, curve="secp256r1")
