"""Wire-format roundtrip tests: a refresh must succeed when every broadcast
message crosses the canonical JSON wire (the reference's serde surface,
SURVEY.md §2c), and LocalKey checkpoints must roundtrip."""

from fsdkr_tpu.config import TEST_CONFIG
from fsdkr_tpu.core import vss
from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen
from fsdkr_tpu.protocol.serialization import (
    local_key_from_json,
    local_key_to_json,
    refresh_message_from_json,
    refresh_message_to_json,
)

CFG = TEST_CONFIG


def test_refresh_through_wire():
    t, n = 1, 3
    keys = simulate_keygen(t, n, CFG)
    old_secret = vss.reconstruct(
        vss.ShamirSecretSharing(t, n),
        list(range(t + 1)),
        [k.keys_linear.x_i for k in keys[: t + 1]],
    )

    wire_msgs, dks = [], []
    for key in keys:
        m, dk = RefreshMessage.distribute(key.i, key, n, CFG)
        wire_msgs.append(refresh_message_to_json(m))  # serialize
        dks.append(dk)

    msgs = [refresh_message_from_json(w) for w in wire_msgs]  # deserialize
    for key, dk in zip(keys, dks):
        RefreshMessage.collect(msgs, key, dk, (), CFG)

    new_secret = vss.reconstruct(
        vss.ShamirSecretSharing(t, n),
        list(range(t + 1)),
        [k.keys_linear.x_i for k in keys[: t + 1]],
    )
    assert old_secret.v == new_secret.v


def test_local_key_checkpoint_roundtrip():
    keys = simulate_keygen(1, 3, CFG)
    k = keys[0]
    restored = local_key_from_json(local_key_to_json(k))
    assert restored.i == k.i and restored.t == k.t and restored.n == k.n
    assert restored.keys_linear.x_i.v == k.keys_linear.x_i.v
    assert restored.paillier_dk.p == k.paillier_dk.p
    assert restored.pk_vec == k.pk_vec
    assert restored.y_sum_s == k.y_sum_s
    assert [e.n for e in restored.paillier_key_vec] == [
        e.n for e in k.paillier_key_vec
    ]
