"""Wire-format roundtrip tests: a refresh must succeed when every broadcast
message crosses the canonical JSON wire (the reference's serde surface,
SURVEY.md §2c), and LocalKey checkpoints must roundtrip."""

from fsdkr_tpu.config import TEST_CONFIG
from fsdkr_tpu.core import vss
from fsdkr_tpu.protocol import JoinMessage, RefreshMessage, simulate_keygen
from fsdkr_tpu.protocol.serialization import (
    join_message_from_json,
    join_message_to_json,
    local_key_from_json,
    local_key_to_json,
    refresh_message_from_json,
    refresh_message_to_json,
)

CFG = TEST_CONFIG


def test_refresh_through_wire():
    t, n = 1, 3
    keys = simulate_keygen(t, n, CFG)
    old_secret = vss.reconstruct(
        vss.ShamirSecretSharing(t, n),
        list(range(t + 1)),
        [k.keys_linear.x_i for k in keys[: t + 1]],
    )

    wire_msgs, dks = [], []
    for key in keys:
        m, dk = RefreshMessage.distribute(key.i, key, n, CFG)
        wire_msgs.append(refresh_message_to_json(m))  # serialize
        dks.append(dk)

    msgs = [refresh_message_from_json(w) for w in wire_msgs]  # deserialize
    for key, dk in zip(keys, dks):
        RefreshMessage.collect(msgs, key, dk, (), CFG)

    new_secret = vss.reconstruct(
        vss.ShamirSecretSharing(t, n),
        list(range(t + 1)),
        [k.keys_linear.x_i for k in keys[: t + 1]],
    )
    assert old_secret.v == new_secret.v


def test_join_message_wire_roundtrip():
    jm, _pair = JoinMessage.distribute(CFG)
    jm.set_party_index(2)
    wire = join_message_to_json(jm)
    restored = join_message_from_json(wire)
    # canonical JSON: a second encode must be byte-identical
    assert join_message_to_json(restored) == wire
    assert restored.party_index == 2
    assert restored.ek.n == jm.ek.n and restored.ek.nn == jm.ek.nn
    assert restored.dlog_statement.N == jm.dlog_statement.N
    assert restored.dlog_statement.g == jm.dlog_statement.g
    assert restored.dlog_statement.ni == jm.dlog_statement.ni
    assert restored.ring_pedersen_statement.N == jm.ring_pedersen_statement.N


def test_permuted_replace_through_wire():
    """Remove party 2 of a (1,4) committee, permute survivors, add one
    fresh party at index 2 — with every refresh AND join message crossing
    the canonical JSON wire (reference scenario src/test.rs:95-224, via
    its serde surface)."""
    t, n = 1, 4
    all_keys = simulate_keygen(t, n, CFG)
    old_secret = vss.reconstruct(
        vss.ShamirSecretSharing(t, n),
        [k.i - 1 for k in all_keys[: t + 1]],
        [k.keys_linear.x_i for k in all_keys[: t + 1]],
    )

    keys = [k for k in all_keys if k.i != 2]
    old_to_new_map = {1: 3, 3: 1, 4: 4}

    jm, pair = JoinMessage.distribute(CFG)
    jm.set_party_index(2)
    join_wire = [join_message_to_json(jm)]

    refresh_wire, dks = [], []
    for key in keys:
        joins = [join_message_from_json(w) for w in join_wire]
        m, dk = RefreshMessage.replace(joins, key, old_to_new_map, n, CFG)
        refresh_wire.append(refresh_message_to_json(m))
        dks.append(dk)

    new_keys = []
    for key, dk in zip(keys, dks):
        msgs = [refresh_message_from_json(w) for w in refresh_wire]
        joins = [join_message_from_json(w) for w in join_wire]
        RefreshMessage.collect(msgs, key, dk, joins, CFG)
        new_keys.append((key.i, key))

    msgs = [refresh_message_from_json(w) for w in refresh_wire]
    joins = [join_message_from_json(w) for w in join_wire]
    lk = joins[0].collect(msgs, pair, joins, t, n, CFG)
    new_keys.append((lk.i, lk))

    new_keys.sort(key=lambda e: e[0])
    ks = [k for _, k in new_keys]
    assert [k.i for k in ks] == [1, 2, 3, 4]
    new_secret = vss.reconstruct(
        vss.ShamirSecretSharing(t, n),
        [k.i - 1 for k in ks[: t + 1]],
        [k.keys_linear.x_i for k in ks[: t + 1]],
    )
    assert old_secret.v == new_secret.v


def test_local_key_checkpoint_roundtrip():
    keys = simulate_keygen(1, 3, CFG)
    k = keys[0]
    restored = local_key_from_json(local_key_to_json(k))
    assert restored.i == k.i and restored.t == k.t and restored.n == k.n
    assert restored.keys_linear.x_i.v == k.keys_linear.x_i.v
    assert restored.paillier_dk.p == k.paillier_dk.p
    assert restored.pk_vec == k.pk_vec
    assert restored.y_sum_s == k.y_sum_s
    assert [e.n for e in restored.paillier_key_vec] == [
        e.n for e in k.paillier_key_vec
    ]
