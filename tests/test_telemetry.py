"""Tests for the unified telemetry subsystem (fsdkr_tpu.telemetry):
hierarchical spans (incl. cross-thread parenting and the background
producer's own track), the labeled metrics registry with bucket-derived
percentiles, the schema-versioned snapshot / Prometheus exposition, the
flight recorder's crash flush, the disabled-path overhead bound, and the
telemetry secrecy rule (no witness material in any export).

tests/test_trace.py pins the legacy `utils.trace` surface through the
back-compat shim; this file pins everything the old flat aggregator
could not do."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from fsdkr_tpu.telemetry import export, flight
from fsdkr_tpu.telemetry.registry import (
    Histogram,
    Registry,
    check_label_value,
)
from fsdkr_tpu.telemetry.spans import Tracer
from fsdkr_tpu.utils.trace import get_tracer


# ---------------------------------------------------------------------------
# spans


class TestSpans:
    def test_nesting_same_thread(self):
        tr = Tracer(enabled=True)
        with tr.phase("outer"):
            with tr.phase("outer.mid"):
                with tr.phase("outer.mid.leaf"):
                    pass
        spans = {s.name: s for s in tr.spans()}
        assert spans["outer"].parent_id is None
        assert spans["outer.mid"].parent_id == spans["outer"].span_id
        assert spans["outer.mid.leaf"].parent_id == spans["outer.mid"].span_id
        # child intervals sit inside the parent's
        assert spans["outer"].t0 <= spans["outer.mid"].t0
        assert spans["outer.mid"].t1 <= spans["outer"].t1

    def test_nesting_across_pipeline_threads(self, monkeypatch):
        """Worker threads primed by utils.pipeline parent their spans to
        the submitting thread's phase — the tile-dispatch shape."""
        monkeypatch.setenv("FSDKR_PIPELINE", "1")
        from fsdkr_tpu.utils.pipeline import pipelined

        tr = get_tracer()
        tr.reset()
        tr.enable()
        try:
            def tile(i):
                with tr.phase("launch.tile", items=1):
                    return i * i

            with tr.phase("launch"):
                out = pipelined(tile, [(i,) for i in range(4)])
        finally:
            tr.disable()
        assert out == [0, 1, 4, 9]
        spans = tr.spans()
        launch = [s for s in spans if s.name == "launch"][0]
        tiles = [s for s in spans if s.name == "launch.tile"]
        assert len(tiles) == 4
        assert all(t.parent_id == launch.span_id for t in tiles)
        # at least one tile really ran off-thread (depth-2 pool, 4 tiles)
        assert any(t.tid != launch.tid for t in tiles)

    def test_producer_thread_spans_parented(self, monkeypatch):
        """The background producer's work shows up as its own thread
        track: step spans rooted on the producer thread (no cross-thread
        parent leakage), with the per-kind produce span nested under the
        step span."""
        monkeypatch.setenv("FSDKR_PRECOMPUTE", "1")
        monkeypatch.setenv("FSDKR_PRECOMPUTE_BG", "1")
        from fsdkr_tpu import precompute

        tr = get_tracer()
        tr.reset()
        tr.enable()
        precompute.clear_targets()
        precompute.clear_pools()
        n_mod = (2**61 - 1) * (2**62 + 135)  # any odd public modulus
        try:
            precompute.register_targets([("enc", n_mod, 4)])
            precompute.kick()
            store = precompute.get_store()
            deadline = time.time() + 30
            while store.depth("enc", n_mod) < 4 and time.time() < deadline:
                time.sleep(0.02)
            assert store.depth("enc", n_mod) == 4, "producer never filled"
        finally:
            precompute.stop_background()
            precompute.clear_targets()
            precompute.clear_pools()
            tr.disable()
        spans = tr.spans()
        steps = [s for s in spans if s.name == "precompute.producer.step"]
        produces = [s for s in spans if s.name == "precompute.produce.enc"]
        assert steps and produces
        main_tid = [s for s in spans if s.name not in
                    ("precompute.producer.step", "precompute.produce.enc")]
        step_ids = {s.span_id for s in steps}
        for s in steps:
            assert s.thread_name == "fsdkr-precompute"
            assert s.parent_id is None  # its own root, not a leaked parent
        for p in produces:
            assert p.thread_name == "fsdkr-precompute"
            assert p.parent_id in step_ids
        del main_tid

    def test_attr_allowlist_drops_wide_ints(self):
        tr = Tracer(enabled=True)
        secret = 1 << 2048
        with tr.phase("p", kind="enc", rows=4, modulus=secret):
            pass
        (span,) = tr.spans()
        assert span.attrs == {"kind": "enc", "rows": 4}
        assert tr.attrs_dropped() == 1
        assert tr.spans_dropped() == 0  # the SPAN itself was kept

    def test_span_cap_bounds_memory(self):
        tr = Tracer(enabled=True, max_spans=8)
        for _ in range(20):
            with tr.phase("p"):
                pass
        assert len(tr.spans()) == 8
        assert tr.spans_dropped() == 12
        # aggregates keep counting past the cap
        assert tr.stats()["p"].calls == 20

    def test_disabled_tracer_overhead_bound(self):
        """The disabled path (two perf_counter calls + one histogram
        observe + one ring append) must stay micro-cheap: 20k phases in
        well under 2 s even on a loaded box (~100 us/phase budget; the
        real cost is ~2-4 us)."""
        tr = Tracer(enabled=False)
        t0 = time.perf_counter()
        for _ in range(20000):
            with tr.phase("hot", items=1):
                pass
        dt = time.perf_counter() - t0
        assert tr.stats() == {}
        assert not tr.spans()
        assert dt < 2.0, f"disabled-phase overhead {dt / 20000 * 1e6:.1f} us"


class TestChromeTrace:
    def test_chrome_trace_json_validity(self, tmp_path):
        tr = Tracer(enabled=True)
        with tr.phase("collect", items=2):
            with tr.phase("collect.verify", items=2):
                pass
        path = tr.write_chrome_trace(str(tmp_path / "trace.json"))
        doc = json.load(open(path))
        evs = doc["traceEvents"]
        xs = [e for e in evs if e["ph"] == "X"]
        assert len(xs) == 2
        for e in xs:
            assert e["ts"] >= 0 and e["dur"] >= 0
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
            assert "span_id" in e["args"]
        parent = [e for e in xs if e["name"] == "collect"][0]
        child = [e for e in xs if e["name"] == "collect.verify"][0]
        assert child["args"]["parent_id"] == parent["args"]["span_id"]
        assert child["ts"] >= parent["ts"]
        assert child["ts"] + child["dur"] <= parent["ts"] + parent["dur"] + 1
        # thread metadata present so Perfetto labels the tracks
        assert any(e["ph"] == "M" and e["name"] == "thread_name" for e in evs)


# ---------------------------------------------------------------------------
# registry


class TestRegistry:
    def test_histogram_percentiles_vs_oracle(self):
        buckets = tuple(i / 100 for i in range(1, 201))  # 10ms-wide .. 2.0
        h = Histogram("t_hist", "", (), buckets=buckets)
        values = [0.015 * (i % 97) + 0.003 for i in range(3000)]
        child = h._child(())
        for v in values:
            child.observe(v)
        ordered = sorted(values)
        for q in (0.50, 0.95, 0.99):
            oracle = ordered[min(len(ordered) - 1, int(q * len(ordered)))]
            got = child.percentile(q)
            # resolution bound: one bucket width (0.01) + half the value
            # spacing (0.015/2) — bucket-derived percentiles are honest
            # to the ladder, not to the sample
            assert abs(got - oracle) <= 0.0185, (q, got, oracle)
        snap = child.snapshot()
        assert snap["count"] == 3000
        assert abs(snap["sum"] - sum(values)) < 1e-6
        assert snap["p50"] < snap["p95"] < snap["p99"]

    def test_histogram_overflow_clamps(self):
        h = Histogram("t_hist2", "", (), buckets=(0.1, 1.0))
        c = h._child(())
        for _ in range(10):
            c.observe(50.0)  # beyond the last bound
        assert c.percentile(0.99) == 1.0  # clamped, honest resolution

    def test_counter_and_gauge(self):
        r = Registry()
        c = r.counter("t_events", "ev", labelnames=("event",))
        c.inc(3, event="a")
        c.inc(event="b")
        assert c.value(event="a") == 3 and c.total() == 4
        with pytest.raises(ValueError):
            c.inc(-1, event="a")
        g = r.gauge("t_depth", "d", labelnames=("kind",))
        g.set(7, kind="enc")
        g.dec(2, kind="enc")
        assert g.labels(kind="enc").value == 5

    def test_snapshot_schema(self):
        r = Registry()
        r.counter("t_c", "help c", ("k",)).inc(2, k="x")
        r.gauge("t_g", "help g").set(1.5)
        r.histogram("t_h", "help h", buckets=(1.0, 2.0)).observe(1.5)
        snap = r.snapshot()
        assert snap["schema"].startswith("fsdkr-telemetry/")
        m = snap["metrics"]
        assert m["t_c"]["type"] == "counter"
        assert m["t_c"]["values"] == [{"labels": {"k": "x"}, "value": 2.0}]
        assert m["t_g"]["values"][0]["value"] == 1.5
        h = m["t_h"]["values"][0]
        assert h["count"] == 1 and "p99" in h and h["buckets"][0] == [1.0, 0]
        assert json.loads(json.dumps(snap)) == snap  # JSON-clean

    def test_function_gauges(self):
        r = Registry()
        r.gauge("t_fn", "lazy").set_function(lambda: 42)
        r.gauge("t_fn_lab", "lazy", ("kind",)).set_labeled_function(
            lambda: {("enc",): 3, ("keys",): 1}
        )
        r.gauge("t_fn_broken", "raises").set_function(
            lambda: (_ for _ in ()).throw(RuntimeError())
        )
        m = r.snapshot()["metrics"]
        assert m["t_fn"]["values"][0]["value"] == 42
        vals = {
            v["labels"]["kind"]: v["value"] for v in m["t_fn_lab"]["values"]
        }
        assert vals == {"enc": 3.0, "keys": 1.0}
        assert m["t_fn_broken"]["values"] == []  # no sample, no crash

    def test_type_conflict_raises(self):
        r = Registry()
        r.counter("t_once", "")
        with pytest.raises(ValueError):
            r.gauge("t_once", "")
        with pytest.raises(ValueError):
            r.counter("t_once", "", labelnames=("x",))

    def test_bucket_conflict_raises(self):
        r = Registry()
        h = r.histogram("t_hb", "", buckets=(0.001, 0.01, 0.1))
        assert r.histogram("t_hb", "") is h  # None buckets: get existing
        assert r.histogram("t_hb", "", buckets=(0.1, 0.01, 0.001)) is h
        with pytest.raises(ValueError):
            r.histogram("t_hb", "", buckets=(0.5, 1.0))

    def test_label_allowlist_rejects_operands(self):
        with pytest.raises(ValueError):
            check_label_value(1 << 64)
        with pytest.raises(ValueError):
            check_label_value([1, 2])
        with pytest.raises(ValueError):
            check_label_value("x" * 500)
        assert check_label_value(True) == "true"
        assert check_label_value(12) == "12"
        r = Registry()
        c = r.counter("t_sec", "", ("modulus",))
        with pytest.raises(ValueError):
            c.inc(modulus=(2**127 - 1) * (2**89 - 1))

    def test_reset_window(self):
        r = Registry()
        c = r.counter("t_w", "", ("e",))
        c.inc(5, e="a")
        g = r.gauge("t_wg", "")
        g.set(3)
        r.reset_window()
        assert c.total() == 0
        assert g.labels().value == 3  # gauges keep point-in-time state


class TestPortedStatBlocks:
    """The five legacy stat surfaces stay API-identical but read from
    the registry now — one snapshot carries all of them."""

    def test_rlc_stats_ride_registry(self):
        from fsdkr_tpu.backend import rlc

        rlc.stats_reset()
        rlc.count("rlc_groups", 2)
        rlc.count("rows_folded", 64)
        assert rlc.stats()["rlc_groups"] == 2
        snap = export.snapshot()["metrics"]["fsdkr_rlc_events"]
        vals = {v["labels"]["event"]: v["value"] for v in snap["values"]}
        assert vals["rlc_groups"] == 2 and vals["rows_folded"] == 64
        rlc.stats_reset()
        assert rlc.stats()["rlc_groups"] == 0

    def test_precompute_stats_ride_registry(self, monkeypatch):
        monkeypatch.setenv("FSDKR_PRECOMPUTE", "1")
        from fsdkr_tpu import precompute

        precompute.clear_pools()
        precompute.stats_reset()
        precompute.put("enc", 15, (3, 9))
        assert precompute.precompute_stats()["produced"] == 1
        snap = export.snapshot()["metrics"]
        depth = {
            v["labels"]["kind"]: v["value"]
            for v in snap["fsdkr_pool_depth"]["values"]
        }
        assert depth.get("enc") == 1
        assert precompute.take("enc", 15) == (3, 9)
        assert precompute.take("enc", 15) is None  # dry
        st = precompute.precompute_stats()
        assert st["consumed"] == 1 and st["dry_fallbacks"] == 1
        precompute.clear_pools()
        precompute.stats_reset()

    def test_gen_stats_and_crt_stats_ride_registry(self):
        from fsdkr_tpu.backend import crt
        from fsdkr_tpu.core import primes

        primes.gen_stats_reset()
        primes.gen_primes_batch(64, 1)
        gs = primes.gen_stats()
        assert gs["candidates"] > 0 and gs["mr_rounds"] > 0
        snap = export.snapshot()["metrics"]
        vals = {
            v["labels"]["event"]: v["value"]
            for v in snap["fsdkr_primegen_events"]["values"]
        }
        assert vals["candidates"] == gs["candidates"]
        primes.gen_stats_reset()
        crt.stats_reset()
        assert set(crt.crt_stats()) == {
            "rows", "legs", "fault_checks", "fallback_rows", "exp_bits_saved"
        }
        from fsdkr_tpu.utils import lru as _lru  # registers its gauges

        assert _lru.cache_stats() is not None
        assert "fsdkr_powm_cache_hits" in export.snapshot()["metrics"]


# ---------------------------------------------------------------------------
# export


class TestExport:
    def test_prometheus_text(self):
        from fsdkr_tpu.telemetry.registry import get_registry

        get_registry().counter(
            "t_prom_events", "prom test", ("event",)
        ).inc(4, event="x")
        text = export.prometheus_text()
        assert "# TYPE t_prom_events_total counter" in text
        assert 't_prom_events_total{event="x"} 4' in text
        assert "# TYPE fsdkr_phase_seconds histogram" in text
        assert "fsdkr_phase_seconds_bucket" in text
        assert 'le="+Inf"' in text

    def test_dump_metrics_roundtrip(self, tmp_path):
        path = export.dump_metrics(str(tmp_path / "m.prom"))
        body = open(path).read()
        assert body.startswith("# fsdkr telemetry schema fsdkr-telemetry/")


# ---------------------------------------------------------------------------
# flight recorder


_CRASH_SCRIPT = """
import os, sys
sys.path.insert(0, {repo!r})
from fsdkr_tpu import telemetry
telemetry.flight.record("work", "step1", dur=0.5, rows=4)
telemetry.get_tracer().enable()
with telemetry.phase("doomed.phase"):
    pass
raise RuntimeError("simulated tunnel-window crash")
"""

_SIGTERM_SCRIPT = """
import os, sys, time
sys.path.insert(0, {repo!r})
from fsdkr_tpu import telemetry
telemetry.flight.record("work", "before-term")
print("READY", flush=True)
time.sleep(30)
"""


class TestFlightRecorder:
    def test_ring_is_bounded(self):
        rec = flight.FlightRecorder(cap=16)
        for i in range(100):
            rec.record("span", f"p{i}", dur=0.001)
        evs = rec.snapshot()
        assert len(evs) == 16
        assert evs[-1]["name"] == "p99"  # last N survive

    def test_fields_allowlisted(self):
        rec = flight.FlightRecorder(cap=8)
        rec.record("span", "p", rows=3, modulus=1 << 2048)
        (ev,) = rec.snapshot()
        assert ev["fields"] == {"rows": 3}

    def test_crash_flush_subprocess(self, tmp_path):
        """An unhandled exception in a real interpreter leaves the
        postmortem artifact (the tunnel-window failure mode)."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        out = tmp_path / "flight.json"
        env = {**os.environ, "FSDKR_FLIGHT": str(out)}
        res = subprocess.run(
            [sys.executable, "-c", _CRASH_SCRIPT.format(repo=repo)],
            env=env, capture_output=True, timeout=60,
        )
        assert res.returncode != 0  # still died
        assert b"simulated tunnel-window crash" in res.stderr  # still printed
        doc = json.load(open(out))
        assert doc["schema"].startswith("fsdkr-flight/")
        assert doc["reason"] == "unhandled:RuntimeError"
        names = [e["name"] for e in doc["events"]]
        assert "step1" in names and "doomed.phase" in names
        assert "RuntimeError" in names  # the crash event itself
        assert doc["metrics"]["schema"].startswith("fsdkr-telemetry/")

    def test_sigterm_flush_subprocess(self, tmp_path):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        out = tmp_path / "flight_term.json"
        env = {**os.environ, "FSDKR_FLIGHT": str(out)}
        proc = subprocess.Popen(
            [sys.executable, "-c", _SIGTERM_SCRIPT.format(repo=repo)],
            env=env, stdout=subprocess.PIPE,
        )
        try:
            assert proc.stdout.readline().strip() == b"READY"
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=30)
        finally:
            proc.kill()
        doc = json.load(open(out))
        assert doc["reason"] == "SIGTERM"
        assert any(e["name"] == "before-term" for e in doc["events"])

    def test_crash_detail_scrubs_wide_numbers(self, tmp_path, monkeypatch):
        """Exception messages are free text — wide decimal/hex runs
        (operand material) must not survive into the postmortem."""
        p = (2**127 - 1) * (2**89 - 1)
        scrubbed = flight._scrub_detail(f"bad modulus {p} (0x{p:x}) rows=3")
        assert str(p) not in scrubbed and f"{p:x}" not in scrubbed
        assert "<wide-int>" in scrubbed and "<wide-hex>" in scrubbed
        assert "rows=3" in scrubbed  # small scalars survive
        out = tmp_path / "scrub.json"
        monkeypatch.setenv("FSDKR_FLIGHT", str(out))
        flight.handle_exception(ValueError, ValueError(f"leak {p}"), None)
        assert str(p) not in out.read_text()

    def test_env_path_off_values_case_insensitive(self, monkeypatch):
        for v in ("off", "OFF", "No", "False", "0", ""):
            monkeypatch.setenv("FSDKR_FLIGHT", v)
            assert flight._env_path() is None, v
        monkeypatch.setenv("FSDKR_FLIGHT", "On")
        assert flight._env_path().startswith("fsdkr_flight_")
        monkeypatch.setenv("FSDKR_FLIGHT", "/tmp/x.json")
        assert flight._env_path() == "/tmp/x.json"

    def test_signal_dump_survives_held_metric_lock(
        self, tmp_path, monkeypatch
    ):
        """SIGTERM can interrupt the main thread INSIDE a registry
        critical section; the signal-path dump must not deadlock on the
        lock the interrupted frame holds — it falls back to an
        events-only dump (the failure mode is a hung process that
        neither dumps nor dies)."""
        from fsdkr_tpu.telemetry.registry import get_registry

        out = tmp_path / "held.json"
        monkeypatch.setenv("FSDKR_FLIGHT", str(out))
        flight.record("span", "held-evidence")
        reg = get_registry()
        with reg._lock:  # the interrupted frame's held lock
            flight._dump_on_signal(reason="SIGTERM", timeout=0.3)
            # read while the lock is still held: the blocked watchdog
            # thread must not have been able to write a full dump
            doc = json.load(open(out))
        assert doc["reason"] == "SIGTERM:events-only"
        assert doc["metrics"] is None
        assert any(e["name"] == "held-evidence" for e in doc["events"])

    def test_handle_exception_inprocess(self, tmp_path):
        """The hook body is directly callable (simulated crash without a
        subprocess) and dumps to an explicit FSDKR_FLIGHT path."""
        out = tmp_path / "inproc.json"
        old = os.environ.get("FSDKR_FLIGHT")
        os.environ["FSDKR_FLIGHT"] = str(out)
        try:
            flight.record("span", "inproc-evidence")
            flight.handle_exception(ValueError, ValueError("boom"), None)
        finally:
            if old is None:
                os.environ.pop("FSDKR_FLIGHT", None)
            else:
                os.environ["FSDKR_FLIGHT"] = old
        doc = json.load(open(out))
        assert doc["reason"] == "unhandled:ValueError"


# ---------------------------------------------------------------------------
# telemetry secrecy (satellite): a full traced transcript dump carries no
# witness material


@pytest.mark.fresh_committees
def test_traced_transcript_dump_has_no_secret_bytes(test_config, tmp_path):
    """Run a full FSDKR_TRACE=1 n=4 refresh (distribute + collect, pools
    on), export EVERY telemetry artifact — chrome trace, registry
    snapshot, Prometheus text, flight dump — and grep the lot for the
    run's planted secrets (Paillier factors, shares, pool randomizers) in
    decimal and hex. Fresh committees so the secrets are this test's own,
    not the cached session committee's."""
    from fsdkr_tpu import precompute
    from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen

    tr = get_tracer()
    tr.reset()
    tr.enable()
    try:
        keys = simulate_keygen(1, 4, test_config)
        secrets_planted = []
        for k in keys:
            secrets_planted += [
                k.paillier_dk.p, k.paillier_dk.q, k.keys_linear.x_i.to_int()
            ]
        # pool entries are secret too: prefill so spans cover production
        precompute.prefill(keys[0], 4, 4, test_config)
        secrets_planted += precompute.get_store().secret_values()
        results = RefreshMessage.distribute_batch(
            [(k.i, k) for k in keys], 4, test_config
        )
        msgs = [m for m, _ in results]
        RefreshMessage.collect(msgs, keys[0].clone(), results[0][1], (),
                               test_config)
        secrets_planted += [r[1].p for r in results] + [
            r[1].q for r in results
        ]
    finally:
        tr.disable()
        precompute.clear_pools()
        precompute.clear_targets()

    # ISSUE 12: the public-broadcast journal is a persisted artifact
    # too — run one journaled serving session over the same committee
    # and grep its segments alongside everything else. The post-adopt
    # committee keys hold the session's NEW secrets (rotated dks and
    # shares); plant those as well, so "secrets are never journaled"
    # covers the session's own key material, not just the seed state.
    from fsdkr_tpu.serving import RefreshService

    jdir = tmp_path / "journal"
    svc = RefreshService(journal=str(jdir))
    served = [k.clone() for k in keys]
    svc.admit("sec", served, test_config)
    svc.start()
    try:
        sid = svc.submit("sec")
        assert svc.drain(timeout=180)
        assert svc.wait(sid, timeout=1).state == "done"
    finally:
        svc.stop()
        precompute.clear_pools()
        precompute.clear_targets()
    for k in served:
        secrets_planted += [
            k.paillier_dk.p, k.paillier_dk.q, k.keys_linear.x_i.to_int()
        ]
    journal_blob = "".join(
        p.read_bytes().decode("latin1")
        for p in sorted(jdir.glob("wal-*.seg"))
    )
    assert journal_blob, "journal left no segments to audit"

    trace_path = tr.write_chrome_trace(str(tmp_path / "t.json"))
    flight_path = flight.dump(str(tmp_path / "f.json"), reason="test")
    blob = (
        open(trace_path).read()
        + json.dumps(export.snapshot())
        + export.prometheus_text()
        + open(flight_path).read()
        + journal_blob
    )
    assert len(tr.spans()) > 10  # the dump really covered the pipeline
    for s in secrets_planted:
        s = abs(int(s))
        if s.bit_length() < 64:
            continue  # small ints collide with benign counters
        assert str(s) not in blob, "decimal secret leaked into telemetry"
        assert format(s, "x") not in blob, "hex secret leaked into telemetry"
