"""Cross-session verify amortization (ISSUE 17 tentpole (a)/(b)).

Fused multi-session `collect_sessions` launches merge pair-family RLC
fold groups across sessions sharing a modulus family, dedup
value-identical pair rows (FSDKR_XSESSION_DEDUP), and bisect failing
merged groups session-first (backend.rlc.bisect_sessions). These tests
pin the contract that makes all of that safe to ship:

- verdicts AND adopted key state of a fused honest S-session launch are
  bit-identical to S independent collects (n=3 here; the n=16
  full-committee shape is `slow`);
- one tampered session of four is blamed exactly, with the identical
  error an independent collect raises, and healthy siblings stay clean
  — in both dedup knob positions (dedup off routes the failure through
  bisect_sessions);
- the cross-launch fold-ladder cache (FSDKR_FOLD_CACHE,
  backend.powm.fold_ladder2) goes mark -> build -> warm across
  back-to-back launches, with hit/miss accounting in rlc.stats().
"""

import dataclasses

import pytest

from fsdkr_tpu.backend import rlc
from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen
from fsdkr_tpu.protocol.serialization import local_key_to_json


def _one_round(cfg, t=1, n=3, fresh=False):
    keygen = getattr(simulate_keygen, "uncached", simulate_keygen) if fresh \
        else simulate_keygen
    keys = keygen(t, n, cfg)
    res = RefreshMessage.distribute_batch([(k.i, k) for k in keys], n, cfg)
    return keys, [m for m, _ in res], [dk for _, dk in res]


def _adopted_state(key):
    # full checkpoint surface: any divergence in rotated shares, adopted
    # paillier keys, or commitments shows up here
    return local_key_to_json(key)


def _tpu(cfg):
    return cfg.with_backend("tpu")


def _fused_vs_independent(cfg, t, n, s_count):
    keys, msgs, dks = _one_round(cfg, t, n)

    solo_states = []
    for _ in range(s_count):
        k = keys[0].clone()
        errs = RefreshMessage.collect_sessions([(msgs, k, dks[0], ())], cfg)
        assert errs == [None], errs
        solo_states.append(_adopted_state(k))
    # determinism baseline: independent collects agree with each other
    assert len(set(solo_states)) == 1

    fused_keys = [keys[0].clone() for _ in range(s_count)]
    rlc.stats_reset()
    errs = RefreshMessage.collect_sessions(
        [(msgs, k, dks[0], ()) for k in fused_keys], cfg
    )
    assert errs == [None] * s_count, errs
    for k in fused_keys:
        assert _adopted_state(k) == solo_states[0]
    return rlc.stats()


class TestFusedBitIdentity:
    def test_fused_s4_matches_independent_n3(self, test_config):
        st = _fused_vs_independent(_tpu(test_config), 1, 3, 4)
        # the amortization claim itself: the fused launch ran its
        # full-width ladders once per merged group, not once per
        # (group, session)
        assert st["fullwidth_ladders"] == st["rlc_groups"]
        # same-committee sessions collapse through the value dedup
        assert st["xsession_rows_deduped"] > 0

    @pytest.mark.slow
    def test_fused_s4_matches_independent_n16(self, test_config):
        st = _fused_vs_independent(_tpu(test_config), 8, 16, 4)
        assert st["fullwidth_ladders"] == st["rlc_groups"]

    @pytest.mark.slow
    def test_dedup_off_same_verdicts_and_state(self, test_config, monkeypatch):
        cfg = _tpu(test_config)
        keys, msgs, dks = _one_round(cfg)
        k_on = [keys[0].clone() for _ in range(2)]
        errs = RefreshMessage.collect_sessions(
            [(msgs, k, dks[0], ()) for k in k_on], cfg
        )
        assert errs == [None, None]

        monkeypatch.setenv("FSDKR_XSESSION_DEDUP", "0")
        k_off = [keys[0].clone() for _ in range(2)]
        rlc.stats_reset()
        errs = RefreshMessage.collect_sessions(
            [(msgs, k, dks[0], ()) for k in k_off], cfg
        )
        assert errs == [None, None]
        assert rlc.stats()["xsession_rows_deduped"] == 0
        assert {_adopted_state(k) for k in k_on} == {
            _adopted_state(k) for k in k_off
        }


class TestSessionBlame:
    @staticmethod
    def _tampered_pdl(msgs):
        """Session copy of the broadcast where one sender's PDL proof is
        corrupted — fails in the pair-family RLC fold groups, the path
        that actually merges across sessions."""
        bad_pv = list(msgs[1].pdl_proof_vec)
        bad_pv[0] = dataclasses.replace(bad_pv[0], u2=bad_pv[0].u2 + 1)
        out = list(msgs)
        out[1] = dataclasses.replace(msgs[1], pdl_proof_vec=bad_pv)
        return out

    # the dedup-off variant recompiles the non-merged fold path from
    # cold (~100 s on the fallback platform) — slow lane; the dedup-on
    # default path stays in tier-1.
    @pytest.mark.parametrize(
        "dedup", ["1", pytest.param("0", marks=pytest.mark.slow)]
    )
    def test_one_tampered_of_four_blames_guilty(
        self, test_config, monkeypatch, dedup
    ):
        monkeypatch.setenv("FSDKR_XSESSION_DEDUP", dedup)
        cfg = _tpu(test_config)
        keys, msgs, dks = _one_round(cfg)
        msgs_bad = self._tampered_pdl(msgs)

        rlc.stats_reset()
        out = RefreshMessage.collect_sessions(
            [
                (msgs_bad if s == 2 else msgs, keys[0].clone(), dks[0], ())
                for s in range(4)
            ],
            cfg,
        )
        assert [out[s] is None for s in range(4)] == [True, True, False, True]
        if dedup == "0":
            # merged-group failure resolved session-first
            assert rlc.stats()["session_bisects"] > 0

        # blame is bit-identical to an independent collect of the
        # guilty session (same exception type, same per-equation bits)
        ref = RefreshMessage.collect_sessions(
            [(msgs_bad, keys[0].clone(), dks[0], ())], cfg
        )[0]
        assert type(out[2]) is type(ref)
        assert str(out[2]) == str(ref)

    def test_tampered_range_blamed_exactly(self, test_config):
        cfg = _tpu(test_config)
        keys, msgs, dks = _one_round(cfg)
        bad_rp = list(msgs[1].range_proofs)
        bad_rp[0] = dataclasses.replace(bad_rp[0], z=bad_rp[0].z + 1)
        msgs_bad = list(msgs)
        msgs_bad[1] = dataclasses.replace(msgs[1], range_proofs=bad_rp)

        out = RefreshMessage.collect_sessions(
            [
                (msgs_bad if s == 1 else msgs, keys[0].clone(), dks[0], ())
                for s in range(3)
            ],
            cfg,
        )
        assert out[0] is None and out[2] is None
        ref = RefreshMessage.collect_sessions(
            [(msgs_bad, keys[0].clone(), dks[0], ())], cfg
        )[0]
        assert str(out[1]) == str(ref)


@pytest.mark.fresh_committees
def test_ladder_cache_warms_across_launches(test_config, monkeypatch):
    """FSDKR_FOLD_CACHE lifecycle on a cold committee: launch 1 marks
    the shared (h1, h2) base pairs (miss, Straus fallback), launch 2
    builds the comb tables (miss), launch 3 applies them warm (hit).
    Host route only — the device joint ladder has no persistent tables
    — so FSDKR_DEVICE_POWM is forced off (conftest forces it on)."""
    from fsdkr_tpu import native

    if not native.available():
        pytest.skip("fold-ladder cache needs the native comb engine")
    monkeypatch.setenv("FSDKR_DEVICE_POWM", "0")
    cfg = _tpu(test_config)
    # fresh committee: cached committees' base pairs may already be
    # marked/built by earlier launches in the process
    keys, msgs, dks = _one_round(cfg, fresh=True)

    seen = []
    for _ in range(3):
        rlc.stats_reset()
        k = keys[0].clone()
        errs = RefreshMessage.collect_sessions([(msgs, k, dks[0], ())], cfg)
        assert errs == [None]
        st = rlc.stats()
        seen.append((st["ladder_cache_hits"], st["ladder_cache_misses"]))

    assert seen[0][0] == 0 and seen[0][1] > 0  # cold: marked, all miss
    assert seen[1][0] == 0 and seen[1][1] > 0  # second: table build
    assert seen[2][0] > 0 and seen[2][1] == 0  # warm: served from cache


def test_fold_cache_off_matches_on(test_config, monkeypatch):
    """FSDKR_FOLD_CACHE=0 (multi_powm fallback) and =1 agree on verdicts
    and adopted state — the cache is a routing decision, not math."""
    monkeypatch.setenv("FSDKR_DEVICE_POWM", "0")
    cfg = _tpu(test_config)
    keys, msgs, dks = _one_round(cfg)

    k_on = keys[0].clone()
    assert RefreshMessage.collect_sessions(
        [(msgs, k_on, dks[0], ())], cfg
    ) == [None]

    monkeypatch.setenv("FSDKR_FOLD_CACHE", "0")
    k_off = keys[0].clone()
    assert RefreshMessage.collect_sessions(
        [(msgs, k_off, dks[0], ())], cfg
    ) == [None]
    assert _adopted_state(k_on) == _adopted_state(k_off)
