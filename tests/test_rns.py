"""Differential tests for the RNS/MXU modexp pipeline (ops.rns) against
the CPython oracle. Runs on the virtual CPU platform (conftest); the MXU
matmuls lower to ordinary XLA dots there, so these tests check the full
algorithm — base sizing, fast first extension, exact Shenoy second
extension, fallback rows — not TPU-specific codegen."""

import random

import pytest

from fsdkr_tpu.core import primes
from fsdkr_tpu.ops.rns import rns_bases_for_bits, rns_modexp

random.seed(0xF5DC)


class TestBases:
    def test_sizing_invariant(self):
        for bits in (256, 2048):
            rb = rns_bases_for_bits(bits, bits // 16)
            bound = (rb.k + 1) * (rb.k + 1) << bits
            assert rb.A > bound and rb.B > bound
            assert rb.m_r > 2 * rb.k  # Shenoy beta < k must fit m_r
            all_ps = rb.A_primes + rb.B_primes + [rb.m_r]
            assert len(set(all_ps)) == len(all_ps)

    def test_cached(self):
        assert rns_bases_for_bits(256, 16) is rns_bases_for_bits(256, 16)


class TestModexp:
    @pytest.mark.parametrize("bits", [256, 512])
    def test_vs_host_oracle(self, bits):
        mods = [random.getrandbits(bits) | (1 << (bits - 1)) | 1 for _ in range(4)]
        bases = [random.getrandbits(bits) for _ in range(4)]
        exps = [random.getrandbits(bits) for _ in range(3)] + [0]
        got = rns_modexp(bases, exps, mods, bits)
        assert got == [pow(b % n, e, n) for b, e, n in zip(bases, exps, mods)]

    def test_edge_exponents(self):
        bits = 256
        n = random.getrandbits(bits) | (1 << (bits - 1)) | 1
        exps = [0, 1, 2, 15, 16, 17, (1 << 256) - 1]
        got = rns_modexp([7] * len(exps), exps, [n] * len(exps), bits)
        assert got == [pow(7, e, n) for e in exps]

    def test_worst_case_values(self):
        # all-ones modulus and operands stress the domain bound (< (k+1)N)
        bits = 256
        n = (1 << bits) - 1
        got = rns_modexp([n - 1, n - 2], [n - 1, (1 << 255) + 1], [n, n], bits)
        assert got == [pow(n - 1, n - 1, n), pow(n - 2, (1 << 255) + 1, n)]

    def test_channel_factor_modulus_falls_back(self):
        # a modulus divisible by a channel prime cannot ride the pipeline;
        # the row must still come back correct via the host fallback
        bits = 256
        rb = rns_bases_for_bits(bits, bits // 16)
        bad = rb.A_primes[3] * primes.gen_prime(bits - 16)
        good = random.getrandbits(bits) | (1 << (bits - 1)) | 1
        bases = [123456789, 987654321]
        exps = [random.getrandbits(200), random.getrandbits(200)]
        got = rns_modexp(bases, exps, [bad, good], bits)
        assert got == [
            pow(bases[0], exps[0], bad),
            pow(bases[1], exps[1], good),
        ]

    def test_wide_exponent_narrow_modulus(self):
        # 2816-bit exponents over 2048-class moduli (the PDL s1 shape)
        bits = 512
        n = primes.gen_prime(256) * primes.gen_prime(256)
        e = random.getrandbits(700)
        (got,) = rns_modexp([3], [e], [n], bits)
        assert got == pow(3, e, n)

    def test_tpu_powm_rns_routing(self, monkeypatch):
        # force the generic-path router through the RNS pipeline and
        # check the full hand-off: width-class bucketing, pow2 padding
        # (modulus-3 dummy rows), result slicing
        from fsdkr_tpu.backend import powm

        monkeypatch.setattr(powm, "_RNS_MIN_ROWS", 1)
        bits = 384
        mods = [primes.gen_prime(192) * primes.gen_prime(192) for _ in range(3)]
        bases = [random.getrandbits(bits) % n for n in mods]
        exps = [random.getrandbits(bits) for _ in mods]
        got = powm.tpu_powm(bases, exps, mods)
        assert got == [pow(b, e, n) for b, e, n in zip(bases, exps, mods)]

    @pytest.mark.slow
    def test_full_size_2048(self):
        n = primes.gen_prime(1024) * primes.gen_prime(1024)
        b, e = random.getrandbits(2048) % n, random.getrandbits(2048)
        (got,) = rns_modexp([b], [e], [n], 2048)
        assert got == pow(b, e, n)


@pytest.mark.heavy
def test_shared_comb_sequential_ladder(monkeypatch):
    """FSDKR_COMB_TREE=0 forces tree_chunk=1, the sequential per-window
    accumulation branch of _rns_shared_modexp_kernel. It must agree with
    the default tree-chunked path and the host oracle (regression: the
    round-3 refactor left window_table unbound in this branch)."""
    import random

    from fsdkr_tpu.ops import rns

    rng = random.Random(47)
    bits = 512
    gmods = [rng.getrandbits(bits) | (1 << (bits - 1)) | 1 for _ in range(3)]
    gbases = [rng.getrandbits(bits - 1) for _ in range(3)]
    gexps = [[rng.getrandbits(96) for _ in range(2)] for _ in range(3)]
    want = [
        [pow(b % n, e, n) for e in grp]
        for b, grp, n in zip(gbases, gexps, gmods)
    ]
    monkeypatch.setenv("FSDKR_COMB_TREE", "0")
    assert rns.rns_modexp_shared(gbases, gexps, gmods, bits) == want
    monkeypatch.delenv("FSDKR_COMB_TREE")
    assert rns.rns_modexp_shared(gbases, gexps, gmods, bits) == want


@pytest.mark.heavy
def test_shared_comb_device_ladder(monkeypatch):
    """Above _DEVICE_LADDER_MIN_GROUPS the comb builds its power ladder
    on the device batch; results must match the host-ladder path / pow."""
    import random

    from fsdkr_tpu.ops import rns

    rng = random.Random(21)
    bits = 512
    monkeypatch.setattr(rns, "_DEVICE_LADDER_MIN_GROUPS", 2)
    gmods = [rng.getrandbits(bits) | (1 << (bits - 1)) | 1 for _ in range(4)]
    gbases = [rng.getrandbits(bits - 1) for _ in range(4)]
    gexps = [[rng.getrandbits(96) for _ in range(3)] for _ in range(4)]
    got = rns.rns_modexp_shared(gbases, gexps, gmods, bits)
    want = [
        [pow(b % n, e, n) for e in grp]
        for b, grp, n in zip(gbases, gexps, gmods)
    ]
    assert got == want
