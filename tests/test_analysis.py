"""fsdkr-lint framework tests (ISSUE 14): planted-violation negative
fixtures (one per rule family, each asserted DETECTED), the clean-tree
positive run, suppression semantics, the knob registry contract, and
the FSDKR_LOCK_CHECK runtime watchdog.

The fixtures are the gate's proof obligation: a static-analysis pass
that cannot catch a planted violation is a green light painted on a
wall. ci.sh runs the same proof in a subprocess against the real
driver so the *gate* (exit code) is what's tested there.
"""

import pathlib
import subprocess
import sys
import textwrap
import threading

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from fsdkr_tpu.analysis import run_passes  # noqa: E402
from fsdkr_tpu.analysis import lockwatch  # noqa: E402
from fsdkr_tpu.analysis.knobs import load_registry  # noqa: E402


def _lint(tmp_path, source: str, passes: str, name="fixture_mod.py"):
    """Write one fixture file and run the selected passes over it."""
    f = tmp_path / name
    f.write_text(textwrap.dedent(source))
    res = run_passes([str(f)], which=passes.split(","),
                     repo_root=str(REPO))
    return res["findings"], res


def _rules(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# planted violations — one per rule


def test_planted_secret_to_journal_detected(tmp_path):
    findings, _ = _lint(tmp_path, """
        def settle(journal, dk):
            journal.append({"t": "terminal", "p": dk.p})
    """, "taint")
    assert any(f.rule == "secret-flow" and "journal" in f.message
               for f in findings), findings


def test_planted_secret_to_telemetry_and_log_detected(tmp_path):
    findings, _ = _lint(tmp_path, """
        def report(counter, local_key):
            counter.labels(share=local_key.keys_linear).inc()

        def debug(dks):
            print("dks are", dks)
    """, "taint")
    msgs = [f.message for f in findings if f.rule == "secret-flow"]
    assert any("telemetry label" in m for m in msgs), msgs
    assert any("log" in m for m in msgs), msgs


def test_planted_secret_to_lru_and_json_detected(tmp_path):
    findings, _ = _lint(tmp_path, """
        import json

        def persist(cache, keys):
            cache.put(("k",), keys[0].paillier_dk, 64)

        def emit(fh, shares):
            json.dump({"shares": shares}, fh)
    """, "taint")
    msgs = [f.message for f in findings if f.rule == "secret-flow"]
    assert any("public LRU" in m for m in msgs), msgs
    assert any("JSON emission" in m for m in msgs), msgs


def test_sanitized_flow_not_flagged(tmp_path):
    findings, _ = _lint(tmp_path, """
        def ok(journal, dk, keys):
            journal.append({"t": "x", "n": len(keys), "tt": keys[0].t})

        def hashed(counter, local_key):
            counter.labels(fp=fingerprint(local_key)).inc()
    """, "taint")
    assert not findings, findings


def test_planted_lock_order_cycle_detected(tmp_path):
    findings, _ = _lint(tmp_path, """
        import threading

        A = threading.Lock()
        B = threading.Lock()

        def ab():
            with A:
                with B:
                    pass

        def ba():
            with B:
                with A:
                    pass
    """, "locks")
    assert any(f.rule == "lock-order" for f in findings), findings


def test_planted_fsync_under_lock_detected(tmp_path):
    findings, _ = _lint(tmp_path, """
        import os
        import threading

        L = threading.Lock()

        def flush(fh):
            with L:
                os.fsync(fh.fileno())
    """, "locks")
    assert any(f.rule == "lock-blocking-call" and "fsync" in f.message
               for f in findings), findings


def test_planted_sleep_and_transitive_blocking_detected(tmp_path):
    findings, _ = _lint(tmp_path, """
        import time
        import threading

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()

            def _slow(self):
                time.sleep(1.0)

            def tick(self):
                with self._lock:
                    self._slow()
    """, "locks")
    assert any(f.rule == "lock-blocking-call" and "sleep" in f.message
               for f in findings), findings


def test_cv_wait_on_held_lock_not_flagged(tmp_path):
    findings, _ = _lint(tmp_path, """
        import threading

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)

            def park(self):
                with self._cv:
                    self._cv.wait(timeout=1.0)
    """, "locks")
    assert not findings, findings


def test_planted_undeclared_knob_detected(tmp_path):
    findings, _ = _lint(tmp_path, """
        import os

        FLAG = os.environ.get("FSDKR_NOT_A_REAL_KNOB", "0")
    """, "knobs")
    assert any(f.rule == "knob-undeclared" for f in findings), findings


def test_planted_hot_loop_env_read_detected(tmp_path):
    findings, _ = _lint(tmp_path, """
        import os

        def hot(rows):
            out = []
            for r in rows:
                out.append(r * int(os.environ.get("FSDKR_THREADS", "1")))
            return out
    """, "knobs")
    assert any(f.rule == "knob-hot-read" for f in findings), findings


def test_planted_layering_violation_detected(tmp_path):
    # the serving layering rule keys on the path, so plant the fixture
    # under a fsdkr_tpu/serving/ directory
    d = tmp_path / "fsdkr_tpu" / "serving"
    d.mkdir(parents=True)
    f = d / "rogue.py"
    f.write_text("from fsdkr_tpu.backend import rlc\n")
    res = run_passes([str(f)], which=["imports"], repo_root=str(REPO))
    assert any(x.rule == "layering" for x in res["findings"]), res


def test_planted_unused_import_detected(tmp_path):
    findings, _ = _lint(tmp_path, """
        import json
        import os

        def f():
            return os.getpid()
    """, "imports")
    assert any(f.rule == "unused-import" and "json" in f.message
               for f in findings), findings


# ---------------------------------------------------------------------------
# suppressions


def test_suppression_honored_and_counted(tmp_path):
    findings, res = _lint(tmp_path, """
        import os
        import threading

        L = threading.Lock()

        def flush(fh):
            with L:
                os.fsync(fh.fileno())  # fsdkr-lint: allow(lock-blocking-call) fixture residual
    """, "locks")
    assert not findings, findings
    assert res["suppressed"] == 1


def test_suppression_without_reason_is_a_finding(tmp_path):
    # the marker is spelled LINTMARK here so the tree-lint of THIS file
    # does not read the fixture literal as a reasonless suppression
    src = """
        import os
        import threading

        L = threading.Lock()

        def flush(fh):
            with L:
                os.fsync(fh.fileno())  # LINTMARK: allow(lock-blocking-call)
    """.replace("LINTMARK", "fsdkr-lint")
    findings, _ = _lint(tmp_path, src, "locks")
    assert any(f.rule == "suppression-missing-reason" for f in findings), \
        findings


def test_suppression_only_covers_named_rule(tmp_path):
    findings, _ = _lint(tmp_path, """
        import os
        import threading

        L = threading.Lock()

        def flush(fh):
            with L:
                os.fsync(fh.fileno())  # fsdkr-lint: allow(knob-hot-read) wrong rule on purpose
    """, "locks")
    assert any(f.rule == "lock-blocking-call" for f in findings), findings


# ---------------------------------------------------------------------------
# clean tree + gate


def test_clean_tree_all_passes():
    """The tree itself must lint clean — every remaining finding either
    fixed or carrying a documented in-code suppression (the ISSUE 14
    acceptance bar)."""
    res = run_passes(
        ["fsdkr_tpu", "scripts", "tests", "bench.py", "__graft_entry__.py"],
        repo_root=str(REPO),
    )
    assert not res["findings"], "\n".join(str(f) for f in res["findings"])
    assert res["files"] > 100  # coverage sanity: the whole tree was read


def test_driver_gate_fails_on_planted_violation(tmp_path):
    f = tmp_path / "bad.py"
    f.write_text(
        "def leak(journal, dk):\n"
        "    journal.append({'p': dk.p})\n"
    )
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "fsdkr_lint.py"),
         "--passes", "taint", str(f)],
        capture_output=True, text=True, cwd=str(REPO),
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "secret-flow" in proc.stdout


def test_driver_fails_on_missing_root():
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "fsdkr_lint.py"),
         "no_such_dir_xyz"],
        capture_output=True, text=True, cwd=str(REPO),
    )
    assert proc.returncode == 1  # renamed root must fail, not shrink


def test_knob_registry_contract():
    reg = load_registry(REPO)
    assert "FSDKR_THREADS" in reg and "FSDKR_LOCK_CHECK" in reg
    assert all(isinstance(v, str) and v for v in reg.values())


# ---------------------------------------------------------------------------
# runtime lock-order watchdog


@pytest.fixture
def clean_watch():
    """Isolate each watchdog test's planted inversions while PRESERVING
    any violations earlier tests legitimately recorded — under
    FSDKR_LOCK_CHECK=1 the sessionfinish gate reads the global list,
    and a bare reset() here would launder a real session violation."""
    saved = lockwatch.snapshot_state()
    lockwatch.reset()
    yield
    lockwatch.restore_state(saved)


def test_lockwatch_detects_order_inversion(clean_watch):
    a = lockwatch.make_lock("fix_a.py:1")
    b = lockwatch.make_lock("fix_b.py:1")
    with a:
        with b:
            pass
    assert not lockwatch.violations()
    with b:
        with a:
            pass
    v = lockwatch.violations()
    assert len(v) == 1, v
    assert v[0]["held"] == "fix_b.py:1"
    assert v[0]["acquiring"] == "fix_a.py:1"
    assert v[0]["cycle"][0] == "fix_a.py:1"


def test_lockwatch_transitive_cycle_detected(clean_watch):
    a = lockwatch.make_lock("t_a.py:1")
    b = lockwatch.make_lock("t_b.py:1")
    c = lockwatch.make_lock("t_c.py:1")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with a:  # closes a 3-cycle a->b->c->a
            pass
    v = lockwatch.violations()
    assert len(v) == 1, v
    assert set(v[0]["cycle"]) == {"t_a.py:1", "t_b.py:1", "t_c.py:1"}


def test_lockwatch_same_order_and_reentrant_rlock_clean(clean_watch):
    a = lockwatch.make_lock("ok_a.py:1")
    r = lockwatch.make_rlock("ok_r.py:1")
    for _ in range(3):
        with a:
            with r:
                with r:  # re-entry: no self-edge, no violation
                    pass
    assert not lockwatch.violations()
    assert "ok_a.py:1" in lockwatch.edges()


def test_lockwatch_condition_compatible(clean_watch):
    """threading.Condition over a tracked lock: wait() releases the
    held entry (so a CV wait can never read as a held-while-acquiring
    edge), notify wakes it, and no violation is recorded."""
    lk = lockwatch.make_lock("cv.py:1")
    cv = threading.Condition(lk)
    hits = []

    def waiter():
        with cv:
            hits.append("waiting")
            cv.wait(timeout=5.0)
            hits.append("woken")

    t = threading.Thread(target=waiter)
    t.start()
    while "waiting" not in hits:
        pass
    with cv:
        cv.notify()
    t.join(5.0)
    assert not t.is_alive()
    assert hits == ["waiting", "woken"]
    assert not lockwatch.violations()


def test_lockwatch_violation_stamps_flight_and_counter(clean_watch):
    from fsdkr_tpu.telemetry import registry

    base = registry.counter(
        "fsdkr_lock_order_violations",
        "runtime lock-order violations (FSDKR_LOCK_CHECK watchdog)",
    ).value()
    a = lockwatch.make_lock("st_a.py:1")
    b = lockwatch.make_lock("st_b.py:1")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert registry.counter(
        "fsdkr_lock_order_violations",
        "runtime lock-order violations (FSDKR_LOCK_CHECK watchdog)",
    ).value() == base + 1


def test_lockwatch_tier1_smoke_subprocess():
    """A tiny pytest selection under FSDKR_LOCK_CHECK=1 completes with
    zero violations and exercises the install()/sessionfinish wiring
    end to end (full tier-1 under the knob is the acceptance run)."""
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_journal.py", "-q",
         "-m", "not slow and not heavy", "-p", "no:cacheprovider"],
        capture_output=True, text=True, cwd=str(REPO),
        env={**__import__("os").environ, "FSDKR_LOCK_CHECK": "1",
             "JAX_PLATFORMS": "cpu"},
        timeout=600,
    )
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    assert "lock-order violations" not in proc.stderr
