"""Unit tests for the host crypto core (oracle layer)."""

import secrets

import pytest

from fsdkr_tpu.core import intops, paillier, primes, secp256k1, transcript, vss
from fsdkr_tpu.core.secp256k1 import GENERATOR, N, Point, Scalar


class TestIntops:
    def test_mod_inv(self):
        m = 101
        for x in range(1, 20):
            inv = intops.mod_inv(x, m)
            assert (x * inv) % m == 1
        assert intops.mod_inv(6, 12) is None

    def test_mod_pow_signed_negative(self):
        m = 10007
        x = 1234
        assert intops.mod_pow_signed(x, -5, m) == pow(pow(x, -1, m), 5, m)

    def test_bytes_roundtrip(self):
        for _ in range(20):
            x = secrets.randbits(517)
            assert intops.from_bytes(intops.to_bytes(x)) == x

    def test_sample_unit_coprime(self):
        n = 15 * 77
        for _ in range(10):
            assert intops.gcd(intops.sample_unit(n), n) == 1


class TestPrimes:
    def test_small_primality(self):
        known = {2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37, 41, 43, 47}
        for n in range(2, 50):
            assert primes.is_probable_prime(n) == (n in known)

    def test_gen_prime_bits(self):
        p = primes.gen_prime(128)
        assert p.bit_length() == 128
        assert primes.is_probable_prime(p)

    def test_gen_modulus_exact_bits(self):
        n, p, q = primes.gen_modulus(256)
        assert n == p * q
        assert n.bit_length() == 256


class TestTranscript:
    def test_deterministic_and_length_prefixed(self):
        a = transcript.hash_ints([1, 2, 3])
        b = transcript.hash_ints([1, 2, 3])
        assert a == b
        # length prefixing: (0x0102, 0x03) != (0x01, 0x0203)
        t1 = transcript.Transcript().chain_int(0x0102).chain_int(0x03).result_int()
        t2 = transcript.Transcript().chain_int(0x01).chain_int(0x0203).result_int()
        assert t1 != t2

    def test_challenge_bits_lsb0(self):
        # e with known byte layout: first byte of the 32-byte BE digest is 0xA5
        e = 0xA5 << 248
        bits = transcript.challenge_bits(e, 8)
        # 0xA5 = 0b10100101, Lsb0 -> [1,0,1,0,0,1,0,1]
        assert bits == [1, 0, 1, 0, 0, 1, 0, 1]

    def test_challenge_bits_count(self):
        bits = transcript.challenge_bits(transcript.hash_ints([7]), 256)
        assert len(bits) == 256
        assert set(bits) <= {0, 1}


class TestSecp256k1:
    def test_generator_on_curve(self):
        g = GENERATOR
        assert (g.y * g.y - (g.x**3 + 7)) % secp256k1.P == 0

    def test_group_law(self):
        a, b = Scalar.random(), Scalar.random()
        assert GENERATOR * a + GENERATOR * b == GENERATOR * (a + b)
        assert GENERATOR * a - GENERATOR * a == Point.identity()

    def test_order(self):
        assert GENERATOR * N == Point.identity()
        assert GENERATOR * (N + 1) == GENERATOR

    def test_compressed_roundtrip(self):
        p = GENERATOR * Scalar.random()
        assert Point.from_bytes(p.to_bytes(compressed=True)) == p
        assert Point.from_bytes(Point.identity().to_bytes()) == Point.identity()

    def test_scalar_inverse(self):
        s = Scalar.random()
        assert (s * s.invert()).v == 1

    def test_fixed_base_comb_matches_generic_ladder(self):
        """The generator fast path (_fixed_base_mul comb table) must
        agree with the generic Jacobian double-and-add: this module is
        the differential oracle for ops.ec_batch, so its own two scalar-
        mul paths are pinned against each other on random and boundary
        scalars (window edges, cancellation, order wraparound)."""
        import random

        # the fast-path dispatch is by coordinates, so ANY point with
        # G's coords takes the comb — route the reference computation
        # through 2G (different coords -> generic ladder)
        plain_g = Point(GENERATOR.x, GENERATOR.y)
        two_g = plain_g + plain_g
        rng = random.Random(0xFE1D)
        cases = [1, 2, 15, 16, 17, N - 1, N - 16, 15 << 252, (1 << 256) - 1]
        cases += [rng.randrange(1, N) for _ in range(64)]
        for k in cases:
            fast = GENERATOR * k
            ref = two_g * (k % N // 2)
            if k % N % 2:
                ref = ref + plain_g
            assert fast == ref, hex(k)


class TestPaillier:
    @pytest.fixture(scope="class")
    def keypair(self):
        return paillier.keygen(512)

    def test_roundtrip(self, keypair):
        ek, dk = keypair
        m = secrets.randbelow(ek.n)
        assert paillier.decrypt(dk, ek, paillier.encrypt(ek, m)) == m

    def test_homomorphic_add_mul(self, keypair):
        # mirrors the MtA algebra of the reference's bob_zkp test
        # (/root/reference/src/range_proofs.rs:676-744)
        ek, dk = keypair
        a = secrets.randbelow(1 << 128)
        b = secrets.randbelow(1 << 64)
        c = secrets.randbelow(1 << 128)
        enc_a = paillier.encrypt(ek, a)
        ab = paillier.mul(ek, enc_a, b)
        ab_plus_c = paillier.add(ek, ab, paillier.encrypt(ek, c))
        assert paillier.decrypt(dk, ek, ab_plus_c) == (a * b + c) % ek.n

    def test_chosen_randomness_deterministic(self, keypair):
        ek, _ = keypair
        r = paillier.sample_randomness(ek)
        assert paillier.encrypt_with_randomness(ek, 42, r) == paillier.encrypt_with_randomness(ek, 42, r)

    def test_zeroized_dk_refuses(self, keypair):
        ek, _ = keypair
        dk = paillier.DecryptionKey(p=0, q=0)
        with pytest.raises(ValueError):
            paillier.decrypt(dk, ek, 123)


class TestVSS:
    def test_share_validate_reconstruct(self):
        secret = Scalar.random()
        scheme, shares = vss.share(2, 5, secret)
        for i, s in enumerate(shares):
            assert scheme.validate_share_public(GENERATOR * s, i + 1)
        # reconstruct from any t+1 shares
        assert scheme.reconstruct([0, 2, 4], [shares[0], shares[2], shares[4]]).v == secret.v
        assert scheme.reconstruct([1, 2, 3], [shares[1], shares[2], shares[3]]).v == secret.v

    def test_validate_rejects_wrong_share(self):
        scheme, shares = vss.share(1, 3, Scalar.random())
        bad = GENERATOR * (shares[0] + Scalar.from_int(1))
        assert not scheme.validate_share_public(bad, 1)

    def test_lagrange_identity(self):
        params = vss.ShamirSecretSharing(2, 5)
        s = [0, 2, 4]
        total = Scalar.zero()
        # sum of lagrange basis coefficients at 0 equals 1
        for idx in s:
            total = total + vss.map_share_to_new_params(params, idx, s)
        assert total.v == 1
