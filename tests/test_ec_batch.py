"""Differential tests for the batched secp256k1 kernels and the
random-linear-combination (RLC) EC verification paths (SURVEY.md §7 step 4
and hard part 4: batch verdicts must preserve per-row attribution)."""

import secrets

import pytest

from fsdkr_tpu.core.secp256k1 import GENERATOR, N, Point, Scalar
from fsdkr_tpu.core import vss
from fsdkr_tpu.ops.ec_batch import batch_msm, batch_scalar_mul


def _host_msm(ps, ss):
    acc = Point.identity()
    for p, s in zip(ps, ss):
        acc = acc + p * Scalar.from_int(s)
    return acc


def _rand_point():
    return GENERATOR * Scalar.random()


@pytest.mark.heavy
class TestScalarMul:
    def test_edge_scalars(self):
        pts = [GENERATOR, _rand_point(), Point.identity(), _rand_point(), GENERATOR]
        scs = [0, 1, 7, N - 1, secrets.randbelow(N)]
        got = batch_scalar_mul(pts, scs)
        assert got == [p * Scalar.from_int(s) for p, s in zip(pts, scs)]

    def test_128bit_width(self):
        pts = [_rand_point() for _ in range(4)]
        scs = [secrets.randbits(128) for _ in range(4)]
        got = batch_scalar_mul(pts, scs, scalar_bits=128)
        assert got == [p * Scalar.from_int(s) for p, s in zip(pts, scs)]

    def test_doubling_through_complete_formula(self):
        # P + P exercises the doubling branch the complete law absorbs
        (got,) = batch_msm([[GENERATOR, GENERATOR]], [[1, 1]])
        assert got == GENERATOR * Scalar(2)

    def test_inverse_cancellation_to_identity(self):
        p = _rand_point()
        (got,) = batch_msm([[p, p]], [[3, N - 3]])
        assert got.infinity


@pytest.mark.heavy
class TestMSM:
    def test_ragged_groups(self):
        groups_p = [
            [_rand_point() for _ in range(5)],
            [GENERATOR, Point.identity(), _rand_point()],
            [_rand_point()],
        ]
        groups_s = [[secrets.randbelow(N) for _ in g] for g in groups_p]
        got = batch_msm(groups_p, groups_s)
        assert got == [_host_msm(p, s) for p, s in zip(groups_p, groups_s)]

    def test_length_mismatch_rejected(self):
        with pytest.raises(ValueError):
            batch_msm([[GENERATOR]], [[1, 2]])


@pytest.mark.heavy
class TestFeldmanRLC:
    def _items(self, t, n):
        secret = Scalar.random()
        scheme, shares = vss.share(t, n, secret)
        points = [GENERATOR * sh for sh in shares]
        return [(scheme, points[i], i + 1) for i in range(n)], shares

    def test_all_valid(self):
        from fsdkr_tpu.backend.tpu_verifier import TpuBatchVerifier

        items, _ = self._items(2, 5)
        assert TpuBatchVerifier().validate_feldman(items) == [True] * 5

    def test_corrupted_row_attributed(self):
        from fsdkr_tpu.backend.tpu_verifier import TpuBatchVerifier

        items, _ = self._items(2, 5)
        bad = list(items)
        scheme, point, idx = bad[3]
        bad[3] = (scheme, point + GENERATOR, idx)  # wrong public share
        verdicts = TpuBatchVerifier().validate_feldman(bad)
        assert verdicts == [True, True, True, False, True]

    def test_two_schemes_mixed(self):
        from fsdkr_tpu.backend.tpu_verifier import TpuBatchVerifier

        items_a, _ = self._items(1, 3)
        items_b, _ = self._items(2, 4)
        scheme, point, idx = items_b[0]
        items_b[0] = (scheme, point + GENERATOR, idx)
        verdicts = TpuBatchVerifier().validate_feldman(items_a + items_b)
        assert verdicts == [True] * 3 + [False, True, True, True]


@pytest.mark.heavy
class TestPdlU1RLC:
    def test_corrupted_u1_attributed(self, test_config):
        from fsdkr_tpu.backend.tpu_verifier import TpuBatchVerifier
        from fsdkr_tpu.proofs.pdl_slack import (
            PDLwSlackProof,
            PDLwSlackStatement,
            PDLwSlackWitness,
        )
        from fsdkr_tpu.protocol.keygen import generate_h1_h2_n_tilde
        from fsdkr_tpu.core import paillier

        ek, dk = paillier.keygen(test_config.paillier_bits)
        n_tilde, h1, h2, _, _ = generate_h1_h2_n_tilde(test_config)
        items = []
        for _ in range(3):
            x = Scalar.random()
            r = paillier.sample_randomness(ek)
            c = paillier.encrypt_with_randomness(ek, x.v, r)
            st = PDLwSlackStatement(
                ciphertext=c, ek=ek, Q=GENERATOR * x, G=GENERATOR,
                h1=h1, h2=h2, N_tilde=n_tilde,
            )
            proof = PDLwSlackProof.prove(PDLwSlackWitness(x=x, r=r), st)
            items.append((proof, st))

        verifier = TpuBatchVerifier(test_config)
        assert verifier.verify_pdl(items) == [None] * 3

        # corrupt row 1's u1: whole-batch RLC fails, host fallback
        # must attribute exactly that row's u1 equation
        proof, st = items[1]
        object.__setattr__(proof, "u1", proof.u1 + GENERATOR)
        verdicts = verifier.verify_pdl(items)
        assert verdicts[0] is None and verdicts[2] is None
        assert verdicts[1] is not None and verdicts[1][0] is False
