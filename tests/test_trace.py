"""Tests for the tracing/metrics subsystem (fsdkr_tpu.utils.trace) and its
integration with the protocol hot paths."""

from fsdkr_tpu.utils import Tracer, get_tracer


class TestTracer:
    def test_disabled_records_nothing(self):
        tr = Tracer(enabled=False)
        with tr.phase("x", items=5):
            pass
        assert tr.stats() == {}

    def test_phase_accumulates(self):
        tr = Tracer(enabled=True)
        for _ in range(3):
            with tr.phase("verify", items=10):
                pass
        st = tr.stats()["verify"]
        assert st.calls == 3 and st.items == 30 and st.seconds >= 0

    def test_phase_records_on_exception(self):
        tr = Tracer(enabled=True)
        try:
            with tr.phase("boom", items=1):
                raise RuntimeError
        except RuntimeError:
            pass
        assert tr.stats()["boom"].calls == 1

    def test_report_renders(self):
        tr = Tracer(enabled=True)
        with tr.phase("a", items=2):
            pass
        rep = tr.report()
        assert "a" in rep and "items/s" in rep
        assert Tracer(enabled=True).report() == "(no phases recorded)"


class TestProtocolIntegration:
    def test_refresh_stamps_phases(self, test_config):
        from fsdkr_tpu.protocol import simulate_dkr, simulate_keygen

        tracer = get_tracer()
        tracer.reset()
        tracer.enable()
        try:
            keys = simulate_keygen(1, 3, test_config)
            simulate_dkr(keys, test_config)
        finally:
            tracer.disable()
        stats = tracer.stats()
        for expected in (
            "distribute.prove_stage1",
            "distribute.prove_stage2",
            "collect.verify_pairs",  # PDL + range, one fused launch set
            "collect.verify_ring_pedersen",
            "collect.validate_feldman",
        ):
            assert expected in stats, (expected, sorted(stats))
            assert stats[expected].items > 0


def test_mac_attribution_to_innermost_phase():
    """add_macs lands on the innermost active phase (the launch layer
    calls it without knowing its protocol phase), and mfu derives from
    the same phase's wall-clock."""
    from fsdkr_tpu.utils.trace import Tracer

    tr = Tracer(enabled=True)
    with tr.phase("outer"):
        with tr.phase("outer.inner"):
            tr.add_macs(1e9)
    tr.add_macs(5.0)  # outside any phase
    stats = tr.stats()
    assert stats["outer.inner"].macs == 1e9
    assert stats["outer"].macs == 0
    assert stats["(unphased)"].macs == 5.0
    assert stats["outer.inner"].mfu(1e12) > 0
    assert "mfu%" in tr.report()


def test_roofline_formulas_scale():
    from fsdkr_tpu.utils import roofline as rl

    # 2048-bit modulus = 128 limbs; full-width exponent
    per_row = rl.generic_modexp_macs(1, 2048, 128)
    assert 5e7 < per_row < 1.2e8  # ~2577 MontMuls x ~32.8k MACs
    # comb amortizes: per-row cost at large m is ~W MontMuls
    g, m, w = 16, 1024, 512
    per_row_comb = rl.shared_modexp_macs(g, m, w, 128) / (g * m)
    assert per_row_comb < per_row / 3
    assert rl.peak_macs() > 1e13
