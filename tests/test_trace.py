"""Tests for the tracing/metrics subsystem (fsdkr_tpu.utils.trace) and its
integration with the protocol hot paths."""

from fsdkr_tpu.utils import Tracer, get_tracer


class TestTracer:
    def test_disabled_records_nothing(self):
        tr = Tracer(enabled=False)
        with tr.phase("x", items=5):
            pass
        assert tr.stats() == {}

    def test_phase_accumulates(self):
        tr = Tracer(enabled=True)
        for _ in range(3):
            with tr.phase("verify", items=10):
                pass
        st = tr.stats()["verify"]
        assert st.calls == 3 and st.items == 30 and st.seconds >= 0

    def test_phase_records_on_exception(self):
        tr = Tracer(enabled=True)
        try:
            with tr.phase("boom", items=1):
                raise RuntimeError
        except RuntimeError:
            pass
        assert tr.stats()["boom"].calls == 1

    def test_report_renders(self):
        tr = Tracer(enabled=True)
        with tr.phase("a", items=2):
            pass
        rep = tr.report()
        assert "a" in rep and "items/s" in rep
        assert Tracer(enabled=True).report() == "(no phases recorded)"


class TestProtocolIntegration:
    def test_refresh_stamps_phases(self, test_config):
        from fsdkr_tpu.protocol import simulate_dkr, simulate_keygen

        tracer = get_tracer()
        tracer.reset()
        tracer.enable()
        try:
            keys = simulate_keygen(1, 3, test_config)
            simulate_dkr(keys, test_config)
        finally:
            tracer.disable()
        stats = tracer.stats()
        for expected in (
            "distribute.prove_stage1",
            "distribute.prove_stage2",
            "collect.verify_pairs",  # PDL + range, one fused launch set
            "collect.verify_ring_pedersen",
            "collect.validate_feldman",
        ):
            assert expected in stats, (expected, sorted(stats))
            assert stats[expected].items > 0
