"""Precompute pool subsystem (FSDKR_PRECOMPUTE, fsdkr_tpu/precompute).

Pins the five contracts of the offline/online tentpole:
- PARITY: under seeded nonces the broadcast transcript (every
  RefreshMessage field and the returned decryption keys) is
  bit-identical between FSDKR_PRECOMPUTE=0, =1 with prefilled pools,
  and =1 with dry pools (per-phase inline fallback). The split-out
  samplers (PDLwSlackProof.sample_stage1, AliceProof.sample_stage1,
  RingPedersenProof.sample_commit, intops.sample_unit,
  vss.sample_poly) are the ONE sampling surface of both the inline
  prover and the offline producer, which is what makes the arms
  comparable at all.
- SINGLE-USE: consuming a pool entry twice raises PrecomputeReuseError
  (a replayed sigma nonce answers two challenges and reveals the
  witness) and consumption drops the pool's references.
- DRY FALLBACK: an empty pool degrades to the inline path with
  identical verdicts under tamper (identifiable abort unchanged).
- CONCURRENCY: the background producer filling pools while the
  protocol consumes them yields valid transcripts (verdict parity).
- ISOLATION: pooled secrets (randomizers, nonces, key material) never
  appear in the public precompute LRU (utils/lru.py) — they live only
  in the precompute store with its wipe discipline.

This file must stay green with FSDKR_PRECOMPUTE=0 forced from the
environment (scripts/ci.sh runs that leg): tests pin their own gate
values via monkeypatch.
"""

import copy
import hashlib
import math
import time

import pytest

from fsdkr_tpu import precompute
from fsdkr_tpu.config import TEST_CONFIG
from fsdkr_tpu.core import intops as intops_mod
from fsdkr_tpu.core import paillier
from fsdkr_tpu.core import vss as vss_mod
from fsdkr_tpu.core.paillier import DecryptionKey
from fsdkr_tpu.core.secp256k1 import N as CURVE_N
from fsdkr_tpu.core.secp256k1 import Scalar
from fsdkr_tpu.errors import FsDkrError, PrecomputeReuseError
from fsdkr_tpu.proofs.alice_range import AliceProof
from fsdkr_tpu.proofs.pdl_slack import PDLwSlackProof
from fsdkr_tpu.proofs.ring_pedersen import (
    RingPedersenProof,
    RingPedersenStatement,
)
from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen
from fsdkr_tpu.protocol.serialization import refresh_message_to_json

CFG = TEST_CONFIG


# ---------------------------------------------------------------------------
# deterministic sampling harness


def _det_below(tag, key, idx, bound):
    """Deterministic uniform-ish integer in [0, bound) — a pure function
    of (tag, key, idx), so any consumption ORDER of per-key streams
    yields the same values (the property global seeding cannot give,
    since pooled and inline runs interleave draws differently)."""
    assert bound > 0
    nbytes = (bound.bit_length() + 7) // 8 + 16
    seed = repr((tag, key, idx)).encode()
    out = b""
    c = 0
    while len(out) < nbytes:
        out += hashlib.sha256(seed + c.to_bytes(4, "big")).digest()
        c += 1
    return int.from_bytes(out[:nbytes], "big") % bound


def _det_unit(tag, key, idx, modulus):
    j = 0
    while True:
        r = _det_below(tag, (key, j), idx, modulus)
        if r and math.gcd(r, modulus) == 1:
            return r
        j += 1


class _Ctr:
    def __init__(self):
        self.d = {}

    def next(self, key):
        v = self.d.get(key, 0)
        self.d[key] = v + 1
        return v

    def reset(self):
        self.d.clear()


@pytest.fixture(scope="module")
def canned_key_material():
    """Real key material generated ONCE (prime search is the only
    sampling we cannot make a cheap pure function), handed out in call
    order by the patched keygen_batch/generate_batch below."""
    count = 3
    kb = paillier.keygen_batch(CFG.paillier_bits, count)
    rp = RingPedersenStatement.generate_batch(count, CFG)
    return kb, rp


def _install_det_samplers(monkeypatch, canned):
    """Patch every sampling surface of distribute() to per-(purpose,
    environment, sequence) deterministic streams. Returns the counter
    object; reset it (plus the canned cursors) between arms."""
    ctr = _Ctr()
    kb, rp = canned
    cursors = {"k": 0, "r": 0}
    q = CURVE_N
    q3 = q**3

    def det_sample_poly(t, n, secret):
        k = ctr.next(("poly", t, n, secret.v))
        coeffs = [secret] + [
            Scalar(_det_below("poly", (t, n, secret.v, k), j, CURVE_N))
            for j in range(t)
        ]
        shares = []
        for i in range(1, n + 1):
            acc = 0
            for c in reversed(coeffs):
                acc = (acc * i + c.v) % CURVE_N
            shares.append(Scalar(acc))
        return coeffs, shares

    monkeypatch.setattr(vss_mod, "sample_poly", det_sample_poly)

    def det_unit(modulus):
        return _det_unit("unit", modulus, ctr.next(("unit", modulus)), modulus)

    monkeypatch.setattr(intops_mod, "sample_unit", det_unit)

    def det_pdl_sample(ntv, nv):
        alpha, beta, rho, gamma = [], [], [], []
        for nt, n_ in zip(ntv, nv):
            i = ctr.next(("pdl", nt, n_))
            alpha.append(_det_below("pdl.alpha", (nt, n_), i, q3))
            beta.append(1 + _det_below("pdl.beta", (nt, n_), i, n_ - 1))
            rho.append(_det_below("pdl.rho", (nt, n_), i, q * nt))
            gamma.append(_det_below("pdl.gamma", (nt, n_), i, q3 * nt))
        return alpha, beta, rho, gamma

    monkeypatch.setattr(PDLwSlackProof, "sample_stage1", det_pdl_sample)

    def det_alice_sample(ntv, nv, q_=q):
        alpha, beta, gamma, rho = [], [], [], []
        for nt, n_ in zip(ntv, nv):
            i = ctr.next(("alice", nt, n_))
            alpha.append(_det_below("alice.alpha", (nt, n_), i, q3))
            beta.append(_det_unit("alice.beta", (nt, n_), i, n_))
            gamma.append(_det_below("alice.gamma", (nt, n_), i, q3 * nt))
            rho.append(_det_below("alice.rho", (nt, n_), i, q * nt))
        return alpha, beta, gamma, rho

    monkeypatch.setattr(AliceProof, "sample_stage1", det_alice_sample)

    def det_rp_sample(witnesses, m_security=CFG.m_security):
        out = []
        for w in witnesses:
            i = ctr.next(("rp", w.phi))
            out.append(
                [
                    _det_below("rp.a", (w.phi, i), j, w.phi)
                    for j in range(m_security)
                ]
            )
        return out

    monkeypatch.setattr(RingPedersenProof, "sample_commit", det_rp_sample)

    def canned_keygen_batch(bits, count):
        assert bits == CFG.paillier_bits
        got = kb[cursors["k"] : cursors["k"] + count]
        cursors["k"] += count
        assert len(got) == count, "canned key material exhausted"
        # fresh DecryptionKey objects: dks are mutable (zeroized by
        # collect) and must not alias across arms
        return [(ek, DecryptionKey(dk.p, dk.q)) for ek, dk in got]

    monkeypatch.setattr(paillier, "keygen_batch", canned_keygen_batch)

    def canned_generate_batch(count, config=None):
        got = rp[cursors["r"] : cursors["r"] + count]
        cursors["r"] += count
        assert len(got) == count, "canned ring-Pedersen material exhausted"
        return list(got)

    monkeypatch.setattr(
        RingPedersenStatement, "generate_batch", canned_generate_batch
    )

    def reset():
        ctr.reset()
        cursors["k"] = cursors["r"] = 0

    return reset


# ---------------------------------------------------------------------------
# seeded transcript bit-parity: off == pooled == dry


@pytest.mark.parametrize("multiexp", ["1", "0"])
def test_transcript_bit_parity(monkeypatch, canned_key_material, multiexp):
    monkeypatch.setenv("FSDKR_MULTIEXP", multiexp)
    monkeypatch.setenv("FSDKR_PRECOMPUTE_BG", "0")
    t, n = 1, 3
    keys = simulate_keygen(t, n, CFG)
    reset = _install_det_samplers(monkeypatch, canned_key_material)

    def arm(mode):
        reset()
        precompute.clear_pools()
        precompute.clear_targets()
        monkeypatch.setenv(
            "FSDKR_PRECOMPUTE", "0" if mode == "off" else "1"
        )
        kcopy = copy.deepcopy(keys)
        if mode == "pooled":
            precompute.stats_reset()
            precompute.prefill(kcopy[0], n, len(kcopy), CFG)
        res = RefreshMessage.distribute_batch(
            [(k.i, k) for k in kcopy], n, CFG
        )
        if mode == "pooled":
            st = precompute.precompute_stats()
            # the pooled arm must actually have consumed pools: n^2 pair
            # entries per kind + enc + the key bundles, zero dry rows
            assert st["consumed"] == 3 * n * len(kcopy) + len(kcopy)
            assert st["dry_fallbacks"] == 0
        return (
            [refresh_message_to_json(m) for m, _ in res],
            [(dk.p, dk.q) for _, dk in res],
        )

    off = arm("off")
    pooled = arm("pooled")
    dry = arm("dry")
    assert off == pooled, "pooled transcript differs from inline"
    assert off == dry, "dry-pool fallback transcript differs from inline"
    precompute.clear_pools()
    precompute.clear_targets()


# ---------------------------------------------------------------------------
# single-use trip wire


def test_single_use_entry_raises_on_reuse():
    precompute.clear_pools()
    store = precompute.get_store()
    assert precompute.put("enc", 101, (2, 4))
    # hold a reference to the live entry, consume through the store,
    # then attempt a replay of the same entry object
    ent = store._pools[("enc", 101)][0]
    assert store.take("enc", 101) == (2, 4)
    with pytest.raises(PrecomputeReuseError):
        ent.take()
    # direct double-take too
    ent2 = precompute.PoolEntry((7,))
    assert ent2.take() == (7,)
    with pytest.raises(PrecomputeReuseError):
        ent2.take()
    precompute.clear_pools()


def test_pool_depth_budget_and_wipe(monkeypatch):
    monkeypatch.setenv("FSDKR_POOL_DEPTH", "2")
    precompute.clear_pools()
    precompute.stats_reset()
    assert precompute.put("enc", 103, (1, 2))
    assert precompute.put("enc", 103, (3, 4))
    assert not precompute.put("enc", 103, (5, 6))  # depth cap: wiped
    st = precompute.precompute_stats()
    assert st["produced"] == 2 and st["wiped"] == 1
    assert st["entries"] == 2 and st["bytes_pooled"] > 0
    precompute.clear_pools()
    st = precompute.precompute_stats()
    assert st["entries"] == 0 and st["bytes_pooled"] == 0
    assert st["wiped"] == 3  # the two unconsumed entries were wiped too


# ---------------------------------------------------------------------------
# dry-pool fallback: tamper verdicts identical across modes


def test_dry_pool_tamper_verdict_parity(monkeypatch):
    monkeypatch.setenv("FSDKR_PRECOMPUTE_BG", "0")
    t, n = 1, 3
    verdicts = {}
    for mode in ("off", "dry", "pooled"):
        precompute.clear_pools()
        precompute.clear_targets()
        monkeypatch.setenv(
            "FSDKR_PRECOMPUTE", "0" if mode == "off" else "1"
        )
        keys = [k.clone() for k in simulate_keygen(t, n, CFG)]
        if mode == "pooled":
            precompute.prefill(keys[0], n, n, CFG)
        res = RefreshMessage.distribute_batch(
            [(k.i, k) for k in keys], n, CFG
        )
        msgs = [m for m, _ in res]
        msgs[1].points_encrypted_vec[0] += 1  # tamper one ciphertext
        with pytest.raises(FsDkrError) as ei:
            RefreshMessage.collect(msgs, keys[0], res[0][1], (), CFG)
        verdicts[mode] = (
            type(ei.value).__name__,
            getattr(ei.value, "party_index", None),
        )
    assert verdicts["off"] == verdicts["dry"] == verdicts["pooled"]
    precompute.clear_pools()
    precompute.clear_targets()


# ---------------------------------------------------------------------------
# concurrent producer/consumer


def test_concurrent_producer_consumer_parity(monkeypatch):
    from fsdkr_tpu.precompute import producer as producer_mod

    monkeypatch.setenv("FSDKR_PRECOMPUTE", "1")
    monkeypatch.setenv("FSDKR_PRECOMPUTE_BG", "1")
    precompute.clear_pools()
    precompute.clear_targets()
    t, n = 1, 3
    keys = [k.clone() for k in simulate_keygen(t, n, CFG)]
    try:
        for _epoch in range(2):
            res = RefreshMessage.distribute_batch(
                [(k.i, k) for k in keys], n, CFG
            )
            # distribute registered next-epoch targets and kicked the
            # producer: it now fills pools while collect verifies here
            msgs = [m for m, _ in res]
            for k, (_m, dk) in zip(keys, res):
                RefreshMessage.collect(msgs, k, dk, (), CFG)
        # the producer must have run, produced valid entries, and hit no
        # errors; epoch 2's collects above already pinned verdict parity
        deadline = time.time() + 60
        while (
            precompute.precompute_stats()["produced"] == 0
            and time.time() < deadline
        ):
            time.sleep(0.1)
        assert precompute.precompute_stats()["produced"] > 0
        assert producer_mod._PRODUCER is not None
        assert producer_mod._PRODUCER.errors == 0
        # a third epoch consumes concurrently-produced entries
        res = RefreshMessage.distribute_batch(
            [(k.i, k) for k in keys], n, CFG
        )
        msgs = [m for m, _ in res]
        for k, (_m, dk) in zip(keys, res):
            RefreshMessage.collect(msgs, k, dk, (), CFG)
        assert precompute.precompute_stats()["consumed"] > 0
    finally:
        precompute.stop_background()
        precompute.clear_targets()
        precompute.clear_pools()


# ---------------------------------------------------------------------------
# secret isolation from the public LRU


def test_pool_secrets_never_in_public_lru(monkeypatch):
    from fsdkr_tpu import native
    from fsdkr_tpu.utils import lru

    monkeypatch.setenv("FSDKR_PRECOMPUTE", "1")
    monkeypatch.setenv("FSDKR_PRECOMPUTE_BG", "0")
    lru.clear_caches()
    precompute.clear_pools()
    precompute.clear_targets()
    t, n = 1, 3
    keys = [k.clone() for k in simulate_keygen(t, n, CFG)]
    precompute.prefill(keys[0], n, n, CFG)
    pooled_secrets = set(precompute.get_store().secret_values())
    assert pooled_secrets  # the pools really hold material
    res = RefreshMessage.distribute_batch([(k.i, k) for k in keys], n, CFG)
    assert res
    # seed one PUBLIC comb entry for contrast (the cacheable path)
    nt = keys[0].h1_h2_n_tilde_vec[0].N
    native.modexp_shared(3, [5, 7, 9, 11], nt)

    cache = lru.global_cache()
    seen_public = False
    for key in list(cache._d.keys()):
        for part in key:
            assert not (
                isinstance(part, int) and part in pooled_secrets
            ), f"pooled secret leaked into public LRU key {key!r}"
        if key[0] == "native-comb":
            seen_public = True
    for val in list(cache._d.values()):
        assert not isinstance(val, precompute.PoolEntry)
    assert seen_public  # the public path DID cache; isolation is real
    precompute.clear_pools()
    precompute.clear_targets()
