"""Differential tests host-vs-TPU backend: same verdicts on valid and
tampered proofs, and a full collect() running end-to-end on the batched
backend (on the virtual CPU platform; bench.py exercises the real chip)."""

import copy
import dataclasses

import pytest

from fsdkr_tpu.backend.batch_verifier import HostBatchVerifier
from fsdkr_tpu.backend.tpu_verifier import TpuBatchVerifier
from fsdkr_tpu.config import TEST_CONFIG
from fsdkr_tpu.core import vss
from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen

CFG = TEST_CONFIG
TPU_CFG = TEST_CONFIG.with_backend("tpu")


@pytest.fixture(scope="module")
def refresh_round():
    """One distributed refresh round's worth of messages (n=3, t=1)."""
    keys = simulate_keygen(1, 3, CFG)
    msgs, dks = [], []
    for key in keys:
        m, dk = RefreshMessage.distribute(key.i, key, 3, CFG)
        msgs.append(m)
        dks.append(dk)
    return keys, msgs, dks


def _pdl_items(keys, msgs, n):
    from fsdkr_tpu.core.secp256k1 import GENERATOR
    from fsdkr_tpu.proofs.pdl_slack import PDLwSlackStatement

    key = keys[0]
    items = []
    for msg in msgs:
        for i in range(n):
            st = PDLwSlackStatement(
                ciphertext=msg.points_encrypted_vec[i],
                ek=key.paillier_key_vec[i],
                Q=msg.points_committed_vec[i],
                G=GENERATOR,
                h1=key.h1_h2_n_tilde_vec[i].g,
                h2=key.h1_h2_n_tilde_vec[i].ni,
                N_tilde=key.h1_h2_n_tilde_vec[i].N,
            )
            items.append((msg.pdl_proof_vec[i], st))
    return items


@pytest.mark.heavy
class TestFamilyParity:
    """Each family: host and TPU verdict vectors must be identical, on
    valid batches and on batches with tampered rows."""

    def test_pdl(self, refresh_round):
        keys, msgs, _ = refresh_round
        items = _pdl_items(keys, msgs, 3)
        # tamper row 2: claim a different s1
        bad = dataclasses.replace(items[2][0], s1=items[2][0].s1 + 1)
        items[2] = (bad, items[2][1])
        host = HostBatchVerifier().verify_pdl(items)
        tpu = TpuBatchVerifier(TPU_CFG).verify_pdl(items)
        assert host == tpu
        assert host[2] is not None and all(v is None for i, v in enumerate(host) if i != 2)

    def test_range(self, refresh_round):
        keys, msgs, _ = refresh_round
        key = keys[0]
        items = []
        for msg in msgs:
            for i in range(3):
                items.append(
                    (
                        msg.range_proofs[i],
                        msg.points_encrypted_vec[i],
                        key.paillier_key_vec[i],
                        key.h1_h2_n_tilde_vec[i],
                    )
                )
        bad = dataclasses.replace(items[4][0], s2=items[4][0].s2 + 1)
        items[4] = (bad, *items[4][1:])
        host = HostBatchVerifier().verify_range(items)
        tpu = TpuBatchVerifier(TPU_CFG).verify_range(items)
        assert host == tpu
        assert host == [i != 4 for i in range(len(items))]

    def test_pairs_fused_matches_per_family(self, refresh_round):
        """verify_pairs (one cross-family fused launch set) must produce
        the same verdict vectors as the separate family calls, including
        tampered rows in each family."""
        keys, msgs, _ = refresh_round
        key = keys[0]
        pdl_items = _pdl_items(keys, msgs, 3)
        range_items = []
        for msg in msgs:
            for i in range(3):
                range_items.append(
                    (
                        msg.range_proofs[i],
                        msg.points_encrypted_vec[i],
                        key.paillier_key_vec[i],
                        key.h1_h2_n_tilde_vec[i],
                    )
                )
        bad_p = dataclasses.replace(pdl_items[1][0], s2=pdl_items[1][0].s2 + 1)
        pdl_items[1] = (bad_p, pdl_items[1][1])
        bad_r = dataclasses.replace(
            range_items[5][0], s1=range_items[5][0].s1 + 1
        )
        range_items[5] = (bad_r, *range_items[5][1:])

        tpu = TpuBatchVerifier(TPU_CFG)
        fused = tpu.verify_pairs(pdl_items, range_items)
        assert fused[0] == tpu.verify_pdl(pdl_items)
        assert fused[1] == tpu.verify_range(range_items)
        host = HostBatchVerifier().verify_pairs(pdl_items, range_items)
        assert fused[0] == host[0] and fused[1] == host[1]
        assert fused[0][1] is not None and fused[1][5] is False

    def test_ring_pedersen(self, refresh_round):
        _, msgs, _ = refresh_round
        items = [(m.ring_pedersen_proof, m.ring_pedersen_statement) for m in msgs]
        bad = dataclasses.replace(
            items[1][0], Z=[z + 1 for z in items[1][0].Z]
        )
        items.append((bad, items[1][1]))
        host = HostBatchVerifier().verify_ring_pedersen(items, CFG.m_security)
        tpu = TpuBatchVerifier(TPU_CFG).verify_ring_pedersen(items, CFG.m_security)
        assert host == tpu == [True, True, True, False]

    def test_correct_key(self, refresh_round):
        _, msgs, _ = refresh_round
        items = [(m.dk_correctness_proof, m.ek) for m in msgs]
        # wrong modulus for row 1's proof
        items.append((msgs[1].dk_correctness_proof, msgs[0].ek))
        host = HostBatchVerifier().verify_correct_key(items, CFG.correct_key_rounds)
        tpu = TpuBatchVerifier(TPU_CFG).verify_correct_key(items, CFG.correct_key_rounds)
        assert host == tpu == [True, True, True, False]

    def test_composite_dlog(self):
        from fsdkr_tpu.proofs.composite_dlog import CompositeDLogProof, DLogStatement
        from fsdkr_tpu.protocol.keygen import generate_dlog_statement_proofs

        st, p1, p2 = generate_dlog_statement_proofs(CFG)
        st_inv = DLogStatement(N=st.N, g=st.ni, ni=st.g)
        bogus = CompositeDLogProof.prove(st, 999)
        items = [(p1, st), (p2, st_inv), (bogus, st)]
        host = HostBatchVerifier().verify_composite_dlog(items)
        tpu = TpuBatchVerifier(TPU_CFG).verify_composite_dlog(items)
        assert host == tpu == [True, True, False]

    def test_empty_batches(self):
        v = TpuBatchVerifier(TPU_CFG)
        assert v.verify_pdl([]) == []
        assert v.verify_range([]) == []
        assert v.verify_ring_pedersen([], CFG.m_security) == []
        assert v.verify_correct_key([], CFG.correct_key_rounds) == []
        assert v.verify_composite_dlog([]) == []


@pytest.mark.heavy
class TestCollectOnTpuBackend:
    def test_full_refresh_tpu_backend(self):
        """End-to-end: distribute on host, collect entirely through the
        batched TPU verifier; secret must be preserved."""
        t, n = 1, 3
        keys = simulate_keygen(t, n, CFG)
        old_secret = vss.reconstruct(
            vss.ShamirSecretSharing(t, n),
            list(range(t + 1)),
            [k.keys_linear.x_i for k in keys[: t + 1]],
        )
        msgs, dks = [], []
        for key in keys:
            m, dk = RefreshMessage.distribute(key.i, key, n, CFG)
            msgs.append(m)
            dks.append(dk)
        for key, dk in zip(keys, dks):
            RefreshMessage.collect(msgs, key, dk, (), TPU_CFG)
        new_secret = vss.reconstruct(
            vss.ShamirSecretSharing(t, n),
            list(range(t + 1)),
            [k.keys_linear.x_i for k in keys[: t + 1]],
        )
        assert old_secret.v == new_secret.v

    def test_tampered_detected_on_tpu_backend(self):
        from fsdkr_tpu.errors import FsDkrError

        t, n = 1, 3
        keys = simulate_keygen(t, n, CFG)
        msgs, dks = [], []
        for key in keys:
            m, dk = RefreshMessage.distribute(key.i, key, n, CFG)
            msgs.append(m)
            dks.append(dk)
        bad = copy.deepcopy(msgs)
        bad[2].points_encrypted_vec[1] += 1
        with pytest.raises(FsDkrError):
            RefreshMessage.collect(bad, keys[1], dks[1], (), TPU_CFG)


@pytest.mark.heavy
def test_launch_tiling_matches_unchunked(monkeypatch):
    """HBM tiling: chunked launches (FSDKR_MAX_ROWS_PER_LAUNCH) must be
    row-for-row identical to one launch."""
    import random

    from fsdkr_tpu.backend import powm

    rng = random.Random(31)
    bits = 512
    mods = [rng.getrandbits(bits) | (1 << (bits - 1)) | 1 for _ in range(6)]
    bases, exps, moduli = [], [], []
    for b_, m_ in zip([rng.getrandbits(bits - 1) for _ in range(6)], mods):
        for _ in range(8):
            bases.append(b_)
            exps.append(rng.getrandbits(128))
            moduli.append(m_)
    want = powm.tpu_powm_grouped(bases, exps, moduli)

    monkeypatch.setattr(powm, "_MAX_ROWS", 16)
    got = powm.tpu_powm_grouped(bases, exps, moduli)
    assert got == want
    got_gen = powm.tpu_powm(bases, exps, moduli)
    assert got_gen == [pow(b % m, e, m) for b, e, m in zip(bases, exps, moduli)]
