"""Sharded verification on the virtual 8-device CPU mesh (conftest forces
jax_num_cpu_devices=8): row-sharded modexp, verdict psum, multi-axis
(session x batch) meshes, and the driver entry points."""

import secrets

import jax
import pytest

from fsdkr_tpu.ops.limbs import limbs_for_bits
from fsdkr_tpu.parallel import make_mesh, sharded_modexp, sharded_verdict_step

BITS = 256
K = limbs_for_bits(BITS)


def _rows(b):
    moduli = [secrets.randbits(BITS) | (1 << (BITS - 1)) | 1 for _ in range(b)]
    bases = [secrets.randbelow(n) for n in moduli]
    exps = [secrets.randbits(128) for _ in range(b)]
    want = [pow(x, e, n) for x, e, n in zip(bases, exps, moduli)]
    return moduli, bases, exps, want


def test_eight_devices_present():
    assert len(jax.devices()) == 8


def test_sharded_modexp_uneven_rows():
    mesh = make_mesh()  # all 8 devices
    moduli, bases, exps, want = _rows(13)  # forces padding
    got = sharded_modexp(bases, exps, moduli, K, mesh)
    assert got == want


def test_verdict_step_psum():
    mesh = make_mesh()
    moduli, bases, exps, want = _rows(16)
    expected = list(want)
    expected[3] += 1
    expected[11] += 1
    ok, failures = sharded_verdict_step(bases, exps, moduli, expected, K, mesh)
    assert failures == 2
    assert [i for i, o in enumerate(ok) if not o] == [3, 11]


def test_2d_session_mesh():
    mesh = make_mesh((2, 4), ("session", "batch"))
    moduli, bases, exps, want = _rows(8)
    got = sharded_modexp(bases, exps, moduli, K, mesh)
    assert got == want
    ok, failures = sharded_verdict_step(bases, exps, moduli, want, K, mesh)
    assert failures == 0 and ok.all()


def test_mesh_validation():
    with pytest.raises(ValueError):
        make_mesh((16,))
    with pytest.raises(ValueError):
        make_mesh((2, 4), ("batch",))


@pytest.mark.heavy
class TestMeshedProtocol:
    """config.mesh_shape consumed end-to-end: the production collect()
    path with every kernel launch row-sharded over the 8-device mesh."""

    def test_collect_with_mesh(self, test_config):
        from fsdkr_tpu.backend import powm
        from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen

        t, n = 1, 3
        import dataclasses

        cfg = test_config
        mesh_cfg = dataclasses.replace(cfg, backend="tpu", mesh_shape=(8,))
        keys = simulate_keygen(t, n, cfg)
        results = RefreshMessage.distribute_batch(
            [(k.i, k) for k in keys], n, mesh_cfg
        )
        msgs = [m for m, _ in results]
        dks = [dk for _, dk in results]
        RefreshMessage.collect(msgs, keys[0], dks[0], (), mesh_cfg)
        assert powm.active_mesh() is not None
        assert int(powm.active_mesh().devices.size) == 8
        # rotation happened: the new share signs consistently
        from fsdkr_tpu.core.secp256k1 import GENERATOR

        assert GENERATOR * keys[0].keys_linear.x_i == keys[0].keys_linear.y

    def test_collect_sessions_with_joins(self, test_config):
        """Fused sessions where one session carries join messages: the
        per-session ck/dlog span bookkeeping must attribute join-side
        verdicts to the right session."""
        from fsdkr_tpu.protocol import (
            JoinMessage,
            RefreshMessage,
            simulate_keygen,
        )

        t, n = 1, 3
        cfg = test_config
        # independent committees matter here: identical moduli across
        # sessions would mask cross-session row-attribution bugs, so
        # bypass the conftest keygen cache for the second session
        fresh_keygen = getattr(simulate_keygen, "uncached", simulate_keygen)

        # session 0: plain refresh
        keys0 = simulate_keygen(t, n, cfg)
        res0 = RefreshMessage.distribute_batch([(k.i, k) for k in keys0], n, cfg)

        # session 1: 2 existing parties + 1 join at index 3
        keys1 = fresh_keygen(t, n, cfg)
        keys1 = [k for k in keys1 if k.i != 3]
        jm, _pair = JoinMessage.distribute(cfg)
        jm.set_party_index(3)
        ident = {1: 1, 2: 2}
        res1 = [
            RefreshMessage.replace([jm], k, ident, n, cfg) for k in keys1
        ]

        errs = RefreshMessage.collect_sessions(
            [
                ([m for m, _ in res0], keys0[0], res0[0][1], ()),
                ([m for m, _ in res1], keys1[0], res1[0][1], (jm,)),
            ],
            cfg,
        )
        assert errs == [None, None], errs
        # join session adopted the joining party's ek
        assert keys1[0].paillier_key_vec[2] == jm.ek

    def test_collect_sessions_fused(self, test_config):
        """Two independent sessions through one fused launch set; a
        tampered session fails alone (identifiable abort preserved)."""
        from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen

        t, n = 1, 3
        cfg = test_config
        # distinct committees per session (see test_collect_sessions_with_joins)
        fresh_keygen = getattr(simulate_keygen, "uncached", simulate_keygen)
        sessions = []
        for i in range(2):
            keys = (simulate_keygen if i == 0 else fresh_keygen)(t, n, cfg)
            results = RefreshMessage.distribute_batch(
                [(k.i, k) for k in keys], n, cfg
            )
            msgs = [m for m, _ in results]
            dks = [dk for _, dk in results]
            sessions.append((keys, msgs, dks))

        # tamper session 1: swap one ciphertext so its range proof fails
        bad_msgs = list(sessions[1][1])
        tampered = bad_msgs[0]
        tampered.points_encrypted_vec = list(tampered.points_encrypted_vec)
        tampered.points_encrypted_vec[0] += 1

        errs = RefreshMessage.collect_sessions(
            [
                (sessions[0][1], sessions[0][0][0], sessions[0][2][0], ()),
                (sessions[1][1], sessions[1][0][0], sessions[1][2][0], ()),
            ],
            cfg,
        )
        assert errs[0] is None
        assert errs[1] is not None


def test_graft_entry_single_chip():
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert bool(out.all())


@pytest.mark.heavy
def test_graft_entry_dryrun():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


def test_multihost_single_host_degenerates():
    """multihost: initialize() is a no-op without a coordinator; the
    global mesh degenerates to (1, local devices). On this box the TPU
    tunnel exports TPU_WORKER_HOSTNAMES, so a late detection-based call
    warns as it degrades — that warning is the documented behavior."""
    import warnings

    from fsdkr_tpu.parallel import multihost

    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        multihost.initialize()
    assert not multihost.is_multihost()
    mesh = multihost.global_mesh()
    assert mesh.devices.shape == (1, 8)
    assert mesh.axis_names == ("session", "batch")
