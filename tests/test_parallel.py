"""Sharded verification on the virtual 8-device CPU mesh (conftest forces
jax_num_cpu_devices=8): row-sharded modexp, verdict psum, multi-axis
(session x batch) meshes, and the driver entry points."""

import secrets

import jax
import pytest

from fsdkr_tpu.ops.limbs import limbs_for_bits
from fsdkr_tpu.parallel import make_mesh, sharded_modexp, sharded_verdict_step

BITS = 256
K = limbs_for_bits(BITS)


def _rows(b):
    moduli = [secrets.randbits(BITS) | (1 << (BITS - 1)) | 1 for _ in range(b)]
    bases = [secrets.randbelow(n) for n in moduli]
    exps = [secrets.randbits(128) for _ in range(b)]
    want = [pow(x, e, n) for x, e, n in zip(bases, exps, moduli)]
    return moduli, bases, exps, want


def test_eight_devices_present():
    assert len(jax.devices()) == 8


def test_sharded_modexp_uneven_rows():
    mesh = make_mesh()  # all 8 devices
    moduli, bases, exps, want = _rows(13)  # forces padding
    got = sharded_modexp(bases, exps, moduli, K, mesh)
    assert got == want


def test_verdict_step_psum():
    mesh = make_mesh()
    moduli, bases, exps, want = _rows(16)
    expected = list(want)
    expected[3] += 1
    expected[11] += 1
    ok, failures = sharded_verdict_step(bases, exps, moduli, expected, K, mesh)
    assert failures == 2
    assert [i for i, o in enumerate(ok) if not o] == [3, 11]


def test_2d_session_mesh():
    mesh = make_mesh((2, 4), ("session", "batch"))
    moduli, bases, exps, want = _rows(8)
    got = sharded_modexp(bases, exps, moduli, K, mesh)
    assert got == want
    ok, failures = sharded_verdict_step(bases, exps, moduli, want, K, mesh)
    assert failures == 0 and ok.all()


def test_mesh_validation():
    with pytest.raises(ValueError):
        make_mesh((16,))
    with pytest.raises(ValueError):
        make_mesh((2, 4), ("batch",))


def test_graft_entry_single_chip():
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert bool(out.all())


def test_graft_entry_dryrun():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)
