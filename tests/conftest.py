"""Test configuration.

Multi-chip sharding is tested on a virtual 8-device CPU mesh. NOTE: in
this environment the axon TPU plugin ignores JAX_PLATFORMS / XLA_FLAGS
environment variables, so the platform must be forced through jax.config
*before* the backend initializes — which is why this happens here, ahead
of any test importing jax.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")  # belt (honored by stock jax)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# braces (required with the axon plugin installed)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import pytest  # noqa: E402

from fsdkr_tpu.config import TEST_CONFIG  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: full-size security parameters; excluded from quick runs"
    )


@pytest.fixture(scope="session")
def test_config():
    """Reduced-size parameters (768-bit Paillier, M=32) so the single-core
    host oracle runs the full protocol in seconds; full-size runs are marked
    `slow`."""
    return TEST_CONFIG
