"""Test configuration.

Multi-chip sharding is tested on a virtual 8-device CPU mesh. NOTE: in
this environment the axon TPU plugin ignores JAX_PLATFORMS / XLA_FLAGS
environment variables, so the platform must be forced through jax.config
*before* the backend initializes — which is why this happens here, ahead
of any test importing jax.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")  # belt (honored by stock jax)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# braces (required with the axon plugin installed)
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax: the XLA_FLAGS fallback above already forces 8 host devices
    pass

# The suite runs on the CPU platform, where auto EC and modexp routing
# would send every hot path to the host oracle (fsdkr_tpu.config
# device_ec, backend.powm._device_powm) — force the device routes so the
# batched kernels keep integration coverage.
os.environ.setdefault("FSDKR_DEVICE_EC", "1")
os.environ.setdefault("FSDKR_DEVICE_POWM", "1")

# The background precompute producer (fsdkr_tpu.precompute.producer) is
# an optimization thread, not a correctness dependency: pools fall back
# inline when dry. Keep it off in the suite so tests are deterministic
# (seeded-nonce tests monkeypatch the samplers process-globally) and the
# single-core box doesn't time-share production against the tests; the
# dedicated concurrency test in test_precompute.py turns it on
# explicitly. FSDKR_PRECOMPUTE itself stays at its default (on), so the
# consume-or-compute path is exercised by every protocol test.
os.environ.setdefault("FSDKR_PRECOMPUTE_BG", "0")

# ISSUE 14: runtime lock-order watchdog. FSDKR_LOCK_CHECK=1 swaps
# threading.Lock/RLock for order-tracking wrappers BEFORE any fsdkr_tpu
# module creates its locks (module-level locks are built at import
# time), validating the static lock graph (scripts/fsdkr_lint.py locks
# pass) against the orders tier-1 actually executes. Violations stamp
# the flight recorder like injected faults and fail the session in
# pytest_sessionfinish below. Off by default everywhere: the
# bookkeeping costs a dict touch per acquisition on every hot lock.
_LOCK_CHECK = os.environ.get("FSDKR_LOCK_CHECK", "0").lower() not in (
    "", "0", "false", "off"
)
if _LOCK_CHECK:
    from fsdkr_tpu.analysis import lockwatch as _lockwatch  # noqa: E402

    _lockwatch.install()

import pytest  # noqa: E402

from fsdkr_tpu.config import TEST_CONFIG  # noqa: E402

# ---------------------------------------------------------------------------
# Session-scoped keygen cache. simulate_keygen dominates suite wall-clock on
# this single-core box (every call generates n Paillier pairs + n ring-
# Pedersen moduli at 768 bits); most tests just need *a* valid committee.
# Cache the first result per (t, n, config) and hand out deepcopies — tests
# mutate LocalKeys (refresh rotates shares in place, collect zeroizes dks),
# so each test gets a private copy of an identical committee.
#
# Sharing is visible and escapable at the test site: mark a test
# @pytest.mark.fresh_committees to bypass the cache for that test (every
# simulate_keygen call inside it generates fresh randomness), or call
# simulate_keygen.uncached directly. Disable globally with
# FSDKR_TEST_KEYGEN_CACHE=0.
# ---------------------------------------------------------------------------
_keygen_cache_bypassed = False

if os.environ.get("FSDKR_TEST_KEYGEN_CACHE", "1").lower() not in (
    "",
    "0",
    "false",
    "off",
    "no",
):
    import copy  # noqa: E402

    from fsdkr_tpu import protocol as _protocol  # noqa: E402
    from fsdkr_tpu.protocol import keygen as _keygen_mod  # noqa: E402

    _real_simulate_keygen = _keygen_mod.simulate_keygen
    _keygen_cache: dict = {}

    def _cached_simulate_keygen(t, n, *args, **kwargs):
        if _keygen_cache_bypassed:
            return _real_simulate_keygen(t, n, *args, **kwargs)
        # pass config through untouched so the wrapped function's own
        # default (DEFAULT_CONFIG) applies identically with cache on/off
        config = args[0] if args else kwargs.get("config")
        key = (t, n, repr(config))  # content key: configs are dataclasses
        if key not in _keygen_cache:
            _keygen_cache[key] = _real_simulate_keygen(t, n, *args, **kwargs)
        return copy.deepcopy(_keygen_cache[key])

    # tests that NEED independent committees (e.g. cross-session row
    # attribution in fused collects) call simulate_keygen.uncached
    _cached_simulate_keygen.uncached = _real_simulate_keygen
    _keygen_mod.simulate_keygen = _cached_simulate_keygen
    _protocol.simulate_keygen = _cached_simulate_keygen
    # simulation.py binds the name at import time as well
    from fsdkr_tpu.protocol import simulation as _simulation  # noqa: E402

    if hasattr(_simulation, "simulate_keygen"):
        _simulation.simulate_keygen = _cached_simulate_keygen


@pytest.fixture(autouse=True)
def _keygen_cache_marker(request):
    """Honor @pytest.mark.fresh_committees: bypass the session keygen
    cache for the marked test."""
    global _keygen_cache_bypassed
    if request.node.get_closest_marker("fresh_committees") is None:
        yield
        return
    _keygen_cache_bypassed = True
    try:
        yield
    finally:
        _keygen_cache_bypassed = False


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: full-size security parameters; excluded from quick runs"
    )
    config.addinivalue_line(
        "markers",
        "heavy: minutes-long kernel differentials / mesh compiles; excluded "
        "from the smoke gate (scripts/ci.sh) but part of the quick suite",
    )
    config.addinivalue_line(
        "markers",
        "fresh_committees: bypass the session-scoped keygen cache — every "
        "simulate_keygen call in the test generates a fresh committee",
    )


def pytest_sessionfinish(session, exitstatus):
    """Under FSDKR_LOCK_CHECK=1 the whole run doubles as a lock-order
    test: any violation the watchdog observed fails the session, naming
    the cycle — the same hard-gate posture as the static locks pass."""
    if not _LOCK_CHECK:
        return
    from fsdkr_tpu.analysis import lockwatch

    bad = lockwatch.violations()
    if bad:
        import sys as _sys

        print("\nFSDKR_LOCK_CHECK: lock-order violations:", file=_sys.stderr)
        for v in bad:
            print(
                f"  thread {v['thread']}: acquiring {v['acquiring']} "
                f"while holding {v['held']} (cycle: "
                + " -> ".join(v["cycle"]) + ")",
                file=_sys.stderr,
            )
        session.exitstatus = 1


@pytest.fixture(scope="session")
def test_config():
    """Reduced-size parameters (768-bit Paillier, M=32) so the single-core
    host oracle runs the full protocol in seconds; full-size runs are marked
    `slow`."""
    return TEST_CONFIG


@pytest.fixture(scope="session")
def one_refresh_round(test_config):
    """One honest (t=1, n=3) refresh round: (keys-post-distribute,
    messages, new dks). Shared by the object-level (test_tamper) and
    wire-level (test_wire_negative) adversarial suites — consumers must
    deepcopy messages / clone keys before mutating."""
    from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen

    keys = simulate_keygen(1, 3, test_config)
    out = [RefreshMessage.distribute(k.i, k, 3, test_config) for k in keys]
    return keys, [m for m, _ in out], [dk for _, dk in out]
