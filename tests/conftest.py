"""Test configuration.

Multi-chip sharding is tested on a virtual 8-device CPU mesh: JAX is forced
onto the CPU platform with 8 host devices before any test imports JAX, so
`jax.sharding.Mesh`/`shard_map` paths compile and execute without TPU
hardware. The single real TPU chip is exercised by bench.py, not the unit
suite.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402

from fsdkr_tpu.config import TEST_CONFIG  # noqa: E402


@pytest.fixture(scope="session")
def test_config():
    """Reduced-size parameters (768-bit Paillier, M=32) so the single-core
    host oracle runs the full protocol in seconds; full-size runs are marked
    `slow`."""
    return TEST_CONFIG
