"""Worker process for tests/test_multihost.py — NOT a test module.

Each worker joins a 2-process jax.distributed CPU cluster (the same
coordination path a real multi-host TPU pod uses, over a local Gloo
backend), builds the host-aligned global mesh, contributes its own block
of proof rows, runs the sharded Montgomery modmul kernel across all four
(2 hosts x 2 local devices) devices, gathers the verdict rows, and
checks them against the host oracle. Usage:

    python _multihost_worker.py <process_id> <port>
"""

import os
import sys

proc_id, port = int(sys.argv[1]), sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:  # older jax: XLA_FLAGS above already forces 2
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fsdkr_tpu.parallel import multihost  # noqa: E402

multihost.initialize(
    coordinator_address=f"localhost:{port}", num_processes=2, process_id=proc_id
)
assert multihost.is_multihost(), "expected a 2-process cluster"
mesh = multihost.global_mesh()
assert mesh.devices.shape == (2, 2), mesh.devices.shape

import random  # noqa: E402

import numpy as np  # noqa: E402
from jax.sharding import PartitionSpec  # noqa: E402

from fsdkr_tpu.ops.limbs import (  # noqa: E402
    MontgomeryContext,
    ints_to_limbs,
    limbs_to_ints,
)
from fsdkr_tpu.parallel.shard_kernels import sharded_modmul_fn  # noqa: E402

rng = random.Random(7)
rows, bits = 8, 256
k = bits // 16
mods = [rng.getrandbits(bits) | (1 << (bits - 1)) | 1 for _ in range(rows)]
a = [rng.getrandbits(bits - 1) for _ in range(rows)]
b = [rng.getrandbits(bits - 1) for _ in range(rows)]
ctx = MontgomeryContext(mods, k)
want = [(x * y) % m for x, y, m in zip(a, b, mods)]

row_axes = tuple(mesh.axis_names)
half = rows // 2
lo, hi = proc_id * half, (proc_id + 1) * half


def glob(x, spec):
    return multihost.rows_to_global(mesh, np.asarray(x)[lo:hi], spec)


out = sharded_modmul_fn(mesh)(
    glob(ints_to_limbs(a, k), PartitionSpec(row_axes, None)),
    glob(ints_to_limbs(b, k), PartitionSpec(row_axes, None)),
    glob(ctx.n, PartitionSpec(row_axes, None)),
    glob(ctx.n_prime, PartitionSpec(row_axes)),
    glob(ctx.r2, PartitionSpec(row_axes, None)),
)
got = limbs_to_ints(multihost.gather_rows(out))
assert got == want, "sharded modmul mismatch across processes"
print(f"proc {proc_id}: MULTIHOST-OK", flush=True)
