"""Range-family verifier engines (FSDKR_RANGEOPT, ISSUE 8).

Pins the three structure-exploiting engines the range wall was killed
with — the shared-exponent ladder (native.shared_exp_powm /
backend.powm.tpu_powm_shared_exp), the joint 2-term fixed-base comb
apply (native.comb2_apply / backend.powm.joint_comb2), and the
FSDKR_RANGEOPT verifier path — against the host oracle:

- engine parity on adversarial shapes: gcd(z, N~) > 1 / gcd(c, n^2) > 1
  rows, zero/one bases, e = 0 rows;
- FSDKR_RANGEOPT=0/1 verdict and tamper-blame bit-identity (n=16
  committee in test_rangeopt_collect_blame_identity_n16);
- FSDKR_THREADS 1-vs-8 bit-identity of the new row-parallel engines;
- FSDKR_MPN (GMP mpn inner loop vs portable u128 core) bit-identity;
- the protocol-dead proofs.bob_range module stays importable and
  self-consistent (its prover is referenced by SURVEY parity only).

Device-kernel AOT lowering for the shared-exponent kernel lives in
tests/test_tpu_lowering.py (test_cios_shared_exp).
"""

import copy
import dataclasses
import random

import pytest

from fsdkr_tpu import native
from fsdkr_tpu.backend.batch_verifier import HostBatchVerifier
from fsdkr_tpu.backend.tpu_verifier import TpuBatchVerifier
from fsdkr_tpu.config import TEST_CONFIG

TPU_CFG = TEST_CONFIG.with_backend("tpu")


def _odd(rng, bits):
    return rng.getrandbits(bits) | (1 << (bits - 1)) | 1


# ---------------------------------------------------------------------------
# engine-level parity


def test_shared_exp_powm_parity_and_edge_bases():
    rng = random.Random(0xA11CE)
    n = _odd(rng, 512)
    nn = n * n
    bases = [rng.randrange(nn) for _ in range(12)]
    auxb = [rng.randrange(nn) for _ in range(12)]
    auxe = [rng.getrandbits(128) for _ in range(12)]
    # edge rows: zero/one bases, e = 0, aux base 1, base a multiple of n
    bases[0] = 0
    bases[1] = 1
    auxe[2] = 0
    auxb[3] = 1
    bases[4] = n  # gcd(base, n^2) = n > 1: still exact, no unit needed
    got = native.shared_exp_powm(bases, n, nn, auxb, auxe)
    want = [
        pow(b, n, nn) * pow(ab, ae, nn) % nn
        for b, ab, ae in zip(bases, auxb, auxe)
    ]
    assert got == want
    # no-aux form and exp = 0
    assert native.shared_exp_powm(bases, n, nn) == [pow(b, n, nn) for b in bases]
    assert native.shared_exp_powm(bases[:2], 0, nn) == [1, 1]
    with pytest.raises(ValueError):
        native.shared_exp_powm(bases, -1, nn)


def test_shared_exp_powm_even_modulus_falls_back():
    """An even modulus cannot enter the Montgomery core: the bridge must
    degrade to the split-chain fallback with identical results."""
    rng = random.Random(7)
    mod = rng.getrandbits(512) | (1 << 511)
    mod ^= mod & 1  # force even
    bases = [rng.randrange(1, mod) for _ in range(3)]
    exp = rng.getrandbits(64)
    assert native.shared_exp_powm(bases, exp, mod) == [
        pow(b, exp, mod) for b in bases
    ]


def test_shared_exp_powm_mpn_vs_portable(monkeypatch):
    """FSDKR_MPN=0 (portable u128 core) and the GMP mpn inner loop are a
    pure speed A/B: bit-identical outputs."""
    rng = random.Random(99)
    n = _odd(rng, 384)
    nn = n * n
    bases = [rng.randrange(nn) for _ in range(6)]
    auxb = [rng.randrange(nn) for _ in range(6)]
    auxe = [rng.getrandbits(96) for _ in range(6)]
    a = native.shared_exp_powm(bases, n, nn, auxb, auxe)
    monkeypatch.setenv("FSDKR_MPN", "0")
    b = native.shared_exp_powm(bases, n, nn, auxb, auxe)
    assert a == b
    if native.available():
        assert native.engine_kind() == "portable"


def test_shared_exp_powm_threads_parity(monkeypatch):
    """FSDKR_THREADS 1-vs-8: the row split cannot change any row's math
    (independent per-row state; same contract as the other row pools)."""
    rng = random.Random(1234)
    n = _odd(rng, 384)
    nn = n * n
    bases = [rng.randrange(nn) for _ in range(9)]
    auxb = [rng.randrange(nn) for _ in range(9)]
    auxe = [rng.getrandbits(128) for _ in range(9)]
    monkeypatch.setenv("FSDKR_THREADS", "1")
    a = native.shared_exp_powm(bases, n, nn, auxb, auxe)
    monkeypatch.setenv("FSDKR_THREADS", "8")
    b = native.shared_exp_powm(bases, n, nn, auxb, auxe)
    assert a == b


def test_comb2_apply_parity_and_cache(monkeypatch):
    """Joint 2-term comb vs oracle, including zero exponents and the
    zero/one base edge; the second call must be served from the
    persistent public-base LRU (warm tables: no rebuild)."""
    if not native.available():
        pytest.skip("native core unavailable")
    from fsdkr_tpu.utils import lru

    rng = random.Random(0xC0B2)
    nt = _odd(rng, 512)
    h1 = rng.randrange(nt)
    h2 = rng.randrange(nt)
    s1 = [rng.getrandbits(192) for _ in range(8)]
    s2 = [rng.getrandbits(700) for _ in range(8)]
    s1[0] = 0
    s2[1] = 0
    want = [pow(h1, a, nt) * pow(h2, b, nt) % nt for a, b in zip(s1, s2)]
    got = native.comb2_apply(h1, s1, h2, s2, nt)
    assert got == want
    before = lru.cache_stats()["hits"]
    assert native.comb2_apply(h1, s1, h2, s2, nt) == want
    assert lru.cache_stats()["hits"] >= before + 2  # both tables warm
    # one/zero bases build degenerate-but-exact tables
    assert native.comb2_apply(1, s1, 0, s2, nt) == [
        pow(0, b, nt) if b else 1 for b in s2
    ]
    monkeypatch.setenv("FSDKR_THREADS", "8")
    assert native.comb2_apply(h1, s1, h2, s2, nt) == want


def test_backend_routes_match_oracle():
    """backend.powm routing (device kernels forced by conftest) must
    agree with the native/host engines and the oracle on both new
    column shapes."""
    from fsdkr_tpu.backend.powm import joint_comb2, tpu_powm_shared_exp

    rng = random.Random(0xD0)
    n = _odd(rng, 256)
    nn = n * n
    bases = [rng.randrange(nn) for _ in range(5)]
    auxb = [rng.randrange(nn) for _ in range(5)]
    auxe = [rng.getrandbits(64) for _ in range(5)]
    assert tpu_powm_shared_exp(bases, n, nn, auxb, auxe) == [
        pow(b, n, nn) * pow(ab, ae, nn) % nn
        for b, ab, ae in zip(bases, auxb, auxe)
    ]
    nt = _odd(rng, 256)
    h1, h2 = rng.randrange(nt), rng.randrange(nt)
    e1 = [rng.getrandbits(96) for _ in range(5)]
    e2 = [rng.getrandbits(200) for _ in range(5)]
    assert joint_comb2(h1, e1, h2, e2, nt) == [
        pow(h1, a, nt) * pow(h2, b, nt) % nt for a, b in zip(e1, e2)
    ]


# ---------------------------------------------------------------------------
# verifier-level identity (FSDKR_RANGEOPT=0/1 and host oracle)


def _range_items(keys, msgs, n):
    key = keys[0]
    items = []
    for msg in msgs:
        for i in range(n):
            items.append(
                (
                    msg.range_proofs[i],
                    msg.points_encrypted_vec[i],
                    key.paillier_key_vec[i],
                    key.h1_h2_n_tilde_vec[i],
                )
            )
    return items


@pytest.fixture(scope="module")
def range_round():
    from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen

    keys = simulate_keygen(1, 3, TEST_CONFIG)
    out = [
        RefreshMessage.distribute(k.i, k, 3, TEST_CONFIG) for k in keys
    ]
    return keys, [m for m, _ in out]


def test_rangeopt_verdicts_identical_adversarial_rows(
    range_round, monkeypatch
):
    """FSDKR_RANGEOPT=0/1 and the host oracle agree row-by-row on a
    batch holding every adversarial shape the grouped engines must not
    mis-stage: gcd(z, N~) > 1, gcd(c, n^2) > 1, e = 0, a tampered s,
    and an out-of-domain (q^3-violating) s1."""
    keys, msgs = range_round
    items = _range_items(keys, msgs, 3)
    q = 1 << 256
    # row 0: z shares a factor with N~ (z := N~ * k staged mod N~ -> 0;
    # use a multiple of neither unit): z = N~ - (N~ // 3) ... simplest
    # non-invertible wire value with gcd > 1 is z = 0
    items[0] = (
        dataclasses.replace(items[0][0], z=0),
        *items[0][1:],
    )
    # row 1: ciphertext c = n -> gcd(c, n^2) = n > 1 (and e != 0)
    items[1] = (items[1][0], items[1][2].n, items[1][2], items[1][3])
    # row 2: e = 0 (challenge never matches, but both paths must stage
    # the row without inversion failure: x^0 = 1 is always invertible)
    items[2] = (
        dataclasses.replace(items[2][0], e=0),
        *items[2][1:],
    )
    # row 3: honest proof tampered in s
    items[3] = (
        dataclasses.replace(items[3][0], s=items[3][0].s + 1),
        *items[3][1:],
    )
    # row 4: s1 out of the q^3 slack domain (gated pre-launch)
    items[4] = (
        dataclasses.replace(items[4][0], s1=q**3 + 7),
        *items[4][1:],
    )
    host = HostBatchVerifier().verify_range(items)
    verdicts = {}
    for leg in ("0", "1"):
        monkeypatch.setenv("FSDKR_RANGEOPT", leg)
        verdicts[leg] = TpuBatchVerifier(TPU_CFG).verify_range(items)
    assert verdicts["0"] == verdicts["1"] == host
    assert not any(host[:5]) and all(host[5:])


def test_rangeopt_pairs_identical(range_round, monkeypatch):
    """verify_pairs under the concurrent column scheduler returns the
    same two verdict vectors as the unscheduled FSDKR_RANGEOPT=0 fused
    path (tampered rows in both families)."""
    from tests.test_tpu_backend import _pdl_items

    keys, msgs = range_round
    pdl_items = _pdl_items(keys, msgs, 3)
    range_items = _range_items(keys, msgs, 3)
    bad_p = dataclasses.replace(pdl_items[2][0], s1=pdl_items[2][0].s1 + 1)
    pdl_items[2] = (bad_p, pdl_items[2][1])
    bad_r = dataclasses.replace(range_items[4][0], s2=range_items[4][0].s2 + 1)
    range_items[4] = (bad_r, *range_items[4][1:])
    out = {}
    for leg in ("0", "1"):
        monkeypatch.setenv("FSDKR_RANGEOPT", leg)
        out[leg] = TpuBatchVerifier(TPU_CFG).verify_pairs(
            pdl_items, range_items
        )
    assert out["0"][0] == out["1"][0]
    assert out["0"][1] == out["1"][1]
    assert out["1"][1][4] is False and out["1"][0][2] is not None


@pytest.fixture(scope="module")
def committee16():
    """(t=1, n=16) honest round: 16 receiver environments exercise the
    grouped shared-exponent / joint-comb engines at the committee shape
    the acceptance criteria name."""
    from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen

    keys = simulate_keygen(1, 16, TEST_CONFIG)
    results = RefreshMessage.distribute_batch(
        [(k.i, k) for k in keys], 16, TEST_CONFIG
    )
    return keys, [m for m, _ in results], [dk for _, dk in results]


@pytest.mark.heavy  # n=16 keygen+distribute: tier-1, not the smoke gate
def test_rangeopt_collect_blame_identity_n16(committee16, monkeypatch):
    """Collect-level A/B at n=16: a single tampered range proof raises
    RangeProofError blaming the exact same party under FSDKR_RANGEOPT=0
    and =1, and the honest transcript is accepted by both legs."""
    from fsdkr_tpu.errors import RangeProofError
    from fsdkr_tpu.protocol import RefreshMessage

    monkeypatch.setenv("FSDKR_DEVICE_POWM", "0")
    monkeypatch.setenv("FSDKR_DEVICE_EC", "0")
    keys, msgs, dks = committee16
    cfg = TEST_CONFIG.with_backend("tpu")
    blames = {}
    for leg in ("0", "1"):
        monkeypatch.setenv("FSDKR_RANGEOPT", leg)
        bad = copy.deepcopy(msgs)
        bad[3].range_proofs[5] = dataclasses.replace(
            bad[3].range_proofs[5], s=bad[3].range_proofs[5].s + 1
        )
        with pytest.raises(RangeProofError) as ei:
            RefreshMessage.collect(bad, keys[0].clone(), dks[0], (), cfg)
        blames[leg] = ei.value.party_index
    assert blames["0"] == blames["1"]
    monkeypatch.setenv("FSDKR_RANGEOPT", "1")
    RefreshMessage.collect(
        copy.deepcopy(msgs), keys[0].clone(), dks[0], (), cfg
    )


def test_scheduler_workers_bit_identical(range_round, monkeypatch):
    """The concurrent column scheduler's worker count is a pure
    execution-shape knob: forcing a 4-wide pool (vs sequential) on the
    same batch must produce identical verdicts — jobs only ever write
    disjoint result slots."""
    keys, msgs = range_round
    items = _range_items(keys, msgs, 3)
    monkeypatch.setenv("FSDKR_SCHED", "1")
    a = TpuBatchVerifier(TPU_CFG).verify_range(items)
    monkeypatch.setenv("FSDKR_SCHED", "4")
    b = TpuBatchVerifier(TPU_CFG).verify_range(items)
    assert a == b


def test_multimegabit_s1_never_staged(range_round, monkeypatch):
    """White-box pin of the dead-row fix: a q^3-violating multi-megabit
    s1 fails the domain gate and must appear in NO launch group of the
    range-opt planner — and the legacy path must not build its gs1
    either (both paths return False for the row, True elsewhere)."""
    keys, msgs = range_round
    items = _range_items(keys, msgs, 3)
    huge = (1 << 2_000_001) + 5
    k = 2
    items[k] = (
        dataclasses.replace(items[k][0], s1=huge),
        *items[k][1:],
    )
    tpu = TpuBatchVerifier(TPU_CFG)
    state = tpu._range_opt_prepare(items)
    assert not state["row_ok"][k] and not state["live"][k]
    assert all(k not in idxs for idxs in state["nn_groups"].values())
    assert all(k not in idxs for idxs in state["nt_groups"].values())
    for leg in ("0", "1"):
        monkeypatch.setenv("FSDKR_RANGEOPT", leg)
        verdicts = TpuBatchVerifier(TPU_CFG).verify_range(items)
        assert verdicts == [i != k for i in range(len(items))]


# ---------------------------------------------------------------------------
# protocol-dead module guard (ISSUE 8 satellite)


def test_bob_range_importable_and_roundtrips():
    """proofs.bob_range is PROTOCOL-DEAD in the refresh (no collect()
    path constructs or verifies it; see its module docstring) but must
    not rot: the module imports, stays out of the batch verifier
    surface, and its prove/verify pair round-trips on a tiny synthetic
    instance so an accidental future wiring starts from working code.
    (The full MtA-flow round-trip at protocol size lives in
    tests/test_proofs.py::TestBobRange.)"""
    from fsdkr_tpu.backend import tpu_verifier
    from fsdkr_tpu.core import paillier
    from fsdkr_tpu.core.secp256k1 import Scalar
    from fsdkr_tpu.proofs import bob_range
    from fsdkr_tpu.proofs.composite_dlog import DLogStatement

    assert "protocol-dead" in (bob_range.__doc__ or "").lower()
    # the batch verifier must not have grown a bob_range family
    assert not any(
        "bob" in name.lower() for name in dir(tpu_verifier.TpuBatchVerifier)
    )
    rng = random.Random(0xB0B)
    ek, _dk = paillier.keygen(768)
    dlog = DLogStatement(
        N=_odd(rng, 512), g=rng.getrandbits(256), ni=rng.getrandbits(256)
    )
    a = Scalar.random().to_int()
    enc_a = paillier.encrypt(ek, a)
    b = Scalar.random()
    b_enc = paillier.mul(ek, enc_a, b.to_int())
    beta_prim = rng.randrange(ek.n)
    r = paillier.sample_randomness(ek)
    mta_out = paillier.add(
        ek, b_enc, paillier.encrypt_with_randomness(ek, beta_prim, r)
    )
    proof, _ = bob_range.BobProof.generate(
        enc_a, mta_out, b, beta_prim, ek, dlog, r
    )
    assert proof.verify(enc_a, mta_out, ek, dlog)
