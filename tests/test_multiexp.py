"""Differential parity suite for the joint multi-exponentiation
(Straus/Shamir) engines, plus planner semantics and the FSDKR_MULTIEXP
collect-level A/B identity.

Three engines compute `prod_t bases[r][t]^exps[r][t] mod moduli[r]`:
the native C++ interleaved ladder (csrc/fsdkr_native.cpp), the CIOS
device kernel (ops.montgomery._multi_modexp_kernel) and the RNS/MXU
kernel (ops.rns._rns_multi_modexp_kernel). Every one is checked against
the CPython pow oracle over random k in {1..4}, mixed exponent widths,
negative exponents (planner base-inversion folding), shared-modulus
groups, and 768/2048/4096-bit moduli.
"""

import copy
import dataclasses
import random

import pytest

from fsdkr_tpu import native
from fsdkr_tpu.backend import powm as powm_mod
from fsdkr_tpu.backend.powm import (
    batch_base_inv,
    host_powm,
    multi_powm,
    powm_columns,
)

RNG = random.Random(0xF5DC)


def _odd_mod(bits):
    return RNG.getrandbits(bits) | (1 << (bits - 1)) | 1


def _oracle_row(bases, exps, m):
    acc = 1
    for b, e in zip(bases, exps):
        acc = acc * pow(b, e, m) % m
    return acc


def _random_rows(bits, widths, rows, shared_mod=False):
    mods = (
        [_odd_mod(bits)] * rows
        if shared_mod
        else [_odd_mod(bits) for _ in range(rows)]
    )
    bases = [tuple(RNG.randrange(1, m) for _ in widths) for m in mods]
    exps = [
        tuple(RNG.getrandbits(w) for w in widths) for _ in range(rows)
    ]
    return bases, exps, mods


# ---------------------------------------------------------------------------
# native engine


@pytest.mark.skipif(not native.available(), reason="no native core")
@pytest.mark.parametrize(
    "bits,widths",
    [
        (768, (768, 256)),
        (768, (768, 256, 17, 1)),
        (2048, (2048, 256)),
        (4096, (2048, 256, 256)),
    ],
)
def test_native_multi_parity(bits, widths):
    bases, exps, mods = _random_rows(bits, widths, rows=4)
    got = native.multi_modexp_batch(bases, exps, mods)
    for r in range(len(mods)):
        assert got[r] == _oracle_row(bases[r], exps[r], mods[r])


@pytest.mark.skipif(not native.available(), reason="no native core")
def test_native_multi_edge_cases():
    n = _odd_mod(768)
    # zero exponents, base >= modulus, k=1
    assert native.multi_modexp_batch([(n + 5, 3)], [(0, 0)], [n]) == [1]
    assert native.multi_modexp_batch([(2,)], [(100,)], [n]) == [
        pow(2, 100, n)
    ]
    # even modulus: pure-Python row fallback, still exact
    assert native.multi_modexp_batch([(3, 5)], [(7, 2)], [1 << 700]) == [
        pow(3, 7, 1 << 700) * 25 % (1 << 700)
    ]


@pytest.mark.skipif(not native.available(), reason="no native core")
@pytest.mark.parametrize("m_rows", [3, 256])
def test_native_comb_window_widths(m_rows):
    """The comb picks its window width by group shape (w=4 small groups,
    w=6 at ring-Pedersen-like groups); both must match the oracle,
    including exponents that straddle 64-bit limb boundaries."""
    n = _odd_mod(768)
    base = RNG.randrange(1, n)
    exps = [
        RNG.getrandbits(RNG.choice([1, 63, 64, 65, 768, 1500]))
        for _ in range(m_rows)
    ]
    assert native.modexp_shared(base, exps, n) == [
        pow(base, e, n) for e in exps
    ]


# ---------------------------------------------------------------------------
# planner (multi_powm): term routing, negative exponents, recombination


@pytest.mark.parametrize("device", [False, True])
def test_multi_powm_parity(device):
    bases, exps, mods = _random_rows(768, (768, 256), rows=6)
    got = multi_powm(bases, exps, mods, device=device)
    for r in range(len(mods)):
        assert got[r] == _oracle_row(bases[r], exps[r], mods[r])


@pytest.mark.parametrize("device", [False, True])
def test_multi_powm_negative_exponents(device):
    rows = 5
    m = _odd_mod(768)
    mods = [m] * rows
    import math

    bases, exps = [], []
    for _ in range(rows):
        bs, es = [], []
        for w, sign in ((768, 1), (256, -1)):
            while True:
                b = RNG.randrange(2, m)
                if math.gcd(b, m) == 1:
                    break
            bs.append(b)
            es.append(sign * RNG.getrandbits(w))
        bases.append(tuple(bs))
        exps.append(tuple(es))
    got = multi_powm(bases, exps, mods, device=device)
    for r in range(rows):
        want = 1
        for b, e in zip(bases[r], exps[r]):
            want = want * pow(b, e, m) % m
        assert got[r] == want


def test_multi_powm_shared_base_comb_routing():
    """Rows sharing (base, modulus) terms must route through the comb
    and still recombine exactly (the prover stage-1 shape: h1^x h2^rho
    per receiver group)."""
    m = _odd_mod(768)
    h1, h2 = RNG.randrange(2, m), RNG.randrange(2, m)
    rows = 8  # >= _SHARED_MIN_ROWS so both terms ride the comb
    bases = [(h1, h2)] * rows
    exps = [
        (RNG.getrandbits(256), RNG.getrandbits(1024)) for _ in range(rows)
    ]
    mods = [m] * rows
    for device in (False, True):
        got = multi_powm(bases, exps, mods, device=device)
        for r in range(rows):
            assert got[r] == _oracle_row(bases[r], exps[r], mods[r])


def test_multi_powm_rns_path(monkeypatch):
    """Force the RNS router threshold to zero so the joint rows take the
    RNS/MXU kernel."""
    monkeypatch.setattr(powm_mod, "_RNS_MIN_ROWS", 0)
    bases, exps, mods = _random_rows(768, (768, 256), rows=4)
    got = multi_powm(bases, exps, mods, device=True)
    for r in range(len(mods)):
        assert got[r] == _oracle_row(bases[r], exps[r], mods[r])


def test_multi_powm_meshed():
    from fsdkr_tpu.parallel.mesh import make_mesh

    mesh = make_mesh()
    monkey = powm_mod._MESH
    powm_mod._MESH = mesh
    try:
        bases, exps, mods = _random_rows(768, (768, 256), rows=8)
        got = multi_powm(bases, exps, mods, device=True)
    finally:
        powm_mod._MESH = monkey
    for r in range(len(mods)):
        assert got[r] == _oracle_row(bases[r], exps[r], mods[r])


def test_powm_columns_mixed_scalar_and_multi():
    m1, m2 = _odd_mod(768), _odd_mod(768)
    scalar_col = (
        [RNG.randrange(1, m1) for _ in range(3)],
        [RNG.getrandbits(256) for _ in range(3)],
        [m1] * 3,
    )
    mb, me, mm = _random_rows(768, (512, 256), rows=3, shared_mod=False)
    multi_col = (mb, me, mm)
    out = powm_columns(host_powm, scalar_col, multi_col, multi_col)
    assert out[0] == [
        pow(b, e, m) for b, e, m in zip(*scalar_col)
    ]
    for r in range(3):
        assert out[1][r] == _oracle_row(mb[r], me[r], mm[r])
    assert out[2] == out[1]  # dedup path
    assert out[2] is not out[1]  # no aliasing across columns
    assert m2  # keep the second modulus sampled (determinism of RNG use)


def test_batch_base_inv():
    m = _odd_mod(768)
    vals = [RNG.randrange(2, m) for _ in range(6)]
    out = batch_base_inv(vals, [m] * 6)
    for v, inv in zip(vals, out):
        if inv is not None:
            assert v * inv % m == 1
    # a non-invertible row reports None without poisoning its neighbors
    import math

    p = 0xFFFF_FFFB  # prime factor of the modulus
    m2 = p * _odd_mod(64)
    vals2 = [p, RNG.randrange(2, m2) | 1]
    while math.gcd(vals2[1], m2) != 1:
        vals2[1] = RNG.randrange(2, m2) | 1
    out2 = batch_base_inv(vals2, [m2] * 2)
    assert out2[0] is None
    assert out2[1] is not None and vals2[1] * out2[1] % m2 == 1


# ---------------------------------------------------------------------------
# collect-level A/B identity: joint and column planners must produce
# bit-identical accept/reject behavior on the tamper surface they share


def _collect(refreshed, config, mutate, collector=0):
    keys, msgs, dks = refreshed
    msgs = copy.deepcopy(msgs)
    mutate(msgs)
    key = keys[collector].clone()
    from fsdkr_tpu.protocol import RefreshMessage

    RefreshMessage.collect(msgs, key, dks[collector], (), config)


_AB_CASES = [
    ("honest", lambda msgs: None),
    (
        "pdl_s1",
        lambda msgs: msgs[1].pdl_proof_vec.__setitem__(
            0,
            dataclasses.replace(
                msgs[1].pdl_proof_vec[0], s1=msgs[1].pdl_proof_vec[0].s1 + 1
            ),
        ),
    ),
    (
        "pdl_s2",
        lambda msgs: msgs[1].pdl_proof_vec.__setitem__(
            0,
            dataclasses.replace(
                msgs[1].pdl_proof_vec[0], s2=msgs[1].pdl_proof_vec[0].s2 + 1
            ),
        ),
    ),
    (
        "pdl_u2",
        lambda msgs: msgs[1].pdl_proof_vec.__setitem__(
            0,
            dataclasses.replace(
                msgs[1].pdl_proof_vec[0], u2=msgs[1].pdl_proof_vec[0].u2 + 1
            ),
        ),
    ),
    (
        "range_s",
        lambda msgs: msgs[1].range_proofs.__setitem__(
            0,
            dataclasses.replace(
                msgs[1].range_proofs[0], s=msgs[1].range_proofs[0].s + 1
            ),
        ),
    ),
    (
        "range_z",
        lambda msgs: msgs[1].range_proofs.__setitem__(
            0,
            dataclasses.replace(
                msgs[1].range_proofs[0], z=msgs[1].range_proofs[0].z + 1
            ),
        ),
    ),
    (
        "range_e",
        lambda msgs: msgs[1].range_proofs.__setitem__(
            0,
            dataclasses.replace(
                msgs[1].range_proofs[0], e=msgs[1].range_proofs[0].e ^ 1
            ),
        ),
    ),
]


@pytest.mark.heavy
@pytest.mark.parametrize("name,mutate", _AB_CASES, ids=[c[0] for c in _AB_CASES])
def test_collect_joint_vs_column_identity(
    name, mutate, one_refresh_round, test_config, monkeypatch
):
    """The FSDKR_MULTIEXP=1 (joint rows) and =0 (column) planners must
    accept/reject identically, with the same error class, on the exact
    equations the joint rewrite touched (PDL u2, range u/w)."""
    config = test_config.with_backend("tpu")
    outcomes = {}
    for flag in ("1", "0"):
        monkeypatch.setenv("FSDKR_MULTIEXP", flag)
        try:
            _collect(one_refresh_round, config, mutate)
            outcomes[flag] = None
        except Exception as e:  # noqa: BLE001 - compare classes exactly
            outcomes[flag] = type(e).__name__
    assert outcomes["1"] == outcomes["0"], outcomes
    if name == "honest":
        assert outcomes["1"] is None
