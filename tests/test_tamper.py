"""Adversarial tamper matrix: every broadcast field of RefreshMessage is
perturbed post-distribute and collect() must reject with the matching
identifiable-abort error.

Generalizes the reference's single soundness negative
(`/root/reference/src/zk_pdl_with_slack.rs:268-331`, which encrypts x+1
and expects verification failure) to the full wire surface of
`RefreshMessage` (`src/refresh_message.rs:31-48`) — a malicious rushing
adversary controls every byte it broadcasts (`src/lib.rs:5-9`)."""

import copy
import dataclasses

import pytest

from fsdkr_tpu.core.secp256k1 import GENERATOR
from fsdkr_tpu.errors import (
    BroadcastedPublicKeyError,
    ModuliTooSmall,
    PaillierVerificationError,
    PartiesThresholdViolation,
    PDLwSlackProofError,
    PublicShareValidationError,
    RangeProofError,
    RingPedersenProofError,
    SizeMismatchError,
)
from fsdkr_tpu.protocol import RefreshMessage


@pytest.fixture(scope="module")
def refreshed(one_refresh_round):
    """Shared honest round (see conftest.one_refresh_round)."""
    return one_refresh_round


def _collect_tampered(refreshed, config, mutate, collector=0):
    keys, msgs, dks = refreshed
    msgs = copy.deepcopy(msgs)
    mutate(msgs)
    key = keys[collector].clone()
    RefreshMessage.collect(msgs, key, dks[collector], (), config)


CASES = [
    # (name, expected error, mutation)
    (
        "public_key",
        BroadcastedPublicKeyError,
        lambda msgs: setattr(msgs[1], "public_key", msgs[1].public_key + GENERATOR),
    ),
    (
        "committed_point",
        PublicShareValidationError,  # Feldman check
        lambda msgs: msgs[1].points_committed_vec.__setitem__(
            0, msgs[1].points_committed_vec[0] + GENERATOR
        ),
    ),
    (
        "pdl_proof_s1",
        PDLwSlackProofError,
        lambda msgs: msgs[1].pdl_proof_vec.__setitem__(
            0, dataclasses.replace(msgs[1].pdl_proof_vec[0], s1=msgs[1].pdl_proof_vec[0].s1 + 1)
        ),
    ),
    (
        "range_proof_s",
        RangeProofError,
        lambda msgs: msgs[1].range_proofs.__setitem__(
            0, dataclasses.replace(msgs[1].range_proofs[0], s=msgs[1].range_proofs[0].s + 1)
        ),
    ),
    (
        "ring_pedersen_Z",
        RingPedersenProofError,
        lambda msgs: msgs[1].ring_pedersen_proof.Z.__setitem__(
            0, msgs[1].ring_pedersen_proof.Z[0] + 1
        ),
    ),
    (
        "correct_key_sigma",
        PaillierVerificationError,
        lambda msgs: msgs[1].dk_correctness_proof.sigma_vec.__setitem__(
            0, msgs[1].dk_correctness_proof.sigma_vec[0] + 1
        ),
    ),
    (
        "new_ek_too_small",
        (PaillierVerificationError, ModuliTooSmall),
        lambda msgs: setattr(
            msgs[1], "ek", type(msgs[1].ek).from_n((1 << 520) + 21)
        ),
    ),
    (
        "ciphertext",
        PDLwSlackProofError,  # the PDL statement binds the ciphertext
        lambda msgs: msgs[1].points_encrypted_vec.__setitem__(
            0, msgs[1].points_encrypted_vec[0] + 1
        ),
    ),
    (
        "lagrange_index",
        PublicShareValidationError,  # constant-term interpolation gate:
        # a lying old_party_index skews the Lagrange weights and would
        # silently rotate onto a different secret (reference quirk 4
        # leaves this undetected)
        lambda msgs: setattr(msgs[0], "old_party_index", msgs[1].old_party_index),
    ),
    (
        "short_vector",
        SizeMismatchError,
        lambda msgs: msgs[1].points_encrypted_vec.pop(),
    ),
    # ---- out-of-domain integers (in-process objects bypass the strict
    # wire decode): the batched backend must fail the row with the same
    # identifiable-abort error as the host oracle — never crash the limb
    # encoder / transcript, never inflate the fused launch width --------
    (
        "negative_range_s1",
        RangeProofError,
        lambda msgs: msgs[1].range_proofs.__setitem__(
            0, dataclasses.replace(msgs[1].range_proofs[0], s1=-5)
        ),
    ),
    (
        "negative_pdl_s3",
        PDLwSlackProofError,
        lambda msgs: msgs[1].pdl_proof_vec.__setitem__(
            0, dataclasses.replace(msgs[1].pdl_proof_vec[0], s3=-5)
        ),
    ),
    (
        "negative_pdl_z",
        PDLwSlackProofError,  # transcript-position field
        lambda msgs: msgs[1].pdl_proof_vec.__setitem__(
            0, dataclasses.replace(msgs[1].pdl_proof_vec[0], z=-5)
        ),
    ),
    (
        "negative_ringped_Z",
        RingPedersenProofError,
        lambda msgs: msgs[1].ring_pedersen_proof.Z.__setitem__(0, -5),
    ),
    (
        "huge_range_s1_dos",
        RangeProofError,  # width cap: must fail the row pre-launch, not
        # pad every row of the fused column to 2^20-bit exponents
        lambda msgs: msgs[1].range_proofs.__setitem__(
            0,
            dataclasses.replace(msgs[1].range_proofs[0], s1=1 << (1 << 20)),
        ),
    ),
]


@pytest.mark.parametrize(
    "backend",
    [
        "host",
        # batched-backend collects on the CPU platform cost ~30 s each:
        # keep the smoke gate under 3 minutes (scripts/ci.sh)
        pytest.param("tpu", marks=pytest.mark.heavy),
    ],
)
@pytest.mark.parametrize("name,err,mutate", CASES, ids=[c[0] for c in CASES])
def test_tampered_broadcast_rejected(
    refreshed, test_config, backend, name, err, mutate
):
    """Both verification backends must reject every tamper with the same
    identifiable-abort error — the TPU backend's batched launches and
    loop-order attribution are the production path."""
    with pytest.raises(err):
        _collect_tampered(refreshed, test_config.with_backend(backend), mutate)


# ---- FSDKR_RLC (cross-proof randomized batch verification) -----------
# The RLC path must be verdict-identical to the per-row column path on
# honest AND tampered transcripts (its combined-check failures bisect
# down to exact per-row verdicts), and a single tampered proof must
# blame exactly the culpable party through the bisection path.

# tampers covering every RLC-folded family (PDL eq2+eq3, ring-Pedersen,
# correct-key) plus the unfolded range family and a domain-gated row
_RLC_CASE_NAMES = (
    "pdl_proof_s1",
    "range_proof_s",
    "ring_pedersen_Z",
    "correct_key_sigma",
    "negative_pdl_s3",
)
RLC_CASES = [c for c in CASES if c[0] in _RLC_CASE_NAMES]


def _err_key(e):
    """Comparable identity of an identifiable-abort error: type plus the
    attribution fields (per-equation booleans / party index)."""
    return (
        type(e).__name__,
        getattr(e, "is_u1_eq", None),
        getattr(e, "is_u2_eq", None),
        getattr(e, "is_u3_eq", None),
        getattr(e, "party_index", None),
    )


@pytest.mark.parametrize("name,err,mutate", RLC_CASES, ids=[c[0] for c in RLC_CASES])
def test_rlc_verdicts_identical_to_column_path(
    refreshed, test_config, monkeypatch, name, err, mutate
):
    """Collect-level A/B: FSDKR_RLC=1 raises the exact same
    identifiable-abort error (type + attribution fields) as the =0
    column path on a tampered transcript. Host engines: the planner and
    bisection logic are engine-independent, and the device kernels are
    covered by tests/test_rlc.py."""
    monkeypatch.setenv("FSDKR_DEVICE_POWM", "0")
    monkeypatch.setenv("FSDKR_DEVICE_EC", "0")
    keys = {}
    for leg in ("0", "1"):
        monkeypatch.setenv("FSDKR_RLC", leg)
        with pytest.raises(err) as ei:
            _collect_tampered(
                refreshed, test_config.with_backend("tpu"), mutate
            )
        keys[leg] = _err_key(ei.value)
    assert keys["0"] == keys["1"]


def test_rlc_honest_verdicts_identical(refreshed, test_config, monkeypatch):
    """Collect-level A/B on an honest transcript: both legs accept, and
    the RLC leg actually folded (groups > 0, no bisection)."""
    from fsdkr_tpu.backend import rlc

    monkeypatch.setenv("FSDKR_DEVICE_POWM", "0")
    monkeypatch.setenv("FSDKR_DEVICE_EC", "0")
    monkeypatch.setenv("FSDKR_RLC", "0")
    _collect_tampered(refreshed, test_config.with_backend("tpu"), lambda m: None)
    monkeypatch.setenv("FSDKR_RLC", "1")
    rlc.stats_reset()
    _collect_tampered(
        refreshed, test_config.with_backend("tpu"), lambda m: None, collector=2
    )
    s = rlc.stats()
    assert s["rlc_groups"] > 0
    assert s["rows_folded"] > s["rlc_groups"]
    assert s["bisect_fallbacks"] == 0
    # the O(1)-per-group property the fold exists for
    assert s["fullwidth_ladders"] <= 2 * s["rlc_groups"]


@pytest.fixture(scope="module")
def committee16(test_config):
    """(t=1, n=16) honest round for the bisection-blame test: 16-row RLC
    groups give the bisection four levels to walk."""
    from fsdkr_tpu.protocol import RefreshMessage, simulate_keygen

    keys = simulate_keygen(1, 16, test_config)
    results = RefreshMessage.distribute_batch(
        [(k.i, k) for k in keys], 16, test_config
    )
    return keys, [m for m, _ in results], [dk for _, dk in results]


@pytest.mark.heavy  # n=16 keygen+distribute: tier-1, not the smoke gate
def test_rlc_bisection_blames_exact_party_n16(
    committee16, test_config, monkeypatch
):
    """Satellite gate: under FSDKR_RLC=1 a single tampered proof at n=16
    blames exactly the culpable (sender, receiver) row through the
    bisection path, and the full per-row verdict vector is bit-identical
    to FSDKR_RLC=0."""
    from fsdkr_tpu.backend import rlc
    from fsdkr_tpu.backend.batch_verifier import get_backend
    from fsdkr_tpu.core.secp256k1 import GENERATOR
    from fsdkr_tpu.proofs.pdl_slack import PDLwSlackStatement

    monkeypatch.setenv("FSDKR_DEVICE_POWM", "0")
    monkeypatch.setenv("FSDKR_DEVICE_EC", "0")
    keys, msgs, _dks = committee16
    msgs = copy.deepcopy(msgs)
    key = keys[0]
    n = 16
    bad_sender, bad_receiver = 7, 3
    p = msgs[bad_sender].pdl_proof_vec[bad_receiver]
    msgs[bad_sender].pdl_proof_vec[bad_receiver] = dataclasses.replace(
        p, s2=p.s2 + 1  # breaks eq2 only: eq3 and u1 stay valid
    )

    pdl_items, range_items = [], []
    for msg in msgs:
        for i in range(n):
            st = PDLwSlackStatement(
                ciphertext=msg.points_encrypted_vec[i],
                ek=key.paillier_key_vec[i],
                Q=msg.points_committed_vec[i],
                G=GENERATOR,
                h1=key.h1_h2_n_tilde_vec[i].g,
                h2=key.h1_h2_n_tilde_vec[i].ni,
                N_tilde=key.h1_h2_n_tilde_vec[i].N,
            )
            pdl_items.append((msg.pdl_proof_vec[i], st))
            range_items.append(
                (
                    msg.range_proofs[i],
                    msg.points_encrypted_vec[i],
                    key.paillier_key_vec[i],
                    key.h1_h2_n_tilde_vec[i],
                )
            )
    bad_row = bad_sender * n + bad_receiver

    verdicts = {}
    for leg in ("0", "1"):
        monkeypatch.setenv("FSDKR_RLC", leg)
        rlc.stats_reset()
        backend = get_backend(test_config.with_backend("tpu"))
        pdl_v, range_v = backend.verify_pairs(pdl_items, range_items)
        verdicts[leg] = (pdl_v, range_v)
        if leg == "1":
            s = rlc.stats()
            assert s["bisect_fallbacks"] >= 1  # the bisection path ran
            assert s["rows_folded"] >= 2 * n * n - 2
            # O(1) full-width ladders per group, not O(rows)
            assert s["fullwidth_ladders"] <= 2 * s["rlc_groups"]
    assert verdicts["1"] == verdicts["0"]
    pdl_v, range_v = verdicts["1"]
    assert all(range_v)
    for row, v in enumerate(pdl_v):
        if row == bad_row:
            assert v == (True, False, True)  # exactly eq2, exactly this row
        else:
            assert v is None


def test_too_few_messages(refreshed, test_config):
    keys, msgs, dks = refreshed
    with pytest.raises(PartiesThresholdViolation):
        RefreshMessage.collect(msgs[:1], keys[0].clone(), dks[0], (), test_config)


def test_honest_baseline_still_accepts(refreshed, test_config):
    """The fixture's messages are genuinely valid — the matrix fails for
    the tamper, not because the fixture is broken."""
    _collect_tampered(refreshed, test_config, lambda msgs: None, collector=2)
