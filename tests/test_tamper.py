"""Adversarial tamper matrix: every broadcast field of RefreshMessage is
perturbed post-distribute and collect() must reject with the matching
identifiable-abort error.

Generalizes the reference's single soundness negative
(`/root/reference/src/zk_pdl_with_slack.rs:268-331`, which encrypts x+1
and expects verification failure) to the full wire surface of
`RefreshMessage` (`src/refresh_message.rs:31-48`) — a malicious rushing
adversary controls every byte it broadcasts (`src/lib.rs:5-9`)."""

import copy
import dataclasses

import pytest

from fsdkr_tpu.core.secp256k1 import GENERATOR
from fsdkr_tpu.errors import (
    BroadcastedPublicKeyError,
    ModuliTooSmall,
    PaillierVerificationError,
    PartiesThresholdViolation,
    PDLwSlackProofError,
    PublicShareValidationError,
    RangeProofError,
    RingPedersenProofError,
    SizeMismatchError,
)
from fsdkr_tpu.protocol import RefreshMessage


@pytest.fixture(scope="module")
def refreshed(one_refresh_round):
    """Shared honest round (see conftest.one_refresh_round)."""
    return one_refresh_round


def _collect_tampered(refreshed, config, mutate, collector=0):
    keys, msgs, dks = refreshed
    msgs = copy.deepcopy(msgs)
    mutate(msgs)
    key = keys[collector].clone()
    RefreshMessage.collect(msgs, key, dks[collector], (), config)


CASES = [
    # (name, expected error, mutation)
    (
        "public_key",
        BroadcastedPublicKeyError,
        lambda msgs: setattr(msgs[1], "public_key", msgs[1].public_key + GENERATOR),
    ),
    (
        "committed_point",
        PublicShareValidationError,  # Feldman check
        lambda msgs: msgs[1].points_committed_vec.__setitem__(
            0, msgs[1].points_committed_vec[0] + GENERATOR
        ),
    ),
    (
        "pdl_proof_s1",
        PDLwSlackProofError,
        lambda msgs: msgs[1].pdl_proof_vec.__setitem__(
            0, dataclasses.replace(msgs[1].pdl_proof_vec[0], s1=msgs[1].pdl_proof_vec[0].s1 + 1)
        ),
    ),
    (
        "range_proof_s",
        RangeProofError,
        lambda msgs: msgs[1].range_proofs.__setitem__(
            0, dataclasses.replace(msgs[1].range_proofs[0], s=msgs[1].range_proofs[0].s + 1)
        ),
    ),
    (
        "ring_pedersen_Z",
        RingPedersenProofError,
        lambda msgs: msgs[1].ring_pedersen_proof.Z.__setitem__(
            0, msgs[1].ring_pedersen_proof.Z[0] + 1
        ),
    ),
    (
        "correct_key_sigma",
        PaillierVerificationError,
        lambda msgs: msgs[1].dk_correctness_proof.sigma_vec.__setitem__(
            0, msgs[1].dk_correctness_proof.sigma_vec[0] + 1
        ),
    ),
    (
        "new_ek_too_small",
        (PaillierVerificationError, ModuliTooSmall),
        lambda msgs: setattr(
            msgs[1], "ek", type(msgs[1].ek).from_n((1 << 520) + 21)
        ),
    ),
    (
        "ciphertext",
        PDLwSlackProofError,  # the PDL statement binds the ciphertext
        lambda msgs: msgs[1].points_encrypted_vec.__setitem__(
            0, msgs[1].points_encrypted_vec[0] + 1
        ),
    ),
    (
        "lagrange_index",
        PublicShareValidationError,  # constant-term interpolation gate:
        # a lying old_party_index skews the Lagrange weights and would
        # silently rotate onto a different secret (reference quirk 4
        # leaves this undetected)
        lambda msgs: setattr(msgs[0], "old_party_index", msgs[1].old_party_index),
    ),
    (
        "short_vector",
        SizeMismatchError,
        lambda msgs: msgs[1].points_encrypted_vec.pop(),
    ),
    # ---- out-of-domain integers (in-process objects bypass the strict
    # wire decode): the batched backend must fail the row with the same
    # identifiable-abort error as the host oracle — never crash the limb
    # encoder / transcript, never inflate the fused launch width --------
    (
        "negative_range_s1",
        RangeProofError,
        lambda msgs: msgs[1].range_proofs.__setitem__(
            0, dataclasses.replace(msgs[1].range_proofs[0], s1=-5)
        ),
    ),
    (
        "negative_pdl_s3",
        PDLwSlackProofError,
        lambda msgs: msgs[1].pdl_proof_vec.__setitem__(
            0, dataclasses.replace(msgs[1].pdl_proof_vec[0], s3=-5)
        ),
    ),
    (
        "negative_pdl_z",
        PDLwSlackProofError,  # transcript-position field
        lambda msgs: msgs[1].pdl_proof_vec.__setitem__(
            0, dataclasses.replace(msgs[1].pdl_proof_vec[0], z=-5)
        ),
    ),
    (
        "negative_ringped_Z",
        RingPedersenProofError,
        lambda msgs: msgs[1].ring_pedersen_proof.Z.__setitem__(0, -5),
    ),
    (
        "huge_range_s1_dos",
        RangeProofError,  # width cap: must fail the row pre-launch, not
        # pad every row of the fused column to 2^20-bit exponents
        lambda msgs: msgs[1].range_proofs.__setitem__(
            0,
            dataclasses.replace(msgs[1].range_proofs[0], s1=1 << (1 << 20)),
        ),
    ),
]


@pytest.mark.parametrize(
    "backend",
    [
        "host",
        # batched-backend collects on the CPU platform cost ~30 s each:
        # keep the smoke gate under 3 minutes (scripts/ci.sh)
        pytest.param("tpu", marks=pytest.mark.heavy),
    ],
)
@pytest.mark.parametrize("name,err,mutate", CASES, ids=[c[0] for c in CASES])
def test_tampered_broadcast_rejected(
    refreshed, test_config, backend, name, err, mutate
):
    """Both verification backends must reject every tamper with the same
    identifiable-abort error — the TPU backend's batched launches and
    loop-order attribution are the production path."""
    with pytest.raises(err):
        _collect_tampered(refreshed, test_config.with_backend(backend), mutate)


def test_too_few_messages(refreshed, test_config):
    keys, msgs, dks = refreshed
    with pytest.raises(PartiesThresholdViolation):
        RefreshMessage.collect(msgs[:1], keys[0].clone(), dks[0], (), test_config)


def test_honest_baseline_still_accepts(refreshed, test_config):
    """The fixture's messages are genuinely valid — the matrix fails for
    the tamper, not because the fixture is broken."""
    _collect_tampered(refreshed, test_config, lambda msgs: None, collector=2)
