"""RefreshService / capacity planner / batching policy (ISSUE 9).

Protocol-level correctness of the serving loop lives in
tests/test_streaming.py (streaming == barrier); here the SCHEDULER is
under test: lifecycle, coalescing, the FSDKR_SERVE=0 single-shot arm,
SLO -> depth planning, churn invalidation wiring, and the serving
metric surface.
"""

import pytest

from fsdkr_tpu import precompute
from fsdkr_tpu.core.paillier import EncryptionKey
from fsdkr_tpu.proofs.composite_dlog import DLogStatement
from fsdkr_tpu.protocol import simulate_keygen
from fsdkr_tpu.serving import (
    SLO,
    BatchPolicy,
    CapacityPlanner,
    RefreshService,
    serve_owner,
)


@pytest.fixture(autouse=True)
def _clean_pools():
    precompute.clear_targets()
    precompute.clear_pools()
    yield
    precompute.clear_targets()
    precompute.clear_pools()


# ---------------------------------------------------------------------------
# policy


def test_batch_policy_size_and_linger():
    p = BatchPolicy(max_sessions=4, linger_s=0.5)
    assert p.take(0, 99.0) == 0
    assert p.take(2, 0.1) == 0  # under size, under linger: wait
    assert p.take(2, 0.6) == 2  # linger expired: flush what's there
    assert p.take(4, 0.0) == 4  # at size: launch now
    assert p.take(9, 0.0) == 4  # capped at max_sessions
    assert p.wait_budget(0.1) == pytest.approx(0.4)


def test_batch_policy_mesh_alignment():
    from fsdkr_tpu.parallel.shard_kernels import align_session_batch

    # 8 devices, 12 rows/session: 6 sessions -> 72 rows divides; 5 -> 60
    # does not, largest aligned k <= 5 is 4 (48 rows)
    assert align_session_batch(6, 12, 8) == 6
    assert align_session_batch(5, 12, 8) == 4
    assert align_session_batch(3, 12, 8) == 2
    assert align_session_batch(5, 12, 1) == 5  # single device: no-op
    assert align_session_batch(3, 7, 8) == 3  # no aligned k: unchanged
    p = BatchPolicy(max_sessions=6, linger_s=0.0, devices=8)
    assert p.take(5, 1.0, rows_per_session=12) == 4


# ---------------------------------------------------------------------------
# planner


def _fake_committee(n=3, bits=64):
    """Synthetic LocalKey stand-in for target math: committee_targets
    only reads paillier_key_vec[i].n and h1_h2_n_tilde_vec[i] fields."""

    class FakeKey:
        pass

    k = FakeKey()
    k.paillier_key_vec = [
        EncryptionKey.from_n((1 << bits) + 100 * i + 1) for i in range(n)
    ]
    k.h1_h2_n_tilde_vec = [
        DLogStatement(N=(1 << bits) + 200 * i + 3, g=2 + i, ni=5 + i)
        for i in range(n)
    ]
    return k


def test_planner_depth_math(test_config):
    pl = CapacityPlanner(horizon_s=30.0, max_ahead=4)
    assert pl.epochs_ahead(SLO(arrival_rate_hz=0.001)) == 1
    assert pl.epochs_ahead(SLO(arrival_rate_hz=0.1)) == 3
    assert pl.epochs_ahead(SLO(arrival_rate_hz=10.0)) == 4  # clamped
    fk = _fake_committee()
    pl.register("c1", fk, 3, test_config, SLO(arrival_rate_hz=0.2))
    # keys demand aggregates across committees sharing the config
    w1 = pl.keys_want(test_config)
    pl.register("c2", _fake_committee(), 3, test_config, SLO(arrival_rate_hz=0.2))
    assert pl.keys_want(test_config) > w1


def test_planner_register_retarget_invalidate(test_config):
    pl = CapacityPlanner(horizon_s=10.0, max_ahead=2)
    fk = _fake_committee()
    pl.register("com", fk, 3, test_config, SLO(arrival_rate_hz=0.5))
    owned = precompute.target_keys(owner=serve_owner("com"))
    assert len(owned) == 9  # 3 receivers x enc/pdl/alice; keys is fleet-owned
    assert precompute.target_keys(owner=precompute.KEYS_POOL_OWNER)
    # fill one owned pool, then rotate one receiver's modulus: retarget
    # must wipe the stale pool and target
    kind, key = next(k for k in owned if k[0] == "enc")
    precompute.put(kind, key, (5, 7))
    assert precompute.get_store().depth(kind, key) == 1
    fk.paillier_key_vec[0] = EncryptionKey.from_n((1 << 64) + 9999)
    pl.retarget("com")
    assert (kind, key) not in precompute.target_keys(owner=serve_owner("com"))
    assert precompute.get_store().depth(kind, key) == 0  # wiped
    # eviction drops everything owned by the committee but NOT the
    # shared keys pool target
    pl.invalidate("com")
    assert precompute.target_keys(owner=serve_owner("com")) == []
    assert precompute.target_keys(owner=precompute.KEYS_POOL_OWNER)


# ---------------------------------------------------------------------------
# producer churn API (ROADMAP 5a regression)


def test_invalidate_owner_wipes_pools():
    precompute.register_targets(
        [("enc", 1009, 2), ("enc", 2003, 2)], owner="A"
    )
    precompute.register_targets([("enc", 3001, 2)], owner="B")
    for n in (1009, 2003, 3001):
        precompute.put("enc", n, (3, 9))
    stats0 = precompute.precompute_stats()
    assert precompute.invalidate_owner("A") == 2
    store = precompute.get_store()
    assert store.depth("enc", 1009) == 0 and store.depth("enc", 2003) == 0
    assert store.depth("enc", 3001) == 1  # other owner untouched
    assert precompute.precompute_stats()["wiped"] == stats0["wiped"] + 2
    assert precompute.target_keys(owner="A") == []
    assert precompute.target_keys(owner="B") == [("enc", 3001)]


def test_replace_targets_wipes_only_stale():
    precompute.register_targets([("enc", 11, 1), ("enc", 13, 1)], owner="C")
    precompute.put("enc", 11, (1, 1))
    precompute.put("enc", 13, (1, 1))
    precompute.replace_targets([("enc", 13, 1), ("enc", 17, 1)], owner="C")
    store = precompute.get_store()
    assert store.depth("enc", 11) == 0  # stale: wiped
    assert store.depth("enc", 13) == 1  # still wanted: kept
    assert sorted(precompute.target_keys(owner="C")) == [
        ("enc", 13), ("enc", 17),
    ]


@pytest.mark.fresh_committees
def test_replace_churn_invalidates_stale_pools(test_config):
    """ROADMAP 5a: a replace() churn explicitly invalidates the pools
    registered for the pre-churn committee layout — the single-use
    secrets are wiped NOW, and the post-churn epoch can only consume
    entries keyed by the live layout."""
    keys = simulate_keygen(1, 3, test_config)
    # pre-churn registration: what the last epoch's distribute would
    # have left behind, keyed by the CURRENT layout's fingerprint owner
    owner = precompute.committee_owner(keys[0].h1_h2_n_tilde_vec)
    sentinel = ("enc", keys[0].paillier_key_vec[0].n)
    precompute.register_targets([sentinel + (2,)], owner=owner)
    precompute.put(*sentinel, (7, 11))
    assert precompute.get_store().depth(*sentinel) == 1

    from fsdkr_tpu.protocol import RefreshMessage

    old_n0 = keys[0].paillier_key_vec[0].n
    msg, dk = RefreshMessage.replace(
        (), keys[0], {1: 1, 2: 2, 3: 3}, 3, test_config
    )
    assert msg.party_index == 1 and dk is not None
    # the pre-churn registration is gone and its pooled entry wiped:
    # replace() invalidated the owner, and the epoch's own registration
    # replaced the target set with next-epoch keys
    assert sentinel not in precompute.target_keys()
    assert precompute.get_store().depth(*sentinel) == 0
    # the post-churn registration is keyed by the NEXT epoch's layout:
    # the rotated-out modulus appears in no per-receiver target, the
    # freshly broadcast ek does — so no stale-keyed entry can ever be
    # consumed by a post-churn epoch
    next_ns = {msg.ek.n} | {ek.n for ek in keys[0].paillier_key_vec[1:]}
    targeted_enc = {key for kind, key in precompute.target_keys() if kind == "enc"}
    assert targeted_enc and targeted_enc <= next_ns
    assert old_n0 not in targeted_enc
    for kind, key in precompute.target_keys():
        if kind in ("pdl", "alice"):
            assert key[3] in next_ns and key[3] != old_n0


# ---------------------------------------------------------------------------
# the service


@pytest.fixture
def small_service(test_config):
    base = simulate_keygen(1, 3, test_config)
    svc = RefreshService(policy=BatchPolicy(max_sessions=6, linger_s=0.02))
    for cid in ("alpha", "beta"):
        svc.admit(
            cid, [k.clone() for k in base], test_config,
            SLO(arrival_rate_hz=0.5),
        )
    yield svc
    svc.stop()


def test_service_end_to_end(small_service):
    svc = small_service
    svc.start()
    sids = [svc.submit("alpha"), svc.submit("beta"), svc.submit("alpha")]
    assert svc.drain(timeout=180)
    for sid in sids:
        s = svc.wait(sid, timeout=1)
        assert s.state == "done", s.error
        assert s.finalized_at >= s.quorum_at >= s.started_at > 0
    st = svc.stats()
    assert st["sessions_done"] == 3 and st["sessions_aborted"] == 0
    assert st["inflight"] == 0
    # two sessions for "alpha" serialized on one committee: both epochs
    # landed, so the committee advanced twice
    assert svc._committees["alpha"].epochs == 2
    # serving metrics materialized in the registry
    from fsdkr_tpu.serving import metrics as sm

    assert sm.sessions_counter().value(outcome="done") >= 3
    snap = sm.phase_histogram().snapshot_values()
    phases = {v["labels"]["phase"] for v in snap}
    assert {"queue", "distribute", "stream", "finalize", "total"} <= phases


def test_service_single_shot_arm(small_service, monkeypatch):
    """FSDKR_SERVE=0: submit() is synchronous barrier collect — no
    service threads involved, same outcome surface."""
    monkeypatch.setenv("FSDKR_SERVE", "0")
    svc = small_service  # not started: the single-shot arm needs no threads
    sid = svc.submit("alpha")
    s = svc.wait(sid, timeout=0)
    assert s.state == "done", s.error
    assert svc.stats()["sessions_done"] == 1


def test_service_admission_guards(small_service):
    svc = small_service
    with pytest.raises(ValueError):
        svc.admit("alpha", [], None)
    with pytest.raises(KeyError):
        svc.submit("nope")
    svc.evict("beta")
    with pytest.raises(KeyError):
        svc.submit("beta")
    assert precompute.target_keys(owner=serve_owner("beta")) == []
